"""Autoregressive decoding with a KV cache: ``generate()`` for the LM family.

Capability ADD with no reference analogue (dist-keras predates generative
models; its Predictor is batch-scoring only — SURVEY §3.4). TPU-first
design:

  * One compiled program per configuration: a batched PREFILL over the
    whole prompt (one causal flash pass per layer writing all cache
    positions at once — round 4; an 8K prompt is one kernel sweep, not
    8K sequential steps) followed by ONE jitted ``lax.scan`` over the
    new tokens — no per-token Python dispatch, static shapes throughout.
  * The cache is a head-major ``[B, Hkv, cap, Dh]`` buffer created
    INSIDE the compiled program and written with
    ``dynamic_update_slice``; ``cache_dtype="int8"`` stores quantized
    payloads with per-token-per-head scales.
  * Per-step attention is the fused Pallas kernel
    (``ops.decode_attention``) for deep caches on TPU, or a
    storage-dtype einsum with a causal validity mask otherwise — the
    [S, S] score matrix never exists; each step is O(L) like flash
    decoding.

Works on ``zoo.transformer_lm``-shaped models: a ``Sequential`` of
Embedding / PositionalEmbedding / TransformerBlock (optionally
Remat-wrapped) / norm / Dense. MoE blocks: ``generate()``'s scalar path
runs each block's configured routing (dense routing is per-token
already — it is the serving oracle); the SLOT-level steps below default
to the decode-specialized DISPATCHED path (``MoE.decode_apply`` —
drop-free by construction, fused Pallas gather-into-GEMM on TPU, the
XLA tokens floor elsewhere; MoE-serving PR), which equals dense routing
token-for-token while engaging the sparse-dispatch machinery at decode
shapes. Sequence-parallel ``attn_impl`` settings are ignored at decode
time — generation is a single-device (or TP/EP-sharded) path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distkeras_tpu.compat import backend_is_tpu
from distkeras_tpu.models.attention import (MultiHeadAttention,
                                            PositionalEmbedding,
                                            TransformerBlock)
from distkeras_tpu.models.core import Model, Sequential
from distkeras_tpu.models.layers import Dropout
from distkeras_tpu.ops.attention import NEG_INF, apply_rope


def _decode_block_of(layer):
    """The TransformerBlock a decode step should run for ``layer``, or
    None for position-wise layers. Unwraps ``Remat`` (a training-time
    memory policy — decoding reads the inner block directly; round 4:
    before this, a remat-wrapped model silently decoded GARBAGE because
    the wrapper fell through to the position-wise branch, running
    cache-less self-attention on single tokens)."""
    from distkeras_tpu.models.blocks import Remat
    if isinstance(layer, TransformerBlock):
        return layer
    if isinstance(layer, Remat) and isinstance(layer.inner,
                                               TransformerBlock):
        return layer.inner
    return None


def init_cache(module: Sequential, batch: int, max_len: int,
               dtype=jnp.float32, check_len: int = None):
    """Per-layer KV buffers ([B, H, max_len, Dh]) mirroring the Sequential;
    non-attention layers get ``None``. The HEAD-major layout (round 4)
    keeps each head's [L, Dh] plane contiguous, so the per-step cache
    einsums read full DMA lines — the token-major [B, L, H, Dh] layout
    made every head read a 128-byte strided gather (~1/4 effective HBM
    bandwidth measured at L=2113 on v5e).

    ``dtype="int8"`` (round 4) builds a QUANTIZED cache: int8 k/v plus f32
    per-token-per-head scales ([B, H, max_len]) — each written entry
    stores ``round(x / scale) * scale`` with ``scale = max|x| / 127`` over
    its head vector. At long contexts the cache read dominates the decode
    roofline (docs/PERF.md), so int8 halves the dominant term vs bf16;
    the scale read is Dh=64x smaller than the payload. Composes with GQA
    (scales are per KV head).

    ``dtype="int4"`` (this PR) extends the ladder one more rung: entries
    quantize to 4-bit symmetric (``scale = max|x| / 7``). In THIS
    unpacked request/slab cache the payload still occupies one int8 byte
    per entry holding a value in [-7, 7] — the dequant contract
    (``q * scale``) is byte-for-byte the int8 contract, so every cache
    read path is shared verbatim; the 2x byte saving is realized where
    it matters, in ``PagedKVPool``'s packed page planes (two nibbles
    per byte along the position axis). The empty ``"q4"`` marker leaf
    records the 4-bit grid in the pytree STRUCTURE (jit-static, rides
    through scans/vmaps for free).
    """
    int4 = isinstance(dtype, str) and dtype == "int4"
    int8 = int4 or (isinstance(dtype, str) and dtype == "int8") or \
        (not isinstance(dtype, str) and jnp.dtype(dtype) == jnp.int8)
    cache = []
    for layer in module.layers:
        # custom serving loops enter through here: out-of-range position
        # gathers CLAMP under jit (silently wrong-position logits), so the
        # capacity check must fail loudly at cache construction too
        need = max_len if check_len is None else check_len
        if isinstance(layer, PositionalEmbedding) and need > layer.max_len:
            raise ValueError(
                f"PositionalEmbedding(max_len={layer.max_len}) is too small "
                f"for a {need}-position decode cache")
        block = _decode_block_of(layer)
        if block is not None:
            attn = block.attn
            # GQA: the cache stores only the kv heads — the whole point
            # of grouped queries at serving time
            h = attn.kv_heads
            # head_dim resolves at init; recover it from the layer config
            dh = attn.head_dim
            if dh is None:
                raise ValueError(
                    "init_cache needs head_dim; build the model first "
                    "(Model.build resolves it) or pass head_dim explicitly")
            shape = (batch, h, max_len, dh)
            if int8:
                kv = {
                    "k": jnp.zeros(shape, jnp.int8),
                    "v": jnp.zeros(shape, jnp.int8),
                    "k_scale": jnp.zeros(shape[:3], jnp.float32),
                    "v_scale": jnp.zeros(shape[:3], jnp.float32)}
                if int4:
                    # structural marker, not data: 4-dim so every
                    # blind cache tree_map (slab row insert/slice,
                    # offload gather/scatter) stays shape-compatible
                    kv["q4"] = jnp.zeros((1, 1, 1, 1), jnp.int8)
                cache.append(kv)
            else:
                cache.append({"k": jnp.zeros(shape, dtype),
                              "v": jnp.zeros(shape, dtype)})
        else:
            if getattr(layer, "accepts_segment_ids", False):
                # the layer contains attention the decode loop does not
                # know how to cache — applying it position-wise would
                # silently decode garbage (each token attending only to
                # itself), so refuse up front
                raise ValueError(
                    f"decode path does not support layer {layer!r}: it "
                    "contains attention but is not a TransformerBlock "
                    "(or Remat-wrapped TransformerBlock)")
            cache.append(None)
    return cache


def _quantize_kv(x, bits: int = 8):
    """[..., Dh] float -> (int8 payload, f32 [...] per-vector scale).
    ``bits=4`` quantizes to the symmetric 4-bit grid (values in
    [-7, 7], ``scale = max|x| / 7``) while still returning one int8
    byte per entry — the dequant contract (``q * scale``) is identical
    across bit widths, so every read path is shared; nibble packing is
    a storage concern owned by the paged pool."""
    qmax = 7.0 if bits == 4 else 127.0
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / qmax
    safe = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / safe[..., None]),
                 -qmax, qmax).astype(jnp.int8)
    return q, jnp.where(scale == 0.0, 0.0, safe)


def _kv_bits(kv) -> int:
    """Quantization bit width of a cache dict: 4 when the ``"q4"``
    marker leaf is present (pytree-structural, jit-static), else 8."""
    return 4 if "q4" in kv else 8


def pack_int4(q):
    """Pack an int4-valued int8 array to nibbles along ``axis=-2``
    (the position axis of a [..., L, D] plane): byte row ``r`` holds
    position ``r`` in the LOW nibble and position ``r + L//2`` in the
    HIGH nibble, halving the sublane extent (L must be even). All
    nibble math runs in int32 for portable two's-complement handling."""
    n = q.shape[-2]
    lo = q[..., : n // 2, :].astype(jnp.int32)
    hi = q[..., n // 2:, :].astype(jnp.int32)
    b = ((hi & 15) << 4) | (lo & 15)
    return (b - 256 * (b > 127)).astype(jnp.int8)


def unpack_int4(b):
    """Inverse of :func:`pack_int4`: [..., L//2, D] packed bytes ->
    [..., L, D] int4-valued int8 (positions in order along axis -2)."""
    b32 = b.astype(jnp.int32) & 255
    lo = b32 & 15
    lo = lo - 16 * (lo > 7)
    hi = (b32 >> 4) & 15
    hi = hi - 16 * (hi > 7)
    return jnp.concatenate([lo, hi], axis=-2).astype(jnp.int8)


def _cache_write(kv, k, v, t):
    """Write one [B, S_w, H, Dh] k/v slab (BSHD, as projected) at
    position ``t`` (S_w = 1 for decode steps, P for prefill) into the
    head-major [B, H, L, Dh] cache, quantizing if it is int8."""
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    if "k_scale" in kv:
        bits = _kv_bits(kv)
        qk, sk = _quantize_kv(kh, bits)
        qv, sv = _quantize_kv(vh, bits)
        out = {
            "k": lax.dynamic_update_slice_in_dim(kv["k"], qk, t, axis=2),
            "v": lax.dynamic_update_slice_in_dim(kv["v"], qv, t, axis=2),
            "k_scale": lax.dynamic_update_slice_in_dim(
                kv["k_scale"], sk, t, axis=2),
            "v_scale": lax.dynamic_update_slice_in_dim(
                kv["v_scale"], sv, t, axis=2)}
        if bits == 4:
            out["q4"] = kv["q4"]
        return out
    return {"k": lax.dynamic_update_slice_in_dim(
                kv["k"], kh.astype(kv["k"].dtype), t, axis=2),
            "v": lax.dynamic_update_slice_in_dim(
                kv["v"], vh.astype(kv["v"].dtype), t, axis=2)}


def _int8_mm_dtype():
    """Matmul dtype for the int8-dequant cache contractions: bf16 on TPU
    (native MXU mode), f32 elsewhere (CPU XLA's dot runtime has no
    bf16xbf16->f32 kernel)."""
    return jnp.bfloat16 if backend_is_tpu() else jnp.float32


def _decode_scores(qg, kv):
    """[B, 1, Hkv, G, D] f32 queries x cache -> [B, Hkv, G, 1, L] f32
    scores, matmul'ing in the cache's STORAGE dtype with f32 accumulation.
    Casting the cache itself up to f32 (the round-3 form) materializes a
    full-cache f32 copy per layer per step — 3x the HBM traffic the
    cache was shrunk to avoid. For int8 the per-token scale factors out
    of the D-contraction (s = kscale_k * <qg, k_int8>), so the payload
    read stays int8 and the scale applies on the tiny [.., L] scores."""
    if "k_scale" in kv:
        mdt = _int8_mm_dtype()
        s = jnp.einsum("bqhgd,bhkd->bhgqk", qg.astype(mdt),
                       kv["k"].astype(mdt),
                       preferred_element_type=jnp.float32)
        return s * kv["k_scale"][:, :, None, None, :]
    cdt = kv["k"].dtype
    return jnp.einsum("bqhgd,bhkd->bhgqk", qg.astype(cdt), kv["k"],
                      preferred_element_type=jnp.float32)


def _decode_mix(w, kv):
    """[B, Hkv, G, 1, L] f32 probabilities x cached values ->
    [B, 1, Hkv, G, D] f32, same storage-dtype contract as
    ``_decode_scores`` (for int8 the value scale folds into the
    probabilities BEFORE the matmul: <w * vscale, v_int8>)."""
    if "v_scale" in kv:
        mdt = _int8_mm_dtype()
        ws = w * kv["v_scale"][:, :, None, None, :]
        return jnp.einsum("bhgqk,bhkd->bqhgd", ws.astype(mdt),
                          kv["v"].astype(mdt),
                          preferred_element_type=jnp.float32)
    cdt = kv["v"].dtype
    return jnp.einsum("bhgqk,bhkd->bqhgd", w.astype(cdt), kv["v"],
                      preferred_element_type=jnp.float32)


def _resolve_head_dims(module: Sequential, params) -> None:
    """Fill in ``head_dim`` on each attention layer from its params (the
    layer leaves it None until init; decode needs it statically)."""
    for layer, p in zip(module.layers, params):
        block = _decode_block_of(layer)
        if block is not None and block.attn.head_dim is None:
            block.attn.head_dim = int(p["attn"]["wq"].shape[-1])


def _decode_attn(attn: MultiHeadAttention, p, kv, x, t):
    """One-token attention against the cache. x: [B, 1, d]; t: step.

    GQA-aware: the cache holds ``kv_heads`` heads; queries are grouped
    ``[B, 1, Hkv, G, D]`` and contracted against the cache directly — the
    shared K/V heads are never materialized ``G`` times."""
    dt = jnp.dtype(attn.dtype)
    xc = x.astype(dt)
    q, k, v = _project_qkv(attn, p, xc)
    if attn.use_rope:
        pos = jnp.full((1,), t)
        q = apply_rope(q, pos, scale=attn.rope_scale)
        k = apply_rope(k, pos, scale=attn.rope_scale)
    kv = _cache_write(kv, k, v, t)
    scale = (attn.head_dim or q.shape[-1]) ** -0.5
    b = q.shape[0]
    hkv = attn.kv_heads
    g = attn.num_heads // hkv
    dh = q.shape[-1]
    L = kv["k"].shape[2]
    from distkeras_tpu.ops.decode_attention import (MIN_KERNEL_LEN,
                                                    block_of,
                                                    decode_attention)
    if backend_is_tpu() and L >= MIN_KERNEL_LEN \
            and block_of(L) is not None:
        # deep caches only: at L < 1024 the per-program overhead of the
        # kernel's grid outweighs its single-pass read (measured — the
        # einsum path wins at the 136-position headline config), while
        # at depth the kernel is a clear multiple over the einsum
        # lowering's materialized broadcast product
        # fused Pallas path (round 4): one kernel per layer streams the
        # cache once — the XLA einsum lowering materializes the f32
        # broadcast product of every cache plane in HBM (~3x the bytes;
        # measured 0.37 ms/layer-step at L=2113). generate() sizes the
        # cache to a block multiple so serving always takes this path.
        qr = q[:, 0].astype(dt).reshape(b, hkv, g, dh)             .reshape(b * hkv, g, dh)
        kr = kv["k"].reshape(b * hkv, L, dh)
        vr = kv["v"].reshape(b * hkv, L, dh)
        sc = {}
        if "k_scale" in kv:
            sc = {"k_scale": kv["k_scale"].reshape(b * hkv, L),
                  "v_scale": kv["v_scale"].reshape(b * hkv, L)}
        o = decode_attention(qr, kr, vr, t, scale=scale,
                             window=attn.attn_window, **sc)
        out = o.reshape(b, hkv, g, dh).reshape(b, 1, attn.num_heads, dh)             .astype(dt)
    else:
        qg = (q.astype(jnp.float32) * scale).reshape(
            b, 1, hkv, g, dh)                            # [B, 1, Hkv, G, D]
        s = _decode_scores(qg, kv)                       # [B, Hkv, G, 1, L]
        valid = jnp.arange(L) <= t
        if attn.attn_window is not None:
            valid &= jnp.arange(L) > t - attn.attn_window
        s = jnp.where(valid[None, None, None, None, :], s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        out = _decode_mix(w, kv).astype(dt)
        out = out.reshape(b, 1, attn.num_heads, dh)
    y = jnp.einsum("bshe,hed->bsd", out, p["wo"].astype(dt))
    return y.astype(x.dtype), kv


def _decode_block(block: TransformerBlock, p, s, kv, x, t):
    h, _ = block.norm1.apply(p["norm1"], s["norm1"], x)
    a, kv = _decode_attn(block.attn, p["attn"], kv, h, t)
    x = x + a
    h, _ = block.norm2.apply(p["norm2"], s["norm2"], x)
    m, _ = block.mlp.apply(p["mlp"], s["mlp"], h, training=False)
    return x + m, kv


def _prefill_block(block: TransformerBlock, p, s, kv, x, positions):
    """Whole-prompt pass through one TransformerBlock: ONE causal
    attention over [B, P] (flash kernel on TPU) instead of P sequential
    decode steps, writing the block's K/V cache entries for every prompt
    position at once. Attention inside the prompt uses the exact
    (unquantized) K/V; an int8 cache quantizes what later DECODE steps
    read — the standard serving contract."""
    from distkeras_tpu.models.attention import _attention_compute

    attn = block.attn
    dt = jnp.dtype(attn.dtype)
    h_, _ = block.norm1.apply(p["norm1"], s["norm1"], x)
    xc = h_.astype(dt)
    q, k, v = _project_qkv(attn, p["attn"], xc)
    if attn.use_rope:
        q = apply_rope(q, positions, scale=attn.rope_scale)
        k = apply_rope(k, positions, scale=attn.rope_scale)
    kv = _cache_write(kv, k, v, 0)
    ke, ve = attn._expand_kv(k, 2), attn._expand_kv(v, 2)
    impl = "flash" if backend_is_tpu() else "xla"
    out = _attention_compute(q, ke, ve, causal=True, impl=impl,
                             window=attn.attn_window)
    y = jnp.einsum("bshe,hed->bsd", out.astype(dt), p["attn"]["wo"]
                   .astype(dt))
    x = x + y.astype(x.dtype)
    h_, _ = block.norm2.apply(p["norm2"], s["norm2"], x)
    m, _ = block.mlp.apply(p["mlp"], s["mlp"], h_, training=False)
    return x + m, kv


def _merge_attention(o_a, lse_a, o_b, lse_b):
    """Combine two normalized attention partials over DISJOINT key sets
    via their log-sum-exps (the flash-decoding combine): each partial is
    acc_i / l_i with lse_i = log l_i + m_i, so the exact joint result is
    the l-weighted average, computed through a shared max for stability.
    o: [..., S, D]; lse: [..., S]."""
    m = jnp.maximum(lse_a, lse_b)
    wa = jnp.exp(lse_a - m)[..., None]
    wb = jnp.exp(lse_b - m)[..., None]
    return (o_a.astype(jnp.float32) * wa + o_b.astype(jnp.float32) * wb) \
        / (wa + wb)


def _attn_lse(q, k, v, *, causal: bool, scale: float, layout: str,
              window=None):
    """Attention WITH its log-sum-exp: the real flash kernel on TPU, a
    plain XLA softmax path elsewhere (the chunked-prefill building block;
    interpreter-mode Pallas is too slow for long-prefix CPU tests).
    Layouts as in ``ops.flash_attention`` ('bshd'/'bhsd')."""
    from distkeras_tpu.ops.flash_attention import _flash_forward
    if backend_is_tpu():
        # mirror flash_attention's adaptive default (round 5): the
        # square 1024 tile wins at exactly d_head 128, causal unwindowed
        bq = 1024 if (q.shape[-1] == 128 and causal
                      and window is None) else 512
        bk = 1024 if window is None else 512
        return _flash_forward(q, k, v, scale, causal, bq, bk, False,
                              layout == "bhsd", window)
    if layout == "bshd":
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
    else:
        qh, kh, vh = q, k, v
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32) * scale,
                   kh.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        s = jnp.where(qpos >= jnp.arange(sk)[None, :], s, NEG_INF)
        if window is not None:
            s = jnp.where(jnp.arange(sk)[None, :] > qpos - window, s,
                          NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", jnp.exp(s - lse[..., None]),
                   vh.astype(jnp.float32))
    if layout == "bshd":
        return o.transpose(0, 2, 1, 3).astype(q.dtype), lse
    return o.astype(q.dtype), lse


def _banded_prefix_attn(q, kp, vp, t0: int, lo: int, window: int,
                        scale: float):
    """Chunk queries against the sliding-window PREFIX BAND
    ``[lo, t0)`` (at most ``window - 1`` keys): plain masked attention
    with its lse — global query position ``t0 + i`` attends band key
    ``j`` iff ``j > t0 + i - window`` (causality ``j < t0 <= t0+i`` is
    structural). Queries whose window lies entirely inside the chunk
    get a fully-masked row; with the finite ``NEG_INF`` its lse is
    ~-1e30, so the lse merge weights that partial to exactly 0 — no
    special-casing needed. q: [B, Q, H, D]; kp/vp: [B, H, Lb, D]
    (already head-expanded; the band is < window keys, so the
    expansion is small)."""
    qh = q.transpose(0, 2, 1, 3)                         # [B, H, Q, D]
    s = jnp.einsum("bhqd,bhkd->bhqk", qh.astype(jnp.float32) * scale,
                   kp.astype(jnp.float32))
    jpos = lo + jnp.arange(s.shape[-1])[None, :]         # band keys
    gi = t0 + jnp.arange(s.shape[-2])[:, None]           # global q pos
    s = jnp.where(jpos > gi - window, s, NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", jnp.exp(s - lse[..., None]),
                   vp.astype(jnp.float32))
    return o.transpose(0, 2, 1, 3).astype(q.dtype), lse


def _cache_prefix(kv, upto: int, dt, lo: int = 0):
    """Cache positions ``[lo, upto)`` as dense [B, Hkv, upto-lo, D] k/v
    in the compute dtype (int8 payloads dequantize here — the chunked
    prefill attends to what later decode steps will read, the standard
    quantized-cache serving contract). Slicing BEFORE the dequant keeps
    the SWA band path O(window), not O(prefix)."""
    k = kv["k"][:, :, lo:upto]
    v = kv["v"][:, :, lo:upto]
    if "k_scale" in kv:
        k = (k.astype(jnp.float32)
             * kv["k_scale"][:, :, lo:upto, None]).astype(dt)
        v = (v.astype(jnp.float32)
             * kv["v_scale"][:, :, lo:upto, None]).astype(dt)
    return k.astype(dt), v.astype(dt)


def _prefill_block_chunked(block: TransformerBlock, p, s, kv, x, positions,
                           t0: int):
    """One chunk of one TransformerBlock (round 5, VERDICT r4 #5): the
    chunk's queries attend to (a) the ALREADY-WRITTEN cache prefix
    [0, t0) — one non-causal flash pass, with the GQA group folded into
    the query rows so the shared K/V heads are never expanded — and (b)
    the chunk itself, causally; the two partials merge exactly through
    their log-sum-exps. Sliding-window models use a windowed diagonal
    pass plus a masked PREFIX BAND of the last ``window - 1`` positions
    (``_banded_prefix_attn``). Activation memory is O(chunk), not O(P):
    the [B, P, H, D] per-layer q/k/v of the one-pass prefill never
    exist."""
    attn = block.attn
    dt = jnp.dtype(attn.dtype)
    h_, _ = block.norm1.apply(p["norm1"], s["norm1"], x)
    xc = h_.astype(dt)
    q, k, v = _project_qkv(attn, p["attn"], xc)
    if attn.use_rope:
        q = apply_rope(q, positions, scale=attn.rope_scale)
        k = apply_rope(k, positions, scale=attn.rope_scale)
    kv = _cache_write(kv, k, v, t0)
    b, q_len, nh, dh = q.shape
    hkv = attn.kv_heads
    g = nh // hkv
    scale = (attn.head_dim or dh) ** -0.5
    window = attn.attn_window
    # (b) causal within the chunk (small: kv expansion is chunk-sized);
    # sliding-window models window the diagonal pass too
    ke, ve = attn._expand_kv(k, 2), attn._expand_kv(v, 2)
    o_diag, lse_diag = _attn_lse(q, ke, ve, causal=True, scale=scale,
                                 layout="bshd", window=window)
    # prefix reach: everything before the chunk for full attention; only
    # the last window-1 positions for SWA (older keys are out of every
    # chunk query's reach)
    lo = 0 if window is None else max(0, t0 - window + 1)
    if t0 > lo:
        kp, vp = _cache_prefix(kv, t0, dt, lo=lo)
        if window is None:
            # (a) chunk vs prefix: no causal structure (every chunk
            # query is newer than every prefix key), so the G query
            # heads sharing one KV head fold into the ROW axis —
            # [B*Hkv, G*Q, D] against [B*Hkv, t0, D] — and the cache is
            # read in its native head-major layout with no expansion
            qg = q.reshape(b, q_len, hkv, g, dh) \
                  .transpose(0, 2, 3, 1, 4) \
                  .reshape(b * hkv, 1, g * q_len, dh)
            o_pre, lse_pre = _attn_lse(
                qg, kp.reshape(b * hkv, 1, t0, dh),
                vp.reshape(b * hkv, 1, t0, dh),
                causal=False, scale=scale, layout="bhsd")
            o_pre = o_pre.reshape(b, hkv, g, q_len, dh) \
                         .transpose(0, 3, 1, 2, 4) \
                         .reshape(b, q_len, nh, dh)
            # (hkv, g) are already adjacent in head order
            # h = hkv_i*g + g_i: flatten directly — a transpose here
            # would scramble (pos, group)
            lse_pre = lse_pre.reshape(b, hkv, g, q_len) \
                             .reshape(b, nh, q_len)
        else:
            # (a') SWA prefix BAND [lo, t0): the window edge crosses the
            # band per query, so this is masked attention (the GQA fold
            # would break the per-position mask); the band is < window
            # keys, so expanding its kv heads in place (axis 1 of the
            # native [B, Hkv, Lb, D] layout) is small. Round 5: closes
            # the chunked-prefill SWA gap.
            o_pre, lse_pre = _banded_prefix_attn(
                q, attn._expand_kv(kp, 1), attn._expand_kv(vp, 1),
                t0, lo, window, scale)
        out = _merge_attention(
            o_pre.transpose(0, 2, 1, 3), lse_pre,
            o_diag.transpose(0, 2, 1, 3), lse_diag).transpose(0, 2, 1, 3)
    else:
        out = o_diag
    y = jnp.einsum("bshe,hed->bsd", out.astype(dt),
                   p["attn"]["wo"].astype(dt))
    x = x + y.astype(x.dtype)
    h_, _ = block.norm2.apply(p["norm2"], s["norm2"], x)
    m, _ = block.mlp.apply(p["mlp"], s["mlp"], h_, training=False)
    return x + m, kv


def prefill_chunk_step(module: Sequential, params, state, cache, chunk,
                       t0: int, *, final: bool):
    """ONE ``[B, q_len]`` chunk through the whole stack — the resumable
    unit of :func:`prefill_chunked`, factored out (this PR) so the
    serving engine can interleave prompt chunks between decode
    iterations instead of stalling in-flight streams for a whole
    prompt. ``t0`` is the chunk's global start position (STATIC — the
    per-layer chunk pass branches on it in Python); positions
    ``[0, t0)`` of ``cache`` must already be written. Returns
    ``(last_logits [B, V] if final else None, cache)`` — non-final
    chunks stop after the deepest attention block: the final norm +
    vocab head only matter for the last chunk's logits (review r5)."""
    new_cache = list(cache)
    last_block = max((i for i, l in enumerate(module.layers)
                      if _decode_block_of(l) is not None), default=-1)
    last = len(module.layers) - 1
    q_len = chunk.shape[1]
    x = chunk
    positions = jnp.arange(t0, t0 + q_len)
    for i, layer in enumerate(module.layers):
        if not final and i > last_block:
            break
        p, s = params[i], state[i]
        block = _decode_block_of(layer)
        if block is not None:
            x, new_cache[i] = _prefill_block_chunked(
                block, p, s, new_cache[i], x, positions, t0)
        elif isinstance(layer, PositionalEmbedding):
            x = x + p["embeddings"][t0:t0 + q_len][None] \
                .astype(x.dtype)
        elif isinstance(layer, Dropout):
            pass                                         # eval: identity
        else:
            if i == last and x.ndim == 3:
                x = x[:, -1:]        # head on the final position only
            x, _ = layer.apply(p, s, x, training=False)
    return (x[:, -1] if final else None), new_cache


def prefill_chunked(module: Sequential, params, state, cache, prompts,
                    chunk_len: int):
    """Block-by-block prompt ingestion (round 5): like :func:`prefill`
    but the prompt streams through the stack in ``chunk_len``-position
    chunks, each attending to the cache prefix written by the chunks
    before it. TTFT stays quadratic-COMPUTE-bound, but peak activation
    memory is flat in P — the regime >= 32K prompts need (the one-pass
    prefill materializes [B, P, H, D] q/k/v per layer and falls over
    around P=32K at d_model 1024). Greedy continuations match the
    one-pass prefill exactly up to blockwise-softmax fp reassociation
    (the merge is algebraically exact)."""
    b, p_len = prompts.shape
    new_cache = cache
    last_logits = None
    for t0 in range(0, p_len, chunk_len):
        q_len = min(chunk_len, p_len - t0)
        last_logits, new_cache = prefill_chunk_step(
            module, params, state, new_cache, prompts[:, t0:t0 + q_len],
            t0, final=t0 + q_len >= p_len)
    return last_logits, new_cache


def prefill(module: Sequential, params, state, cache, prompts):
    """Batched prompt ingestion (round 4): run the stack ONCE over the
    [B, P] prompt, filling every attention layer's cache at positions
    0..P-1, and return ``(last_logits [B, V], cache)``.

    This replaces replaying the prompt through the sequential decode scan
    — P compute-bound flash steps collapse into one kernel pass, which is
    what makes long-context serving (P = 2048-16384) usable at all: an
    8K-token prompt is ~250x fewer sequential device steps. The vocab
    head is applied to the LAST position only (the [B, P, V] logits
    tensor for a 32k vocab would be ~2 GB at P=8192 and is never
    needed)."""
    b, p_len = prompts.shape
    x = prompts
    new_cache = list(cache)
    positions = jnp.arange(p_len)
    last = len(module.layers) - 1
    for i, layer in enumerate(module.layers):
        p, s = params[i], state[i]
        block = _decode_block_of(layer)
        if block is not None:
            x, new_cache[i] = _prefill_block(block, p, s, cache[i], x,
                                             positions)
        elif isinstance(layer, PositionalEmbedding):
            x = x + p["embeddings"][:p_len][None].astype(x.dtype)
        elif isinstance(layer, Dropout):
            pass                                         # eval: identity
        else:
            if i == last and x.ndim == 3:
                x = x[:, -1:]        # head on the final position only
            x, _ = layer.apply(p, s, x, training=False)
    return x[:, -1], new_cache


def decode_step(module: Sequential, params, state, cache, tok, t):
    """One token through the stack. tok: [B] int; returns ([B, V] logits,
    cache)."""
    x = tok[:, None]                                     # [B, 1]
    new_cache = list(cache)
    for i, layer in enumerate(module.layers):
        p, s, kv = params[i], state[i], cache[i]
        block = _decode_block_of(layer)
        if block is not None:
            x, new_cache[i] = _decode_block(block, p, s, kv, x, t)
        elif isinstance(layer, PositionalEmbedding):
            x = x + p["embeddings"][t][None, None, :].astype(x.dtype)
        elif isinstance(layer, Dropout):
            pass                                         # eval: identity
        else:
            x, _ = layer.apply(p, s, x, training=False)
    return x[:, 0], new_cache                            # [B, V]


# --- slot-level decode (serving engine, this PR) ---------------------------
#
# Continuous batching runs ONE compiled step over a fixed pool of S slots
# whose sequences are at DIFFERENT positions: ``t`` becomes a [S] vector.
# The per-slot variants below mirror the scalar-``t`` functions exactly —
# same projections, same storage-dtype contractions — with three changes:
# the cache write selects each slot's own position (a one-hot select, so a
# slot whose ``t`` is out of range, the engine's free-slot sentinel,
# writes NOTHING and cannot corrupt a neighbour), the validity mask is
# per-slot, and rope positions are per-slot. The fused Pallas decode
# kernel takes a scalar step and is not used here; the einsum path's
# per-slot masks cost nothing extra (the mask was already materialized).
#
# MoE blocks (MoE-serving PR): the slot steps run MoE MLPs through the
# decode-specialized dispatched path by default (``moe_dispatched=True``
# -> ``MoE.decode_apply``: capacity = the slot-token count, so routing
# can never drop and a slot's output is independent of its batch
# neighbours; fused kernel on TPU, tokens path elsewhere).
# ``moe_dispatched=False`` opts back into each layer's own ``apply`` —
# the dense-routing baseline the bench prices the dispatch against.
# ``moe_stats`` (an int: the live-position bound, the engine's
# ``max_len``) makes the step ALSO return per-expert load and router
# entropy over live slots — the serving engine's expert telemetry.


def _apply_mlp_decode(mlp, p, s, x, moe_dispatched, routing):
    """MLP application for the slot decode steps: MoE layers take the
    decode-specialized dispatched path (:meth:`MoE.decode_apply` —
    drop-free, fused on TPU) unless the caller opts back into the
    layer's own ``apply`` (``moe_dispatched=False``, the dense-routing
    baseline); plain MLPs are untouched. ``routing`` (a list, or None)
    collects per-MoE-layer ``(num_experts, (topi, full))`` for the
    expert-load telemetry."""
    from distkeras_tpu.models.moe import MoE
    if moe_dispatched and isinstance(mlp, MoE):
        if routing is None:
            return mlp.decode_apply(p, x)
        out, r = mlp.decode_apply(p, x, return_routing=True)
        routing.append((mlp.num_experts, r))
        return out
    out, _ = mlp.apply(p, s, x, training=False)
    return out


def _moe_route_stats(routing, t, w_len: int, live_len: int):
    """Reduce the collected per-layer routing to the step's expert
    telemetry: ``expert_load`` [E] (routing-slot assignments per expert,
    summed over MoE layers — layers whose expert count differs from the
    first are skipped) and ``router_entropy`` (mean nats of the full
    router softmax), both masked to LIVE slots (``t < live_len``; the
    engine's free-slot sentinel routes garbage that must not pollute
    the load picture). Returns None when no MoE layer ran."""
    if not routing:
        return None
    live = ((t >= 0) & (t < live_len)).astype(jnp.float32)     # [S]
    e0 = routing[0][0]
    load = jnp.zeros((e0,), jnp.float32)
    ent_sum = jnp.zeros((), jnp.float32)
    n_layers = 0
    for e, (topi, full) in routing:
        if e != e0:
            continue
        oh = jax.nn.one_hot(topi, e0, dtype=jnp.float32).sum(-2)
        load = load + (oh * live[:, None, None]).sum((0, 1))
        p = full.astype(jnp.float32)
        ent = -(p * jnp.log(p + 1e-9)).sum(-1)                 # [S, W]
        ent_sum = ent_sum + (ent * live[:, None]).sum()
        n_layers += 1
    n_tok = jnp.maximum(live.sum() * w_len * n_layers, 1.0)
    return {"expert_load": load, "router_entropy": ent_sum / n_tok}


def _cache_write_slots(kv, k, v, t):
    """Write one [S, 1, H, D] k/v decode slab at PER-SLOT positions
    ``t`` ([S] int) into the head-major [S, H, L, D] cache. Slot ``s``
    writes position ``t[s]``; ``t[s] >= L`` (the engine's free/prefilling
    sentinel) writes nothing."""
    kh = k.transpose(0, 2, 1, 3)                         # [S, H, 1, D]
    vh = v.transpose(0, 2, 1, 3)
    L = kv["k"].shape[2]
    hit = (jnp.arange(L)[None, :] == t[:, None])         # [S, L]
    hit4 = hit[:, None, :, None]                         # [S, 1, L, 1]
    if "k_scale" in kv:
        bits = _kv_bits(kv)
        qk, sk = _quantize_kv(kh, bits)
        qv, sv = _quantize_kv(vh, bits)
        hit3 = hit[:, None, :]                           # [S, 1, L]
        out = {"k": jnp.where(hit4, qk, kv["k"]),
               "v": jnp.where(hit4, qv, kv["v"]),
               "k_scale": jnp.where(hit3, sk, kv["k_scale"]),
               "v_scale": jnp.where(hit3, sv, kv["v_scale"])}
        if bits == 4:
            out["q4"] = kv["q4"]
        return out
    return {"k": jnp.where(hit4, kh.astype(kv["k"].dtype), kv["k"]),
            "v": jnp.where(hit4, vh.astype(kv["v"].dtype), kv["v"])}


def _window_positions(t, w_len: int, tree):
    """Per-window-query cache positions: ``t + j`` for the causal chain
    (window query j sits j steps past the slot's start), or
    ``t + depth[j]`` for a token TREE (tree-speculation PR — each node's
    position is its depth on its own root path, so siblings share a
    position while occupying distinct window columns)."""
    if tree is None:
        return t[:, None] + jnp.arange(w_len)            # [S, W]
    return t[:, None] + tree["depth"]                    # [S, W]


def _window_valid_mask(t, w_len: int, L: int, tree, window):
    """[S, W, L] attention validity for the windowed readout.

    Chain (``tree`` None): window query j admits cache positions
    ``<= t + j`` — the established window-causal mask.

    Tree: node j was WRITTEN at cache position ``t + j`` (its window
    column), so query i admits (a) the committed prefix ``< t`` and
    (b) window column j's position ``t + j`` iff j is an ancestor of i
    (self included) per ``tree["anc"]`` — rejected/sibling branches
    stay invisible exactly like the chain's future positions. Sentinel
    slots (t out of range) admit garbage either way; their logits are
    discarded by contract. ``window`` adds the SWA band around each
    query's own position (``t + depth``)."""
    ar = jnp.arange(L)[None, None, :]                    # [1, 1, L]
    if tree is None:
        pos = t[:, None] + jnp.arange(w_len)             # [S, W]
        valid = ar <= pos[:, :, None]
    else:
        anc = tree["anc"]                                # [S, W, W] bool
        s_n = anc.shape[0]
        rel = jnp.arange(L)[None, :] - t[:, None]        # [S, L]
        within = (rel >= 0) & (rel < w_len)
        anc_g = anc[jnp.arange(s_n)[:, None, None],
                    jnp.arange(w_len)[None, :, None],
                    jnp.clip(rel, 0, w_len - 1)[:, None, :]]
        valid = (rel < 0)[:, None, :] | (within[:, None, :] & anc_g)
        pos = t[:, None] + tree["depth"]
    if window is not None:
        valid &= ar > (pos - window)[:, :, None]
    return valid


def _attn_out(p, out, dt):
    """Output projection shared by the serving readouts: the fused
    dequant-matmul when the engine left ``wo`` quantized
    (``ops.quant_matmul`` qdict), the plain einsum otherwise."""
    wo = p["wo"]
    if isinstance(wo, dict):
        from distkeras_tpu.ops.quant_matmul import quant_matmul
        b, s_len = out.shape[:2]
        y = quant_matmul(out.reshape(b * s_len, -1), wo)
        return y.astype(dt).reshape(b, s_len, -1)
    return jnp.einsum("bshe,hed->bsd", out, wo.astype(dt))


def _slot_attn_readout(attn: MultiHeadAttention, p, q, kv, t, dt,
                       tree=None):
    """Masked per-slot attention of the projected decode queries against
    a logically contiguous ``[S, H, L, D]`` kv view — a slab pool or a
    page gather in logical-position order — plus the output projection.
    Shared by the slab and paged decode paths so the two are bitwise
    identical wherever the view holds identical values.

    ``q`` is ``[S, W, H, D]`` for a W-position window at per-slot
    positions ``t .. t+W-1`` (the speculative-verify step; W = 1 is the
    plain decode step): window query ``j`` of slot ``s`` attends cache
    positions ``<= t[s] + j`` — causal WITHIN the window too, so the
    drafts just written at ``t+1 .. t+j`` are visible to later window
    positions while rejected-tail garbage stays masked for every query
    that must not see it. ``tree`` (tree-speculation PR: ``{"depth":
    [S, W], "anc": [S, W, W]}``) generalizes the window to a token
    tree — see ``_window_valid_mask``; a chain-shaped tree produces the
    exact mask above, bit for bit."""
    scale = (attn.head_dim or q.shape[-1]) ** -0.5
    b = q.shape[0]
    w_len = q.shape[1]
    hkv = attn.kv_heads
    g = attn.num_heads // hkv
    dh = q.shape[-1]
    L = kv["k"].shape[2]
    qg = (q.astype(jnp.float32) * scale).reshape(
        b, w_len, hkv, g, dh)                        # [S, W, Hkv, G, D]
    s = _decode_scores(qg, kv)                       # [S, Hkv, G, W, L]
    valid = _window_valid_mask(t, w_len, L, tree, attn.attn_window)
    s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = _decode_mix(w, kv).astype(dt)              # [S, W, Hkv, G, D]
    out = out.reshape(b, w_len, attn.num_heads, dh)
    return _attn_out(p, out, dt)


def _decode_attn_slots(attn: MultiHeadAttention, p, kv, x, t):
    """One-token attention against the pooled cache at per-slot
    positions. x: [S, 1, d]; t: [S]. The einsum/storage-dtype path of
    ``_decode_attn`` with a [S, L] validity mask."""
    dt = jnp.dtype(attn.dtype)
    xc = x.astype(dt)
    q, k, v = _project_qkv(attn, p, xc)
    if attn.use_rope:
        q = apply_rope(q, t[:, None], scale=attn.rope_scale)
        k = apply_rope(k, t[:, None], scale=attn.rope_scale)
    kv = _cache_write_slots(kv, k, v, t)
    y = _slot_attn_readout(attn, p, q, kv, t, dt)
    return y.astype(x.dtype), kv


def _decode_block_slots(block: TransformerBlock, p, s, kv, x, t,
                        moe_dispatched=True, routing=None):
    h, _ = block.norm1.apply(p["norm1"], s["norm1"], x)
    a, kv = _decode_attn_slots(block.attn, p["attn"], kv, h, t)
    x = x + a
    h, _ = block.norm2.apply(p["norm2"], s["norm2"], x)
    m = _apply_mlp_decode(block.mlp, p["mlp"], s["mlp"], h,
                          moe_dispatched, routing)
    return x + m, kv


def decode_step_slots(module: Sequential, params, state, cache, tok, t,
                      *, moe_dispatched: bool = True, moe_stats=None):
    """One token through the stack at PER-SLOT positions: tok [S] int,
    t [S] int; returns ([S, V] logits, cache). Slots whose ``t`` is out
    of cache range (the serving engine's free-slot sentinel) produce
    garbage logits and write nothing — the engine discards them
    host-side. The position-table gather clamps for such slots, which
    is safe exactly because their output is never consumed.

    MoE blocks run the decode-specialized dispatched path
    (``moe_dispatched``; see the section comment above). ``moe_stats``
    (an int live-position bound) appends a third return value: the
    ``_moe_route_stats`` dict (None for MoE-free models)."""
    x = tok[:, None]                                     # [S, 1]
    new_cache = list(cache)
    routing = [] if moe_stats is not None else None
    for i, layer in enumerate(module.layers):
        p, s, kv = params[i], state[i], cache[i]
        block = _decode_block_of(layer)
        if block is not None:
            x, new_cache[i] = _decode_block_slots(
                block, p, s, kv, x, t, moe_dispatched, routing)
        elif isinstance(layer, PositionalEmbedding):
            x = x + p["embeddings"][t][:, None, :].astype(x.dtype)
        elif isinstance(layer, Dropout):
            pass                                         # eval: identity
        else:
            x, _ = layer.apply(p, s, x, training=False)
    if moe_stats is not None:
        return x[:, 0], new_cache, _moe_route_stats(
            routing, t, 1, int(moe_stats))
    return x[:, 0], new_cache                            # [S, V]


# --- paged decode (serving engine, paged KV cache PR) -----------------------
#
# The paged pool stores every layer's cache as [N, Hkv, page_len, Dh]
# fixed-size pages; a per-slot page table [S, P] maps logical page p of
# slot s to a physical page id (the engine's sentinel — an id >= N —
# marks an unallocated logical page). The decode step is ONE compiled
# program regardless of which pages a slot owns: the table is a traced
# argument, writes scatter through it (out-of-range drops, so the
# free-slot position sentinel writes nothing, exactly like the slab
# one-hot write), and reads gather the slot's pages back into the same
# logically contiguous [S, H, L, D] view the slab step consumes — the
# shared ``_slot_attn_readout`` epilogue then makes the two paths
# bitwise identical wherever the views hold identical values.


def _cache_write_pages(kv, k, v, t, table, page_len: int):
    """Write one [S, 1, H, D] k/v decode slab at per-slot positions
    ``t`` ([S] int) into the paged pool [N, H, page_len, D] through the
    slot page tables ``table`` ([S, P] int). Slot ``s`` writes physical
    page ``table[s, t[s] // page_len]`` at offset ``t[s] % page_len``;
    a ``t[s]`` past the logical capacity (the engine's free/prefilling
    sentinel) or a sentinel table entry writes nothing (scatter drop)."""
    kh = k[:, 0]                                         # [S, H, D]
    vh = v[:, 0]
    n_pages = kv["k"].shape[0]
    n_logical = table.shape[1]
    lp = t // page_len                                   # [S] logical page
    off = t % page_len
    pp = jnp.take_along_axis(
        table, jnp.clip(lp, 0, n_logical - 1)[:, None], axis=1)[:, 0]
    # sentinel: out-of-range t (or an unallocated logical page whose
    # table entry is >= N already) routes the scatter out of bounds,
    # where mode="drop" discards it
    pp = jnp.where((lp >= 0) & (lp < n_logical), pp, n_pages)
    if "q4" in kv:
        # int4 pool pages are nibble-PACKED along the position axis
        # ([N, H, page_len//2, D] bytes — pack_int4's half-split): the
        # one-position write is a read-modify-write of the byte row
        # shared with position off +- page_len//2. The gather clamps
        # sentinel pages to a real page (garbage merged safely — the
        # scatter at the out-of-range pp drops it); scale planes stay
        # per-position, so their write is the int8 write verbatim.
        qk, sk = _quantize_kv(kh, 4)
        qv, sv = _quantize_kv(vh, 4)
        half = page_len // 2
        prow = off % half
        hi = (off >= half)[:, None, None]                # [S, 1, 1]
        gp = jnp.clip(pp, 0, n_pages - 1)
        out = {"k_scale": kv["k_scale"].at[pp, :, off].set(
                   sk, mode="drop"),
               "v_scale": kv["v_scale"].at[pp, :, off].set(
                   sv, mode="drop"),
               "q4": kv["q4"]}
        for key, q in (("k", qk), ("v", qv)):
            cur = kv[key][gp, :, prow].astype(jnp.int32) & 255
            nib = q.astype(jnp.int32) & 15
            b = jnp.where(hi, (cur & 0x0F) | (nib << 4),
                          (cur & 0xF0) | nib)
            b = (b - 256 * (b > 127)).astype(jnp.int8)
            out[key] = kv[key].at[pp, :, prow].set(b, mode="drop")
        return out
    if "k_scale" in kv:
        qk, sk = _quantize_kv(kh)
        qv, sv = _quantize_kv(vh)
        return {
            "k": kv["k"].at[pp, :, off].set(qk, mode="drop"),
            "v": kv["v"].at[pp, :, off].set(qv, mode="drop"),
            "k_scale": kv["k_scale"].at[pp, :, off].set(sk, mode="drop"),
            "v_scale": kv["v_scale"].at[pp, :, off].set(sv, mode="drop")}
    return {"k": kv["k"].at[pp, :, off].set(
                kh.astype(kv["k"].dtype), mode="drop"),
            "v": kv["v"].at[pp, :, off].set(
                vh.astype(kv["v"].dtype), mode="drop")}


def _gather_pages(kv, table):
    """The slot page tables' view of the pool: gather each slot's pages
    into a logically contiguous [S, H, P*page_len, D] cache (scale
    planes [S, H, P*page_len]). Sentinel table entries clamp to the
    last physical page — harmless garbage, masked by the ``<= t``
    validity mask exactly like a slab row's stale tail."""
    out = {}
    for key in ("k", "v"):
        pg = kv[key][table]                  # [S, P, H, page_len, D]
        if "q4" in kv:
            # packed int4 pages gather as [S, P, H, page_len//2, D]
            # bytes; unpacking along the page-position axis restores
            # the unpacked int4-valued int8 plane the shared slab
            # readout dequantizes (q * scale — same contract as int8)
            pg = unpack_int4(pg)
        s, p, h, pl, d = pg.shape
        out[key] = pg.transpose(0, 2, 1, 3, 4).reshape(s, h, p * pl, d)
    if "k_scale" in kv:
        for key in ("k_scale", "v_scale"):
            pg = kv[key][table]              # [S, P, H, page_len]
            s, p, h, pl = pg.shape
            out[key] = pg.transpose(0, 2, 1, 3).reshape(s, h, p * pl)
    if "q4" in kv:
        out["q4"] = kv["q4"]
    return out


def _use_paged_kernel(kv, page_len: int, paged_kernel) -> bool:
    """Should the paged readout take the Pallas page-table kernel?
    ``paged_kernel`` is the caller's tri-state: None = the repo-wide
    backend convention (TPU only), True = force (off-TPU the kernel
    runs in interpreter mode — the tier-1 oracle hook), False = the
    ``_gather_pages`` reference path. Either way an unaligned
    ``page_len`` (Mosaic sublane rule — ``paged_attention
    .page_aligned``) falls back to the gather path."""
    from distkeras_tpu.ops.paged_attention import page_aligned
    if paged_kernel is None:
        paged_kernel = backend_is_tpu()
    if "q4" in kv:
        quant = "int4"
    elif "k_scale" in kv:
        quant = "int8"
    else:
        quant = False
    return bool(paged_kernel) and page_aligned(page_len, quant)


def _paged_attn_readout(attn: MultiHeadAttention, p, q, kv, t, table,
                        page_len: int, dt, paged_kernel, tree=None):
    """Readout for the paged decode/verify paths: the Pallas
    paged-attention kernel (K/V gathered HBM -> VMEM through the page
    table inside the kernel — no materialized [S, H, L, D] view) when
    enabled, else ``_gather_pages`` + the shared slab readout (the
    off-TPU/interpret fallback and the kernel's oracle). ``tree``
    forwards the ancestor-mask window (tree-speculation PR) — the
    kernel takes the ``[S, W, W]`` mask as an operand; the gather path
    threads it into the shared mask builder."""
    if not _use_paged_kernel(kv, page_len, paged_kernel):
        return _slot_attn_readout(attn, p, q,
                                  _gather_pages(kv, table), t, dt,
                                  tree=tree)
    from distkeras_tpu.ops.paged_attention import paged_decode_attention
    b, w_len, nh, dh = q.shape
    hkv = attn.kv_heads
    g = nh // hkv
    scale = (attn.head_dim or dh) ** -0.5
    qg = q.astype(jnp.float32).reshape(b, w_len, hkv, g, dh)
    sc = {}
    if "k_scale" in kv:
        sc = {"k_scale": kv["k_scale"], "v_scale": kv["v_scale"]}
    o = paged_decode_attention(
        qg, kv["k"], kv["v"], t, table, scale=scale,
        window=attn.attn_window,
        anc=None if tree is None else tree["anc"],
        interpret=None if backend_is_tpu() else True, **sc)
    out = o.reshape(b, w_len, nh, dh).astype(dt)
    return _attn_out(p, out, dt)


def _decode_attn_slots_paged(attn: MultiHeadAttention, p, kv, x, t,
                             table, page_len: int, paged_kernel=None):
    """One-token attention against the PAGED pool at per-slot
    positions: scatter the new k/v through the page tables, then read
    back through the paged kernel (or the gathered per-slot view)."""
    dt = jnp.dtype(attn.dtype)
    xc = x.astype(dt)
    q, k, v = _project_qkv(attn, p, xc)
    if attn.use_rope:
        q = apply_rope(q, t[:, None], scale=attn.rope_scale)
        k = apply_rope(k, t[:, None], scale=attn.rope_scale)
    kv = _cache_write_pages(kv, k, v, t, table, page_len)
    y = _paged_attn_readout(attn, p, q, kv, t, table, page_len, dt,
                            paged_kernel)
    return y.astype(x.dtype), kv


def _decode_block_slots_paged(block: TransformerBlock, p, s, kv, x, t,
                              table, page_len: int,
                              moe_dispatched=True, routing=None,
                              paged_kernel=None):
    h, _ = block.norm1.apply(p["norm1"], s["norm1"], x)
    a, kv = _decode_attn_slots_paged(block.attn, p["attn"], kv, h, t,
                                     table, page_len, paged_kernel)
    x = x + a
    h, _ = block.norm2.apply(p["norm2"], s["norm2"], x)
    m = _apply_mlp_decode(block.mlp, p["mlp"], s["mlp"], h,
                          moe_dispatched, routing)
    return x + m, kv


def decode_step_slots_paged(module: Sequential, params, state, cache,
                            tok, t, table, page_len: int,
                            *, moe_dispatched: bool = True,
                            moe_stats=None, paged_kernel=None):
    """One token through the stack against a PAGED pooled cache: tok
    [S] int, t [S] int, table [S, P] int page tables; returns
    ([S, V] logits, cache). The paged mirror of ``decode_step_slots``
    — same garbage-logits contract for sentinel slots, same
    ``moe_dispatched``/``moe_stats`` MoE-decode contract.

    ``paged_kernel`` selects the readout (decode-kernel PR): None =
    the Pallas page-table kernel on TPU and the ``_gather_pages``
    reference elsewhere; True forces the kernel (interpret mode
    off-TPU — the oracle hook); False forces the gather path."""
    x = tok[:, None]                                     # [S, 1]
    new_cache = list(cache)
    routing = [] if moe_stats is not None else None
    for i, layer in enumerate(module.layers):
        p, s, kv = params[i], state[i], cache[i]
        block = _decode_block_of(layer)
        if block is not None:
            x, new_cache[i] = _decode_block_slots_paged(
                block, p, s, kv, x, t, table, page_len,
                moe_dispatched, routing, paged_kernel)
        elif isinstance(layer, PositionalEmbedding):
            x = x + p["embeddings"][t][:, None, :].astype(x.dtype)
        elif isinstance(layer, Dropout):
            pass                                         # eval: identity
        else:
            x, _ = layer.apply(p, s, x, training=False)
    if moe_stats is not None:
        return x[:, 0], new_cache, _moe_route_stats(
            routing, t, 1, int(moe_stats))
    return x[:, 0], new_cache                            # [S, V]


# --- batched speculative verify (serving engine, spec-decode PR) ------------
#
# Speculative decoding amortizes ONE target forward over k candidate
# tokens: the engine proposes drafts d_1..d_k per slot (n-gram lookup or
# a small draft model), then the verify step runs the [S, W = k+1]
# window [tok, d_1, .., d_k] through the stack at per-slot positions
# t..t+k in one program. logits[:, j] is the target's next-token
# distribution AFTER consuming window token j, so the longest prefix of
# drafts matching the target's own choices is accepted and the
# (m+1)-th candidate comes free — between 1 and k+1 tokens per target
# pass. Cache contract: every window position's K/V is written (slab
# one-hot / page-table scatter, same sentinels as the 1-token steps);
# positions past the accepted count hold rejected-draft garbage, which
# is EXACTLY the slab stale-tail situation — masked (`<= t + j`) until
# the stream's own later writes overwrite them, position by position,
# before the mask ever admits them. No explicit rollback needed; an
# unallocated page simply drops the write (the engine only lets a slot
# CONSUME candidates whose supporting positions have allocated pages).


def _decode_block_slots_window(block: TransformerBlock, p, s, kv, x, t,
                               table=None, page_len: int = 0,
                               moe_dispatched=True, routing=None,
                               paged_kernel=None, tree=None,
                               kv_out=None):
    """One TransformerBlock over a [S, W, d] window at per-slot
    positions ``t .. t+W-1``: project the window's q/k/v, write ALL W
    positions into the cache (slab one-hot writes, or page-table
    scatters when ``table`` is given), then run the shared windowed
    readout.

    ``tree`` (tree-speculation PR): rope each node at its ROOT-PATH
    position ``t + depth[j]`` (that is where it lands if accepted —
    siblings share a rope position while writing distinct window
    columns ``t + j``) and attend through the ancestor mask. The
    per-layer roped k/v land in ``kv_out`` (a list the caller owns) so
    the post-acceptance ``commit_tree_path`` can re-write the accepted
    path at its contiguous final positions."""
    attn = block.attn
    h, _ = block.norm1.apply(p["norm1"], s["norm1"], x)
    dt = jnp.dtype(attn.dtype)
    xc = h.astype(dt)
    q, k, v = _project_qkv(attn, p["attn"], xc)          # [S, W, H, D]
    w_len = q.shape[1]
    if attn.use_rope:
        pos = _window_positions(t, w_len, tree)          # [S, W]
        q = apply_rope(q, pos, scale=attn.rope_scale)
        k = apply_rope(k, pos, scale=attn.rope_scale)
    if kv_out is not None:
        kv_out.append((k, v))
    for j in range(w_len):
        if table is None:
            kv = _cache_write_slots(kv, k[:, j:j + 1], v[:, j:j + 1],
                                    t + j)
        else:
            kv = _cache_write_pages(kv, k[:, j:j + 1], v[:, j:j + 1],
                                    t + j, table, page_len)
    if table is None:
        y = _slot_attn_readout(attn, p["attn"], q, kv, t, dt, tree=tree)
    else:
        y = _paged_attn_readout(attn, p["attn"], q, kv, t, table,
                                page_len, dt, paged_kernel, tree=tree)
    x = x + y.astype(x.dtype)
    h, _ = block.norm2.apply(p["norm2"], s["norm2"], x)
    m = _apply_mlp_decode(block.mlp, p["mlp"], s["mlp"], h,
                          moe_dispatched, routing)
    return x + m, kv


def _verify_window(module: Sequential, params, state, cache, toks, t,
                   table, page_len: int, moe_dispatched: bool = True,
                   moe_stats=None, paged_kernel=None, tree=None):
    """Shared body of the verify steps: [S, W] window tokens through the
    whole stack at per-slot positions; returns ([S, W, V] logits,
    cache). MoE blocks see the [S, W] window as ONE slot-token batch
    through the dispatched decode path (capacity = S*W: drop-free even
    when every window position routes to one expert).

    ``tree`` (``{"depth": [S, W], "anc": [S, W, W]}``) switches the
    window from a causal chain to a token TREE: every node still
    writes its own window column ``t + j``, but positions (rope +
    positional embedding) come from the node's root-path depth and the
    ancestor mask decides visibility. The return gains a third value —
    the per-layer roped window k/v (None for non-attention layers) —
    which ``commit_tree_path`` consumes after acceptance."""
    x = toks                                             # [S, W] int
    w_len = toks.shape[1]
    new_cache = list(cache)
    routing = [] if moe_stats is not None else None
    kv_win = [] if tree is not None else None
    for i, layer in enumerate(module.layers):
        p, s, kv = params[i], state[i], cache[i]
        block = _decode_block_of(layer)
        if block is not None:
            x, new_cache[i] = _decode_block_slots_window(
                block, p, s, kv, x, t, table, page_len,
                moe_dispatched, routing, paged_kernel, tree, kv_win)
        elif isinstance(layer, PositionalEmbedding):
            pos = _window_positions(t, w_len, tree)      # [S, W]
            x = x + p["embeddings"][pos].astype(x.dtype)
        elif isinstance(layer, Dropout):
            pass                                         # eval: identity
        else:
            x, _ = layer.apply(p, s, x, training=False)
    if kv_win is not None:
        # index-align the collected (k, v) pairs with the CACHE list
        # (blocks appended in layer order; everything else is None)
        it = iter(kv_win)
        kv_win = [next(it) if _decode_block_of(layer) is not None
                  else None for layer in module.layers]
    out = (x, new_cache) if tree is None else (x, new_cache, kv_win)
    if moe_stats is not None:
        return out + (_moe_route_stats(routing, t, w_len,
                                       int(moe_stats)),)
    return out                                           # [S, W, V], ..


def verify_step_slots(module: Sequential, params, state, cache, toks, t,
                      *, moe_dispatched: bool = True, moe_stats=None,
                      tree=None):
    """Batched speculative VERIFY against the slab pool: toks [S, W]
    int (window token 0 is the slot's pending decode input, tokens
    1..W-1 its drafts), t [S] int per-slot window start positions;
    returns ([S, W, V] logits, cache). ``logits[:, j]`` is the target
    distribution over the token FOLLOWING window position j — the
    greedy accept rule is ``argmax(logits[:, j-1]) == toks[:, j]``.
    Sentinel slots (t out of range) write nothing and produce garbage
    logits, exactly like ``decode_step_slots`` — whose
    ``moe_dispatched``/``moe_stats`` MoE contract also applies.

    ``tree`` (tree-speculation PR: ``{"depth": [S, W] int, "anc":
    [S, W, W] bool}``) generalizes the chain window to a token TREE —
    window column j holds tree node j (node 0 the pending input/root),
    roped and position-embedded at its root-path depth, visible only
    to its descendants via the ancestor mask. With ``tree`` the return
    gains a third value: the per-layer roped window k/v that
    :func:`commit_tree_path` writes back along the accepted path. A
    chain-shaped tree (``depth[j] = j``, lower-triangular ``anc``)
    reproduces the plain window BIT FOR BIT."""
    return _verify_window(module, params, state, cache, toks, t,
                          None, 0, moe_dispatched, moe_stats,
                          tree=tree)


def verify_step_slots_paged(module: Sequential, params, state, cache,
                            toks, t, table, page_len: int,
                            *, moe_dispatched: bool = True,
                            moe_stats=None, paged_kernel=None,
                            tree=None):
    """The paged mirror of :func:`verify_step_slots`: window writes
    scatter through the [S, P] page tables (unallocated logical pages
    drop their writes — the engine pre-allocates pages for every
    position a slot may CONSUME, so dropped writes only ever land on
    the rejected tail). ``paged_kernel`` selects the readout exactly
    as in :func:`decode_step_slots_paged` — the kernel's ``[S, W]``
    window-causal mask generalization is what lets the speculative
    verify ride it too; the tree mask (``tree=``, see
    :func:`verify_step_slots`) rides the kernel as an ``[S, W, W]``
    ancestor-mask operand."""
    return _verify_window(module, params, state, cache, toks, t,
                          table, page_len, moe_dispatched, moe_stats,
                          paged_kernel, tree=tree)


def tree_walk(logits, toks, parents, *, temperature=None, top_k=None,
              top_p=None, keys=None):
    """In-program acceptance over a verified token tree: greedily walk
    the longest accepted root-path.

    ``logits`` [S, W, V] is the verify forward's output (row j = the
    target's next-token distribution AFTER consuming node j's root
    path); ``toks`` [S, W] the window tokens (node 0 = the pending
    input); ``parents`` [S, W] the parent-index vectors (node 0 and
    unused nodes carry -1 — an unused node can never be entered
    because no walk position equals -1).

    The walk starts at the root and repeats: draw the target's choice
    ``x`` at the current node (argmax when ``temperature`` is None,
    else one PRNG split + ``_sample_vec`` — EXACTLY the per-emitted-
    token key discipline of plain decode, so sampled streams stay
    byte-identical); emit ``x``; descend into the lowest-index child
    whose draft token equals ``x``, or stop. Every emitted token is
    either an accepted draft (the child's token) or the final bonus —
    between 1 and W emissions. For a point-mass (deterministic) draft
    this IS the exact multi-draft rejection-sampling rule: each
    candidate child is a distinct point mass, and sampling from the
    target then accepting on equality preserves the plain-decode
    output distribution token for token.

    Returns ``(emitted [S, W], n_emit [S], path [S, W], new_keys)``:
    ``emitted[:, :n_emit]`` are the tokens to append, ``path[:, d]``
    the accepted node at depth d (valid for ``d < n_emit``; the commit
    uses it to place K/V), ``new_keys`` the post-walk per-slot keys
    (None for greedy) — advanced by exactly ``n_emit`` splits, as
    ``n_emit`` plain decode iterations would have."""
    s_n, w_len, _ = logits.shape
    greedy = temperature is None
    rows = jnp.arange(s_n)
    cur = jnp.zeros((s_n,), jnp.int32)
    walking = jnp.ones((s_n,), bool)
    n_emit = jnp.zeros((s_n,), jnp.int32)
    ks = keys
    emitted = []
    path = [cur]
    for _ in range(w_len):
        lg = logits[rows, cur]                           # [S, V]
        if greedy:
            x = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        else:
            split = jax.vmap(jax.random.split)(ks)
            x = _sample_vec(lg, temperature, top_k, top_p,
                            split[:, 1]).astype(jnp.int32)
            # the key advances only on steps that actually emit — a
            # finished walk must not consume entropy plain decode
            # would not have
            ks = jnp.where(walking[:, None], split[:, 0], ks)
        emitted.append(jnp.where(walking, x, -1))
        n_emit = n_emit + walking.astype(jnp.int32)
        is_child = (parents == cur[:, None]) & (toks == x[:, None]) \
            & walking[:, None]                           # [S, W]
        # node 0's parent is -1 and cur >= 0, so the root can never be
        # re-entered; ties (two children with one token) resolve
        # lowest-index — the subtrees are interchangeable up to here
        has = is_child.any(axis=1)
        child = jnp.argmax(is_child, axis=1).astype(jnp.int32)
        walking = walking & has
        cur = jnp.where(walking, child, cur)
        path.append(cur)
    return (jnp.stack(emitted, axis=1),
            n_emit,
            jnp.stack(path[:w_len], axis=1),
            None if greedy else ks)


def commit_tree_path(cache, kv_win, path, t, n_emit, table=None,
                     page_len: int = 0):
    """Post-acceptance cache commit for tree speculation: write the
    accepted root-path's K/V at its CONTIGUOUS final positions.

    The verify forward wrote node j at window column ``t + j``; the
    accepted node at depth d belongs at ``t + d`` (and was roped
    there — ``depth[path[d]] == d`` by construction). This pass
    gathers each layer's window k/v along ``path`` and re-writes
    depths ``0 .. n_emit-1`` through the established slot/page
    writers; depths past the accepted path route to an out-of-range
    position, where the one-hot write misses and the page scatter
    drops — rejected branches stay exactly the stale-tail garbage the
    masks already cover, healed by the stream's own later writes.
    Chain-shaped trees re-write identical bytes (the accepted node AT
    depth d IS window column d), so a width-1 tree's cache equals the
    linear verify's bit for bit."""
    w_len = path.shape[1]
    new_cache = list(cache)
    drop = jnp.int32(2 ** 30)        # past any capacity: writers skip
    for i, kvw in enumerate(kv_win):
        if kvw is None:
            continue
        k, v = kvw                                       # [S, W, H, D]
        kc = jnp.take_along_axis(k, path[:, :, None, None], axis=1)
        vc = jnp.take_along_axis(v, path[:, :, None, None], axis=1)
        kv = new_cache[i]
        for d in range(w_len):
            pos = jnp.where(d < n_emit, t + d, drop)
            if table is None:
                kv = _cache_write_slots(kv, kc[:, d:d + 1],
                                        vc[:, d:d + 1], pos)
            else:
                kv = _cache_write_pages(kv, kc[:, d:d + 1],
                                        vc[:, d:d + 1], pos, table,
                                        page_len)
        new_cache[i] = kv
    return new_cache


# --- fused multi-step decode (zero-bubble serving PR) -----------------------
#
# In steady-state serving (no admissions, no prefill, no speculation)
# every iteration is the SAME per-slot decode step; dispatching them one
# at a time leaves a host gap between device steps — on TPU, where a
# step is ~1-5 ms, that gap is the throughput ceiling. The fused window
# compiles K plain iterations as ONE ``lax.scan`` program: the carry
# feeds each step's sampled token back as the next step's input
# (device-side — the host never sees intermediate tokens), per-slot
# ``done`` masks reproduce ``generate()``'s stop-token padding (a slot
# that emits its stop keeps emitting it for the rest of the window, so
# the host can truncate the emitted buffer at the first stop), and the
# program emits the whole [S, K] token block in one fetch. Every step
# inside the window is bitwise the single-step program's computation —
# same cache writes, same sampler, same per-slot key splits — so fused
# output is token-identical (byte-identical for sampled streams) to K
# separate iterations.


def decode_fused_slots(module: Sequential, params, state, cache, tok, t,
                       stop, num_steps: int, table=None,
                       page_len: int = 0, *, temperature=None,
                       top_k=None, top_p=None, keys=None,
                       moe_dispatched: bool = True, moe_stats=None,
                       paged_kernel=None, sampler=None):
    """``num_steps`` consecutive ``decode_step_slots[_paged]``
    iterations as one compiled scan. tok/t: [S] ints (per-slot pending
    input and write position); ``stop``: [S] int per-slot stop tokens
    (-1 = never). Greedy when ``temperature`` is None; otherwise
    ``temperature``/``top_k``/``top_p`` are the [S] per-slot sampling
    vectors and ``keys`` the [S] per-slot PRNG keys, split once per
    step exactly like the single-step sampled program (byte-identical
    streams). Returns ``(toks [S, num_steps], cache, keys_or_None,
    moe_stats_or_None)`` — ``toks[:, j]`` is the token emitted by
    window step j; after a slot's stop token fires, its remaining
    window positions repeat the stop (``generate()``'s padding rule).
    Sentinel slots (t out of range) ride along writing nothing.

    Cache contract: step j writes position ``t + j`` for every slot —
    the caller must have every page under ``t .. t+num_steps-1``
    allocated for positions it intends to CONSUME (paged writes to
    unallocated pages drop; post-stop writes land as stale-tail
    garbage, overwritten before any mask admits them)."""
    greedy = temperature is None
    stats_on = moe_stats is not None
    # fused-sampling PR: the engine routes the per-step draw through
    # ``ops.sampling.sample_tokens`` (same key-split discipline, same
    # byte stream) when its fused_sampling knob is on
    sample = _sample_vec if sampler is None else sampler

    def body(carry, _):
        if greedy:
            cache, cur, tcur, done = carry
        else:
            cache, cur, tcur, done, ks = carry
        kw = dict(moe_dispatched=moe_dispatched, moe_stats=moe_stats)
        if table is not None:
            out = decode_step_slots_paged(module, params, state, cache,
                                          cur, tcur, table, page_len,
                                          paged_kernel=paged_kernel,
                                          **kw)
        else:
            out = decode_step_slots(module, params, state, cache, cur,
                                    tcur, **kw)
        if stats_on:
            logits, cache, st = out
        else:
            logits, cache = out
        if greedy:
            nxt = jnp.argmax(logits, axis=-1).astype(cur.dtype)
        else:
            split = jax.vmap(jax.random.split)(ks)
            ks = split[:, 0]
            nxt = sample(logits, temperature, top_k, top_p,
                         split[:, 1]).astype(cur.dtype)
        # generate()'s stop rule, per slot: done rows hold the stop
        # token (padding the window), and a freshly emitted stop marks
        # the row done for the remaining steps
        nxt = jnp.where(done, stop.astype(cur.dtype), nxt)
        done = done | ((nxt == stop) & (stop >= 0))
        carry = (cache, nxt, tcur + 1, done) + (() if greedy else (ks,))
        return carry, ((nxt,) if not stats_on else (nxt, st))

    done0 = jnp.zeros(tok.shape, bool)
    carry0 = (cache, tok, t, done0) + (() if greedy else (keys,))
    carry, ys = lax.scan(body, carry0, None, length=int(num_steps))
    toks = jnp.swapaxes(ys[0], 0, 1)                     # [S, K]
    new_cache = carry[0]
    new_keys = None if greedy else carry[4]
    stats = None
    if stats_on:
        # the LAST window step's routing picture (the engine's stats
        # throttle reads at most one sample per window anyway)
        stats = jax.tree_util.tree_map(lambda a: a[-1], ys[1])
    return toks, new_cache, new_keys, stats


def _sample(logits, temperature, top_k, rng, top_p=None):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None:
        # mask from top_k's INDICES, not a value threshold — ties at the
        # k-th logit would otherwise admit more than k candidates (the MoE
        # router masks the same way for the same reason). one_hot keeps
        # this rank-agnostic: any leading batch dims work
        _, idx = lax.top_k(logits, top_k)
        keep = jax.nn.one_hot(idx, logits.shape[-1],
                              dtype=jnp.bool_).any(axis=-2)
        logits = jnp.where(keep, logits, NEG_INF)
    if top_p is not None:
        # nucleus sampling (round 4): keep the smallest probability-sorted
        # prefix whose mass reaches top_p. Token i survives iff the mass
        # STRICTLY ABOVE it is < top_p (so the boundary token that crosses
        # the threshold is included, per the standard construction).
        # Logit-value ties at the boundary admit their whole tie class —
        # the probability-identical analogue of the top_k caveat, accepted
        # because a value threshold keeps this one sort + one compare
        # (composes with top_k: applied after its mask, like HF).
        sorted_logits = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        exclusive = jnp.cumsum(probs, axis=-1) - probs
        keep_sorted = exclusive < top_p
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf),
            axis=-1, keepdims=True)
        logits = jnp.where(logits >= thresh, logits, NEG_INF)
    return jax.random.categorical(rng, logits, axis=-1)


# --- per-sequence sampling state (serving engine + generate arrays) --------


def _sample_vec(logits, temperature, top_k, top_p, rng):
    """Per-SEQUENCE sampling: every knob is a [B] vector, so requests
    with heterogeneous sampling settings coexist in one batch (the
    serving engine's per-slot sampling state; ``generate()`` routes
    per-sequence arrays here too). Disabled sentinels: ``temperature
    0`` = greedy for that row, ``top_k <= 0`` = no truncation,
    ``top_p >= 1`` = no nucleus cut.

    ``rng`` is either one key (the whole batch draws from it, as in
    ``generate``'s scan) or a [B] batch of per-slot keys (the engine:
    each slot's stream must be reproducible regardless of which other
    requests share the batch).

    top_k here masks by RANK from a stable descending argsort — ties at
    the k-th logit resolve lowest-index-first, the same order
    ``lax.top_k`` uses, so the vector path admits exactly the scalar
    path's candidate set."""
    greedy = jnp.argmax(logits, axis=-1)
    lf = _masked_logits_vec(logits, temperature, top_k, top_p)
    if rng.ndim > 1:                                     # per-slot keys
        sampled = jax.vmap(jax.random.categorical)(rng, lf)
    else:
        sampled = jax.random.categorical(rng, lf, axis=-1)
    return jnp.where(temperature > 0.0, sampled, greedy)


def _masked_logits_vec(logits, temperature, top_k, top_p):
    """The mask half of :func:`_sample_vec`: temperature-scaled f32
    logits with the rank top-k and exclusive-cumsum nucleus cuts
    applied (NEG_INF outside the candidate set). Shared with
    ``ops.sampling.sample_epilogue`` so the fused sampling path admits
    BIT-IDENTICAL candidate sets — ``categorical(key, lf)`` IS
    ``argmax(lf + gumbel(key))``, which is exactly how the fused
    epilogue factors it."""
    lf = logits.astype(jnp.float32)
    safe_t = jnp.where(temperature > 0.0, temperature, 1.0)
    lf = lf / safe_t[:, None]
    # top_k by rank (stable argsort == lax.top_k tie order)
    order = jnp.argsort(-lf, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    keep = (top_k[:, None] <= 0) | (ranks < top_k[:, None])
    lf = jnp.where(keep, lf, NEG_INF)
    # nucleus, same boundary construction as the scalar path
    sorted_logits = jnp.flip(jnp.sort(lf, axis=-1), axis=-1)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    exclusive = jnp.cumsum(probs, axis=-1) - probs
    keep_sorted = exclusive < top_p[:, None]
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf),
                     axis=-1, keepdims=True)
    return jnp.where((top_p >= 1.0)[:, None] | (lf >= thresh), lf,
                     NEG_INF)


def _per_seq_vec(value, b, dtype, none_sentinel, name):
    """Normalize a scalar-or-[B]-array sampling knob to a [B] vector
    (``None`` -> the disabled sentinel; scalars broadcast)."""
    if value is None:
        value = none_sentinel
    arr = jnp.asarray(value, dtype)
    if arr.ndim == 0:
        return jnp.full((b,), arr)
    if arr.shape != (b,):
        raise ValueError(
            f"per-sequence {name} must have shape ({b},) to match the "
            f"prompt batch, got {arr.shape}")
    return arr


def _is_per_seq(value) -> bool:
    """True when a sampling knob was passed as a per-sequence array
    (list/tuple or an ndarray with a batch dim) rather than a scalar."""
    if value is None or isinstance(value, (int, float)):
        return False
    if isinstance(value, (list, tuple)):
        return True
    return getattr(value, "ndim", 0) >= 1


def _attn_compute_dtype(module: Sequential):
    """The attention compute dtype of the first TransformerBlock (the
    LM-family convention: one dtype across the stack), or None."""
    for layer in module.layers:
        block = _decode_block_of(layer)
        if block is not None:
            return jnp.dtype(block.attn.dtype)
    return None


def _fuse_qkv_params(module: Sequential, params):
    """Serving-tree rewrite (round 5, decode-overhead attack): replace
    each attention layer's ``wq``/``wk``/``wv`` with ONE concatenated
    ``wqkv`` [d, H + 2*Hkv, Dh], so every decode step (and prefill) runs
    one projection matmul instead of three. At small batch the decode
    step is op-launch/latency-bound (docs/PERF.md §Long-context), and
    the three q/k/v einsums are the most mechanical fusion available.
    Exact: each output column of the concatenated matmul is the same
    d-length dot product as in the separate matmuls. Applied to FLOAT
    serving trees only — the int8 path's per-Dh scales differ across
    q/k/v and cannot share one concatenated payload. SHARDED weights
    (GSPMD/Megatron TP: wq/wk/wv split on the head axis) are left
    unfused — concatenating differently-sharded head axes would re-split
    the fused tensor across q/kv shard boundaries and pay resharding
    collectives every step (review r5)."""
    def replicated(leaf):
        sh = getattr(leaf, "sharding", None)
        return sh is None or getattr(sh, "is_fully_replicated", True)

    fused = list(params)
    for i, layer in enumerate(module.layers):
        block = _decode_block_of(layer)
        if block is None:
            continue
        p = dict(fused[i])
        pa = dict(p["attn"])
        if not all(replicated(pa[k]) for k in ("wq", "wk", "wv")):
            continue
        pa["wqkv"] = jnp.concatenate(
            [pa.pop("wq"), pa.pop("wk"), pa.pop("wv")], axis=1)
        p["attn"] = pa
        fused[i] = p
    return fused


def _project_qkv(attn: MultiHeadAttention, p, xc):
    """q/k/v projections for the serving paths: the fused ``wqkv``
    matmul when the tree carries it (see ``_fuse_qkv_params``), the
    fused dequant-matmul when the engine left the projections
    quantized (``ServingEngine(weight_quant=)`` — ``ops.quant_matmul``
    qdicts; the kernel unpacks int8/int4 bytes in-register, so the
    float weights never touch HBM), the three separate einsums
    otherwise."""
    if "wqkv" in p:
        qkv = jnp.einsum("bsd,dhe->bshe", xc, p["wqkv"].astype(xc.dtype))
        h, hkv = attn.num_heads, attn.kv_heads
        return (qkv[:, :, :h], qkv[:, :, h:h + hkv],
                qkv[:, :, h + hkv:])
    if isinstance(p["wq"], dict):
        from distkeras_tpu.ops.quant_matmul import quant_matmul
        b, s_len, d = xc.shape
        x2 = xc.reshape(b * s_len, d)

        def proj(wdict, heads):
            y = quant_matmul(x2, wdict).astype(xc.dtype)
            return y.reshape(b, s_len, heads, -1)

        return (proj(p["wq"], attn.num_heads),
                proj(p["wk"], attn.kv_heads),
                proj(p["wv"], attn.kv_heads))
    dt = xc.dtype
    q = jnp.einsum("bsd,dhe->bshe", xc, p["wq"].astype(dt))
    k = jnp.einsum("bsd,dhe->bshe", xc, p["wk"].astype(dt))
    v = jnp.einsum("bsd,dhe->bshe", xc, p["wv"].astype(dt))
    return q, k, v


def _serving_params(params, dtype):
    """Pre-cast the big (ndim >= 2) weight matrices to the serving dtype
    ONCE, outside the decode scan. For a bf16-compute model this is
    numerically FREE for every matmul weight (apply casts them per-step
    anyway — pre-casting just stops the per-step f32 HBM read, which is
    half the decode byte budget); only the embedding-table gather and the
    un-cast f32 head read change, both below bf16 round-off of the
    surrounding compute. Vectors (biases, norm scales) stay f32: they are
    applied in f32 and cost nothing."""
    return jax.tree_util.tree_map(
        lambda p: p.astype(dtype)
        if (hasattr(p, "ndim") and p.ndim >= 2
            and jnp.issubdtype(p.dtype, jnp.floating)) else p,
        params)


def generate(model: Model, prompts, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             seed: int = 0, cache_dtype=None,
             stop_token: Optional[int] = None,
             weights_dtype="auto", as_numpy: bool = True,
             prefill_chunk: Optional[int] = None) -> np.ndarray:
    """Autoregressive continuation: ``[B, P]`` int prompts ->
    ``[B, P + max_new_tokens]`` tokens. ``temperature=0`` is greedy;
    otherwise softmax sampling (optionally top-k-truncated).

    Sampling: ``temperature=0`` is greedy; otherwise softmax sampling,
    optionally truncated by ``top_k`` (index-exact) and/or ``top_p``
    (nucleus: smallest probability prefix whose mass reaches ``top_p``;
    applied after the top_k mask when both are given).

    ``temperature``/``top_k``/``top_p``/``stop_token`` also accept
    PER-SEQUENCE ``[B]`` arrays (this PR — the same plumbing the serving
    engine's per-slot sampling uses), so heterogeneous requests share
    one batch: row sentinels ``temperature 0`` = greedy, ``top_k 0`` =
    no truncation, ``top_p 1.0`` = no nucleus cut, ``stop_token -1`` =
    never stop. Scalars broadcast (the scalar API compiles the exact
    pre-existing program); when ANY knob is an array, all four become
    traced [B] vectors, so ONE compiled program serves every
    per-sequence sampling configuration at that shape.

    ``stop_token``: once a sequence emits it, every later position is
    filled with it too (the compiled scan always runs ``max_new_tokens``
    steps — static shapes — so "stopping" is per-sequence padding, which
    is also what makes the batch ragged-safe).

    Decode is weight+cache HBM-read bound (docs/PERF.md roofline), so
    storage dtypes are the throughput levers:

    ``cache_dtype=None`` matches the model's attention COMPUTE dtype —
    for a bf16 model the k/v entries were computed in bf16, so an f32
    cache stores no extra information while doubling the dominant read.
    ``weights_dtype="auto"`` pre-casts matrix weights to the same compute
    dtype once before the scan (see ``_serving_params``); ``None``
    disables, a dtype forces, and ``"int8"`` serves weight-only int8
    (``models.quantize`` per-channel symmetric): matrices live in HBM as
    int8 and dequantize inside each step's matmul fusion — another ~2×
    off the weight-read bound, at int8 weight accuracy.

    ``prefill_chunk`` (round 5): ingest the prompt in chunks of this
    many positions (see :func:`prefill_chunked`) — peak prefill
    activation memory becomes O(chunk) instead of O(P), the enabler for
    >= 32K prompts; TTFT stays quadratic-compute-bound. ``None`` (the
    default) is the one-pass prefill.

    Backend contract (``compat.backend_is_tpu`` — the repo-wide
    convention every Pallas-vs-XLA fork follows, including the fused
    MoE dispatch): kernel selection keys off the TRACE-TIME default
    backend, not the runtime device of the inputs. The traced program
    assumes it executes on ``jax.default_backend()``; to serve from a
    non-default device (e.g. CPU inside a TPU-backed process), wrap the
    call in ``jax.default_device(...)`` so trace-time agrees with
    run-time — per-input device dispatch is deliberately NOT supported
    (it would fork every jitted serving program on an attribute jit
    erases)."""
    module = model.module
    if not isinstance(module, Sequential):
        raise TypeError("generate() expects a Sequential LM "
                        f"(got {type(module).__name__})")
    prompts = jnp.asarray(prompts)
    if prompts.ndim != 2:
        raise ValueError(f"prompts must be [B, P], got {prompts.shape}")
    max_new_tokens = int(max_new_tokens)
    if max_new_tokens < 0:
        raise ValueError(f"max_new_tokens must be >= 0, "
                         f"got {max_new_tokens}")
    per_seq = any(_is_per_seq(v)
                  for v in (temperature, top_k, top_p, stop_token))
    if not per_seq and top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if prefill_chunk is not None:
        prefill_chunk = int(prefill_chunk)
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}")
    if max_new_tokens == 0:
        # nothing to generate; never run the clamped first-token write
        # (it would overwrite the final prompt position — review r4)
        return np.asarray(prompts) if as_numpy else prompts
    b, p_len = prompts.shape
    total = p_len + max_new_tokens
    samp = {}
    if per_seq:
        samp = {
            "temperature": _per_seq_vec(temperature, b, jnp.float32, 0.0,
                                        "temperature"),
            "top_k": _per_seq_vec(top_k, b, jnp.int32, 0, "top_k"),
            "top_p": _per_seq_vec(top_p, b, jnp.float32, 1.0, "top_p"),
            "stop": _per_seq_vec(stop_token, b, jnp.int32, -1,
                                 "stop_token"),
        }
        topp_h = np.asarray(samp["top_p"])
        if ((topp_h <= 0.0) | (topp_h > 1.0)).any():
            raise ValueError(
                f"top_p entries must be in (0, 1], got {topp_h}")
    _resolve_head_dims(module, model.params)
    for layer in module.layers:
        # out-of-range position gathers CLAMP under jit (silent wrong-
        # position logits) — fail loudly up front instead
        if isinstance(layer, PositionalEmbedding) and total > layer.max_len:
            raise ValueError(
                f"PositionalEmbedding(max_len={layer.max_len}) is too "
                f"small for prompt {p_len} + {max_new_tokens} new tokens "
                f"= {total} positions")
    compute_dt = _attn_compute_dtype(module)
    if cache_dtype is None:
        cache_dtype = compute_dt if compute_dt is not None else jnp.float32
    if weights_dtype == "auto":
        weights_dtype = compute_dt if (
            compute_dt is not None
            and compute_dt != jnp.dtype(jnp.float32)) else None
    # normalize: np.int8/jnp.int8 mean the quantized path, same as "int8"
    # (a raw astype(int8) of float weights would zero them); other int
    # dtypes are meaningless for weights
    if weights_dtype is not None and weights_dtype not in ("int8",
                                                           "int4"):
        dt = jnp.dtype(weights_dtype)
        if dt == jnp.dtype(jnp.int8):
            weights_dtype = "int8"
        elif not jnp.issubdtype(dt, jnp.floating):
            # a raw astype to any non-float dtype would silently destroy
            # sub-unity weights (bool/ints round them to 0/1)
            raise ValueError(
                f"weights_dtype {dt.name!r} unsupported: use a float "
                "dtype, 'int8'/'int4' (weight-only quantized serving), "
                "'auto' or None")
    # serving-weight cache: one entry per dtype, each validated against
    # the SOURCE params by identity (strong ref -> no id()-reuse hazard);
    # a loop alternating dtypes must not re-pay full-tree conversion.
    # Entries whose source tree is no longer model.params are purged on
    # any lookup — without this, a weight update would pin every old
    # params tree (plus its converted copy) in memory forever.
    cache_all = getattr(model, "_serving_params_cache", None)
    if cache_all is None:
        cache_all = model._serving_params_cache = {}
    for k in [k for k, v in cache_all.items()
              if v[0] is not model.params]:
        del cache_all[k]
    scales = None
    if weights_dtype in ("int8", "int4"):
        # weight-only quantized serving (models.quantize): matrices
        # stored as {q: int8, scale: f32[out]}; dequant happens INSIDE
        # the scan body so XLA fuses q*scale into each step's matmul
        # reads — the weight HBM traffic per decoded token is int8,
        # halving the dominant read again vs bf16 (docs/PERF.md
        # roofline). "int4" swaps in the 4-bit grid (bits=4): the
        # accuracy rung below int8 — here it still stores one byte per
        # entry; the serving engine's fused dequant-matmul kernel is
        # where nibble packing pays the extra 2x (ops.quant_matmul)
        from distkeras_tpu.models.quantize import quantize_params
        cached = cache_all.get(weights_dtype)
        if cached is None:
            q, s = quantize_params(
                jax.device_get(model.params),
                bits=4 if weights_dtype == "int4" else 8)
            # scales go to device too: per-call H2D of hundreds of small
            # numpy leaves would reintroduce the per-call overhead this
            # cache exists to avoid (device_put preserves None leaves)
            cached = (model.params,
                      (jax.device_put(q), jax.device_put(s)))
            cache_all[weights_dtype] = cached
        run_params, scales = cached[1]
    elif weights_dtype is None:
        run_params = model.params
    else:
        # fuse q/k/v into one wqkv matmul only for DEEP caches (round 5;
        # same LENGTH threshold as the fused decode kernel, though the
        # fusion applies on every backend — it is exact everywhere): at
        # P=8192/b4 the fusion takes the step 1.59 -> ~1.0 ms, but at a
        # short cache it REGRESSES decode 23% (measured 6,967 -> 5,350
        # tok/s at the 136-position headline config — the three
        # separate projections fuse better with their neighbors there)
        from distkeras_tpu.ops.decode_attention import MIN_KERNEL_LEN
        fuse_qkv = total >= MIN_KERNEL_LEN
        dt_key = jnp.dtype(weights_dtype).name
        base = cache_all.get(dt_key)
        if base is None:
            base = (model.params,
                    _serving_params(model.params, weights_dtype))
            cache_all[dt_key] = base
        if fuse_qkv:
            # derive the fused tree FROM the cached base so every
            # non-attention leaf is shared — a server alternating short
            # and long prompts holds one weight tree plus the fused
            # attention deltas, not two full copies
            fused_key = dt_key + "+wqkv"
            cached = cache_all.get(fused_key)
            if cached is None:
                cached = (model.params,
                          _fuse_qkv_params(module, base[1]))
                cache_all[fused_key] = cached
            run_params = cached[1]
        else:
            run_params = base[1]
    # shape/capacity validation runs eagerly (fail loudly BEFORE tracing);
    # the actual buffers are created inside the compiled program below
    init_cache(module, b, 1, cache_dtype)

    # one compiled program per (model, shape, sampling) configuration —
    # cached on the Model so a serving loop pays trace+compile once, like
    # Model.predict's cached forward. Round 4: the program is a batched
    # PREFILL over the whole prompt (one flash pass; see ``prefill``)
    # followed by a decode-only scan over the new tokens — replaying the
    # prompt through the sequential scan made long prompts O(P) device
    # steps instead of O(1) kernel passes.
    if per_seq:
        # the vectors are TRACED args: one program per shape serves every
        # per-sequence sampling configuration
        samp_key = ("per-seq",)
    else:
        samp_key = (float(temperature), top_k,
                    None if top_p is None else float(top_p), stop_token)
    key = (b, p_len, int(max_new_tokens)) + samp_key + (
        "int4" if (isinstance(cache_dtype, str) and cache_dtype == "int4")
        else jnp.dtype(cache_dtype).name,
        None if weights_dtype is None
        else (weights_dtype if weights_dtype in ("int8", "int4")
              else jnp.dtype(weights_dtype).name),
        prefill_chunk)
    jit_cache = getattr(model, "_jit_generate", None)
    if jit_cache is None:
        jit_cache = model._jit_generate = {}
    run = jit_cache.get(key)
    if run is None:
        int8w = scales is not None

        def live_params(params, run_scales):
            if not int8w:
                return params
            # dequant INSIDE the traced region that consumes it (prefill
            # pass / each scan step): q*scale fuses into the matmul
            # reads, so weight HBM traffic stays int8. scales are TRACED
            # args, not closure constants — re-quantized params after a
            # weight update must not meet a stale baked-in scale tree
            from distkeras_tpu.models.quantize import dequantize_params
            return dequantize_params(params, run_scales)

        def sample_next(logits, run_samp, sub):
            if per_seq:
                return _sample_vec(logits, run_samp["temperature"],
                                   run_samp["top_k"], run_samp["top_p"],
                                   sub)
            return _sample(logits, temperature, top_k, sub, top_p)

        @jax.jit
        def run(params, run_scales, state, prompts, rng, run_samp):
            # the cache is created INSIDE the compiled program (shapes
            # are static): no multi-GB host-side zeros allocation per
            # call, and XLA sees a single dead-on-exit buffer instead of
            # distinct input+output copies — at P=8192 the bf16 cache is
            # 3.2 GB, and the in+out pair was what pushed the long-
            # context MHA program over the compile/memory edge (round 4).
            # Capacity rounds up to the decode kernel's block size on
            # TPU so every serving call takes the fused Pallas path
            # (the margin is masked; models position checks use `total`)
            if backend_is_tpu():
                from distkeras_tpu.ops.decode_attention import \
                    MIN_KERNEL_LEN, choose_block
            if backend_is_tpu() and total >= MIN_KERNEL_LEN:
                bl = choose_block(total)
                cap = -(-total // bl) * bl
            else:
                cap = total
            cache = init_cache(module, b, cap, cache_dtype,
                               check_len=total)
            live = live_params(params, run_scales)
            if prefill_chunk is not None and p_len > prefill_chunk:
                last_logits, cache = prefill_chunked(
                    module, live, state, cache, prompts, prefill_chunk)
            else:
                last_logits, cache = prefill(module, live, state, cache,
                                             prompts)
            rng, sub = jax.random.split(rng)
            first = sample_next(last_logits, run_samp, sub)
            done = jnp.zeros((b,), bool)
            if per_seq:
                stop_v = run_samp["stop"]
                done = (first == stop_v) & (stop_v >= 0)
            elif stop_token is not None:
                done = first == stop_token
            tokens = jnp.concatenate(
                [prompts,
                 jnp.zeros((b, int(max_new_tokens)), prompts.dtype)],
                axis=1)
            tokens = lax.dynamic_update_slice_in_dim(
                tokens, first[:, None].astype(tokens.dtype), p_len, axis=1)

            def body(carry, t):
                tokens, cache, rng, done = carry
                p = live_params(params, run_scales)
                tok = lax.dynamic_slice_in_dim(tokens, t, 1, axis=1)[:, 0]
                logits, cache = decode_step(module, p, state, cache,
                                            tok, t)
                rng, sub = jax.random.split(rng)
                nxt = sample_next(logits, run_samp, sub)
                if per_seq:
                    stop_v = run_samp["stop"]
                    # rows already done have stop_v >= 0 by construction
                    nxt = jnp.where(done, stop_v.astype(nxt.dtype), nxt)
                    done = done | ((nxt == stop_v) & (stop_v >= 0))
                elif stop_token is not None:
                    nxt = jnp.where(done, stop_token, nxt)
                    done = done | (nxt == stop_token)
                tokens = lax.dynamic_update_slice_in_dim(
                    tokens, nxt[:, None].astype(tokens.dtype), t + 1,
                    axis=1)
                return (tokens, cache, rng, done), None

            (tokens, _, _, _), _ = lax.scan(
                body, (tokens, cache, rng, done),
                jnp.arange(p_len, total - 1))
            return tokens

        jit_cache[key] = run

    out = run(run_params, {} if scales is None else scales, model.state,
              prompts, jax.random.PRNGKey(seed), samp)
    # as_numpy=False skips the device->host sync: serving loops that
    # pipeline several generate calls only pay one round trip at the end
    # (on tunneled backends the per-call sync is ~100 ms — bench.py
    # measures both modes)
    return np.asarray(out) if as_numpy else out
