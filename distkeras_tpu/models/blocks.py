"""Composite blocks: residual containers and multi-branch layers.

The reference never needed these (its examples are Sequential-only Keras
models), but the north-star config (ResNet-50 on ImageNet, BASELINE config
3) requires residual topology. Blocks are Layers themselves, so they nest
inside ``Sequential`` and serialize through the same registry.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from distkeras_tpu.models.core import (Layer, Sequential, layer_from_spec,
                                       layer_spec, register_layer)
from distkeras_tpu.models.layers import get_activation


@register_layer
class Residual(Layer):
    """``y = act(main(x) + shortcut(x))`` — the ResNet block skeleton.

    ``shortcut=None`` means identity (requires matching shapes). Both
    branches are arbitrary Layers (usually Sequentials).
    """

    def __init__(self, main: Layer = None, shortcut: Optional[Layer] = None,
                 activation: Optional[str] = "relu", main_spec=None,
                 shortcut_spec=None):
        self.main = main if main is not None else layer_from_spec(main_spec)
        self.shortcut = (shortcut if shortcut is not None
                         else layer_from_spec(shortcut_spec))
        self.activation = activation

    @property
    def accepts_segment_ids(self) -> bool:
        return any(getattr(l, "accepts_segment_ids", False)
                   for l in (self.main, self.shortcut) if l is not None)

    def init(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        pm, sm, out_main = self.main.init(k1, input_shape)
        if self.shortcut is not None:
            ps, ss, out_short = self.shortcut.init(k2, input_shape)
        else:
            ps, ss, out_short = {}, {}, tuple(input_shape)
        if tuple(out_main) != tuple(out_short):
            raise ValueError(
                f"Residual branch shapes differ: main {out_main} vs "
                f"shortcut {out_short}")
        return ({"main": pm, "shortcut": ps},
                {"main": sm, "shortcut": ss}, tuple(out_main))

    def apply(self, params, state, x, *, training=False, rng=None,
              segment_ids=None):
        if rng is not None:
            rng, r1, r2 = jax.random.split(rng, 3)
        else:
            r1 = r2 = None

        def branch(layer, p, s, key):
            if segment_ids is not None and \
                    getattr(layer, "accepts_segment_ids", False):
                return layer.apply(p, s, x, training=training, rng=key,
                                   segment_ids=segment_ids)
            return layer.apply(p, s, x, training=training, rng=key)

        y, sm = branch(self.main, params["main"], state["main"], r1)
        if self.shortcut is not None:
            sc, ss = branch(self.shortcut, params["shortcut"],
                            state["shortcut"], r2)
        else:
            sc, ss = x, state["shortcut"]
        out = y + sc
        out = get_activation(self.activation)(out)
        return out, {"main": sm, "shortcut": ss}

    def sub_layers(self):
        subs = {"main": self.main}
        if self.shortcut is not None:
            subs["shortcut"] = self.shortcut
        return subs

    def get_config(self):
        return {"main_spec": layer_spec(self.main),
                "shortcut_spec": layer_spec(self.shortcut),
                "activation": self.activation}


@register_layer
class WideAndDeep(Layer):
    """Wide & Deep (Cheng et al. 2016) as a single-input layer.

    BASELINE config 4 is "DOWNPOUR wide-and-deep on Criteo". The input row
    concatenates wide (cross/one-hot) features and deep features:
    ``x = [wide (wide_dim) | deep (rest)]``; output logits are
    ``Linear(wide) + MLP(deep)``.
    """

    def __init__(self, wide_dim: int, deep_hidden=(256, 128),
                 num_classes: int = 2, activation: str = "relu",
                 dtype: str = "float32"):
        from distkeras_tpu.models.layers import Dense
        self.wide_dim = int(wide_dim)
        self.deep_hidden = tuple(int(h) for h in deep_hidden)
        self.num_classes = int(num_classes)
        self.activation = activation
        self.dtype = dtype
        self.wide = Dense(self.num_classes, use_bias=True, dtype=dtype)
        layers = []
        for h in self.deep_hidden:
            layers.append(Dense(h, activation=activation, dtype=dtype))
        layers.append(Dense(self.num_classes, dtype=dtype))
        self.deep = Sequential(layers)

    def init(self, rng, input_shape):
        total = input_shape[-1]
        if total <= self.wide_dim:
            raise ValueError(
                f"input dim {total} must exceed wide_dim {self.wide_dim}")
        k1, k2 = jax.random.split(rng)
        pw, sw, _ = self.wide.init(k1, (self.wide_dim,))
        pd, sd, _ = self.deep.init(k2, (total - self.wide_dim,))
        return ({"wide": pw, "deep": pd}, {"wide": sw, "deep": sd},
                (self.num_classes,))

    def apply(self, params, state, x, *, training=False, rng=None):
        xw, xd = x[..., :self.wide_dim], x[..., self.wide_dim:]
        yw, sw = self.wide.apply(params["wide"], state["wide"], xw,
                                 training=training)
        yd, sd = self.deep.apply(params["deep"], state["deep"], xd,
                                 training=training, rng=rng)
        return yw + yd, {"wide": sw, "deep": sd}

    def sub_layers(self):
        return {"wide": self.wide, "deep": self.deep}

    def get_config(self):
        return {"wide_dim": self.wide_dim,
                "deep_hidden": list(self.deep_hidden),
                "num_classes": self.num_classes,
                "activation": self.activation, "dtype": self.dtype}


@register_layer
class Remat(Layer):
    """Rematerialization wrapper: recompute ``inner``'s activations during
    the backward pass instead of storing them (``jax.checkpoint``).

    No reference equivalent — this is the TPU HBM-for-FLOPs trade that makes
    long-context/deep models fit: wrap each transformer block (or any
    expensive sub-stack) and the peak activation footprint drops from
    O(layers) to O(1) per wrapped unit at the cost of one extra forward.

    ``policy`` picks what XLA may keep instead of recomputing
    (``jax.checkpoint_policies``): ``None``/"nothing" saves nothing
    (maximum memory saving, one full extra forward), "dots" saves every
    matmul output (recomputes only the cheap elementwise/norm glue — the
    usual best trade on TPU where recomputing MXU work is the expensive
    part), "dots_no_batch" saves only weight-side matmuls. An EXPLICIT
    policy pins what is rematerialized; without one XLA's own
    memory-pressure rematerialization chooses per-compile (the measured
    batch-12 LM regression in docs/PERF.md was exactly that thrash).
    """

    POLICIES = ("nothing", "dots", "dots_no_batch")

    def __init__(self, inner: Layer = None, inner_spec=None,
                 policy: str = None):
        self.inner = inner if inner is not None else \
            layer_from_spec(inner_spec)
        if self.inner is None:
            raise ValueError("Remat needs an inner layer")
        if policy is not None and policy not in self.POLICIES:
            raise ValueError(f"unknown remat policy {policy!r}; "
                             f"known: {self.POLICIES}")
        self.policy = policy

    def _jax_policy(self):
        if self.policy in (None, "nothing"):
            return None  # jax.checkpoint default: save nothing
        return {
            "dots": jax.checkpoint_policies.checkpoint_dots,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        }[self.policy]

    @property
    def accepts_segment_ids(self) -> bool:
        return getattr(self.inner, "accepts_segment_ids", False)

    def init(self, rng, input_shape):
        return self.inner.init(rng, input_shape)

    def apply(self, params, state, x, *, training=False, rng=None,
              segment_ids=None):
        ckpt = partial(jax.checkpoint, policy=self._jax_policy())
        if segment_ids is not None and self.accepts_segment_ids:
            def f(p, s, xb, r, seg):
                return self.inner.apply(p, s, xb, training=training,
                                        rng=r, segment_ids=seg)

            return ckpt(f)(params, state, x, rng, segment_ids)

        def f(p, s, xb, r):
            return self.inner.apply(p, s, xb, training=training, rng=r)

        return ckpt(f)(params, state, x, rng)

    def get_config(self):
        return {"inner_spec": layer_spec(self.inner),
                "policy": self.policy}
