"""Model serialization: architecture JSON + weight arrays.

Mirrors the reference's model-shipping capability (reference:
``distkeras/utils.py :: serialize_keras_model / deserialize_keras_model``,
which packs Keras architecture JSON + a weights list so the model can cross
the driver→executor boundary). Here the same format idea serves (a) on-disk
persistence and (b) hashing/equality in tests. In-process the trainers never
serialize — pytrees move between devices via shardings, not pickles.

Format: a dict ``{"format", "class", "config", "input_shape", "weights"}``
where ``weights`` maps flattened pytree paths to numpy arrays. ``save_model``
writes it as ``<path>.json`` + ``<path>.npz``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import jax
import numpy as np

from distkeras_tpu.models.core import LAYER_REGISTRY, Model

FORMAT_VERSION = "distkeras_tpu.model.v1"


def leaf_key(path) -> str:
    """THE flat-key formula for a pytree key path (``a/b/0/c``): the
    one definition shared by model serialization AND every checkpoint
    read/write path (``utils/checkpoint.py``). Save and restore derive
    keys independently from their trees, so a drift between copies of
    this formula would fail every leaf lookup on restore — which is why
    there is exactly one copy."""
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                    for p in path)


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        flat[leaf_key(path)] = np.asarray(leaf)
    return flat


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    paths_leaves = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves[0]:
        key = leaf_key(path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"weight {key!r} shape {arr.shape} != expected {leaf.shape}")
        leaves.append(jax.numpy.asarray(arr, dtype=leaf.dtype))
    return jax.tree_util.tree_unflatten(paths_leaves[1], leaves)


def serialize_model(model: Model) -> Dict[str, Any]:
    """Model -> plain dict (arch config + numpy weights)."""
    return {
        "format": FORMAT_VERSION,
        "class": model.module.name,
        "config": model.module.get_config(),
        "input_shape": list(model.input_shape),
        "params": _flatten_with_paths(model.params),
        "state": _flatten_with_paths(model.state),
    }


def _abstract_template(payload: Dict[str, Any]):
    """(module, params_template, state_template, in_shape, out_shape) from
    an arch dict — ``jax.eval_shape`` only, so no random initialization
    work is done just to be overwritten (matters for ResNet-scale
    models)."""
    if payload.get("format") != FORMAT_VERSION:
        raise ValueError(f"Unknown model format: {payload.get('format')!r}")
    module = LAYER_REGISTRY[payload["class"]].from_config(payload["config"])
    input_shape = tuple(payload["input_shape"])
    rng = jax.random.PRNGKey(0)
    captured = {}

    def abstract_init():
        p, s, out_shape = module.init(rng, input_shape)
        captured["out_shape"] = out_shape  # static python tuple
        return p, s

    p_template, s_template = jax.eval_shape(abstract_init)
    return module, p_template, s_template, input_shape, \
        captured["out_shape"]


def deserialize_model(payload: Dict[str, Any]) -> Model:
    """Plain dict -> Model (rebuilds spec from registry, restores weights)."""
    module, p_t, s_t, in_shape, out_shape = _abstract_template(payload)
    params = _unflatten_like(p_t, payload["params"])
    state = _unflatten_like(s_t, payload["state"])
    return Model(module, params, state, in_shape, out_shape)


def save_model(model: Model, path: str, quantize: bool = False) -> None:
    """``quantize=True`` stores matrix weights as int8 + per-channel f32
    scales (``models.quantize``) — ~4× smaller files; ``load_model``
    restores f32 transparently (or the int8 form with
    ``keep_quantized=True``)."""
    payload = serialize_model(model)
    arch = {k: payload[k] for k in ("format", "class", "config",
                                    "input_shape")}
    arrays = {f"params:{k}": v for k, v in payload["params"].items()}
    if quantize:
        from distkeras_tpu.models.quantize import (_is_quantizable,
                                                   _quantize_leaf)
        arch["quantized"] = True
        qarrays = {}
        for k, v in arrays.items():
            # param name = last path segment with the "params:" store
            # prefix stripped (root-level params have no "/" at all);
            # scales live in their own "scale:" namespace so a param
            # literally named "scale" can never collide with them
            name = k.split(":", 1)[1].split("/")[-1]
            if _is_quantizable(v, name):
                d = _quantize_leaf(v)
                qarrays[k] = d["q"]
                qarrays["scale:" + k] = d["scale"]
            else:
                qarrays[k] = v
        arrays = qarrays
    with open(path + ".json", "w") as f:
        json.dump(arch, f, indent=2)
    arrays.update({f"state:{k}": v for k, v in payload["state"].items()})
    np.savez(path + ".npz", **arrays)


def load_model(path: str, keep_quantized: bool = False):
    """Returns a ``Model`` (f32) — or, for a quantized file with
    ``keep_quantized=True``, a ``models.quantize.QuantizedModel`` whose
    predict dequantizes in-graph."""
    with open(path + ".json") as f:
        arch = json.load(f)
    arrays = np.load(path + ".npz")
    state = {k[len("state:"):]: arrays[k] for k in arrays.files
             if k.startswith("state:")}
    if arch.pop("quantized", False):
        from distkeras_tpu.models.quantize import (QuantizedModel,
                                                   _dequantize_leaf)
        files = set(arrays.files)

        def scale_key(k):
            """Scale entry for param key ``k``, or None. Current format:
            ``scale:<k>`` namespace; legacy (round-1) format: ``<k>:scale``
            suffix — still read so old files dequantize instead of
            silently loading int8 codes as floats. A genuine param whose
            key happens to end in ``:scale`` is only mistaken for a legacy
            scale if its prefix is itself a stored param key — impossible
            for the current writer (scales live in their own namespace)."""
            if "scale:" + k in files:
                return "scale:" + k
            legacy = k + ":scale"
            return legacy if legacy in files else None

        def is_scale_entry(k):
            return k.startswith("scale:") or (
                k.endswith(":scale") and k[:-len(":scale")] in files)

        if not keep_quantized:
            params = {}
            for k in arrays.files:
                if not k.startswith("params:") or is_scale_entry(k):
                    continue
                name = k[len("params:"):]
                sk = scale_key(k)
                if sk is not None:
                    params[name] = np.asarray(_dequantize_leaf(
                        arrays[k], arrays[sk]))
                else:
                    params[name] = arrays[k]
            return deserialize_model({**arch, "params": params,
                                      "state": state})
        # int8 serving handle built DIRECTLY from the stored q/scale
        # arrays — no f32 materialization, scales verbatim
        module, p_t, s_t, in_shape, out_shape = _abstract_template(arch)
        flat_t, treedef = jax.tree_util.tree_flatten_with_path(p_t)
        qleaves, sleaves = [], []
        for path, leaf in flat_t:
            key = "params:" + "/".join(
                str(getattr(p, "key", getattr(p, "idx", p)))
                for p in path)
            arr = arrays[key]
            if tuple(arr.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"weight {key!r} shape {arr.shape} != "
                    f"expected {leaf.shape}")
            sk = scale_key(key)
            if sk is not None:
                qleaves.append(arr)                       # int8 verbatim
                sleaves.append(arrays[sk])
            else:
                qleaves.append(arr.astype(leaf.dtype))
                sleaves.append(None)
        qparams = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(p_t), qleaves)
        scales = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(p_t), sleaves)
        return QuantizedModel(module, qparams, scales,
                              _unflatten_like(s_t, state),
                              in_shape, out_shape)
    if keep_quantized:
        raise ValueError(
            f"{path} was not saved with quantize=True; load it normally "
            "and call models.quantize.quantize_model()")
    params = {k[len("params:"):]: arrays[k] for k in arrays.files
              if k.startswith("params:")}
    return deserialize_model({**arch, "params": params, "state": state})
