"""Model serialization: architecture JSON + weight arrays.

Mirrors the reference's model-shipping capability (reference:
``distkeras/utils.py :: serialize_keras_model / deserialize_keras_model``,
which packs Keras architecture JSON + a weights list so the model can cross
the driver→executor boundary). Here the same format idea serves (a) on-disk
persistence and (b) hashing/equality in tests. In-process the trainers never
serialize — pytrees move between devices via shardings, not pickles.

Format: a dict ``{"format", "class", "config", "input_shape", "weights"}``
where ``weights`` maps flattened pytree paths to numpy arrays. ``save_model``
writes it as ``<path>.json`` + ``<path>.npz``.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import jax
import numpy as np

from distkeras_tpu.models.core import LAYER_REGISTRY, Model

FORMAT_VERSION = "distkeras_tpu.model.v1"


def _flatten_with_paths(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        flat[key] = np.asarray(leaf)
    return flat


def _unflatten_like(template, flat: Dict[str, np.ndarray]):
    paths_leaves = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves[0]:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(leaf.shape):
            raise ValueError(
                f"weight {key!r} shape {arr.shape} != expected {leaf.shape}")
        leaves.append(jax.numpy.asarray(arr, dtype=leaf.dtype))
    return jax.tree_util.tree_unflatten(paths_leaves[1], leaves)


def serialize_model(model: Model) -> Dict[str, Any]:
    """Model -> plain dict (arch config + numpy weights)."""
    return {
        "format": FORMAT_VERSION,
        "class": model.module.name,
        "config": model.module.get_config(),
        "input_shape": list(model.input_shape),
        "params": _flatten_with_paths(model.params),
        "state": _flatten_with_paths(model.state),
    }


def deserialize_model(payload: Dict[str, Any]) -> Model:
    """Plain dict -> Model (rebuilds spec from registry, restores weights).

    Uses ``jax.eval_shape`` to get the parameter template, so no random
    initialization work is done just to be overwritten (matters for
    ResNet-scale models)."""
    if payload.get("format") != FORMAT_VERSION:
        raise ValueError(f"Unknown model format: {payload.get('format')!r}")
    module = LAYER_REGISTRY[payload["class"]].from_config(payload["config"])
    input_shape = tuple(payload["input_shape"])
    rng = jax.random.PRNGKey(0)
    captured = {}

    def abstract_init():
        p, s, out_shape = module.init(rng, input_shape)
        captured["out_shape"] = out_shape  # static python tuple
        return p, s

    p_template, s_template = jax.eval_shape(abstract_init)
    params = _unflatten_like(p_template, payload["params"])
    state = _unflatten_like(s_template, payload["state"])
    return Model(module, params, state, input_shape, captured["out_shape"])


def save_model(model: Model, path: str) -> None:
    payload = serialize_model(model)
    arch = {k: payload[k] for k in ("format", "class", "config",
                                    "input_shape")}
    with open(path + ".json", "w") as f:
        json.dump(arch, f, indent=2)
    arrays = {f"params:{k}": v for k, v in payload["params"].items()}
    arrays.update({f"state:{k}": v for k, v in payload["state"].items()})
    np.savez(path + ".npz", **arrays)


def load_model(path: str) -> Model:
    with open(path + ".json") as f:
        arch = json.load(f)
    arrays = np.load(path + ".npz")
    params = {k[len("params:"):]: arrays[k] for k in arrays.files
              if k.startswith("params:")}
    state = {k[len("state:"):]: arrays[k] for k in arrays.files
             if k.startswith("state:")}
    return deserialize_model({**arch, "params": params, "state": state})
