"""Model zoo: builders for every BASELINE evaluation config.

  1. ``mlp``               — SingleTrainer MNIST MLP (config 1)
  2. ``lenet5``            — ADAG LeNet-5 on CIFAR-10 (config 2)
  3. ``resnet50``          — AEASGD ResNet-50 on ImageNet (config 3)
  4. ``wide_and_deep``     — DOWNPOUR wide&deep on Criteo (config 4)
  5. ``bilstm_classifier`` — Predictor batched BiLSTM inference (config 5)

The reference builds these ad hoc in example notebooks; here they are
first-class builders returning ``Sequential`` specs (build with
``Model.build(spec, input_shape)``).

TPU notes: convs/matmuls accept ``dtype='bfloat16'`` for MXU-friendly mixed
precision; ResNet uses NHWC + BatchNorm with optional cross-replica
``axis_name``.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

from distkeras_tpu.models.blocks import Residual, WideAndDeep
from distkeras_tpu.models.core import Sequential
from distkeras_tpu.models.layers import (
    Activation, BatchNorm, Conv2D, Dense, DepthwiseConv2D, Dropout,
    Embedding, Flatten, GlobalAveragePooling2D, MaxPooling2D)
from distkeras_tpu.models.recurrent import LSTM, Bidirectional


def mlp(hidden: Sequence[int] = (512, 256), num_classes: int = 10,
        activation: str = "relu", dropout: float = 0.0,
        dtype: str = "float32") -> Sequential:
    """MNIST-style MLP (BASELINE config 1; the reference's
    ``examples/mnist.py`` MLP equivalent)."""
    layers = []
    for h in hidden:
        layers.append(Dense(h, activation=activation, dtype=dtype))
        if dropout > 0:
            layers.append(Dropout(dropout))
    layers.append(Dense(num_classes, dtype=dtype))
    return Sequential(layers)


def lenet5(num_classes: int = 10, dtype: str = "float32") -> Sequential:
    """LeNet-5 (BASELINE config 2: ADAG on CIFAR-10). Classic topology,
    NHWC, tanh activations as in the original."""
    return Sequential([
        Conv2D(6, 5, padding="SAME", activation="tanh", dtype=dtype),
        MaxPooling2D(2),
        Conv2D(16, 5, padding="VALID", activation="tanh", dtype=dtype),
        MaxPooling2D(2),
        Flatten(),
        Dense(120, activation="tanh", dtype=dtype),
        Dense(84, activation="tanh", dtype=dtype),
        Dense(num_classes, dtype=dtype),
    ])


def _resnet_norm(norm: str, bn_axis_name: Optional[str],
                 norm_groups: int = 32):
    """Norm factory for the resnet family: ``"batch"`` (reference-standard
    BN) or ``"group"`` (GroupNorm-32, Wu & He 2018 — no batch statistics,
    so no cross-replica stats axis, identical train/eval, and on TPU no
    f32 stats-reduction epilogue fused after every conv; see docs/PERF.md
    for the measured profile share of BN statistics)."""
    if norm == "batch":
        return lambda: BatchNorm(axis_name=bn_axis_name)
    if norm == "group":
        from distkeras_tpu.models.layers import GroupNorm
        return lambda: GroupNorm(groups=norm_groups)
    raise ValueError(f"norm must be 'batch' or 'group', got {norm!r}")


def _bottleneck(filters: int, stride: int, project: bool,
                dtype: str, bn_axis_name: Optional[str],
                norm: str = "batch", norm_groups: int = 32) -> Residual:
    """ResNet-v1.5 bottleneck: 1x1 -> 3x3(stride) -> 1x1(4f), norm after
    each conv, relu after the residual add."""
    bn = _resnet_norm(norm, bn_axis_name, norm_groups)
    main = Sequential([
        Conv2D(filters, 1, use_bias=False, dtype=dtype), bn(),
        Activation("relu"),
        Conv2D(filters, 3, strides=stride, use_bias=False, dtype=dtype),
        bn(), Activation("relu"),
        Conv2D(4 * filters, 1, use_bias=False, dtype=dtype), bn(),
    ])
    shortcut = None
    if project:
        shortcut = Sequential([
            Conv2D(4 * filters, 1, strides=stride, use_bias=False,
                   dtype=dtype), bn(),
        ])
    return Residual(main, shortcut, activation="relu")


def resnet(stage_sizes: Sequence[int], num_classes: int = 1000,
           width: int = 64, dtype: str = "float32",
           bn_axis_name: Optional[str] = None,
           norm: str = "batch", norm_groups: int = 32) -> Sequential:
    """ResNet-v1.5 family over bottleneck blocks (NHWC). ``norm_groups``
    only applies to ``norm="group"`` and must divide every stage width."""
    layers = [
        Conv2D(width, 7, strides=2, use_bias=False, dtype=dtype),
        _resnet_norm(norm, bn_axis_name, norm_groups)(), Activation("relu"),
        MaxPooling2D(3, strides=2, padding="SAME"),
    ]
    filters = width
    for stage, blocks in enumerate(stage_sizes):
        for block in range(blocks):
            stride = 2 if (stage > 0 and block == 0) else 1
            project = (block == 0)
            layers.append(_bottleneck(filters, stride, project, dtype,
                                      bn_axis_name, norm, norm_groups))
        filters *= 2
    layers += [GlobalAveragePooling2D(), Dense(num_classes, dtype=dtype)]
    return Sequential(layers)


def resnet50(num_classes: int = 1000, dtype: str = "float32",
             bn_axis_name: Optional[str] = None,
             norm: str = "batch") -> Sequential:
    """ResNet-50 (BASELINE config 3 / the north-star model). ``norm=
    "group"`` gives the GroupNorm variant (different numerics — a model
    choice, not a drop-in BN replacement)."""
    return resnet([3, 4, 6, 3], num_classes, 64, dtype, bn_axis_name,
                  norm)


def resnet18_thin(num_classes: int = 10, width: int = 8,
                  dtype: str = "float32") -> Sequential:
    """A few-block thin ResNet for CPU-mesh tests (same topology family)."""
    return resnet([1, 1], num_classes, width, dtype)


def bilstm_classifier(units: int = 64, num_classes: int = 2,
                      dtype: str = "float32") -> Sequential:
    """BiLSTM sequence classifier (BASELINE config 5: batched Predictor
    inference over sharded data)."""
    return Sequential([
        Bidirectional(LSTM(units, return_sequences=True, dtype=dtype)),
        Bidirectional(LSTM(units, dtype=dtype)),
        Dense(num_classes, dtype=dtype),
    ])


def wide_and_deep(wide_dim: int, deep_hidden: Sequence[int] = (256, 128),
                  num_classes: int = 2, dtype: str = "float32") -> Sequential:
    """Wide & Deep for Criteo-style CTR (BASELINE config 4)."""
    return Sequential([
        WideAndDeep(wide_dim, deep_hidden, num_classes, dtype=dtype)])


def transformer_lm(vocab_size: int, d_model: int = 512, num_heads: int = 8,
                   num_layers: int = 6, mlp_ratio: int = 4,
                   max_len: Optional[int] = None, use_rope: bool = True,
                   norm: str = "rmsnorm", dtype: str = "float32",
                   attn_impl: str = "auto",
                   seq_axis_name: Optional[str] = None,
                   num_kv_heads: Optional[int] = None,
                   rope_scale: float = 1.0,
                   attn_window: Optional[int] = None,
                   moe_every: int = 0, num_experts: int = 0,
                   moe_expert_axis: Optional[str] = None,
                   moe_aux_loss_weight: float = 0.0,
                   moe_dispatch: str = "dense",
                   moe_capacity_factor: float = 1.25,
                   moe_expert_unroll: bool = False,
                   remat: Optional[str] = None) -> Sequential:
    """Decoder-only causal transformer LM — the long-context flagship.

    Absent from the reference (no attention models; SURVEY §5.7); this is
    the model the TP/SP/EP parallelism layers are exercised on. Tokens
    [B, S] int in, logits [B, S, vocab] out.

    ``moe_every=k`` (with ``num_experts``) swaps every k-th block's MLP for
    a mixture-of-experts layer (expert-parallel over ``moe_expert_axis``);
    ``moe_dispatch="tokens"`` uses the capacity-based cumsum dispatch
    (per-token expert FLOPs ~ top_k x ``moe_capacity_factor`` MLPs instead
    of all ``num_experts`` — see ``models/moe.py``).
    ``moe_expert_unroll=True`` unrolls the expert dots into small groups
    (a measured per-op MXU win that OOMs the 12-layer training graph at
    batch 8 and forces resharding under GSPMD expert sharding — opt-in
    only; see ``MoE.__init__``).
    ``num_kv_heads < num_heads`` builds a grouped-query (GQA) model — the
    KV cache at serving time shrinks by the group factor.
    ``remat`` wraps every transformer block in ``blocks.Remat`` with that
    checkpoint policy ("nothing" | "dots" | "dots_no_batch") — the
    explicit activation-memory policy for deep/long-context training
    (see ``Remat``'s docstring for the trade-offs).
    """
    from distkeras_tpu.models.attention import (
        LayerNorm, PositionalEmbedding, RMSNorm, TransformerBlock)

    layers = [Embedding(vocab_size, d_model)]
    if not use_rope:
        if max_len is None:
            raise ValueError("max_len required when use_rope=False")
        # thread the sequence axis through so positions are global under
        # sequence parallelism (shard-local positions would be silently wrong)
        layers.append(PositionalEmbedding(max_len,
                                          seq_axis_name=seq_axis_name))
    for i in range(num_layers):
        mlp_layer = None
        if moe_every and num_experts and (i + 1) % moe_every == 0:
            from distkeras_tpu.models.moe import MoE
            mlp_layer = MoE(num_experts, mlp_ratio * d_model,
                            dtype=dtype, expert_axis_name=moe_expert_axis,
                            aux_loss_weight=moe_aux_loss_weight,
                            dispatch=moe_dispatch,
                            capacity_factor=moe_capacity_factor,
                            expert_unroll=moe_expert_unroll)
        block = TransformerBlock(
            num_heads, mlp_ratio=mlp_ratio, causal=True, use_rope=use_rope,
            norm=norm, dtype=dtype, attn_impl=attn_impl,
            seq_axis_name=seq_axis_name, mlp_layer=mlp_layer,
            num_kv_heads=num_kv_heads, rope_scale=rope_scale,
            attn_window=attn_window)
        if remat is not None:
            from distkeras_tpu.models.blocks import Remat
            block = Remat(block, policy=remat)
        layers.append(block)
    layers.append(RMSNorm() if norm == "rmsnorm" else LayerNorm())
    layers.append(Dense(vocab_size, use_bias=False, dtype=dtype))
    return Sequential(layers)


def vit(image_size: int = 224, patch_size: int = 16, d_model: int = 384,
        num_heads: int = 6, num_layers: int = 12, mlp_ratio: int = 4,
        num_classes: int = 1000, dtype: str = "float32",
        dropout_rate: float = 0.0) -> Sequential:
    """Vision Transformer (ViT; Dosovitskiy et al. 2020) — capability ADD
    (the reference predates transformers, SURVEY §5.7). Patchify is ONE
    strided conv (a single MXU matmul per patch grid), then mean-pooled
    pre-norm encoder blocks; GAP head instead of a class token keeps the
    whole model a ``Sequential``.
    """
    from distkeras_tpu.models.attention import (LayerNorm,
                                                PositionalEmbedding,
                                                TransformerBlock)
    from distkeras_tpu.models.layers import GlobalAveragePooling1D, Reshape

    if image_size % patch_size:
        raise ValueError(
            f"image_size {image_size} not divisible by patch_size "
            f"{patch_size}")
    n_patches = (image_size // patch_size) ** 2
    layers = [
        Conv2D(d_model, patch_size, strides=patch_size, padding="VALID",
               dtype=dtype),
        Reshape((n_patches, d_model)),
        PositionalEmbedding(n_patches),
    ]
    for _ in range(num_layers):
        layers.append(TransformerBlock(
            num_heads, mlp_ratio=mlp_ratio, causal=False, use_rope=False,
            norm="layernorm", dtype=dtype, dropout_rate=dropout_rate))
    layers += [LayerNorm(), GlobalAveragePooling1D(),
               Dense(num_classes, dtype=dtype)]
    return Sequential(layers)


def mobilenet(num_classes: int = 1000, width_mult: float = 1.0,
              dtype: str = "float32",
              bn_axis_name: Optional[str] = None) -> Sequential:
    """MobileNet-v1 (Howard et al. 2017) — depthwise-separable CNN built
    on ``DepthwiseConv2D``; the classic efficient-inference counterpart to
    ``resnet50`` (capability ADD: the reference's CNN examples stop at
    LeNet-scale). NHWC, BN after every conv, ``width_mult`` scales every
    channel count."""
    from distkeras_tpu.models.layers import DepthwiseConv2D

    def ch(c):
        return max(8, int(c * width_mult))

    bn = lambda: BatchNorm(axis_name=bn_axis_name)
    layers = [Conv2D(ch(32), 3, strides=2, use_bias=False, dtype=dtype),
              bn(), Activation("relu")]
    # (pointwise out-channels, stride) per separable block
    plan = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
            (1024, 1)]
    for out_c, stride in plan:
        layers += [
            DepthwiseConv2D(3, strides=stride, use_bias=False, dtype=dtype),
            bn(), Activation("relu"),
            Conv2D(ch(out_c), 1, use_bias=False, dtype=dtype),
            bn(), Activation("relu"),
        ]
    layers += [GlobalAveragePooling2D(), Dense(num_classes, dtype=dtype)]
    return Sequential(layers)
