"""Weight-only int8 quantization for inference and model shipping.

A capability ADD with no reference analogue (dist-keras ships full-precision
Keras weight lists across the wire — ``utils.py :: serialize_keras_model``).
TPU-first rationale: serving big models is HBM-bandwidth-bound, and int8
weights halve both checkpoint size (vs bf16; 4× vs f32) and the HBM traffic
of reading parameters. This module does **symmetric per-output-channel
weight-only** quantization:

  * matrix-shaped float leaves (ndim ≥ 2) become
    ``{"q": int8, "scale": f32[out_channels]}`` — scales along the LAST
    axis, which is the output-features axis for every kernel layout in
    ``models.layers`` (Dense ``[in, out]``, convs ``[*k, in, out]``,
    attention ``[d, h, dh]``, stacked experts ``[e, in, out]``);
  * small leaves (biases, norm scales, 1-D) stay f32 — they are a
    rounding-error fraction of the bytes and matter for accuracy.

Compute stays in the model's compute dtype: ``QuantizedModel.predict``
passes int8 arrays into ONE jitted forward whose first op dequantizes
``q * scale`` — XLA keeps the int8 tensors in HBM and fuses the dequant
into the consuming matmul/conv epilogue, so the bandwidth saving is real,
not just on-disk.

Training on quantized weights is deliberately unsupported (use the full-
precision master model; quantize AFTER training).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from distkeras_tpu.models.core import Model, user_float


def _quantize_leaf(w: np.ndarray, bits: int = 8) -> Dict[str, np.ndarray]:
    """Symmetric per-last-axis-channel quantization: w ≈ q * scale.
    ``bits=8`` is the established int8 grid; ``bits=4`` (quantized-
    decode PR) quantizes to [-7, 7] while still storing one int8 byte
    per entry — the dequant contract is identical, and the serving
    engine's fused dequant-matmul kernel owns nibble PACKING for the
    matrices it streams (``ops.quant_matmul``)."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    qmax = 7.0 if bits == 4 else 127.0
    absmax = np.abs(w).max(axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (absmax / qmax).astype(np.float32)
    scale = np.where(scale == 0.0, 1.0, scale)          # all-zero channels
    q = np.clip(np.round(w / scale), -qmax, qmax).astype(np.int8)
    return {"q": q, "scale": scale.reshape(-1).astype(np.float32)}


def _dequantize_leaf(q, scale, dtype=jnp.float32):
    return (q.astype(jnp.float32) * scale).astype(dtype)


# Weight names that take int8: the big matmul/conv kernels and embedding
# tables. Everything else (biases — including MoE's stacked [E, ...] bias
# MATRICES — norm scales/offsets, and the MoE router gate, whose tiny
# logits decide routing) stays f32: negligible bytes, outsized accuracy
# role.
QUANTIZABLE_NAMES = frozenset(
    {"kernel", "embeddings", "w1", "w2", "wq", "wk", "wv", "wo"})


def _is_quantizable(leaf, name: str) -> bool:
    return (name in QUANTIZABLE_NAMES
            and hasattr(leaf, "ndim") and leaf.ndim >= 2
            and np.issubdtype(np.asarray(leaf).dtype, np.floating))


def quantize_params(params, bits: int = 8) -> Tuple[Any, Any]:
    """params pytree -> (same-structure tree of int8 ``q`` / passthrough
    leaves, matching tree of f32 ``scale`` / None leaves). ``bits=4``
    uses the 4-bit grid (:func:`_quantize_leaf`); storage stays one
    int8 byte per entry, so :func:`dequantize_params` serves both."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    qs, scales = [], []
    for path, leaf in flat:
        name = str(getattr(path[-1], "key", "")) if path else ""
        if _is_quantizable(leaf, name):
            d = _quantize_leaf(np.asarray(leaf), bits)
            qs.append(d["q"])
            scales.append(d["scale"])
        else:
            qs.append(np.asarray(leaf))
            scales.append(None)
    return (jax.tree_util.tree_unflatten(treedef, qs),
            jax.tree_util.tree_unflatten(treedef, scales))


def dequantize_params(qtree, scales):
    """Inverse of :func:`quantize_params` (f32 leaves)."""
    def leaf(q, s):
        if s is None:
            return q
        return _dequantize_leaf(jnp.asarray(q), jnp.asarray(s))
    # scales tree has None leaves -> zip manually over flattened lists
    qleaves, treedef = jax.tree_util.tree_flatten(qtree)
    sleaves = jax.tree_util.tree_flatten(
        scales, is_leaf=lambda x: x is None)[0]
    return jax.tree_util.tree_unflatten(
        treedef, [leaf(q, s) for q, s in zip(qleaves, sleaves)])


class QuantizedModel:
    """Inference handle over int8 weights: ``predict`` runs one jitted
    forward that dequantizes in-graph (int8 stays in HBM)."""

    def __init__(self, module, qparams, scales, state, input_shape,
                 output_shape):
        self.module = module
        self.qparams = qparams
        self.scales = scales
        self.state = state
        self.input_shape = tuple(input_shape)
        self.output_shape = tuple(output_shape)
        self._jit_fwd = None

    def predict(self, x) -> np.ndarray:
        x = jnp.asarray(x)
        if self._jit_fwd is None:
            def fwd(qp, scales, state, xb):
                # scales' None leaves are pytree STRUCTURE, so they pass
                # through jit unchanged; arrays are traced args (no
                # weight constants baked into the executable)
                params = dequantize_params(qp, scales)
                return user_float(
                    self.module.apply(params, state, xb,
                                      training=False)[0])

            self._jit_fwd = jax.jit(fwd)
        return np.asarray(self._jit_fwd(self.qparams, self.scales,
                                        self.state, x))

    def num_bytes(self) -> int:
        return sum(np.asarray(l).nbytes
                   for l in jax.tree_util.tree_leaves(self.qparams)) + \
            sum(np.asarray(l).nbytes
                for l in jax.tree_util.tree_leaves(self.scales)
                if l is not None)


def quantize_model(model: Model) -> QuantizedModel:
    """Post-training weight-only int8 quantization of a trained Model."""
    qparams, scales = quantize_params(model.params)
    return QuantizedModel(model.module, qparams, scales, model.state,
                          model.input_shape, model.output_shape)


def dequantize_model(qmodel: QuantizedModel) -> Model:
    """Back to a full-precision Model (f32 weights)."""
    params = jax.device_get(dequantize_params(qmodel.qparams,
                                              qmodel.scales))
    return Model(qmodel.module, params, qmodel.state, qmodel.input_shape,
                 qmodel.output_shape)
