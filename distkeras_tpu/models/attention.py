"""Transformer layers: norms, multi-head attention, transformer block.

The reference has no attention/transformer models (SURVEY §5.7 — dist-keras
predates transformers; examples stop at (Bi)LSTM). These layers are the TPU
build's long-context model family, designed mesh-first:

  * Attention projection params are stored as ``[d_model, heads, head_dim]``
    so tensor parallelism is a single ``PartitionSpec(None, "tensor", None)``
    on the heads axis (see ``parallel.sharding``).
  * The MLP keeps its two matmuls as explicit ``w1``/``w2`` for the standard
    column→row TP split.
  * ``attn_impl`` selects the compute path per layer: ``"auto"`` (the
    default: the Pallas flash kernel on TPU — measured 2.15x faster than
    fused XLA attention at seq 2048 on v5e, ``bench.py --model lm`` —
    and XLA elsewhere), ``"xla"`` (fused reference), ``"flash"`` (Pallas
    kernel, forced), ``"ring"`` (sequence-parallel ring attention over a
    mesh axis — set by the SPMD trainer), or
    ``"ulysses"``/``"ulysses_flash"`` (all-to-all head-scatter sequence
    parallelism, ``ops.ulysses``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from distkeras_tpu.compat import axis_size, backend_is_tpu
from distkeras_tpu.models.core import (Layer, layer_from_spec, layer_spec,
                                       register_layer)
from distkeras_tpu.models.layers import Dropout, get_activation, init_weights
from distkeras_tpu.ops.attention import apply_rope, dot_product_attention


@register_layer
class LayerNorm(Layer):
    def __init__(self, epsilon: float = 1e-5):
        self.epsilon = float(epsilon)

    def init(self, rng, input_shape):
        dim = input_shape[-1]
        return {"scale": jnp.ones((dim,)), "offset": jnp.zeros((dim,))}, {}, \
            tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        y = y * params["scale"] + params["offset"]
        return y.astype(x.dtype), state

    def get_config(self):
        return {"epsilon": self.epsilon}


@register_layer
class RMSNorm(Layer):
    def __init__(self, epsilon: float = 1e-6):
        self.epsilon = float(epsilon)

    def init(self, rng, input_shape):
        dim = input_shape[-1]
        return {"scale": jnp.ones((dim,))}, {}, tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        xf = x.astype(jnp.float32)
        y = xf * jax.lax.rsqrt(
            jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + self.epsilon)
        return (y * params["scale"]).astype(x.dtype), state

    def get_config(self):
        return {"epsilon": self.epsilon}


@register_layer
class PositionalEmbedding(Layer):
    """Learned absolute position embeddings added to a [B, S, D] input.

    Under sequence parallelism the input holds one shard of the sequence, so
    set ``seq_axis_name`` to the mesh axis the sequence is sharded over: the
    layer then offsets into the table by ``axis_index * shard_len`` to use
    GLOBAL positions (mirroring the RoPE handling in MultiHeadAttention).
    """

    def __init__(self, max_len: int, seq_axis_name: Optional[str] = None):
        self.max_len = int(max_len)
        self.seq_axis_name = seq_axis_name

    def init(self, rng, input_shape):
        dim = input_shape[-1]
        params = {"embeddings": init_weights("uniform_scaling", rng,
                                             (self.max_len, dim))}
        return params, {}, tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        s = x.shape[1]
        if self.seq_axis_name and self._axis_bound():
            # fail loudly if the table can't cover the GLOBAL sequence —
            # dynamic_slice would silently clamp out-of-range shard starts
            global_len = s * axis_size(self.seq_axis_name)
            if global_len > self.max_len:
                raise ValueError(
                    f"PositionalEmbedding(max_len={self.max_len}) is too "
                    f"small for global sequence length {global_len} "
                    f"({s} per shard over axis '{self.seq_axis_name}')")
            start = jax.lax.axis_index(self.seq_axis_name) * s
            emb = jax.lax.dynamic_slice_in_dim(params["embeddings"],
                                               start, s, axis=0)
        else:
            emb = params["embeddings"][:s]
        return x + emb[None].astype(x.dtype), state

    def _axis_bound(self) -> bool:
        """True when tracing inside a shard_map that binds the axis. Outside
        (e.g. unsharded eval via model.predict) the input holds the FULL
        sequence, so shard-local slicing is the correct behavior."""
        try:
            axis_size(self.seq_axis_name)
            return True
        except NameError:
            return False

    def get_config(self):
        return {"max_len": self.max_len,
                "seq_axis_name": self.seq_axis_name}


def _attention_compute(q, k, v, *, causal, impl, axis_name=None,
                       ring_block_size=None, window=None,
                       segment_ids=None):
    """Dispatch on attention implementation. q/k/v are BSHD.

    ``segment_ids`` (packed sequences) flows to EVERY impl (round 4):
    flash/xla mask in-kernel; ring rotates the k-side ids with their K/V
    shards; Ulysses all-gathers the ids alongside its head-scatter. For
    the sequence-parallel impls the ids are the local [B, S_local] shard.
    """
    if impl == "auto":
        # measured on TPU v5e (bench.py --model lm): the Pallas flash
        # kernel (in-kernel backward) trains 2.15x faster than fused XLA
        # attention at seq 2048; off-TPU the kernel only runs in
        # interpreter mode, where XLA wins
        impl = "flash" if backend_is_tpu() else "xla"
    if impl == "flash":
        from distkeras_tpu.ops.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal, window=window,
                               segment_ids=segment_ids)
    if window is not None and impl in ("ring", "ulysses",
                                       "ulysses_flash"):
        raise ValueError(
            f"attn_window is not supported with attn_impl={impl!r} "
            "(sequence-parallel paths have no windowed variant yet)")
    if impl == "ring":
        if not axis_name:
            raise ValueError(
                "attn_impl='ring' requires seq_axis_name (the mesh axis the "
                "sequence is sharded over, e.g. 'sp' from parallel.mesh); "
                "without it RoPE positions and causal masks would silently "
                "use shard-local coordinates")
        from distkeras_tpu.ops.ring_attention import ring_attention
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal,
                              block_size=ring_block_size,
                              segment_ids=segment_ids)
    if impl in ("ulysses", "ulysses_flash"):
        if not axis_name:
            raise ValueError(
                "attn_impl='ulysses' requires seq_axis_name (the mesh axis "
                "the sequence is sharded over); without it RoPE positions "
                "and causal masks would silently use shard-local "
                "coordinates")
        from distkeras_tpu.ops.ulysses import ulysses_attention
        return ulysses_attention(
            q, k, v, axis_name=axis_name, causal=causal,
            impl="flash" if impl == "ulysses_flash" else "xla",
            segment_ids=segment_ids)
    return dot_product_attention(q, k, v, causal=causal, window=window,
                                 segment_ids=segment_ids)


@register_layer
class MultiHeadAttention(Layer):
    """Multi-head self-attention over [B, S, d_model].

    Projections are single einsums against ``[d_model, H, Dh]`` tensors —
    one MXU matmul each; the heads axis is the TP shard axis.

    ``num_kv_heads < num_heads`` gives grouped-query attention (GQA;
    ``num_kv_heads=1`` is multi-query): K/V project to fewer heads, each
    shared by ``num_heads // num_kv_heads`` query heads. Training-side
    the shared heads are broadcast before the kernel (compute is
    matmul-dominated either way); the payoff is serving — the KV cache
    shrinks by the group factor (``models.decoding`` sizes it by
    ``num_kv_heads``).
    """

    def __init__(self, num_heads: int, head_dim: Optional[int] = None,
                 causal: bool = True, use_rope: bool = True,
                 dtype: str = "float32", attn_impl: str = "auto",
                 seq_axis_name: Optional[str] = None,
                 kernel_init: str = "glorot_uniform",
                 ring_block_size: Optional[int] = None,
                 num_kv_heads: Optional[int] = None,
                 rope_scale: float = 1.0,
                 attn_window: Optional[int] = None):
        self.rope_scale = float(rope_scale)
        #: causal sliding window (Mistral-style SWA): each query attends
        #: to at most the last attn_window keys. None = full causal.
        self.attn_window = (int(attn_window) if attn_window is not None
                            else None)
        if self.attn_window is not None and not causal:
            raise ValueError("attn_window requires causal=True")
        self.num_heads = int(num_heads)
        self.num_kv_heads = (int(num_kv_heads) if num_kv_heads is not None
                             else None)
        kv = self.num_kv_heads if self.num_kv_heads is not None \
            else self.num_heads
        if kv < 1 or self.num_heads % kv:
            raise ValueError(
                f"num_kv_heads must be a positive divisor of num_heads "
                f"{self.num_heads}, got {kv}")
        self.head_dim = head_dim if head_dim is None else int(head_dim)
        self.causal = bool(causal)
        self.use_rope = bool(use_rope)
        self.dtype = dtype
        self.attn_impl = attn_impl
        self.seq_axis_name = seq_axis_name
        self.kernel_init = kernel_init
        self.ring_block_size = ring_block_size  # inner k-blocking (memory)

    #: packed-sequence capability marker (Sequential forwards segment_ids
    #: only to layers declaring this — containers forward recursively)
    accepts_segment_ids = True

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    def init(self, rng, input_shape):
        d_model = input_shape[-1]
        h, dh = self.num_heads, self.head_dim or d_model // self.num_heads
        hkv = self.kv_heads
        ks = jax.random.split(rng, 4)
        # initialize as the LOGICAL 2D matrices and reshape: the generic
        # fan rules would treat [d_model, H, Dh] as a conv kernel and
        # inflate both fans by the leading axis, shrinking the init scale
        w2d = lambda k, m, n: init_weights(self.kernel_init, k, (m, n))
        params = {
            "wq": w2d(ks[0], d_model, h * dh).reshape(d_model, h, dh),
            "wk": w2d(ks[1], d_model, hkv * dh).reshape(d_model, hkv, dh),
            "wv": w2d(ks[2], d_model, hkv * dh).reshape(d_model, hkv, dh),
            "wo": w2d(ks[3], h * dh, d_model).reshape(h, dh, d_model),
        }
        return params, {}, tuple(input_shape)

    def _expand_kv(self, t, head_axis: int):
        """Broadcast grouped K/V heads up to num_heads for the kernels."""
        reps = self.num_heads // self.kv_heads
        return t if reps == 1 else jnp.repeat(t, reps, axis=head_axis)

    def apply(self, params, state, x, *, training=False, rng=None,
              segment_ids=None):
        dt = jnp.dtype(self.dtype)
        xc = x.astype(dt)
        impl = self.attn_impl
        if impl == "auto":
            impl = "flash" if backend_is_tpu() else "xla"
        positions = None
        if (self.use_rope
                and impl in ("ring", "ulysses", "ulysses_flash")
                and self.seq_axis_name):
            # global positions for this sequence shard
            idx = jax.lax.axis_index(self.seq_axis_name)
            positions = idx * x.shape[1] + jnp.arange(x.shape[1])

        if impl == "flash":
            # project straight to BHSD: the flash kernel's (B*H, S, D)
            # flattening is then a free reshape — no [B,S,H,D]<->[B,H,S,D]
            # transposes around the kernel in either pass (measured ~15%
            # of LM step time as explicit transpose ops)
            q = jnp.einsum("bsd,dhe->bhse", xc, params["wq"].astype(dt))
            k = jnp.einsum("bsd,dhe->bhse", xc, params["wk"].astype(dt))
            v = jnp.einsum("bsd,dhe->bhse", xc, params["wv"].astype(dt))
            if self.use_rope:
                q = apply_rope(q, positions, layout="bhsd",
                               scale=self.rope_scale)
                k = apply_rope(k, positions, layout="bhsd",
                               scale=self.rope_scale)
            k, v = self._expand_kv(k, 1), self._expand_kv(v, 1)
            from distkeras_tpu.ops.flash_attention import flash_attention
            out = flash_attention(q, k, v, causal=self.causal,
                                  layout="bhsd", window=self.attn_window,
                                  segment_ids=segment_ids)
            y = jnp.einsum("bhse,hed->bsd", out, params["wo"].astype(dt))
            return y.astype(x.dtype), state

        q = jnp.einsum("bsd,dhe->bshe", xc, params["wq"].astype(dt))
        k = jnp.einsum("bsd,dhe->bshe", xc, params["wk"].astype(dt))
        v = jnp.einsum("bsd,dhe->bshe", xc, params["wv"].astype(dt))
        if self.use_rope:
            q = apply_rope(q, positions, scale=self.rope_scale)
            k = apply_rope(k, positions, scale=self.rope_scale)
        k, v = self._expand_kv(k, 2), self._expand_kv(v, 2)
        out = _attention_compute(q, k, v, causal=self.causal,
                                 impl=impl,
                                 axis_name=self.seq_axis_name,
                                 ring_block_size=self.ring_block_size,
                                 window=self.attn_window,
                                 segment_ids=segment_ids)
        y = jnp.einsum("bshe,hed->bsd", out, params["wo"].astype(dt))
        return y.astype(x.dtype), state

    def get_config(self):
        return {"num_heads": self.num_heads, "head_dim": self.head_dim,
                "causal": self.causal, "use_rope": self.use_rope,
                "dtype": self.dtype, "attn_impl": self.attn_impl,
                "seq_axis_name": self.seq_axis_name,
                "kernel_init": self.kernel_init,
                "ring_block_size": self.ring_block_size,
                "num_kv_heads": self.num_kv_heads,
                "rope_scale": self.rope_scale,
                "attn_window": self.attn_window}


@register_layer
class TransformerMLP(Layer):
    """Position-wise MLP with the standard column→row TP-splittable pair."""

    def __init__(self, hidden_dim: int, activation: str = "gelu",
                 dtype: str = "float32",
                 kernel_init: str = "glorot_uniform"):
        self.hidden_dim = int(hidden_dim)
        self.activation = activation
        self.dtype = dtype
        self.kernel_init = kernel_init

    def init(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        params = {
            "w1": init_weights(self.kernel_init, k1, (d, self.hidden_dim)),
            "b1": jnp.zeros((self.hidden_dim,)),
            "w2": init_weights(self.kernel_init, k2, (self.hidden_dim, d)),
            "b2": jnp.zeros((d,)),
        }
        return params, {}, tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)
        act = get_activation(self.activation)
        h = act(x.astype(dt) @ params["w1"].astype(dt) +
                params["b1"].astype(dt))
        y = h @ params["w2"].astype(dt) + params["b2"].astype(dt)
        return y.astype(x.dtype), state

    def get_config(self):
        return {"hidden_dim": self.hidden_dim, "activation": self.activation,
                "dtype": self.dtype, "kernel_init": self.kernel_init}


@register_layer
class TransformerBlock(Layer):
    """Pre-norm residual block: x + attn(norm(x)); x + mlp(norm(x)).

    ``mlp`` may be a ``TransformerMLP`` or a ``models.moe.MoE`` (expert
    parallelism); both expose the same Layer protocol.
    """

    accepts_segment_ids = True

    def __init__(self, num_heads: int, mlp_ratio: int = 4,
                 head_dim: Optional[int] = None, causal: bool = True,
                 use_rope: bool = True, activation: str = "gelu",
                 norm: str = "rmsnorm", dtype: str = "float32",
                 attn_impl: str = "auto",
                 seq_axis_name: Optional[str] = None,
                 mlp_layer: Optional[Layer] = None,
                 dropout_rate: float = 0.0,
                 ring_block_size: Optional[int] = None,
                 num_kv_heads: Optional[int] = None,
                 rope_scale: float = 1.0,
                 attn_window: Optional[int] = None):
        self.num_heads = int(num_heads)
        self.num_kv_heads = num_kv_heads
        self.rope_scale = float(rope_scale)
        self.attn_window = attn_window
        self.mlp_ratio = int(mlp_ratio)
        self.head_dim = head_dim
        self.causal = causal
        self.use_rope = use_rope
        self.activation = activation
        self.norm = norm
        self.dtype = dtype
        self.attn_impl = attn_impl
        self.seq_axis_name = seq_axis_name
        self.dropout_rate = float(dropout_rate)
        self.ring_block_size = ring_block_size
        self._mlp_override = mlp_layer

        norm_cls = RMSNorm if norm == "rmsnorm" else LayerNorm
        self.norm1 = norm_cls()
        self.norm2 = norm_cls()
        self._dropout = Dropout(self.dropout_rate)
        self.attn = MultiHeadAttention(
            num_heads, head_dim=head_dim, causal=causal, use_rope=use_rope,
            dtype=dtype, attn_impl=attn_impl, seq_axis_name=seq_axis_name,
            ring_block_size=ring_block_size, num_kv_heads=num_kv_heads,
            rope_scale=rope_scale, attn_window=attn_window)
        self.mlp = mlp_layer  # resolved in init once d_model is known

    def init(self, rng, input_shape):
        d_model = input_shape[-1]
        if self._mlp_override is None:
            # re-resolve on every init: the hidden dim tracks d_model, so a
            # block instance re-initialized at a different width must not
            # keep the previous width's MLP
            self.mlp = TransformerMLP(self.mlp_ratio * d_model,
                                      activation=self.activation,
                                      dtype=self.dtype)
        ks = jax.random.split(rng, 4)
        p, s = {}, {}
        for name, layer, k in (("norm1", self.norm1, ks[0]),
                               ("attn", self.attn, ks[1]),
                               ("norm2", self.norm2, ks[2]),
                               ("mlp", self.mlp, ks[3])):
            p[name], s[name], _ = layer.init(k, tuple(input_shape))
        return p, s, tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None,
              segment_ids=None):
        new_state = dict(state)
        h, new_state["norm1"] = self.norm1.apply(
            params["norm1"], state["norm1"], x, training=training)
        a, new_state["attn"] = self.attn.apply(
            params["attn"], state["attn"], h, training=training,
            segment_ids=segment_ids)

        def drop(y, key):  # both residual branches share the Dropout layer
            return self._dropout.apply({}, {}, y, training=training,
                                       rng=key)[0]

        # independent keys per consumer: an rng-consuming mlp_layer must not
        # derive keys that collide with the block's own dropout keys
        k_drop1 = k_mlp = k_drop2 = None
        if rng is not None:
            k_drop1, k_mlp, k_drop2 = jax.random.split(rng, 3)
        use_dropout = self.dropout_rate and training and rng is not None
        if use_dropout:
            a = drop(a, k_drop1)
        x = x + a
        h, new_state["norm2"] = self.norm2.apply(
            params["norm2"], state["norm2"], x, training=training)
        m, new_state["mlp"] = self.mlp.apply(
            params["mlp"], state["mlp"], h, training=training, rng=k_mlp)
        if use_dropout:
            m = drop(m, k_drop2)
        return x + m, new_state

    def sub_layers(self):
        return {"norm1": self.norm1, "attn": self.attn,
                "norm2": self.norm2, "mlp": self.mlp}

    def get_config(self):
        cfg = {"num_heads": self.num_heads, "mlp_ratio": self.mlp_ratio,
               "head_dim": self.head_dim, "causal": self.causal,
               "use_rope": self.use_rope, "activation": self.activation,
               "norm": self.norm, "dtype": self.dtype,
               "attn_impl": self.attn_impl,
               "seq_axis_name": self.seq_axis_name,
               "dropout_rate": self.dropout_rate,
               "ring_block_size": self.ring_block_size,
               "num_kv_heads": self.num_kv_heads,
               "rope_scale": self.rope_scale,
               "attn_window": self.attn_window}
        if self._mlp_override is not None:
            cfg["mlp_layer"] = layer_spec(self._mlp_override)
        return cfg

    @classmethod
    def from_config(cls, config):
        config = dict(config)
        spec = config.pop("mlp_layer", None)
        if spec is not None:
            config["mlp_layer"] = layer_from_spec(spec)
        return cls(**config)
