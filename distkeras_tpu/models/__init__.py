"""Model substrate: layers, containers, serialization, model zoo."""

from distkeras_tpu.models.core import (  # noqa: F401
    LAYER_REGISTRY, Layer, Model, Sequential, register_layer)
from distkeras_tpu.models.layers import (  # noqa: F401
    ACTIVATIONS, Activation, AveragePooling2D, BatchNorm, Conv1D, Conv2D,
    Conv2DTranspose, Dense, DepthwiseConv2D, Dropout, Embedding, Flatten,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GroupNorm,
    MaxPooling2D, Reshape, SeparableConv2D, UpSampling2D, get_activation)
from distkeras_tpu.models.blocks import Residual, WideAndDeep  # noqa: F401
from distkeras_tpu.models.attention import (  # noqa: F401
    LayerNorm, MultiHeadAttention, PositionalEmbedding, RMSNorm,
    TransformerBlock, TransformerMLP)
from distkeras_tpu.models.recurrent import (  # noqa: F401
    GRU, LSTM, Bidirectional)
from distkeras_tpu.models.moe import MoE  # noqa: F401  (registers 'MoE')
from distkeras_tpu.models import zoo  # noqa: F401
from distkeras_tpu.models.serialization import (  # noqa: F401
    deserialize_model, load_model, save_model, serialize_model)
from distkeras_tpu.models.quantize import (  # noqa: F401
    QuantizedModel, dequantize_model, quantize_model)
from distkeras_tpu.models.decoding import generate  # noqa: F401
