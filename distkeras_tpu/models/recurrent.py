"""Recurrent layers (LSTM / GRU / Bidirectional) built on ``lax.scan``.

The reference's examples train (Bi)LSTM Keras models and run them through the
``Predictor`` path (BASELINE config 5: batched BiLSTM inference). TPU-first
implementation notes:
  * The time loop is a single ``lax.scan`` — traced once, compiled once; no
    Python-level unrolling, static sequence length.
  * The four LSTM gate matmuls are fused into one ``[in+hidden, 4*units]``
    matmul per step so the MXU sees one large GEMM instead of eight small
    ones.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.core import Layer, register_layer
from distkeras_tpu.models.layers import get_activation, init_weights


@register_layer
class LSTM(Layer):
    """LSTM over inputs shaped ``[batch, time, features]``.

    ``return_sequences=False`` (default, Keras-compatible) yields the final
    hidden state ``[batch, units]``; ``True`` yields ``[batch, time, units]``.
    """

    def __init__(self, units: int, return_sequences: bool = False,
                 reverse: bool = False, kernel_init: str = "glorot_uniform",
                 dtype: str = "float32"):
        self.units = int(units)
        self.return_sequences = bool(return_sequences)
        self.reverse = bool(reverse)
        self.kernel_init = kernel_init
        self.dtype = dtype

    def init(self, rng, input_shape):
        t, f = input_shape
        k1, k2 = jax.random.split(rng)
        params = {
            # fused input->gates and hidden->gates kernels, gate order ifgo
            "wx": init_weights(self.kernel_init, k1, (f, 4 * self.units)),
            "wh": init_weights("glorot_uniform", k2,
                               (self.units, 4 * self.units)),
            # forget-gate bias init to 1.0 (standard trick, helps gradients)
            "b": jnp.concatenate([
                jnp.zeros((self.units,)), jnp.ones((self.units,)),
                jnp.zeros((2 * self.units,))]),
        }
        out = (t, self.units) if self.return_sequences else (self.units,)
        return params, {}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)
        b = x.shape[0]
        wx, wh, bias = (params["wx"].astype(dt), params["wh"].astype(dt),
                        params["b"].astype(dt))
        # Pre-compute all input projections in one big [B*T, 4U] GEMM.
        xproj = jnp.matmul(x.astype(dt), wx) + bias  # [B, T, 4U]
        xproj = jnp.swapaxes(xproj, 0, 1)            # time-major for scan

        def step(carry, xp):
            h, c = carry
            gates = xp + jnp.matmul(h, wh)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                       jax.nn.sigmoid(o))
            g = jnp.tanh(g)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h

        h0 = jnp.zeros((b, self.units), dt)
        (h, _), hs = lax.scan(step, (h0, h0), xproj, reverse=self.reverse)
        if self.return_sequences:
            out = jnp.swapaxes(hs, 0, 1)
        else:
            # for a reversed pass the "final" state is still the scan carry
            out = h
        return out, state  # stays in compute dtype (layers.Dense policy)

    def get_config(self):
        return {"units": self.units, "return_sequences": self.return_sequences,
                "reverse": self.reverse, "kernel_init": self.kernel_init,
                "dtype": self.dtype}


@register_layer
class GRU(Layer):
    """GRU over ``[batch, time, features]`` with fused gate matmuls."""

    def __init__(self, units: int, return_sequences: bool = False,
                 reverse: bool = False, kernel_init: str = "glorot_uniform",
                 dtype: str = "float32"):
        self.units = int(units)
        self.return_sequences = bool(return_sequences)
        self.reverse = bool(reverse)
        self.kernel_init = kernel_init
        self.dtype = dtype

    def init(self, rng, input_shape):
        t, f = input_shape
        k1, k2 = jax.random.split(rng)
        params = {
            "wx": init_weights(self.kernel_init, k1, (f, 3 * self.units)),
            "wh": init_weights("glorot_uniform", k2,
                               (self.units, 3 * self.units)),
            "b": jnp.zeros((3 * self.units,)),
        }
        out = (t, self.units) if self.return_sequences else (self.units,)
        return params, {}, out

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)
        b = x.shape[0]
        wx, wh, bias = (params["wx"].astype(dt), params["wh"].astype(dt),
                        params["b"].astype(dt))
        xproj = jnp.matmul(x.astype(dt), wx) + bias
        xproj = jnp.swapaxes(xproj, 0, 1)
        u = self.units

        def step(h, xp):
            hp = jnp.matmul(h, wh)
            r = jax.nn.sigmoid(xp[..., :u] + hp[..., :u])
            z = jax.nn.sigmoid(xp[..., u:2 * u] + hp[..., u:2 * u])
            n = jnp.tanh(xp[..., 2 * u:] + r * hp[..., 2 * u:])
            h = (1 - z) * n + z * h
            return h, h

        h0 = jnp.zeros((b, u), dt)
        h, hs = lax.scan(step, h0, xproj, reverse=self.reverse)
        out = jnp.swapaxes(hs, 0, 1) if self.return_sequences else h
        return out, state  # stays in compute dtype (layers.Dense policy)

    def get_config(self):
        return {"units": self.units, "return_sequences": self.return_sequences,
                "reverse": self.reverse, "kernel_init": self.kernel_init,
                "dtype": self.dtype}


@register_layer
class Bidirectional(Layer):
    """Runs a forward and a backward copy of an LSTM/GRU and concatenates.

    Keras ``Bidirectional(LSTM(...))`` equivalent, used by the BiLSTM
    inference baseline (BASELINE config 5).
    """

    def __init__(self, layer=None, **layer_config):
        if layer is None:
            # from_config path: rebuild from serialized sub-layer spec
            from distkeras_tpu.models.core import layer_from_spec
            layer = layer_from_spec(layer_config.pop("layer_spec"))
        self.forward = layer
        import copy
        self.backward = copy.copy(layer)
        self.backward.reverse = True

    def init(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        pf, sf, of = self.forward.init(k1, input_shape)
        pb, sb, ob = self.backward.init(k2, input_shape)
        out = tuple(of[:-1]) + (of[-1] + ob[-1],)
        return {"forward": pf, "backward": pb}, \
            {"forward": sf, "backward": sb}, out

    def sub_layers(self):
        return {"forward": self.forward, "backward": self.backward}

    def apply(self, params, state, x, *, training=False, rng=None):
        yf, sf = self.forward.apply(params["forward"], state["forward"], x,
                                    training=training, rng=rng)
        # NOTE: lax.scan(reverse=True) keeps stacked outputs positionally
        # aligned with inputs, so no flip is needed for return_sequences.
        yb, sb = self.backward.apply(params["backward"], state["backward"], x,
                                     training=training, rng=rng)
        return jnp.concatenate([yf, yb], axis=-1), \
            {"forward": sf, "backward": sb}

    def get_config(self):
        from distkeras_tpu.models.core import layer_spec
        return {"layer_spec": layer_spec(self.forward)}
