"""Core model substrate: Layer protocol, Sequential container, Model handle.

This replaces the reference's dependency on Keras for per-worker compute
(reference: ``distkeras/workers.py :: Worker.prepare_model`` deserializes and
compiles a Keras model inside every Spark executor). Here a model is a pure
spec (layer list) plus pytree variables; ``apply`` is a pure function suitable
for ``jax.jit`` / ``jax.grad`` / ``shard_map``.

Design notes (TPU-first):
  * Variables are split into ``params`` (differentiated) and ``state``
    (non-differentiated collections such as BatchNorm running stats). Both are
    plain pytrees (lists of dicts aligned with the layer list), so they shard
    transparently under ``jax.sharding`` and stack transparently under
    ``vmap`` (used by EnsembleTrainer).
  * ``apply`` is functional: it returns ``(y, new_state)``; nothing mutates.
  * Shapes are static: ``init`` threads a concrete ``input_shape`` through the
    layer stack once, so everything under ``jit`` has static shapes and XLA
    can tile matmuls/convs onto the MXU.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Registry: layer class name -> class, used by serialization to rebuild specs.
LAYER_REGISTRY: Dict[str, type] = {}


# Reserved state-dict key: a layer may publish a scalar auxiliary TRAINING
# loss (e.g. the MoE router balance loss) under this key in its returned
# state; ``collect_aux_losses`` below sums every occurrence, and
# ``parallel.worker.make_train_step`` adds that sum to the optimized loss.
# State is the one channel that already flows out of ``apply`` through
# every jit/vmap/shard_map wrapper, so regularizer-style terms need no
# signature change anywhere.
AUX_LOSS_KEY = "__aux_loss__"


def collect_aux_losses(state) -> jax.Array:
    """Sum of every ``AUX_LOSS_KEY`` leaf in a state pytree (0.0 if none)."""
    total = 0.0
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    for path, leaf in flat:
        if any(getattr(k, "key", None) == AUX_LOSS_KEY for k in path):
            total = total + leaf
    return total


def user_float(y: jax.Array) -> jax.Array:
    """User-facing output dtype policy: low-precision compute dtypes
    (bf16/f16) stay internal — predictions handed back to the host are f32.
    Non-float outputs (int predictions, bools) pass through untouched."""
    if jnp.issubdtype(y.dtype, jnp.floating) and y.dtype != jnp.float32:
        return y.astype(jnp.float32)
    return y


def register_layer(cls: type) -> type:
    """Class decorator adding a Layer subclass to the serialization registry."""
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_spec(layer):
    """Layer -> registry spec dict (None passes through) — the one encoding
    every container (Sequential/Residual/TransformerBlock/...) uses."""
    if layer is None:
        return None
    return {"class": layer.name, "config": layer.get_config()}


def layer_from_spec(spec):
    """Registry spec dict -> Layer (None passes through)."""
    if spec is None:
        return None
    return LAYER_REGISTRY[spec["class"]].from_config(spec["config"])


class Layer:
    """Base layer: a pure init/apply pair plus a JSON-able config.

    Subclasses implement:
      init(rng, input_shape) -> (params, state, output_shape)
      apply(params, state, x, *, training, rng) -> (y, new_state)
      get_config() -> dict of constructor kwargs (JSON-serializable)
    ``input_shape``/``output_shape`` exclude the batch dimension.
    """

    #: Keras-style freezing: set False BEFORE training and the layer's
    #: params (its whole subtree, for containers) receive no updates —
    #: every trainer masks the gradients, so optimizer moments stay zero
    #: too. Like Keras, this is a training-time attribute, not part of
    #: the serialized architecture config.
    trainable: bool = True

    def init(self, rng: jax.Array, input_shape: Tuple[int, ...]):
        return {}, {}, input_shape

    def apply(self, params, state, x, *, training: bool = False,
              rng: Optional[jax.Array] = None):
        return x, state

    def get_config(self) -> Dict[str, Any]:
        return {}

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> "Layer":
        return cls(**config)

    @property
    def name(self) -> str:
        return type(self).__name__

    def __repr__(self) -> str:
        cfg = ", ".join(f"{k}={v!r}" for k, v in self.get_config().items())
        return f"{self.name}({cfg})"


@register_layer
class Sequential(Layer):
    """Ordered stack of layers — the Keras ``Sequential`` equivalent.

    The reference builds Keras Sequential models in every example and ships
    them serialized to executors (reference: ``distkeras/utils.py ::
    serialize_keras_model``). Here the spec is pure Python data; variables are
    created explicitly by ``init`` and travel separately.
    """

    def __init__(self, layers: Optional[Sequence[Layer]] = None):
        self.layers: List[Layer] = list(layers) if layers else []

    def add(self, layer: Layer) -> "Sequential":
        self.layers.append(layer)
        return self

    def init(self, rng, input_shape):
        params, state = [], []
        shape = tuple(input_shape)
        for layer in self.layers:
            rng, sub = jax.random.split(rng)
            p, s, shape = layer.init(sub, shape)
            params.append(p)
            state.append(s)
        return params, state, shape

    @property
    def accepts_segment_ids(self) -> bool:
        return any(getattr(l, "accepts_segment_ids", False)
                   for l in self.layers)

    def apply(self, params, state, x, *, training=False, rng=None,
              segment_ids=None):
        """``segment_ids`` ([B, S] int, packed/variable-length sequences)
        is forwarded to layers that declare ``accepts_segment_ids``
        (TransformerBlock -> attention masking; containers like Remat /
        Residual / nested Sequential forward recursively); other layers
        are position-wise and need no mask — the LOSS masks padded
        positions (``losses.masked_sparse_categorical_crossentropy_
        from_logits``). Passing segment_ids into a stack where NO layer
        accepts them is an error, not a silent unmasked run.
        """
        if segment_ids is not None and not self.accepts_segment_ids:
            raise ValueError(
                "segment_ids passed, but no layer in this Sequential "
                "accepts them (packed-sequence masking needs a "
                "TransformerBlock-family layer)")
        new_state = []
        for i, layer in enumerate(self.layers):
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            if segment_ids is not None and \
                    getattr(layer, "accepts_segment_ids", False):
                x, s = layer.apply(params[i], state[i], x,
                                   training=training, rng=sub,
                                   segment_ids=segment_ids)
            else:
                x, s = layer.apply(params[i], state[i], x,
                                   training=training, rng=sub)
            new_state.append(s)
        return x, new_state

    def get_config(self):
        return {"layers": [layer_spec(l) for l in self.layers]}

    @classmethod
    def from_config(cls, config):
        return cls([layer_from_spec(spec) for spec in config["layers"]])


class Model:
    """A built model: spec + variables + loss/optimizer metadata.

    Plays the role of a compiled Keras model in the reference API surface
    (what ``Trainer.train`` returns; what ``Predictor`` consumes). The object
    is a thin handle — all compute goes through the pure functions so that
    trainers can jit/shard them freely.
    """

    def __init__(self, module: Layer, params, state, input_shape,
                 output_shape):
        self.module = module
        self.params = params
        self.state = state
        self.input_shape = tuple(input_shape)
        self.output_shape = tuple(output_shape)
        self._jit_fwd = None  # cached jitted forward for predict()

    # -- construction -----------------------------------------------------
    @classmethod
    def build(cls, module: Layer, input_shape: Tuple[int, ...],
              rng: Optional[jax.Array] = None, seed: int = 0) -> "Model":
        if rng is None:
            rng = jax.random.PRNGKey(seed)
        # Jit the whole init: one compiled program instead of hundreds of
        # small eager dispatches (a deep ResNet has ~500 init ops; eager
        # dispatch per op is prohibitively slow on remote/TPU backends).
        captured = {}

        def initf(rng):
            params, state, out_shape = module.init(rng, tuple(input_shape))
            captured["out_shape"] = out_shape  # static python tuple
            return params, state

        params, state = jax.jit(initf)(rng)
        return cls(module, params, state, input_shape, captured["out_shape"])

    # -- compute ----------------------------------------------------------
    def apply(self, params, state, x, *, training=False, rng=None):
        return self.module.apply(params, state, x, training=training, rng=rng)

    def predict(self, x, batch_size: Optional[int] = None) -> np.ndarray:
        """Convenience host-side inference (see inference.predictors for the
        sharded/batched path the reference's Predictor corresponds to)."""
        x = jnp.asarray(x)
        if self._jit_fwd is None:
            self._jit_fwd = jax.jit(lambda p, s, b: user_float(
                self.module.apply(p, s, b, training=False)[0]))
        fn = self._jit_fwd
        if batch_size is None:
            return np.asarray(fn(self.params, self.state, x))
        n = x.shape[0]
        outs = []
        for i in range(0, n, batch_size):
            xb = x[i:i + batch_size]
            pad = batch_size - xb.shape[0]
            if pad:  # pad the remainder so every call shares ONE jit shape
                xb = jnp.concatenate(
                    [xb, jnp.zeros((pad,) + xb.shape[1:], xb.dtype)])
            yb = np.asarray(fn(self.params, self.state, xb))
            outs.append(yb[:batch_size - pad] if pad else yb)
        return np.concatenate(outs, axis=0)

    # -- Keras-style conveniences ----------------------------------------
    def fit(self, x, y=None, *, optimizer="sgd", loss="mean_squared_error",
            batch_size: int = 32, epochs: int = 1, metrics=None,
            validation_data=None, validation_split: float = 0.0,
            seed: int = 0, **trainer_kwargs):
        """Keras-style ``model.fit`` — a thin wrapper over SingleTrainer
        (use the trainer classes directly for distributed training).

        ``x`` may be a ``data.Dataset`` (with the default feature/label
        columns) or a feature array with ``y`` labels. Trains IN PLACE
        (this model's params/state are updated) and returns the History.

        ``validation_split``: Keras semantics — hold out the LAST fraction
        of the (unshuffled) data as validation (mutually exclusive with
        ``validation_data``; not available for ShardedDataset).
        """
        from distkeras_tpu.data.dataset import Dataset
        from distkeras_tpu.data.sharded import ShardedDataset
        from distkeras_tpu.parallel.trainers import SingleTrainer

        if isinstance(x, (Dataset, ShardedDataset)):
            ds = x
        else:
            if y is None:
                raise ValueError("fit(x, y): y is required for array input")
            ds = Dataset({"features": np.asarray(x), "label": np.asarray(y)})
        if validation_split:
            if validation_data is not None:
                raise ValueError(
                    "pass validation_split OR validation_data, not both")
            if not 0.0 < validation_split < 1.0:
                raise ValueError(
                    f"validation_split must be in (0, 1), got "
                    f"{validation_split}")
            if isinstance(ds, ShardedDataset):
                raise ValueError(
                    "validation_split needs in-memory data; hold out "
                    "shards yourself for a ShardedDataset")
            ds, validation_data = ds.split(1.0 - validation_split)
        trainer = SingleTrainer(
            self, worker_optimizer=optimizer, loss=loss,
            batch_size=batch_size, num_epoch=epochs, metrics=metrics,
            validation_data=validation_data, seed=seed, **trainer_kwargs)
        trained = trainer.train(ds)
        self.params, self.state = trained.params, trained.state
        self._jit_fwd = None  # old closure captured nothing, but be tidy
        return trainer.get_history()

    def evaluate(self, x, y=None, *, loss="mean_squared_error",
                 metrics=("accuracy",), batch_size: int = 1024,
                 features_col: str = "features", label_col: str = "label"):
        """Keras-style ``model.evaluate``: ``{"loss": ..., metric: ...}``
        over the full set (batched host-side forward)."""
        from distkeras_tpu.data.dataset import Dataset, coerce_column
        from distkeras_tpu.data.sharded import ShardedDataset
        from distkeras_tpu.ops.losses import get_loss
        from distkeras_tpu.ops.metrics import get_metric, metric_name

        if isinstance(x, ShardedDataset):
            # shard-by-shard, weighted by shard size — only one shard in
            # host memory at a time (matches the out-of-core fit path).
            # Only row-decomposable metrics are EXACT under size-weighted
            # averaging; pooled metrics (macro precision/recall/f1) are
            # not, so refuse rather than return a plausible wrong number.
            decomposable = {"accuracy", "top_5_accuracy", "mse"}
            bad = [metric_name(m) for m in (metrics or ())
                   if metric_name(m) not in decomposable]
            if bad:
                raise ValueError(
                    f"metrics {bad} are not decomposable across shards "
                    "(a size-weighted mean of per-shard macro scores is "
                    "not the pooled score); evaluate them on an in-memory "
                    "Dataset, or use decomposable metrics "
                    f"({sorted(decomposable)}) here")
            totals, n_total = {}, 0
            for i in range(x.num_shards):
                shard = x.load_shard(i)
                res = self.evaluate(shard, loss=loss, metrics=metrics,
                                    batch_size=batch_size,
                                    features_col=features_col,
                                    label_col=label_col)
                n = len(shard)
                n_total += n
                for k, v in res.items():
                    totals[k] = totals.get(k, 0.0) + n * v
            return {k: v / n_total for k, v in totals.items()}
        if isinstance(x, Dataset):
            X, yv = x.arrays(features_col, label_col)
            if yv is None:
                raise ValueError(
                    f"evaluate(dataset): label column {label_col!r} not in "
                    f"dataset (columns: {x.columns})")
        else:
            if y is None:
                raise ValueError("evaluate(x, y): y is required")
            X, yv = coerce_column(x), coerce_column(y)
        preds = self.predict(X, batch_size=batch_size)
        res = {"loss": float(get_loss(loss)(yv, jnp.asarray(preds)))}
        for m in (metrics or ()):
            res[metric_name(m)] = float(get_metric(m)(yv, preds))
        return res

    def save(self, path: str, quantize: bool = False) -> None:
        """Keras-style ``model.save`` (see ``models.serialization
        .save_model``; writes ``<path>.json`` + ``<path>.npz``)."""
        from distkeras_tpu.models.serialization import save_model
        save_model(self, path, quantize=quantize)

    @staticmethod
    def load(path: str, keep_quantized: bool = False):
        """Keras-style loader (``models.serialization.load_model``)."""
        from distkeras_tpu.models.serialization import load_model
        return load_model(path, keep_quantized=keep_quantized)

    def generate(self, prompts, max_new_tokens: int, **kwargs):
        """Keras-style convenience over ``models.decoding.generate`` (KV-
        cache autoregressive sampling for transformer-LM-shaped models)."""
        from distkeras_tpu.models.decoding import generate
        return generate(self, prompts, max_new_tokens, **kwargs)

    def get_weights(self) -> List[np.ndarray]:
        """Keras-style flat weight list: params THEN state leaves (host
        numpy, pytree leaf order). State is included so BatchNorm running
        stats round-trip — as Keras's moving_mean/moving_variance do."""
        return [np.asarray(w) for w in
                jax.tree_util.tree_leaves((self.params, self.state))]

    def set_weights(self, weights: Sequence[np.ndarray]) -> None:
        """Keras-style inverse of :meth:`get_weights` — shapes must match
        leaf-for-leaf."""
        leaves, treedef = jax.tree_util.tree_flatten(
            (self.params, self.state))
        if len(weights) != len(leaves):
            raise ValueError(
                f"set_weights got {len(weights)} arrays, model has "
                f"{len(leaves)} weight tensors (params + state)")
        new = []
        for i, (leaf, w) in enumerate(zip(leaves, weights)):
            w = jnp.asarray(w, dtype=leaf.dtype)
            if tuple(w.shape) != tuple(leaf.shape):
                raise ValueError(
                    f"set_weights: tensor {i} has shape {w.shape}, "
                    f"expected {leaf.shape}")
            new.append(w)
        self.params, self.state = jax.tree_util.tree_unflatten(treedef, new)
        self._jit_fwd = None

    # -- bookkeeping ------------------------------------------------------
    def num_params(self) -> int:
        return sum(int(np.prod(p.shape))
                   for p in jax.tree_util.tree_leaves(self.params))

    def summary(self) -> str:
        """Keras-style per-layer table (layer, config, params). Printed
        AND returned."""
        rows = []
        if isinstance(self.module, Sequential):
            for layer, p in zip(self.module.layers, self.params):
                n = sum(int(np.prod(l.shape))
                        for l in jax.tree_util.tree_leaves(p))
                rows.append((repr(layer), n))
        else:
            rows.append((repr(self.module), self.num_params()))
        name_w = min(72, max([len(r[0]) for r in rows] + [10]))
        lines = [f"Model: in={self.input_shape} out={self.output_shape}",
                 "-" * (name_w + 14)]
        for name, n in rows:
            disp = name if len(name) <= name_w else name[:name_w - 1] + "…"
            lines.append(f"{disp:<{name_w}}  {n:>12,}")
        lines.append("-" * (name_w + 14))
        lines.append(f"{'total':<{name_w}}  {self.num_params():>12,}")
        out = "\n".join(lines)
        print(out)
        return out

    def replace(self, params=None, state=None) -> "Model":
        return Model(self.module,
                     params if params is not None else self.params,
                     state if state is not None else self.state,
                     self.input_shape, self.output_shape)

    def __repr__(self):
        return (f"Model({self.module.name}, in={self.input_shape}, "
                f"out={self.output_shape}, params={self.num_params():,})")


def trainable_mask(module: Layer, tree):
    """Boolean pytree matching ``tree`` (params OR state — containers lay
    both out identically): True where updates may flow.

    Returns ``None`` when every layer is trainable (the common case — the
    trainers then skip the masking entirely). Keras container semantics:
    a layer with ``trainable = False`` freezes its WHOLE subtree;
    ``Sequential`` recurses per sublayer, and composite containers that
    implement ``sub_layers() -> {subtree_key: Layer}`` (Residual,
    TransformerBlock, ...) recurse through it, so freezing e.g. only a
    block's attention works. Custom containers without ``sub_layers`` are
    atomic: only their own flag counts.
    """
    def walk(layer, sub, enabled):
        enabled = enabled and getattr(layer, "trainable", True)
        if isinstance(layer, Sequential):
            return [walk(l, p, enabled)
                    for l, p in zip(layer.layers, sub)]
        subs = getattr(layer, "sub_layers", None)
        if callable(subs) and isinstance(sub, dict):
            named = subs()
            return {key: (walk(named[key], child, enabled)
                          if key in named
                          else jax.tree_util.tree_map(
                              lambda _: enabled, child))
                    for key, child in sub.items()}
        return jax.tree_util.tree_map(lambda _: enabled, sub)

    mask = walk(module, tree, True)
    if all(jax.tree_util.tree_leaves(mask)):
        return None
    return mask
