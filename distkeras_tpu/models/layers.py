"""Standard layers (Keras-equivalent surface, TPU-first internals).

Covers the layer vocabulary the reference's examples use to build models
(Dense/Conv2D/MaxPooling2D/Flatten/Dropout/Activation/Embedding — reference:
``examples/`` MNIST + ATLAS notebooks build Keras Sequential models from
exactly these), plus BatchNorm for the ResNet-50 north-star config.

TPU notes:
  * Conv uses NHWC with ``lax.conv_general_dilated`` — XLA's native layout for
    TPU convolutions (maps onto the MXU).
  * Compute dtype is configurable per layer (``dtype=jnp.bfloat16``) while
    params stay float32 — the standard TPU mixed-precision recipe.
  * Everything is shape-static and control-flow-free so layers fuse cleanly
    under jit.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distkeras_tpu.models.core import Layer, register_layer

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

ACTIVATIONS = {
    "linear": lambda x: x,
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "tanh": jnp.tanh,
    "sigmoid": jax.nn.sigmoid,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "elu": jax.nn.elu,
    "leaky_relu": jax.nn.leaky_relu,
    "softplus": jax.nn.softplus,
}


def get_activation(name):
    if callable(name):
        return name
    if name is None:
        return ACTIVATIONS["linear"]
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation {name!r}; known: {sorted(ACTIVATIONS)}")


# ---------------------------------------------------------------------------
# initializers (Keras-compatible names)
# ---------------------------------------------------------------------------

def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels HWIO: receptive field * channels
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


def init_weights(name: str, rng, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    if name == "zeros":
        return jnp.zeros(shape, dtype)
    if name == "ones":
        return jnp.ones(shape, dtype)
    if name == "glorot_uniform":
        limit = np.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -limit, limit)
    if name == "glorot_normal":
        std = np.sqrt(2.0 / (fan_in + fan_out))
        return jax.random.normal(rng, shape, dtype) * std
    if name == "he_normal":
        std = np.sqrt(2.0 / fan_in)
        return jax.random.normal(rng, shape, dtype) * std
    if name == "he_uniform":
        limit = np.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -limit, limit)
    if name == "lecun_normal":
        std = np.sqrt(1.0 / fan_in)
        return jax.random.normal(rng, shape, dtype) * std
    if name == "uniform_scaling":
        return jax.random.uniform(rng, shape, dtype, -0.05, 0.05)
    raise ValueError(f"Unknown initializer {name!r}")


# ---------------------------------------------------------------------------
# dense / activation / dropout / reshape
# ---------------------------------------------------------------------------

@register_layer
class Dense(Layer):
    """Fully-connected layer. Keras ``Dense`` equivalent.

    ``dtype`` selects the compute/matmul dtype (bf16 recommended on TPU);
    parameters are stored float32 and cast at apply time.
    """

    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_init: str = "glorot_uniform", dtype: str = "float32"):
        self.units = int(units)
        get_activation(activation)  # fail at construction, not first forward
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_init = kernel_init
        self.dtype = dtype

    def init(self, rng, input_shape):
        in_dim = input_shape[-1]
        params = {"kernel": init_weights(self.kernel_init, rng,
                                         (in_dim, self.units))}
        if self.use_bias:
            params["bias"] = jnp.zeros((self.units,))
        return params, {}, tuple(input_shape[:-1]) + (self.units,)

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)
        y = jnp.matmul(x.astype(dt), params["kernel"].astype(dt))
        if self.use_bias:
            y = y + params["bias"].astype(dt)
        y = get_activation(self.activation)(y)
        # mixed-precision policy: params live in f32, activations FLOW in
        # the compute dtype — bf16 activations halve HBM traffic between
        # fusions (measured 3.3x on ResNet-50/v5e); f32 casts happen only
        # where numerics demand it (norm stats, softmax, losses)
        return y, state

    def get_config(self):
        return {"units": self.units, "activation": self.activation,
                "use_bias": self.use_bias, "kernel_init": self.kernel_init,
                "dtype": self.dtype}


@register_layer
class Activation(Layer):
    def __init__(self, activation: str):
        get_activation(activation)  # fail at construction, not first forward
        self.activation = activation

    def apply(self, params, state, x, *, training=False, rng=None):
        return get_activation(self.activation)(x), state

    def get_config(self):
        return {"activation": self.activation}


@register_layer
class Dropout(Layer):
    """Inverted dropout; identity when not training or rng is None."""

    def __init__(self, rate: float):
        self.rate = float(rate)

    def apply(self, params, state, x, *, training=False, rng=None):
        if not training or rng is None or self.rate <= 0.0:
            return x, state
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0), state

    def get_config(self):
        return {"rate": self.rate}


@register_layer
class Flatten(Layer):
    def init(self, rng, input_shape):
        return {}, {}, (int(np.prod(input_shape)),)

    def apply(self, params, state, x, *, training=False, rng=None):
        return x.reshape(x.shape[0], -1), state


@register_layer
class Reshape(Layer):
    def __init__(self, target_shape: Sequence[int]):
        self.target_shape = tuple(int(d) for d in target_shape)

    def init(self, rng, input_shape):
        return {}, {}, self.target_shape

    def apply(self, params, state, x, *, training=False, rng=None):
        return x.reshape((x.shape[0],) + self.target_shape), state

    def get_config(self):
        return {"target_shape": list(self.target_shape)}


# ---------------------------------------------------------------------------
# convolution / pooling (NHWC)
# ---------------------------------------------------------------------------

def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


class _ConvND(Layer):
    """Shared N-D convolution core; subclasses fix the spatial rank /
    channels-last ``dimension_numbers`` (XLA's native TPU conv layout) and
    may override the kernel shape, output-channel count, and conv
    primitive (depthwise, transpose)."""

    _dims: tuple  # e.g. ("NHWC", "HWIO", "NHWC")

    def __init__(self, filters: int, kernel_size, strides=1, padding="SAME",
                 activation=None, use_bias: bool = True,
                 kernel_init: str = "he_normal", dtype: str = "float32"):
        get_activation(activation)  # fail at construction, not first forward
        self.filters = int(filters)
        self.kernel_size = self._spatial(kernel_size)
        self.strides = self._spatial(strides)
        self.padding = padding.upper()
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_init = kernel_init
        self.dtype = dtype

    def _spatial(self, v) -> tuple:
        """Normalize an int / sequence to the layer's spatial rank — a bare
        int broadcasts; a sequence must match the rank exactly (a clear
        error here beats an opaque conv shape mismatch at build time)."""
        n = len(self._dims[0]) - 2  # spatial rank from the layout string
        if isinstance(v, (tuple, list)):
            if len(v) != n:
                raise ValueError(
                    f"{type(self).__name__} expects {n} spatial dim(s), "
                    f"got {v}")
            return tuple(int(e) for e in v)
        return (int(v),) * n

    # -- subclass hooks -----------------------------------------------------
    def _kernel_shape(self, c: int) -> tuple:
        return self.kernel_size + (c, self.filters)

    def _out_channels(self, c: int) -> int:
        return self.filters

    def _conv(self, x, k):
        return lax.conv_general_dilated(
            x, k, self.strides, self.padding, dimension_numbers=self._dims)

    # -- shared body --------------------------------------------------------
    def init(self, rng, input_shape):
        c = input_shape[-1]
        kshape = self._kernel_shape(c)
        params = {"kernel": init_weights(self.kernel_init, rng, kshape)}
        if self.use_bias:
            params["bias"] = jnp.zeros((self._out_channels(c),))
        out = jax.eval_shape(
            self._conv,
            jax.ShapeDtypeStruct((1,) + tuple(input_shape), jnp.float32),
            jax.ShapeDtypeStruct(kshape, jnp.float32))
        return params, {}, tuple(out.shape[1:])

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)
        y = self._conv(x.astype(dt), params["kernel"].astype(dt))
        if self.use_bias:
            y = y + params["bias"].astype(dt)
        y = get_activation(self.activation)(y)
        return y, state  # stays in compute dtype (see Dense.apply)

    def get_config(self):
        ks, st = self.kernel_size, self.strides
        return {"filters": self.filters,
                "kernel_size": list(ks) if len(ks) > 1 else ks[0],
                "strides": list(st) if len(st) > 1 else st[0],
                "padding": self.padding,
                "activation": self.activation, "use_bias": self.use_bias,
                "kernel_init": self.kernel_init, "dtype": self.dtype}


@register_layer
class Conv2D(_ConvND):
    """2-D convolution over [B, H, W, C]."""

    _dims = ("NHWC", "HWIO", "NHWC")


@register_layer
class Conv1D(_ConvND):
    """1-D convolution over [B, W, C] (text-CNN / signal models)."""

    _dims = ("NWC", "WIO", "NWC")


@register_layer
class DepthwiseConv2D(_ConvND):
    """Depthwise 2-D convolution (each input channel convolved with its
    own ``depth_multiplier`` filters) — the MobileNet-era Keras staple.
    Lowered with ``feature_group_count = C`` so XLA picks its native
    grouped-conv path."""

    _dims = ("NHWC", "HWIO", "NHWC")

    def __init__(self, kernel_size, strides=1, padding: str = "SAME",
                 depth_multiplier: int = 1, activation=None,
                 use_bias: bool = True, kernel_init: str = "he_normal",
                 dtype: str = "float32"):
        # filters is unused (output width derives from C × multiplier) but
        # kept so the base get_config can read it before we pop the key
        super().__init__(filters=0, kernel_size=kernel_size,
                         strides=strides, padding=padding,
                         activation=activation, use_bias=use_bias,
                         kernel_init=kernel_init, dtype=dtype)
        self.depth_multiplier = int(depth_multiplier)

    def _kernel_shape(self, c):
        # HWIO with I=1 per group (feature_group_count = C)
        return self.kernel_size + (1, c * self.depth_multiplier)

    def _out_channels(self, c):
        return c * self.depth_multiplier

    def _conv(self, x, k):
        return lax.conv_general_dilated(
            x, k, self.strides, self.padding, dimension_numbers=self._dims,
            feature_group_count=x.shape[-1])

    def get_config(self):
        cfg = super().get_config()
        cfg.pop("filters")
        cfg["depth_multiplier"] = self.depth_multiplier
        return cfg


@register_layer
class SeparableConv2D(Layer):
    """Depthwise-separable convolution (Keras ``SeparableConv2D``): a
    ``DepthwiseConv2D`` followed by a 1×1 pointwise ``Conv2D`` — the
    MobileNet/Xception building block as one layer."""

    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "SAME", depth_multiplier: int = 1,
                 activation=None, use_bias: bool = True,
                 kernel_init: str = "he_normal", dtype: str = "float32"):
        self.filters = int(filters)
        self.depth_multiplier = int(depth_multiplier)
        self.activation = activation
        self.use_bias = use_bias
        self.kernel_init = kernel_init
        self.dtype = dtype
        self.depthwise = DepthwiseConv2D(
            kernel_size, strides=strides, padding=padding,
            depth_multiplier=depth_multiplier, use_bias=False,
            kernel_init=kernel_init, dtype=dtype)
        # activation/bias live on the pointwise half, Keras-style
        self.pointwise = Conv2D(filters, 1, activation=activation,
                                use_bias=use_bias, kernel_init=kernel_init,
                                dtype=dtype)

    def init(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        pd, _, shape = self.depthwise.init(k1, input_shape)
        pp, _, shape = self.pointwise.init(k2, shape)
        return {"depthwise": pd, "pointwise": pp}, {}, shape

    def sub_layers(self):
        return {"depthwise": self.depthwise, "pointwise": self.pointwise}

    def apply(self, params, state, x, *, training=False, rng=None):
        y, _ = self.depthwise.apply(params["depthwise"], {}, x,
                                    training=training)
        y, _ = self.pointwise.apply(params["pointwise"], {}, y,
                                    training=training)
        return y, state

    def get_config(self):
        # spatial formatting delegated to the depthwise sublayer's base
        cfg = _ConvND.get_config(self.depthwise)
        cfg.pop("filters")
        cfg.update(filters=self.filters,
                   depth_multiplier=self.depth_multiplier,
                   activation=self.activation, use_bias=self.use_bias)
        return cfg


@register_layer
class Conv2DTranspose(_ConvND):
    """Transposed 2-D convolution (learned upsampling for decoder /
    segmentation heads) via ``lax.conv_transpose``."""

    _dims = ("NHWC", "HWIO", "NHWC")

    def _conv(self, x, k):
        return lax.conv_transpose(x, k, self.strides, self.padding,
                                  dimension_numbers=self._dims)


@register_layer
class UpSampling2D(Layer):
    """Nearest-neighbor spatial upsampling ([B, H, W, C] -> [B, rH, rW, C])
    — a pure repeat, no parameters."""

    def __init__(self, size=2):
        if isinstance(size, (tuple, list)) and len(size) != 2:
            raise ValueError(
                f"UpSampling2D expects 2 spatial factors, got {size}")
        self.size = _pair(size)

    def init(self, rng, input_shape):
        h, w, c = input_shape
        return {}, {}, (h * self.size[0], w * self.size[1], c)

    def apply(self, params, state, x, *, training=False, rng=None):
        y = jnp.repeat(jnp.repeat(x, self.size[0], axis=1),
                       self.size[1], axis=2)
        return y, state

    def get_config(self):
        return {"size": list(self.size)}


class _Pool2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding="VALID"):
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = padding.upper()

    def _reduce(self, x):
        raise NotImplementedError

    def init(self, rng, input_shape):
        out = jax.eval_shape(
            lambda x: self._reduce(x),
            jax.ShapeDtypeStruct((1,) + tuple(input_shape), jnp.float32))
        return {}, {}, tuple(out.shape[1:])

    def apply(self, params, state, x, *, training=False, rng=None):
        return self._reduce(x), state

    def get_config(self):
        return {"pool_size": list(self.pool_size),
                "strides": list(self.strides), "padding": self.padding}


@register_layer
class MaxPooling2D(_Pool2D):
    def _reduce(self, x):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1,) + self.pool_size + (1,),
            (1,) + self.strides + (1,), self.padding)


@register_layer
class AveragePooling2D(_Pool2D):
    def _reduce(self, x):
        ones = lax.reduce_window(
            jnp.ones_like(x), 0.0, lax.add, (1,) + self.pool_size + (1,),
            (1,) + self.strides + (1,), self.padding)
        summed = lax.reduce_window(
            x, 0.0, lax.add, (1,) + self.pool_size + (1,),
            (1,) + self.strides + (1,), self.padding)
        return summed / ones


@register_layer
class GlobalAveragePooling2D(Layer):
    def init(self, rng, input_shape):
        return {}, {}, (input_shape[-1],)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.mean(x, axis=(1, 2)), state


@register_layer
class GlobalAveragePooling1D(Layer):
    """Mean over the sequence axis of a [B, S, D] input (ViT/BERT heads)."""

    def init(self, rng, input_shape):
        return {}, {}, (input_shape[-1],)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.mean(x, axis=1), state


# ---------------------------------------------------------------------------
# batch norm
# ---------------------------------------------------------------------------

@register_layer
class BatchNorm(Layer):
    """Batch normalization with functional running stats.

    Running mean/var live in the ``state`` collection and are returned
    (not mutated) from ``apply`` — this is what lets BN work unchanged under
    jit/shard_map in the distributed trainers. When training under a
    data-parallel mesh axis, pass ``axis_name`` so batch statistics are
    all-reduced over ICI (the cross-replica BN the reference could never do —
    each Spark executor normalized over its local batch only).
    """

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 axis_name: Optional[str] = None,
                 virtual_batch_size: Optional[int] = None):
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self.axis_name = axis_name
        # ghost batch norm (Hoffer et al. 2017; Keras' virtual_batch_size):
        # each sub-batch of this size normalizes by its OWN stats — a
        # regularizer at large batch, and what per-worker BN looked like in
        # the reference (each Spark executor normalized its local batch)
        self.virtual_batch_size = (None if virtual_batch_size is None
                                   else int(virtual_batch_size))
        if self.virtual_batch_size is not None and axis_name is not None:
            raise ValueError(
                "virtual_batch_size (deliberately LOCAL ghost stats) and "
                "axis_name (cross-replica stats) contradict each other; "
                "pick one")

    def init(self, rng, input_shape):
        dim = input_shape[-1]
        params = {"scale": jnp.ones((dim,)), "offset": jnp.zeros((dim,))}
        state = {"mean": jnp.zeros((dim,)), "var": jnp.ones((dim,))}
        return params, state, tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        xf = x.astype(jnp.float32)  # stats in f32 even for bf16 activations
        if training and self.virtual_batch_size is not None:
            v = self.virtual_batch_size
            if x.shape[0] % v:
                raise ValueError(
                    f"batch size {x.shape[0]} not divisible by "
                    f"virtual_batch_size {v}")
            g = x.shape[0] // v
            xg = xf.reshape((g, v) + x.shape[1:])       # ghost groups
            gaxes = tuple(range(1, xg.ndim - 1))        # within-group stats
            mean_g = jnp.mean(xg, axis=gaxes)           # [g, C]
            var_g = jnp.mean(jnp.square(xg), axis=gaxes) - jnp.square(mean_g)
            sh = (g,) + (1,) * (xg.ndim - 2) + (-1,)
            inv = lax.rsqrt(var_g.reshape(sh) + self.epsilon) \
                * params["scale"]
            y = (xg - mean_g.reshape(sh)) * inv + params["offset"]
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1 - m) * mean_g.mean(axis=0),
                "var": m * state["var"] + (1 - m) * var_g.mean(axis=0)}
            return y.reshape(x.shape).astype(x.dtype), new_state
        axes = tuple(range(x.ndim - 1))
        if training:
            mean = jnp.mean(xf, axis=axes)
            mean2 = jnp.mean(jnp.square(xf), axis=axes)
            if self.axis_name is not None:
                mean = lax.pmean(mean, self.axis_name)
                mean2 = lax.pmean(mean2, self.axis_name)
            var = mean2 - jnp.square(mean)
            m = self.momentum
            new_state = {"mean": m * state["mean"] + (1 - m) * mean,
                         "var": m * state["var"] + (1 - m) * var}
            # hand-derived 2-reduction backward (ops/normalization.py):
            # autodiff through the expression below produced ~5 full-tensor
            # f32 reduce chains per BN that made ResNet backward convs
            # VPU-bound (60 of 98 ms/step in the round-2 profile)
            from distkeras_tpu.ops.normalization import bn_train_apply
            y = bn_train_apply(x, params["scale"], params["offset"],
                               mean, var, self.epsilon, axes,
                               self.axis_name)
            return y, new_state
        mean, var = state["mean"], state["var"]
        inv = lax.rsqrt(var + self.epsilon) * params["scale"]
        y = (xf - mean) * inv + params["offset"]
        return y.astype(x.dtype), state

    def get_config(self):
        return {"momentum": self.momentum, "epsilon": self.epsilon,
                "axis_name": self.axis_name,
                "virtual_batch_size": self.virtual_batch_size}


@register_layer
class GroupNorm(Layer):
    """Group normalization (Wu & He 2018) over the channel axis of a
    [B, ..., C] input: batch-size-independent (no running stats, identical
    train/eval), the usual BN replacement when per-device batches are
    small. Stats are computed in f32 per (sample, group) over all spatial
    positions and the group's channels."""

    def __init__(self, groups: int = 32, epsilon: float = 1e-5):
        self.groups = int(groups)
        self.epsilon = float(epsilon)

    def init(self, rng, input_shape):
        dim = input_shape[-1]
        if dim % self.groups:
            raise ValueError(
                f"channels {dim} not divisible by groups {self.groups}")
        params = {"scale": jnp.ones((dim,)), "offset": jnp.zeros((dim,))}
        return params, {}, tuple(input_shape)

    def apply(self, params, state, x, *, training=False, rng=None):
        g = self.groups
        xf = x.astype(jnp.float32)
        xg = xf.reshape(x.shape[:-1] + (g, x.shape[-1] // g))
        axes = tuple(range(1, x.ndim - 1)) + (x.ndim,)  # spatial + in-group
        mean = jnp.mean(xg, axis=axes, keepdims=True)
        var = jnp.var(xg, axis=axes, keepdims=True)
        y = ((xg - mean) * lax.rsqrt(var + self.epsilon)).reshape(x.shape)
        y = y * params["scale"] + params["offset"]
        return y.astype(x.dtype), state

    def get_config(self):
        return {"groups": self.groups, "epsilon": self.epsilon}


# ---------------------------------------------------------------------------
# embedding
# ---------------------------------------------------------------------------

@register_layer
class Embedding(Layer):
    def __init__(self, vocab_size: int, dim: int,
                 embeddings_init: str = "uniform_scaling"):
        self.vocab_size = int(vocab_size)
        self.dim = int(dim)
        self.embeddings_init = embeddings_init

    def init(self, rng, input_shape):
        params = {"embeddings": init_weights(self.embeddings_init, rng,
                                             (self.vocab_size, self.dim))}
        return params, {}, tuple(input_shape) + (self.dim,)

    def apply(self, params, state, x, *, training=False, rng=None):
        return jnp.take(params["embeddings"], x.astype(jnp.int32), axis=0), \
            state

    def get_config(self):
        return {"vocab_size": self.vocab_size, "dim": self.dim,
                "embeddings_init": self.embeddings_init}
