"""Mixture-of-experts MLP with expert parallelism over a mesh axis.

Absent from the reference (SURVEY §2.3: expert parallelism "out of scope"
for the Spark design) — this is a TPU-native addition. Design:

  * Expert weights are stacked on a leading ``[num_experts, ...]`` axis, so
    expert parallelism is a single ``PartitionSpec("expert", ...)`` shard of
    that axis (see ``parallel.sharding``).
  * Routing is a **static-shape dense top-k**: the router's softmax is
    masked to the top-k experts per token and every (local) expert runs on
    every token. There is no gather/scatter and no capacity dropping —
    data-dependent dispatch would force dynamic shapes XLA can't tile; the
    masked-dense form keeps the MXU fed and is exact (same output as
    dispatched top-k).
  * Under expert parallelism each device computes only its ``E / A`` local
    experts and the weighted outputs are ``psum``'d over the ``expert``
    axis — compute per device drops by the axis size A.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.core import (AUX_LOSS_KEY, Layer,
                                       register_layer)
from distkeras_tpu.models.layers import get_activation, init_weights


@register_layer
class MoE(Layer):
    """Top-k gated mixture of expert MLPs over [B, S, d_model]."""

    def __init__(self, num_experts: int, hidden_dim: int, top_k: int = 2,
                 activation: str = "gelu", dtype: str = "float32",
                 expert_axis_name: Optional[str] = None,
                 kernel_init: str = "glorot_uniform",
                 aux_loss_weight: float = 0.0):
        self.num_experts = int(num_experts)
        self.hidden_dim = int(hidden_dim)
        self.top_k = int(top_k)
        self.activation = activation
        self.dtype = dtype
        self.expert_axis_name = expert_axis_name
        self.kernel_init = kernel_init
        # Switch/GShard load-balancing loss coefficient: adds
        # ``weight · E · Σ_e f_e·P_e`` to the TRAINING loss (f_e = fraction
        # of routing slots sent to expert e, P_e = mean router prob),
        # pushing the router away from expert collapse. Published via the
        # AUX_LOSS_KEY state channel (parallel.worker picks it up).
        self.aux_loss_weight = float(aux_loss_weight)

    def init(self, rng, input_shape):
        d = input_shape[-1]
        e, hid = self.num_experts, self.hidden_dim
        kg, k1, k2 = jax.random.split(rng, 3)
        # per-expert init: split so experts start decorrelated
        w1 = jnp.stack([init_weights(self.kernel_init, k, (d, hid))
                        for k in jax.random.split(k1, e)])
        w2 = jnp.stack([init_weights(self.kernel_init, k, (hid, d))
                        for k in jax.random.split(k2, e)])
        params = {
            "gate": init_weights(self.kernel_init, kg, (d, e)),
            "w1": w1, "b1": jnp.zeros((e, hid)),
            "w2": w2, "b2": jnp.zeros((e, d)),
        }
        state = {}
        if self.aux_loss_weight:
            state[AUX_LOSS_KEY] = jnp.zeros((), jnp.float32)
        return params, state, tuple(input_shape)

    def _gate_probs(self, x, gate):
        """Routing weights [B, S, E] (softmax over top-k logits, 0
        elsewhere) plus the full softmax and slot mask for the balance
        loss."""
        logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32),
                            gate.astype(jnp.float32))
        full = jax.nn.softmax(logits, axis=-1)
        mask = None
        if self.top_k < self.num_experts:
            # mask from top_k INDICES, not a >= kth-value test: on tied
            # logits the value test would admit every tied expert, breaking
            # the exact-top-k contract
            idxs = lax.top_k(logits, self.top_k)[1]
            mask = jax.nn.one_hot(idxs, self.num_experts,
                                  dtype=jnp.bool_).any(axis=-2)
            logits = jnp.where(mask, logits, -jnp.inf)
        return jax.nn.softmax(logits, axis=-1), full, mask

    def _balance_loss(self, full, mask):
        """E · Σ_e f_e·P_e (Switch eq. 4, GShard): minimized at uniform
        routing, where it equals 1."""
        e = self.num_experts
        if mask is None:            # top_k == E: every slot hits every expert
            frac = jnp.full((e,), 1.0 / e)
        else:
            frac = jnp.mean(mask.astype(jnp.float32), axis=(0, 1)) \
                / self.top_k        # fraction of routing slots per expert
        pmean = jnp.mean(full, axis=(0, 1))
        return e * jnp.sum(frac * pmean)

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)
        act = get_activation(self.activation)
        probs, full, mask = self._gate_probs(x, params["gate"])  # f32

        xc = x.astype(dt)
        # local experts: [El, ...] slice when sharded over the expert axis
        h = jnp.einsum("bsd,edf->besf", xc, params["w1"].astype(dt))
        h = act(h + params["b1"].astype(dt)[None, :, None, :])
        y = jnp.einsum("besf,efd->besd", h, params["w2"].astype(dt))
        y = y + params["b2"].astype(dt)[None, :, None, :]

        if self.expert_axis_name is None:
            out = jnp.einsum("bse,besd->bsd", probs.astype(dt), y)
        else:
            # Sharded: this shard holds experts [idx*El, (idx+1)*El); pick
            # the matching slice of the (replicated) router probabilities,
            # then combine across the axis.
            el = y.shape[1]
            idx = lax.axis_index(self.expert_axis_name)
            local = lax.dynamic_slice_in_dim(probs, idx * el, el, axis=-1)
            out = jnp.einsum("bse,besd->bsd", local.astype(dt), y)
            out = lax.psum(out, self.expert_axis_name)
        new_state = state
        if self.aux_loss_weight and training:
            # router inputs/gate are replicated under expert sharding, so
            # this value is identical on every shard — no psum needed
            new_state = dict(state)
            new_state[AUX_LOSS_KEY] = (self.aux_loss_weight *
                                       self._balance_loss(full, mask))
        return out.astype(x.dtype), new_state

    def get_config(self):
        return {"num_experts": self.num_experts, "hidden_dim": self.hidden_dim,
                "top_k": self.top_k, "activation": self.activation,
                "dtype": self.dtype,
                "expert_axis_name": self.expert_axis_name,
                "kernel_init": self.kernel_init,
                "aux_loss_weight": self.aux_loss_weight}
