"""Mixture-of-experts MLP with expert parallelism over a mesh axis.

Absent from the reference (SURVEY §2.3: expert parallelism "out of scope"
for the Spark design) — this is a TPU-native addition. Design:

  * Expert weights are stacked on a leading ``[num_experts, ...]`` axis, so
    expert parallelism is a single ``PartitionSpec("expert", ...)`` shard of
    that axis (see ``parallel.sharding``).
  * Two routing executions share one router:

    - ``dispatch="dense"``: static-shape masked top-k — the router's
      softmax is masked to the top-k experts per token and every (local)
      expert runs on every token. No gather/scatter, no capacity drops,
      exact — but every token pays ALL experts' FLOPs (E/top_k× the
      dispatched cost). Kept as the numerics oracle and for tiny shapes
      where dispatch bookkeeping dominates.
    - ``dispatch="tokens"`` (round 3; round 4 made it sort-free; round 5
      took the dispatch traffic to its primitive floor): the
      capacity-based GShard/Switch construction with static shapes.
      Each slot's position within its expert comes from an exclusive
      cumsum over one-hot masks in choice-major order (every token's
      first choice outranks all second choices); each expert takes its
      first ``capacity`` arrivals, dropped slots contribute nothing.
      Per-token expert FLOPs are ``top_k * capacity_factor`` MLPs
      instead of ``E`` — the compute-sparse economics the name
      promises. Round 5 exploits the choice-major slot structure
      (slot->token map = ``tile(arange(N), K)``): the buffer build is a
      free broadcast into ONE drop-mode unique-indices scatter, and the
      combine is a gather + reshape-sum — one big scatter and one big
      gather per direction, measured at the chip's gather/scatter
      primitive rate (docs/PERF.md §MoE has the per-category table and
      the measured-negative ragged_dot/unroll alternatives).
    - ``dispatch="fused"`` (round 6): the Pallas fused path
      (``ops/moe_kernels.py``) — the dispatch gather happens INSIDE the
      expert up-projection kernel (token rows are DMA'd from the
      residual stream straight into contiguous VMEM tiles, MegaBlocks-
      style), so the ``tokens`` path's [K*N, d] scatter and [E*C, d]
      HBM dispatch buffer never materialize; the backward pass is the
      gather's transpose in a custom VJP (also gathers — see the kernel
      module doc). Identical routing/drop/tie-break/NaN semantics to
      ``tokens`` (both consume one ``_dispatch_plan``). Off-TPU the
      layer automatically falls back to the ``tokens`` XLA floor
      (``compat.backend_is_tpu`` — the repo's one backend convention);
      tests force the interpreter via ``moe_kernels.force_interpret``.

  * Expert parallelism: under GSPMD (``SPMDTrainer``) the stacked expert
    einsums partition on the expert axis automatically from the weight
    shardings. Under ``shard_map`` (``expert_axis_name``) tokens are
    replicated across the axis, so each shard slices its experts' rows of
    the dispatch tensor — strictly cheaper than an all_to_all — computes
    its ``E/A`` experts, and the combined outputs are ``psum``'d. For
    token-sharded meshes (ep doubling as a data axis) see
    ``moe_all_to_all`` below: the full GShard all_to_all exchange.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from distkeras_tpu.models.core import (AUX_LOSS_KEY, Layer,
                                       register_layer)
from distkeras_tpu.models.layers import get_activation, init_weights


def _dispatch_plan(experts, gates, num_experts: int, capacity: int):
    """Static-shape dispatch bookkeeping.

    experts/gates: [N, K] top-k expert ids / combine weights per token.
    Returns (dest, token, weight, keep) flat [N*K] slot arrays in
    choice-major slot order: ``dest`` indexes an [E*C (+1 overflow)]
    buffer.
    Priority is choice-major (slot s = k*N + n): all first choices beat
    all second choices, ties broken by token order — the GShard rule.
    """
    n, k = experts.shape
    slot_e = experts.T.reshape(-1)                      # [K*N] choice-major
    slot_t = jnp.tile(jnp.arange(n, dtype=jnp.int32), k)
    slot_g = gates.T.reshape(-1)
    # position-in-expert via an exclusive cumsum over one-hot masks (the
    # GShard/Switch construction) — round 4: this replaced a stable
    # argsort over the [K*N] slot keys, which on TPU lowers to a
    # many-pass bitonic sort and dominated the dispatch wall clock; the
    # cumsum is a cheap log-depth scan and needs no reordering at all
    # (slots stay in choice-major order, which IS the priority order).
    onehot = jax.nn.one_hot(slot_e, num_experts, dtype=jnp.int32)
    ranks = jnp.cumsum(onehot, axis=0) - onehot         # [K*N, E] exclusive
    pos = jnp.take_along_axis(ranks, slot_e[:, None], axis=1)[:, 0]
    keep = pos < capacity
    # dropped slots get UNIQUE out-of-range sentinels (E*C + slot index),
    # not one shared overflow value: the consumers scatter with
    # unique_indices=True, a promise a shared sentinel would break
    # (implementation-defined behavior per the XLA scatter contract —
    # review r5); mode="drop" discards every OOB row either way
    dest = jnp.where(keep, slot_e * capacity + pos,
                     num_experts * capacity
                     + jnp.arange(n * k, dtype=pos.dtype))
    return dest, slot_t, slot_g, keep


@register_layer
class MoE(Layer):
    """Top-k gated mixture of expert MLPs over [B, S, d_model]."""

    def __init__(self, num_experts: int, hidden_dim: int, top_k: int = 2,
                 activation: str = "gelu", dtype: str = "float32",
                 expert_axis_name: Optional[str] = None,
                 kernel_init: str = "glorot_uniform",
                 aux_loss_weight: float = 0.0,
                 dispatch: str = "dense",
                 capacity_factor: float = 1.25,
                 expert_unroll: bool = False):
        self.num_experts = int(num_experts)
        self.hidden_dim = int(hidden_dim)
        self.top_k = int(top_k)
        self.activation = activation
        self.dtype = dtype
        self.expert_axis_name = expert_axis_name
        self.kernel_init = kernel_init
        # Switch/GShard load-balancing loss coefficient: adds
        # ``weight · E · Σ_e f_e·P_e`` to the TRAINING loss (f_e = fraction
        # of routing slots sent to expert e, P_e = mean router prob),
        # pushing the router away from expert collapse. Published via the
        # AUX_LOSS_KEY state channel (parallel.worker picks it up).
        self.aux_loss_weight = float(aux_loss_weight)
        if dispatch not in ("dense", "tokens", "fused"):
            raise ValueError(
                "dispatch must be 'dense', 'tokens' or 'fused', "
                f"got {dispatch!r}")
        self.dispatch = dispatch
        # expert capacity = ceil(top_k * tokens / E) * capacity_factor:
        # at 1.0 a perfectly balanced router drops nothing; the default
        # headroom absorbs imbalance while training the balance loss down
        self.capacity_factor = float(capacity_factor)
        # round 5, measured on v5e and left OPT-IN: the stacked
        # [E, C, d] x [E, d, f] einsum lowers to XLA's batched-dot
        # emitter (EmitAllBatchInSublanes), ~40% MXU; statically
        # unrolling into groups of small clean dots microbenches 25-32%
        # faster (3.1 vs 3.9-4.4 ms fwd at E=8/C=4096) — but in the
        # 12-layer training graph the per-group slices + concat defeat
        # XLA's buffer aliasing and the step OOMs by ~1 GB at batch 8
        # (both 2 and 4 groups; full unroll also blows the compile
        # helper). Default stays False; the option remains for shapes
        # with spare HBM. Also keep False under GSPMD expert-axis
        # sharding (SPMDTrainer): per-expert slices of a sharded stacked
        # axis force cross-shard resharding — the shard_map path
        # (expert_axis_name) is unaffected, its weights arrive
        # pre-sliced.
        self.expert_unroll = bool(expert_unroll)

    def init(self, rng, input_shape):
        d = input_shape[-1]
        e, hid = self.num_experts, self.hidden_dim
        kg, k1, k2 = jax.random.split(rng, 3)
        # per-expert init: split so experts start decorrelated
        w1 = jnp.stack([init_weights(self.kernel_init, k, (d, hid))
                        for k in jax.random.split(k1, e)])
        w2 = jnp.stack([init_weights(self.kernel_init, k, (hid, d))
                        for k in jax.random.split(k2, e)])
        params = {
            "gate": init_weights(self.kernel_init, kg, (d, e)),
            "w1": w1, "b1": jnp.zeros((e, hid)),
            "w2": w2, "b2": jnp.zeros((e, d)),
        }
        state = {}
        if self.aux_loss_weight:
            state[AUX_LOSS_KEY] = jnp.zeros((), jnp.float32)
        return params, state, tuple(input_shape)

    def _route(self, x, gate):
        """Shared router: ``(full, topi, gates, mask)`` — full softmax
        [B, S, E], top-k expert ids + their renormalized weights [B, S, K]
        (softmax over the k logits == the masked-softmax restriction, so
        the dense and dispatched paths combine with IDENTICAL weights),
        and the top-k slot mask for the balance loss (None at k == E).
        Top-k INDICES, not a >= kth-value test: on tied logits the value
        test would admit every tied expert."""
        # f32 router on purpose: routing decisions deserve full
        # precision, and a bf16-input variant was MEASURED at identical
        # wall clock (47.2K tok/s both ways, round 5) — the f32 upcast
        # is off the critical path, so there is no speed to buy here
        logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32),
                            gate.astype(jnp.float32))
        full = jax.nn.softmax(logits, axis=-1)
        topv, topi = lax.top_k(logits, self.top_k)
        gates = jax.nn.softmax(topv, axis=-1)
        mask = None
        if self.top_k < self.num_experts:
            mask = jax.nn.one_hot(topi, self.num_experts,
                                  dtype=jnp.bool_).any(axis=-2)
        return full, topi, gates, mask

    def _gate_probs(self, x, gate):
        """Routing weights [B, S, E] (softmax over top-k logits, 0
        elsewhere) plus the full softmax and slot mask for the balance
        loss (the dense path's view of ``_route``)."""
        full, topi, gates, mask = self._route(x, gate)
        probs = jnp.einsum(
            "bske,bsk->bse",
            jax.nn.one_hot(topi, self.num_experts, dtype=gates.dtype),
            gates)
        return probs, full, mask

    def _balance_loss(self, full, mask):
        """E · Σ_e f_e·P_e (Switch eq. 4, GShard): minimized at uniform
        routing, where it equals 1."""
        e = self.num_experts
        if mask is None:            # top_k == E: every slot hits every expert
            frac = jnp.full((e,), 1.0 / e)
        else:
            frac = jnp.mean(mask.astype(jnp.float32), axis=(0, 1)) \
                / self.top_k        # fraction of routing slots per expert
        pmean = jnp.mean(full, axis=(0, 1))
        return e * jnp.sum(frac * pmean)

    def _capacity(self, n_tokens: int) -> int:
        per = -(-self.top_k * n_tokens // self.num_experts)  # ceil
        return max(1, int(per * self.capacity_factor))

    @staticmethod
    def _expert_axis_sharded(w) -> bool:
        """Best-effort: True when a CONCRETE stacked expert weight
        carries a non-replicated GSPMD sharding on its leading (expert)
        axis — the configuration where ``expert_unroll``'s per-expert
        slices force cross-shard resharding collectives every step
        (see ``__init__``). Mirrors the ``replicated()`` probe in
        ``decoding._fuse_qkv_params``; inside jit/shard_map the weights
        are tracers with no sharding attribute and this stays False
        (the shard_map path's weights arrive pre-sliced and are safe;
        the GSPMD-trainer path is covered at SETUP time instead, where
        ``parallel.sharding._rule_MoE`` warns on the concrete
        layer-config x expert-axis combination)."""
        sh = getattr(w, "sharding", None)
        if sh is None or getattr(sh, "is_fully_replicated", True):
            return False
        spec = getattr(sh, "spec", None)
        return bool(spec) and spec[0] is not None

    def _expert_mlp(self, xe, params):
        """Run the stacked expert MLP on [E(_local), C, d]. Under
        shard_map expert parallelism the weights arrive pre-sliced to the
        shard's experts; under GSPMD the einsums partition on ``e`` from
        the weight shardings automatically (set ``expert_unroll=False``
        there — see __init__)."""
        dt = jnp.dtype(self.dtype)
        act = get_activation(self.activation)
        w1 = params["w1"].astype(dt)
        b1 = params["b1"].astype(dt)
        w2 = params["w2"].astype(dt)
        b2 = params["b2"].astype(dt)
        e_here = xe.shape[0]
        unroll = self.expert_unroll
        if unroll and self._expert_axis_sharded(params["w1"]):
            import warnings
            warnings.warn(
                "MoE(expert_unroll=True) with expert-axis-sharded "
                "stacked weights (GSPMD): per-expert slices of a "
                "sharded axis pay cross-shard resharding collectives "
                "every step — falling back to the batched expert dot "
                "for this call. Replicate the expert weights or use "
                "shard_map expert parallelism (expert_axis_name) to "
                "unroll.", stacklevel=3)
            unroll = False
        if unroll and e_here > 1:
            # static unroll into small groups of batched dots: measured
            # sweep on v5e (E=8, C=4096) — 4 groups 3.1/3.4 ms fwd/f+g
            # vs 3.9/4.0 for the single batched dot; FULL unroll (8
            # groups) microbenches the same but its 12-layer training
            # graph blows past the compile helper / HBM (round 5), so
            # groups are capped at 4
            ng = 4 if e_here % 4 == 0 else (2 if e_here % 2 == 0 else 1)
            gsz = e_here // ng
            outs = []
            for g in range(ng):
                sl = slice(g * gsz, (g + 1) * gsz)
                if gsz == 1:
                    h = act(xe[g * gsz] @ w1[g * gsz] + b1[g * gsz])
                    outs.append((h @ w2[g * gsz] + b2[g * gsz])[None])
                else:
                    h = act(jnp.einsum("ecd,edf->ecf", xe[sl], w1[sl])
                            + b1[sl][:, None, :])
                    outs.append(jnp.einsum("ecf,efd->ecd", h, w2[sl])
                                + b2[sl][:, None, :])
            return jnp.concatenate(outs, axis=0)
        h = act(jnp.einsum("ecd,edf->ecf", xe, w1) + b1[:, None, :])
        return jnp.einsum("ecf,efd->ecd", h, w2) + b2[:, None, :]

    def _apply_dispatched(self, params, x, *, fused=False, capacity=None,
                          return_routing=False):
        """Capacity-based (sort-free) dispatch — static shapes; see
        module doc. ``capacity`` overrides the training-time
        ``_capacity`` formula (the decode path passes the full token
        count — drop-free by construction, see :meth:`decode_apply`);
        ``return_routing`` appends the top-k expert ids ``[B, S, K]``
        to the return tuple (the serving engine's expert-load
        telemetry reads them).

        Round 5 (dispatch-traffic restructure, measured in docs/PERF.md
        §MoE): slot ``s = k*N + n`` is CHOICE-major, so the slot->token
        map is ``tile(arange(N), K)`` — pure structure. Exploiting it:

          * the slot-input build is a free ``broadcast_to`` (round 4
            gathered ``xt[st]``, a real [K*N, d] gather whose transpose
            was a real scatter-add);
          * the combine is ``reshape(K, N, d).sum(0)`` (round 4
            scatter-added into ``zeros.at[st]``, whose transpose was
            another gather).

        One [K*N, d] scatter (buffer build) + one gather (combine read)
        remain per direction — half the round-4 traffic; their cost is
        the dispatch's irreducible price on one chip — UNLESS the
        Pallas fused path takes over (``fused=True``, round 6): there
        the SAME plan's indices drive in-kernel row DMA instead, and
        neither the scatter nor the [E*C, d] buffer exists
        (``ops/moe_kernels.py``)."""
        dt = jnp.dtype(self.dtype)
        b, s, d = x.shape
        n = b * s
        e, k = self.num_experts, self.top_k
        c = self._capacity(n) if capacity is None else int(capacity)
        full, topi, gates, mask = self._route(x, params["gate"])

        dest, _st, sg, keep = _dispatch_plan(
            topi.reshape(n, k), gates.reshape(n, k), e, c)
        xt = x.reshape(n, d).astype(dt)

        if fused:
            from distkeras_tpu.ops import moe_kernels
            w1 = params["w1"].astype(dt)
            b1 = params["b1"].astype(dt)
            w2 = params["w2"].astype(dt)
            b2 = params["b2"].astype(dt)
            if self.expert_axis_name is None:
                out = moe_kernels.fused_moe_apply(
                    xt, w1, b1, w2, b2, sg, dest, keep,
                    capacity=c, activation=self.activation)
            else:
                # tokens replicated across the axis (as in the XLA path
                # below): each shard runs the kernel over ITS experts
                # only. The global plan localizes by offsetting ``dest``
                # into this shard's rows; slots belonging to other
                # shards get unique OUT-OF-RANGE sentinels (negative
                # indices would WRAP in the plan-inversion scatter) and
                # a cleared ``keep``, so they contribute exact zeros and
                # the psum over the axis reassembles the full combine.
                el = params["w1"].shape[0]
                idx = lax.axis_index(self.expert_axis_name)
                dest_l = dest - idx * el * c
                keep_l = jnp.logical_and(
                    keep, jnp.logical_and(dest_l >= 0, dest_l < el * c))
                dest_l = jnp.where(
                    keep_l, dest_l,
                    el * c + jnp.arange(n * k, dtype=dest.dtype))
                out = moe_kernels.fused_moe_apply(
                    xt, w1, b1, w2, b2, sg, dest_l, keep_l,
                    capacity=c, activation=self.activation)
                out = lax.psum(out, self.expert_axis_name)
            if return_routing:
                return out.reshape(b, s, d), full, mask, topi
            return out.reshape(b, s, d), full, mask

        src = jnp.broadcast_to(xt[None], (k, n, d)).reshape(k * n, d)
        # dropped slots (dest == E*C) fall off via mode="drop";
        # unique_indices lets XLA skip collision handling (the overflow-
        # row form made every dropped slot collide on one row: measured
        # 3.15 -> 2.46 ms for the [32K, 1024] scatter on v5e, round 5)
        xe = jnp.zeros((e * c, d), dt).at[dest].set(
            src, mode="drop", unique_indices=True)

        if self.expert_axis_name is None:
            ye = self._expert_mlp(xe.reshape(e, c, d), params)
            # combine in the COMPUTE dtype (round 4): the f32 combine
            # buffers ([E*C, d] twice per layer) doubled the dispatch
            # HBM traffic and fed XLA's memory-pressure remat; at most
            # top_k contributions sum per token, well within bf16
            ye_flat = ye.reshape(e * c, d)
        else:
            # tokens are replicated across the axis: each shard runs only
            # its pre-sliced experts on its rows of the dispatch buffer,
            # then the flat outputs are psum-combined (disjoint supports)
            el = params["w1"].shape[0]
            idx = lax.axis_index(self.expert_axis_name)
            xe_l = lax.dynamic_slice_in_dim(
                xe.reshape(e, c, d), idx * el, el, 0)
            ye_l = self._expert_mlp(xe_l, params)
            ye_flat = jnp.zeros((e * c, d), dt) \
                .at[jnp.arange(el * c, dtype=jnp.int32) + idx * el * c] \
                .set(ye_l.reshape(el * c, d))
            ye_flat = lax.psum(ye_flat, self.expert_axis_name)
        # dropped slots' dest clamps into range on the gather; the WHERE
        # (not a bare keep-multiply) forces their contribution to exact
        # zero even if the clamped-into expert row is inf/NaN (inf * 0
        # would poison the dropped token — review r5). Masking the
        # GATHERED ROWS, then multiplying by the gate, keeps the
        # backward clean too: where(keep, row*sg, 0) would still send
        # d(sg) = 0 * inf = NaN into the router gradient.
        safe = jnp.where(keep[:, None], ye_flat[dest], jnp.zeros((), dt))
        contrib = safe * sg[:, None].astype(dt)
        out = contrib.reshape(k, n, d).sum(axis=0)
        if return_routing:
            return out.reshape(b, s, d), full, mask, topi
        return out.reshape(b, s, d), full, mask

    def decode_apply(self, params, x, *, return_routing=False):
        """Decode-specialized dispatched MoE (the serving engine's
        per-step path; MoE-serving PR).

        ``x`` is the ``[S, W, d]`` slot-token batch of one decode step
        (W = 1) or speculative-verify window (W = k+1). Capacity is
        sized to the FULL token count ``n = S * W``: a token's top-k
        expert ids are distinct, so no expert can receive more than
        ``n`` arrivals — the dispatch is drop-free BY CONSTRUCTION and
        the output equals dense routing exactly (same ``_route``
        weights, same per-token dot products), up to fp reassociation.
        That is the serving correctness contract: routing can never
        alter a stream's tokens, and a slot's output is independent of
        which neighbours share the batch (a dropped slot's keep-flag
        would otherwise flip with batch composition).

        Execution ignores the layer's configured ``dispatch`` mode —
        decode-time dispatch is the ENGINE's choice: the fused Pallas
        gather-into-GEMM runs at decode shapes on TPU
        (``moe_kernels.fused_supported``, same plan, same %8-padded
        capacity), the XLA ``tokens`` floor everywhere else. At the
        small-n decode regime both beat the dense path's
        ``[S, E, W, f]`` broadcast einsums (measured ~1.1-1.8x per
        layer on CPU; docs/serving.md §MoE serving has the table).

        Under shard_map expert parallelism (``expert_axis_name``) the
        weights arrive pre-sliced and the combine psums over the axis
        — per-chip expert-weight traffic shrinks with the mesh.

        Returns ``[S, W, d]`` (no aux-loss state: decode never
        trains); with ``return_routing`` also ``(topi [S, W, K], full
        [S, W, E])`` — the top-k expert ids and the full router softmax
        — for expert-load/entropy telemetry."""
        from distkeras_tpu.ops import moe_kernels
        b, s, _d = x.shape
        out, full, _mask, topi = self._apply_dispatched(
            params, x, fused=moe_kernels.fused_supported(),
            capacity=b * s, return_routing=True)
        if return_routing:
            return out.astype(x.dtype), (topi, full)
        return out.astype(x.dtype)

    def apply(self, params, state, x, *, training=False, rng=None):
        dt = jnp.dtype(self.dtype)

        if self.dispatch in ("tokens", "fused"):
            use_fused = False
            if self.dispatch == "fused":
                # one backend convention repo-wide (compat.backend_is_tpu,
                # consulted inside fused_supported): kernels on TPU or
                # under a test's force_interpret; the XLA-floor tokens
                # path — same plan, same numerics — everywhere else
                from distkeras_tpu.ops import moe_kernels
                use_fused = moe_kernels.fused_supported()
            out, full, mask = self._apply_dispatched(params, x,
                                                     fused=use_fused)
            new_state = state
            if self.aux_loss_weight and training:
                new_state = dict(state)
                new_state[AUX_LOSS_KEY] = (self.aux_loss_weight *
                                           self._balance_loss(full, mask))
            return out.astype(x.dtype), new_state

        probs, full, mask = self._gate_probs(x, params["gate"])  # f32

        xc = x.astype(dt)
        # local experts: [El, ...] slice when sharded over the expert axis
        h = jnp.einsum("bsd,edf->besf", xc, params["w1"].astype(dt))
        act = get_activation(self.activation)
        h = act(h + params["b1"].astype(dt)[None, :, None, :])
        y = jnp.einsum("besf,efd->besd", h, params["w2"].astype(dt))
        y = y + params["b2"].astype(dt)[None, :, None, :]

        if self.expert_axis_name is None:
            out = jnp.einsum("bse,besd->bsd", probs.astype(dt), y)
        else:
            # Sharded: this shard holds experts [idx*El, (idx+1)*El); pick
            # the matching slice of the (replicated) router probabilities,
            # then combine across the axis.
            el = y.shape[1]
            idx = lax.axis_index(self.expert_axis_name)
            local = lax.dynamic_slice_in_dim(probs, idx * el, el, axis=-1)
            out = jnp.einsum("bse,besd->bsd", local.astype(dt), y)
            out = lax.psum(out, self.expert_axis_name)
        new_state = state
        if self.aux_loss_weight and training:
            # router inputs/gate are replicated under expert sharding, so
            # this value is identical on every shard — no psum needed
            new_state = dict(state)
            new_state[AUX_LOSS_KEY] = (self.aux_loss_weight *
                                       self._balance_loss(full, mask))
        return out.astype(x.dtype), new_state

    def get_config(self):
        return {"num_experts": self.num_experts, "hidden_dim": self.hidden_dim,
                "top_k": self.top_k, "activation": self.activation,
                "dtype": self.dtype,
                "expert_axis_name": self.expert_axis_name,
                "kernel_init": self.kernel_init,
                "aux_loss_weight": self.aux_loss_weight,
                "dispatch": self.dispatch,
                "capacity_factor": self.capacity_factor,
                "expert_unroll": self.expert_unroll}


def moe_all_to_all(moe: MoE, params, x, *, axis_name: str):
    """Token-SHARDED expert parallelism: the full GShard all_to_all
    exchange, for meshes where the expert axis doubles as a data axis
    (each shard holds DIFFERENT tokens and ``E/A`` experts).

    Must be called inside a ``shard_map`` where ``x`` is batch-sharded and
    the expert-stacked weights are sharded over ``axis_name``. Flow per
    shard: route the local tokens; build the local [E, Cs, d] dispatch
    buffer (Cs = local capacity); ``all_to_all`` so each shard receives
    every source's rows for ITS experts ([El, A*Cs, d]); run the local
    experts; ``all_to_all`` back; combine locally. Compute AND tokens both
    scale 1/A per device — contrast ``MoE.apply``'s replicated-token
    path, where only compute does.

    Returns ``(out, aux)`` with ``aux = (full_probs, topk_mask)`` for the
    balance loss (which must then be ``lax.pmean``'d over ``axis_name`` —
    shards see different tokens).
    """
    if moe.dispatch not in ("tokens", "fused"):
        raise ValueError(
            "moe_all_to_all requires dispatch='tokens' (or 'fused', "
            "which composes identically here: the exchange buffer is "
            "materialized BY the all_to_all, so there is no dispatch "
            "scatter for the fused kernel to remove)")
    dt = jnp.dtype(moe.dtype)
    b, s, d = x.shape
    n = b * s                                       # LOCAL tokens
    e, k = moe.num_experts, moe.top_k
    a = lax.psum(1, axis_name)
    el = params["w1"].shape[0]
    if el * a != e:
        raise ValueError(
            f"num_experts {e} != local experts {el} x axis size {a}")
    cs = moe._capacity(n)                           # per-source capacity

    full, topi, gates, mask = moe._route(x, params["gate"])

    dest, _st, sg, keep = _dispatch_plan(
        topi.reshape(n, k), gates.reshape(n, k), e, cs)
    xt = x.reshape(n, d).astype(dt)
    # choice-major structure exploited as in _apply_dispatched (round 5):
    # broadcast build + drop/unique scatter + reshape-sum combine
    src = jnp.broadcast_to(xt[None], (k, n, d)).reshape(k * n, d)
    xe = jnp.zeros((e * cs, d), dt).at[dest].set(
        src, mode="drop", unique_indices=True)
    # [E, Cs, d] -> exchange: send expert-block a' to shard a', receive
    # one block per source concatenated on the capacity axis
    xe = xe.reshape(e, cs, d)
    recv = lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=1,
                          tiled=True)               # [El, A*Cs, d]
    ye_l = moe._expert_mlp(recv, params)            # local experts
    back = lax.all_to_all(ye_l, axis_name, split_axis=1, concat_axis=0,
                          tiled=True)               # [E, Cs, d]
    ye_flat = back.reshape(e * cs, d).astype(jnp.float32)
    # mask the gathered rows BEFORE the gate multiply: exact zero for
    # dropped slots in forward AND backward even when the clamped gather
    # row is non-finite (see _apply_dispatched)
    contrib = jnp.where(keep[:, None], ye_flat[dest], 0.0) * sg[:, None]
    out = contrib.reshape(k, n, d).sum(axis=0)
    return out.reshape(b, s, d).astype(x.dtype), (full, mask)
