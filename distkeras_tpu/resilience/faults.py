"""Fault injection: a process-global registry of named injection points.

Every failure mode this repo claims to survive must be *injectable and
tested*, not hoped for. Library code plants cheap hooks at the places
real systems break — checkpoint writes/renames/restores
(``utils/checkpoint.py``), the prefetch producer (``utils/prefetch.py``),
shard fetches (``Trainer._sharded_stream``), the serving engine's
prefill/decode (``serving/engine.py``) and the trainer epoch loops — and
the chaos suite (``tests/test_resilience.py``) arms them one at a time.

Disarmed, a hook is one dict lookup under a lock (the sites run per
epoch / per chunk / per engine iteration, never per device op). Armed,
a hook fires per its deterministic trigger:

  * ``nth=N``   — fire exactly once, on the N-th call (1-based);
  * ``every=K`` — fire on every K-th call;
  * ``prob=P``  — fire with probability P per call, from a private
    ``random.Random(seed)`` stream (reproducible chaos).

and performs its action:

  * **raise** (default) — raise ``error`` (default an
    ``InjectedFault``, whose ``transient`` flag decides whether
    ``resilience.retry`` policies may heal it);
  * **stall** (``stall_s=...``) — sleep, then continue (slow disk,
    slow prefill, a wedged producer);
  * **nan** (``action="nan"``) — only at ``corrupt()`` sites: replace
    the value flowing past with NaNs (poisoned loss / gradient).

Activation is by API (``faults.inject("ckpt.write", nth=2)``) or
environment::

    DKT_FAULTS="ckpt.write=nth:2;serving.prefill=every:4,stall:0.05"

(specs split on ``;``, options on ``,``, each ``key:value``; keys:
``nth``, ``every``, ``prob``, ``seed``, ``stall``, ``action``,
``transient``). Every trigger increments the ``faults.triggered``
counter (labeled by point) on the obs registry, so chaos runs are
visible in ``telemetry_snapshot()`` — and notifies any registered
``add_trigger_listener`` callbacks (the flight recorder
``obs.recorder`` uses this to snapshot its ring at the moment of
failure). ``docs/resilience.md`` carries the injection-point catalog.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "InjectedFault", "active", "add_trigger_listener", "clear",
    "corrupt", "fired", "inject", "load_env", "point", "points",
    "remove_trigger_listener", "reset",
]


class InjectedFault(RuntimeError):
    """The default error an armed injection point raises.

    ``transient=True`` marks it retryable (``retry.classify_retryable``
    treats it like a flaky-IO error); the default ``False`` models a
    hard crash that only supervision-level restart can absorb.
    """

    def __init__(self, point: str, transient: bool = False):
        super().__init__(f"injected fault at {point!r}")
        self.point = point
        self.transient = transient


class _Spec:
    """One armed fault: a trigger plus an action."""

    def __init__(self, point: str, nth: Optional[int] = None,
                 every: Optional[int] = None, prob: Optional[float] = None,
                 seed: int = 0, error: Optional[BaseException] = None,
                 stall_s: Optional[float] = None,
                 action: Optional[str] = None, transient: bool = False):
        triggers = [t for t in (nth, every, prob) if t is not None]
        if len(triggers) != 1:
            raise ValueError(
                f"fault {point!r}: exactly one trigger of nth/every/prob "
                f"required, got nth={nth} every={every} prob={prob}")
        if nth is not None and int(nth) < 1:
            raise ValueError(f"fault {point!r}: nth must be >= 1")
        if every is not None and int(every) < 1:
            raise ValueError(f"fault {point!r}: every must be >= 1")
        if prob is not None and not 0.0 < float(prob) <= 1.0:
            raise ValueError(f"fault {point!r}: prob must be in (0, 1]")
        if action is None:
            action = "stall" if stall_s is not None else "raise"
        if action not in ("raise", "stall", "nan"):
            raise ValueError(f"fault {point!r}: unknown action {action!r}")
        if action == "stall" and stall_s is None:
            raise ValueError(f"fault {point!r}: stall action needs stall_s")
        self.point = point
        self.nth = None if nth is None else int(nth)
        self.every = None if every is None else int(every)
        self.prob = None if prob is None else float(prob)
        self.seed = int(seed)
        self.error = error
        self.stall_s = stall_s
        self.action = action
        self.transient = bool(transient)
        self._rng = random.Random(self.seed)

    def fires(self, call_index: int) -> bool:
        """``call_index`` is 1-based, counted per point since the last
        ``reset()``/``inject()`` for that point."""
        if self.nth is not None:
            return call_index == self.nth
        if self.every is not None:
            return call_index % self.every == 0
        return self._rng.random() < self.prob

    def describe(self) -> Dict:
        trig = (f"nth:{self.nth}" if self.nth is not None
                else f"every:{self.every}" if self.every is not None
                else f"prob:{self.prob}(seed={self.seed})")
        return {"trigger": trig, "action": self.action,
                "stall_s": self.stall_s, "transient": self.transient,
                "error": repr(self.error) if self.error else None}


_lock = threading.Lock()
_specs: Dict[str, _Spec] = {}
_calls: Dict[str, int] = {}      # per-point site-call counts
_fires: Dict[str, int] = {}      # per-point trigger counts
_seen: Dict[str, bool] = {}      # self-registering site catalog
_listeners: List = []            # trigger observers (flight recorder)


def add_trigger_listener(fn) -> None:
    """Register ``fn(point_name)`` to run on EVERY fault trigger,
    before the fault's action executes — how the flight recorder
    (``obs.recorder``) snapshots its ring at the moment of failure.
    Idempotent per callable; listener errors are reported as warnings,
    never masking the fault itself."""
    with _lock:
        if fn not in _listeners:
            _listeners.append(fn)


def remove_trigger_listener(fn) -> None:
    with _lock:
        if fn in _listeners:
            _listeners.remove(fn)


def _notify_listeners(name: str) -> None:
    with _lock:
        listeners = list(_listeners)
    for fn in listeners:
        try:
            fn(name)
        except Exception as e:
            import warnings
            warnings.warn(f"fault trigger listener {fn!r} failed for "
                          f"point {name!r}: {e!r}", stacklevel=3)


def inject(name: str, *, nth: Optional[int] = None,
           every: Optional[int] = None, prob: Optional[float] = None,
           seed: int = 0, error: Optional[BaseException] = None,
           stall_s: Optional[float] = None, action: Optional[str] = None,
           transient: bool = False) -> None:
    """Arm injection point ``name``; resets its call/fire counters so
    triggers count from this arming."""
    spec = _Spec(name, nth=nth, every=every, prob=prob, seed=seed,
                 error=error, stall_s=stall_s, action=action,
                 transient=transient)
    with _lock:
        _specs[name] = spec
        _calls[name] = 0
        _fires[name] = 0


def clear(name: str) -> None:
    """Disarm one point (its site stays registered in the catalog)."""
    with _lock:
        _specs.pop(name, None)


def reset() -> None:
    """Disarm everything and zero all counters (test isolation)."""
    with _lock:
        _specs.clear()
        _calls.clear()
        _fires.clear()


def active() -> Dict[str, Dict]:
    """Currently armed faults, ``{point: spec description}``."""
    with _lock:
        return {n: s.describe() for n, s in _specs.items()}


def points() -> List[str]:
    """Every injection point that has registered itself (a site ran) or
    been armed — the live catalog."""
    with _lock:
        return sorted(set(_seen) | set(_specs))


def fired(name: str) -> int:
    """How many times ``name`` has triggered since its arming/reset."""
    with _lock:
        return _fires.get(name, 0)


def _record_trigger(name: str) -> None:
    _fires[name] = _fires.get(name, 0) + 1


def _note_obs(name: str) -> None:
    # imported lazily: faults must stay importable before (and without)
    # the telemetry layer, and obs pulls in jax
    from distkeras_tpu import obs
    obs.get_registry().counter("faults.triggered").inc(point=name)


def _check(name: str):
    """Count a site call; return the armed spec if it fires."""
    with _lock:
        _seen[name] = True
        spec = _specs.get(name)
        if spec is None:
            return None
        _calls[name] = _calls.get(name, 0) + 1
        if not spec.fires(_calls[name]):
            return None
        _record_trigger(name)
    _note_obs(name)
    _notify_listeners(name)
    return spec


def point(name: str) -> None:
    """The control-flow injection hook. Library code calls this at a
    named site; a disarmed point is a cheap no-op. An armed point that
    fires either stalls (``stall_s``) or raises (``error`` or an
    ``InjectedFault``). An ``action="nan"`` spec belongs to
    ``corrupt()`` sites — one firing at a control point is a loud
    usage error, never a silent no-op (the trigger would be consumed
    and ``fired()`` incremented while injecting nothing, making a
    chaos test pass vacuously)."""
    spec = _check(name)
    if spec is None:
        return
    if spec.action == "nan":
        raise ValueError(
            f"fault {name!r}: action='nan' specs only act at corrupt() "
            f"sites, but {name!r} is a control-flow point — arm a "
            "raise/stall action here, or target a corrupt() site")
    if spec.action == "stall":
        time.sleep(spec.stall_s)
        return
    raise spec.error if spec.error is not None \
        else InjectedFault(name, transient=spec.transient)


def corrupt(name: str, value):
    """The value-corruption hook: returns ``value`` unchanged unless an
    armed ``action="nan"`` spec fires, in which case a NaN-filled copy
    comes back (float arrays/scalars). Raise/stall specs act here
    exactly as at ``point()`` sites."""
    spec = _check(name)
    if spec is None:
        return value
    if spec.action == "stall":
        time.sleep(spec.stall_s)
        return value
    if spec.action == "raise":
        raise spec.error if spec.error is not None \
            else InjectedFault(name, transient=spec.transient)
    import numpy as np
    arr = np.asarray(value, dtype=np.result_type(value, np.float32))
    return np.full_like(arr, np.nan)


def load_env(spec_string: Optional[str] = None) -> None:
    """Parse ``DKT_FAULTS`` (or an explicit string) and arm each spec.
    Format: ``point=opt:val,opt:val;point2=...`` — see module doc."""
    raw = (os.environ.get("DKT_FAULTS", "")
           if spec_string is None else spec_string)
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, opts = part.partition("=")
        if not opts:
            raise ValueError(
                f"DKT_FAULTS spec {part!r}: expected point=opt:val[,...]")
        kw: Dict = {}
        for opt in opts.split(","):
            key, _, val = opt.strip().partition(":")
            if key in ("nth", "every", "seed"):
                kw[key] = int(val)
            elif key == "prob":
                kw["prob"] = float(val)
            elif key == "stall":
                kw["stall_s"] = float(val)
            elif key == "action":
                kw["action"] = val
            elif key == "transient":
                kw["transient"] = val.lower() in ("1", "true", "yes")
            else:
                raise ValueError(
                    f"DKT_FAULTS spec {part!r}: unknown option {key!r}")
        inject(name.strip(), **kw)


load_env()
