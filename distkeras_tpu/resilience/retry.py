"""Retry policies: exponential backoff with full jitter, deadline caps,
and retryable-exception classification.

Applied where the repo touches the unreliable world — checkpoint IO
(``utils/checkpoint.py``) and out-of-core shard fetches
(``Trainer._sharded_stream``) — so a flaky filesystem costs a delay, not
a training run. Policy mechanics follow the AWS full-jitter scheme:
``delay = uniform(0, min(max_delay, base * 2**attempt))``, which avoids
the synchronized-retry stampedes plain exponential backoff produces.

Classification is deliberately narrow by default
(``classify_retryable``): OS/IO errors and timeouts retry;
``faults.InjectedFault`` retries only when armed ``transient=True``;
everything else (assertion, value, XLA errors — bugs, not weather)
surfaces immediately. Every retry records on the obs registry
(``retry.attempts`` counter + ``retry.delay_s`` histogram, labeled by
``op``) so healed faults stay visible.
"""

from __future__ import annotations

import functools
import random
import time
from typing import Callable, Optional, Tuple, Type, Union

from distkeras_tpu.resilience.faults import InjectedFault


def _now() -> float:
    # deferred: utils.profiling (the repo's clock owner) sits behind
    # utils/__init__, which imports checkpoint, which imports THIS
    # module — a top-level import would be circular
    from distkeras_tpu.utils.profiling import now
    return now()

__all__ = ["RetryPolicy", "classify_retryable", "io_retry", "no_retry"]


def classify_retryable(err: BaseException) -> bool:
    """Default classification: transient-world errors only."""
    if isinstance(err, InjectedFault):
        return err.transient
    return isinstance(err, (OSError, TimeoutError))


class RetryPolicy:
    """Bounded retries with full-jitter exponential backoff.

    ``max_attempts`` counts total tries (1 = no retry). ``deadline_s``
    caps the whole call including backoff sleeps: a retry whose delay
    would cross the deadline re-raises instead of sleeping. ``sleep``
    and ``seed`` are injectable so tests run deterministic and instant.
    ``retryable`` is either a predicate or an exception-type tuple.
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0,
                 deadline_s: Optional[float] = None,
                 retryable: Union[Callable[[BaseException], bool],
                                  Tuple[Type[BaseException], ...],
                                  None] = None,
                 seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 op: str = "retry"):
        if max_attempts < 1:
            raise ValueError(
                f"max_attempts must be >= 1, got {max_attempts}")
        self.max_attempts = int(max_attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.deadline_s = deadline_s
        if retryable is None:
            self._retryable = classify_retryable
        elif callable(retryable) and not isinstance(retryable, tuple):
            self._retryable = retryable
        else:
            types = tuple(retryable)
            self._retryable = lambda e: isinstance(e, types)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.op = op

    def _delay(self, attempt: int) -> float:
        """Full jitter: uniform over (0, capped exponential]."""
        cap = min(self.max_delay_s,
                  self.base_delay_s * (2.0 ** (attempt - 1)))
        return self._rng.uniform(0.0, cap)

    def call(self, fn: Callable, *args, op: Optional[str] = None, **kw):
        """Run ``fn(*args, **kw)``, retrying retryable failures. The
        final failure re-raises the original exception."""
        op = op if op is not None else self.op
        t0 = _now()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kw)
            except Exception as err:
                if attempt >= self.max_attempts or not self._retryable(err):
                    raise
                delay = self._delay(attempt)
                if self.deadline_s is not None \
                        and (_now() - t0) + delay > self.deadline_s:
                    raise
                self._note(op, delay)
                self._sleep(delay)

    def wrap(self, fn: Callable, op: Optional[str] = None) -> Callable:
        """Decorator form: ``fetch = policy.wrap(fetch, op="data.fetch")``."""
        op = op if op is not None else getattr(fn, "__name__", self.op)

        @functools.wraps(fn)
        def wrapped(*args, **kw):
            return self.call(fn, *args, op=op, **kw)

        return wrapped

    @staticmethod
    def _note(op: str, delay: float) -> None:
        # lazy: keep retry importable without dragging in jax via obs
        from distkeras_tpu import obs
        reg = obs.get_registry()
        reg.counter("retry.attempts").inc(op=op)
        reg.histogram("retry.delay_s").observe(delay, op=op)


def io_retry(**overrides) -> RetryPolicy:
    """The default policy for local checkpoint/data IO: 3 attempts,
    tens-of-ms jittered backoff — heals a transient EIO/ENOSPC blip
    without masking a persistently broken disk for more than ~0.5 s."""
    kw = dict(max_attempts=3, base_delay_s=0.02, max_delay_s=0.25)
    kw.update(overrides)
    return RetryPolicy(**kw)


def no_retry() -> RetryPolicy:
    """A pass-through policy (``max_attempts=1``) for callers that must
    observe every failure raw."""
    return RetryPolicy(max_attempts=1)
