"""Training supervision: auto-resume, preemption handling, anomaly guard.

``TrainingSupervisor`` wraps any checkpointing trainer of the family
(``SingleTrainer``/``SPMDTrainer``/``PipelineTrainer``/the engine
trainers) and turns "a crash loses the run" (SURVEY §5.4) into "a crash
costs at most one checkpoint interval":

  * **Auto-resume** — when ``train()`` dies (real crash or an armed
    ``resilience.faults`` point), the supervisor flips ``resume=True``
    and restarts; the trainer's full-carry checkpoint/resume contract
    makes the rejoined run bitwise-identical to an uninterrupted one.
    Restart attempts are bounded (``max_restarts``); the budget
    exhausting re-raises the last error.
  * **Preemption** — a SIGTERM (the TPU-preemption notice) requests a
    clean stop: the trainer checkpoints the CURRENT epoch and returns,
    and the supervisor either hands the partial model back
    (``on_preempt="return"``) or exits 0 (``on_preempt="exit"``, the
    batch-job contract: the scheduler sees a clean exit and reschedules
    with ``resume=True``).
  * **Anomaly guard** — ``AnomalyGuard`` watches the per-epoch logs
    (loss by default; any logged scalar, e.g. a gradient-norm metric,
    by name) for NaN/Inf or a spike. Detection raises out of the epoch
    loop; the supervisor deletes the checkpoints that may hold the
    poisoned weights (the epoch's save runs before its callbacks) and
    resumes from the last good snapshot — a bounded number of times
    (``rollback_budget``); epoch granularity is deliberate, the epoch
    being ONE compiled scan (see utils/callbacks.py module doc).

Every intervention lands on the obs registry (``supervisor.restarts`` /
``supervisor.rollbacks`` / ``supervisor.preemptions``) so a supervised
run's history is visible in ``telemetry_snapshot()``. State machine and
semantics: ``docs/resilience.md``.
"""

from __future__ import annotations

import math
import signal
import threading
from collections import deque
from typing import Dict, Optional, Sequence, Tuple, Type

from distkeras_tpu.utils.callbacks import Callback

__all__ = ["AnomalyDetected", "AnomalyGuard", "SupervisedRun",
           "TrainingSupervisor"]


class AnomalyDetected(RuntimeError):
    """Raised by ``AnomalyGuard`` out of the trainer's epoch loop."""

    def __init__(self, epoch: int, key: str, value: float, reason: str):
        super().__init__(
            f"training anomaly at epoch {epoch}: {key}={value!r} "
            f"({reason})")
        self.epoch = epoch
        self.key = key
        self.value = value
        self.reason = reason


class AnomalyGuard(Callback):
    """Per-epoch watchdog over the callback ``logs``.

    ``keys`` are the logged scalars to watch (``loss`` by default; add
    any metric the trainer logs — e.g. a grad-norm metric). NaN/Inf
    always trips. ``spike_factor`` (optional) additionally trips when a
    value exceeds ``spike_factor *`` the median of the last ``window``
    good values (needs at least 2 priors, so epoch 0 can't
    false-positive). The guard raises; pairing with a
    ``TrainingSupervisor`` turns the raise into a rollback, but it is
    also usable alone as a loud NaN tripwire.
    """

    def __init__(self, keys: Sequence[str] = ("loss",),
                 spike_factor: Optional[float] = None, window: int = 5):
        if spike_factor is not None and spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor}")
        self.keys = tuple(keys)
        self.spike_factor = spike_factor
        self._history: Dict[str, deque] = {
            k: deque(maxlen=int(window)) for k in self.keys}

    @staticmethod
    def _median(vals) -> float:
        s = sorted(vals)
        n = len(s)
        return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None) -> None:
        logs = logs or {}
        for key in self.keys:
            value = logs.get(key)
            if value is None:
                continue
            value = float(value)
            if not math.isfinite(value):
                raise AnomalyDetected(epoch, key, value, "non-finite")
            hist = self._history[key]
            if self.spike_factor is not None and len(hist) >= 2:
                baseline = self._median(hist)
                if value > self.spike_factor * abs(baseline):
                    raise AnomalyDetected(
                        epoch, key, value,
                        f"spike > {self.spike_factor}x median "
                        f"{baseline:.6g} of last {len(hist)} epochs")
            hist.append(value)


class SupervisedRun:
    """What ``TrainingSupervisor.run`` returns: the trained model (or
    partial model, when preempted) plus the intervention tally."""

    def __init__(self, model, restarts: int, rollbacks: int,
                 preempted: bool):
        self.model = model
        self.restarts = restarts
        self.rollbacks = rollbacks
        self.preempted = preempted

    def __repr__(self):
        return (f"SupervisedRun(restarts={self.restarts}, "
                f"rollbacks={self.rollbacks}, "
                f"preempted={self.preempted})")


class TrainingSupervisor:
    """Supervise one trainer's ``train(dataset)`` (see module doc).

    The trainer must have ``checkpoint_dir`` set — supervision without
    durable snapshots could only ever restart from scratch, which is
    retry, not recovery. ``restart_on`` classifies which exceptions are
    worth a restart (default: any ``Exception``; ``AnomalyDetected``
    is always handled by the rollback path instead, and
    ``KeyboardInterrupt``/``SystemExit`` always propagate).
    ``handle_signals`` installs preemption handlers around ``run()``
    (main thread only — from other threads deliver preemption by
    calling ``trainer.request_preempt()`` directly).
    """

    def __init__(self, trainer, max_restarts: int = 3,
                 restart_on: Tuple[Type[BaseException], ...] = (Exception,),
                 anomaly_guard: Optional[AnomalyGuard] = None,
                 rollback_budget: int = 1,
                 handle_signals: Sequence[int] = (signal.SIGTERM,),
                 on_preempt: str = "return"):
        if getattr(trainer, "checkpoint_dir", None) is None:
            raise ValueError(
                "TrainingSupervisor needs a trainer with checkpoint_dir "
                "set: auto-resume and rollback restore from its "
                "checkpoints")
        if anomaly_guard is not None \
                and getattr(trainer, "checkpoint_async", False):
            raise ValueError(
                "anomaly_guard does not compose with checkpoint_async: "
                "rollback deletes the poisoned epoch's checkpoint, and an "
                "in-flight background write could republish it after the "
                "delete. Use synchronous checkpoints under supervision.")
        if on_preempt not in ("return", "exit"):
            raise ValueError(
                f"on_preempt must be 'return' or 'exit', got {on_preempt}")
        if max_restarts < 0 or rollback_budget < 0:
            raise ValueError("max_restarts/rollback_budget must be >= 0")
        self.trainer = trainer
        self.max_restarts = int(max_restarts)
        self.restart_on = tuple(restart_on)
        self.anomaly_guard = anomaly_guard
        self.rollback_budget = int(rollback_budget)
        self.handle_signals = tuple(handle_signals)
        self.on_preempt = on_preempt
        self.restarts = 0
        self.rollbacks = 0

    # -- plumbing -----------------------------------------------------------
    def _manager(self):
        maker = getattr(self.trainer, "_checkpoint_manager", None)
        if maker is not None:
            return maker()
        from distkeras_tpu.utils.checkpoint import CheckpointManager
        return CheckpointManager(self.trainer.checkpoint_dir)

    def _counter(self, name: str):
        from distkeras_tpu import obs
        # every call site passes a "supervisor.*" literal; the variable
        # here is just the lazy-import shim
        return obs.get_registry().counter(name)  # lint: allow-dynamic-metric-name

    def _recorder(self):
        from distkeras_tpu.obs.recorder import resolve_recorder
        return resolve_recorder()

    def _install_signals(self):
        installed = {}
        if threading.current_thread() is not threading.main_thread():
            return installed

        def handler(signum, frame):
            self.trainer.request_preempt()

        for sig in self.handle_signals:
            installed[sig] = signal.signal(sig, handler)
        return installed

    def _rollback(self, err: AnomalyDetected) -> None:
        """Delete every checkpoint at/after the anomalous epoch: the
        epoch's save ran before its callbacks saw the logs, so the
        latest snapshot may hold the poisoned weights. Training resumes
        from the newest surviving (good) checkpoint — or from scratch
        when none survives."""
        manager = self._manager()
        for step in manager.all_steps():
            if step >= err.epoch:
                manager.delete(step)

    # -- the loop -----------------------------------------------------------
    def run(self, dataset) -> SupervisedRun:
        trainer = self.trainer
        guard_installed = False
        if self.anomaly_guard is not None \
                and self.anomaly_guard not in trainer.callbacks:
            trainer.callbacks.append(self.anomaly_guard)
            guard_installed = True
        old_handlers = self._install_signals()
        try:
            while True:
                try:
                    model = trainer.train(dataset)
                except AnomalyDetected as err:
                    self._counter("supervisor.anomalies").inc(
                        key=err.key, reason=err.reason.split()[0])
                    if self.rollbacks >= self.rollback_budget:
                        raise
                    self.rollbacks += 1
                    self._counter("supervisor.rollbacks").inc()
                    # flight-recorder forensics: ring state at rollback
                    rec = self._recorder()
                    rec.record("supervisor.rollback",
                               epoch=err.epoch, key=err.key,
                               reason=err.reason, attempt=self.rollbacks)
                    rec.auto_dump("supervisor.rollback")
                    self._rollback(err)
                    trainer.resume = True
                    continue
                except self.restart_on as err:
                    if self.restarts >= self.max_restarts:
                        raise
                    self.restarts += 1
                    self._counter("supervisor.restarts").inc()
                    # dump the ring BEFORE the restart overwrites it —
                    # the crash context (recent epochs/iterations) is
                    # exactly what post-mortems need
                    rec = self._recorder()
                    rec.record("supervisor.restart", error=repr(err),
                               attempt=self.restarts)
                    rec.auto_dump("supervisor.restart")
                    trainer.resume = True
                    continue
                preempted = bool(getattr(trainer, "preempted", False))
                if preempted:
                    self._counter("supervisor.preemptions").inc()
                    if self.on_preempt == "exit":
                        # the clean-preemption contract: checkpoint is
                        # durable (train() waits on async writes before
                        # returning), so exit 0 and let the scheduler
                        # relaunch with resume=True
                        raise SystemExit(0)
                return SupervisedRun(model, self.restarts, self.rollbacks,
                                     preempted)
        finally:
            for sig, old in old_handlers.items():
                signal.signal(sig, old)
            if guard_installed:
                trainer.callbacks.remove(self.anomaly_guard)
