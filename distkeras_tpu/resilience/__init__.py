"""``distkeras_tpu.resilience`` — the fault-tolerance subsystem.

The immune system over the fast paths (kernels, continuous batching)
and the eyes (obs telemetry): every failure mode the repo claims to
handle is injectable (``faults``), bounded-retryable (``retry``), and
supervised (``supervisor``); the serving layer degrades gracefully
(deadlines, load shedding, poisoned-request isolation — see
``serving/``). ``docs/resilience.md`` is the subsystem guide;
``tests/test_resilience.py`` is the chaos suite that proves the
invariants (crash-anywhere resume bitwise-identity, clean preemption,
bounded rollback, bounded serving queues).

Quick tour::

    from distkeras_tpu import resilience
    from distkeras_tpu.resilience import faults

    faults.inject("ckpt.write", nth=2)        # or DKT_FAULTS=...
    sup = resilience.TrainingSupervisor(trainer, max_restarts=3)
    result = sup.run(dataset)                 # survives the fault
    assert result.restarts <= 3
"""

from distkeras_tpu.resilience import faults  # noqa: F401
from distkeras_tpu.resilience.faults import InjectedFault  # noqa: F401
from distkeras_tpu.resilience.retry import (  # noqa: F401
    RetryPolicy, classify_retryable, io_retry, no_retry)
from distkeras_tpu.resilience.supervisor import (  # noqa: F401
    AnomalyDetected, AnomalyGuard, SupervisedRun, TrainingSupervisor)
