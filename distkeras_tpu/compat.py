"""Version shims for the narrow band of jax APIs that moved homes,
plus the ONE backend-selection convention every Pallas-vs-XLA fork in
this repo follows (``backend_is_tpu``).

``shard_map`` graduated from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` namespace; this repo targets both sides of
that move (the CI image pins an older jaxlib than some deploy targets).
Import it from here everywhere — the shim prefers the top-level export
and falls back to the experimental module, defaulting ``check_rep`` off
there to match the graduated API's behavior (the experimental checker
rejects some replication patterns the final API accepts).

``pltpu.CompilerParams`` was named ``TPUCompilerParams`` before the
rename; ``tpu_compiler_params`` resolves whichever this jax ships.
"""

from __future__ import annotations

import jax


def backend_is_tpu() -> bool:
    """True when the TRACE-TIME default backend is a TPU — the repo's
    single convention for choosing a Pallas kernel over its XLA
    fallback (``ops.decode_attention``, ``ops.flash_attention``,
    ``ops.moe_kernels``, the decode/prefill paths in
    ``models.decoding``, and ``MoE``'s fused dispatch all route through
    here). The contract this encodes, documented on
    ``models.decoding.generate``: traced programs assume they execute
    on the default backend. Code that must run on a NON-default device
    (e.g. CPU execution inside a TPU-backed process) should wrap the
    call in ``jax.default_device`` so trace-time agrees with run-time,
    rather than expecting per-input device dispatch."""
    return jax.default_backend() == "tpu"


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` where this jax ships it; the classic
    ``psum(1, axis)`` counting identity otherwise (exact — it is what
    the primitive lowers to)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def cost_analysis(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized across jax versions: older
    releases return a one-element LIST of per-computation dicts, newer
    ones the dict itself. Always returns a dict ({} when unavailable)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` under whichever name this jax
    version exports (older: ``TPUCompilerParams``)."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kwargs)

try:  # jax >= 0.4.38-ish: top-level export
    _shard_map = jax.shard_map
except AttributeError:
    _shard_map = None


def shard_map(f, *, mesh, in_specs, out_specs, **kwargs):
    """``jax.shard_map`` where available, the experimental one otherwise.
    The graduated API renamed ``check_rep`` to ``check_vma``; accept
    either spelling and translate to whichever implementation is live."""
    if _shard_map is not None:
        if "check_rep" in kwargs:
            kwargs["check_vma"] = kwargs.pop("check_rep")
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)
    from jax.experimental.shard_map import shard_map as legacy
    if "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    kwargs.setdefault("check_rep", False)
    return legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
