"""SLO engine: declarative latency/availability objectives with
burn-rate accounting over the existing reservoir histograms.

The degradation machinery (deadlines, bounded admission —
``docs/resilience.md``) so far triggered on ad-hoc thresholds; this
module gives it the principled trigger production serving uses:
*objectives* stated as user-facing promises ("99% of requests see
TTFT under X seconds", "99.9% of terminal requests end FINISHED —
sheds, timeouts and cancellations all spend the availability budget")
evaluated continuously, with a *burn rate* that says how fast the
error budget is being spent.

Definitions (the SRE-workbook convention):

* an objective promises that a ``target`` fraction of requests are
  *good* — under the latency ``threshold``, or terminal-state
  ``finished`` for availability;
* the **error budget** is ``1 - target`` (the tolerated bad fraction);
* the **burn rate** is ``bad_fraction / (1 - target)``: 1.0 means
  exactly on budget, 2.0 means the budget spends twice as fast as it
  accrues, 0 means a clean window. A **breach** is
  ``good_fraction < target`` — for a latency objective this is the
  same statement as "the target percentile exceeds the threshold".

Evaluation reads the ``ServingMetrics`` window's reservoir histograms
(``serving.ttft_s`` / ``serving.tpot_s``) and terminal counters — no
new per-request storage; good fractions come from the reservoir
samples (exact until the reservoir fills, a uniform sample after).
Each ``evaluate()`` lands ``slo.good_fraction`` / ``slo.burn_rate``
gauges (labeled by objective) on the obs registry and increments the
``slo.breach`` counter on each ok->breach transition; evaluations are
retained over a rolling ``window_s`` so ``status()`` can report the
window-max burn rate (the page-worthy number) next to the latest one.

``ServingEngine(slo=[...])`` evaluates every few iterations and
reports objective status in ``health()`` and
``telemetry_snapshot()["components"]["serving"]["slo"]``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from distkeras_tpu.obs.timeseries import Ring
from distkeras_tpu.utils.profiling import now, percentiles

__all__ = ["Objective", "SLOEngine", "availability", "latency_objective",
           "tpot_p99", "ttft_p99"]


@dataclass(frozen=True)
class Objective:
    """One declarative objective (see module doc).

    ``kind="latency"``: ``target`` fraction of ``metric`` histogram
    samples must sit at or under ``threshold`` seconds (``ttft_p99 <
    0.5`` == ``Objective("ttft_p99", "latency", "serving.ttft_s",
    0.5, 0.99)``). ``kind="availability"``: ``target`` fraction of
    terminal requests must end FINISHED (not rejected / timed out /
    cancelled)."""

    name: str
    kind: str = "latency"
    metric: str = ""
    threshold: float = 0.0
    target: float = 0.99

    def __post_init__(self):
        if self.kind not in ("latency", "availability"):
            raise ValueError(
                f"objective {self.name!r}: kind must be 'latency' or "
                f"'availability', got {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1), "
                f"got {self.target}")
        if self.kind == "latency":
            if not self.metric:
                raise ValueError(
                    f"objective {self.name!r}: latency objectives need "
                    "a histogram metric name")
            if self.threshold <= 0.0:
                raise ValueError(
                    f"objective {self.name!r}: threshold must be > 0, "
                    f"got {self.threshold}")


def latency_objective(name: str, metric: str, threshold_s: float,
                      target: float = 0.99) -> Objective:
    return Objective(name, "latency", metric, float(threshold_s),
                     float(target))


def ttft_p99(threshold_s: float) -> Objective:
    """``ttft_p99 < threshold_s``: 99% of requests see their first
    token within the threshold (queueing + prompt ingestion)."""
    return latency_objective("ttft_p99", "serving.ttft_s", threshold_s)


def tpot_p99(threshold_s: float) -> Objective:
    """``tpot_p99 < threshold_s``: 99% of finished multi-token requests
    average at most the threshold per generated token after the first
    (the streaming-smoothness promise)."""
    return latency_objective("tpot_p99", "serving.tpot_s", threshold_s)


def availability(target: float = 0.999) -> Objective:
    """``target`` fraction of terminal requests end FINISHED."""
    return Objective("availability", "availability", target=float(target))


class SLOEngine:
    """Evaluate a set of objectives against a ``ServingMetrics`` window
    (module doc has the burn-rate definitions).

    ``registry`` (default: the global obs registry) receives the
    ``slo.good_fraction`` / ``slo.burn_rate`` gauges and the
    ``slo.breach`` transition counter, so SLO state rides every
    exporter. Thread-safe; ``clock`` is injectable for tests and
    should match the metrics window's clock."""

    def __init__(self, objectives: Sequence[Objective],
                 window_s: float = 300.0, clock=now, registry=None,
                 history_capacity: int = 1024):
        objectives = list(objectives)
        if not objectives:
            raise ValueError("SLOEngine needs at least one objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        if registry is None:
            from distkeras_tpu import obs
            registry = obs.get_registry()
        self.objectives = objectives
        self.window_s = float(window_s)
        self.clock = clock
        self.registry = registry
        self._lock = threading.Lock()
        #: timestamped evaluation history — the ONE burn trajectory.
        #: ``status()``/``health()`` window-max and ``obs.report``'s
        #: per-phase max-burn both read this ring (capacity-bounded;
        #: ``window_s`` filtering happens at read time).
        self.history = Ring(history_capacity)  # (t, {name: status})
        self._breached: Dict[str, bool] = {}
        self._g_frac = registry.gauge("slo.good_fraction")
        self._g_burn = registry.gauge("slo.burn_rate")
        self._c_breach = registry.counter("slo.breach")

    # -- evaluation --------------------------------------------------------

    def _eval_one(self, o: Objective, metrics) -> Dict:
        if o.kind == "availability":
            finished = metrics.requests_finished
            bad = (metrics.requests_rejected + metrics.requests_timed_out
                   + metrics.requests_cancelled)
            n = finished + bad
            good_fraction = 1.0 if n == 0 else finished / n
            value = good_fraction
        else:
            # the engine only ever READS configured series here —
            # objective sets are small and static, so the dynamic name
            # cannot explode cardinality
            hist = metrics.registry.histogram(  # lint: allow-dynamic-metric-name
                o.metric)
            samples = hist.samples()
            n = len(samples)
            if n == 0:
                good_fraction, value = 1.0, None
            else:
                good_fraction = (sum(1 for s in samples
                                     if s <= o.threshold) / n)
                pct = percentiles(samples, (o.target * 100.0,))
                value = next(iter(pct.values())) if pct else None
        budget = 1.0 - o.target
        burn_rate = (1.0 - good_fraction) / budget
        breach = good_fraction < o.target
        out = {"kind": o.kind, "target": o.target, "n": n,
               "good_fraction": good_fraction,
               "burn_rate": burn_rate, "breach": breach, "value": value}
        if o.kind == "latency":
            out["threshold_s"] = o.threshold
        return out

    def evaluate(self, metrics, record: bool = True) -> Dict[str, Dict]:
        """One evaluation pass over the given ``ServingMetrics``
        window; returns ``{objective name: status}`` and records the
        gauges/transition counter. ``record=False`` computes the same
        statuses with NO side effects — no history append, no gauges,
        no breach-transition counting — the read-endpoint variant
        ``health()`` probes use (otherwise breach counts and the
        window-max burn would depend on how often a balancer polls)."""
        t = self.clock()
        statuses = {o.name: self._eval_one(o, metrics)
                    for o in self.objectives}
        if not record:
            return statuses
        self.history.append(t, statuses)
        with self._lock:
            transitions = []
            for name, st in statuses.items():
                was = self._breached.get(name, False)
                if st["breach"] and not was:
                    transitions.append(name)
                self._breached[name] = st["breach"]
        for name, st in statuses.items():
            self._g_frac.set(st["good_fraction"], objective=name)
            self._g_burn.set(st["burn_rate"], objective=name)
        for name in transitions:
            self._c_breach.inc(objective=name)
        return statuses

    # -- views -------------------------------------------------------------

    def breached(self) -> List[str]:
        """Objectives in breach as of the latest evaluation."""
        with self._lock:
            return [n for n, b in self._breached.items() if b]

    def burn_history(self, t0: Optional[float] = None,
                     t1: Optional[float] = None
                     ) -> List[tuple]:
        """Timestamped burn trajectory ``[(t, {objective: burn}), ...]``
        over ``[t0, t1]`` (either bound optional) — the join surface
        ``obs.report`` slices per trace phase. Same ring ``status()``
        computes its window-max from, so reports and ``health()`` can
        never disagree."""
        return [(t, {name: st["burn_rate"] for name, st in sts.items()})
                for t, sts in self.history.window(t0, t1)]

    def status(self) -> Optional[Dict]:
        """The latest evaluation, each objective annotated with its
        window-max burn rate (the rolling-window view, computed over
        the ``history`` ring entries within ``window_s`` of the latest
        evaluation); None before the first ``evaluate()``."""
        last = self.history.last()
        if last is None:
            return None
        t_latest, latest = last
        window_max: Dict[str, float] = {}
        for _, statuses in self.history.window(t_latest - self.window_s):
            for name, st in statuses.items():
                window_max[name] = max(window_max.get(name, 0.0),
                                       st["burn_rate"])
        out = {name: dict(st) for name, st in latest.items()}
        for name, st in out.items():
            st["window_max_burn_rate"] = window_max.get(name, 0.0)
        return {"window_s": self.window_s, "objectives": out,
                "ok": not any(st["breach"] for st in out.values())}
