"""Metrics registry: counters, gauges and reservoir histograms with labels.

The repo's telemetry fragments (``utils.profiling.StepTimer``, the
serving-local ``ServingMetrics`` lists, ad-hoc prints in ``bench.py``)
each invented their own storage. This registry is the one shared
substrate: named instruments, optional label sets, thread-safe updates,
and a ``snapshot()`` dict every exporter (``obs.exporters``) renders
from.

Design constraints, stated because they are the point:

* **Bounded memory.** Histograms keep a fixed-size uniform reservoir
  (Vitter's algorithm R) plus exact streaming count/sum/min/max, so a
  server that runs forever holds O(reservoir) floats per series — the
  fix for ``ServingMetrics``' unbounded ``ttfts``/``latencies`` lists.
  Percentiles come from the reservoir (exact until it fills, sampled
  after).
* **Bounded cardinality.** Each metric caps its distinct label sets
  (``max_series``); past the cap new label sets fold into one overflow
  series and warn ONCE — a label-per-request bug degrades telemetry
  instead of eating the heap.
* **Cheap updates.** One lock acquire + a few float ops per record; the
  hot serving/training paths record per *iteration* or *epoch*, never
  per device op.
"""

from __future__ import annotations

import random
import threading
import warnings
import zlib
from typing import Dict, Iterable, Optional, Tuple

from distkeras_tpu.utils.profiling import percentiles

#: label sets per metric before folding into the overflow series
DEFAULT_MAX_SERIES = 64
#: reservoir floats per histogram series
DEFAULT_RESERVOIR = 1024

_OVERFLOW_KEY = (("overflow", "true"),)


def _label_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _esc(s: str) -> str:
    """Escape the flattening metacharacters: label values like a TPU
    device string (``TPU_0(process=0,(0,0,0,0))``) contain ``,`` and
    ``=``, which would otherwise corrupt the flat form and everything
    parsed back out of it (the Prometheus renderer mis-split exactly
    this way before escaping)."""
    return s.replace("\\", "\\\\").replace(",", "\\,").replace("=", "\\=")


def label_string(key: Tuple[Tuple[str, str], ...]) -> str:
    """``(('a','1'),('b','x'))`` -> ``"a=1,b=x"`` (``""`` unlabeled);
    ``,``/``=``/``\\`` inside keys or values are backslash-escaped.
    ``parse_label_string`` is the exact inverse."""
    return ",".join(f"{_esc(k)}={_esc(v)}" for k, v in key)


_process_label: list = [None]


def process_label() -> Tuple[str, str]:
    """``("process_index", "<jax.process_index()>")`` — THE one helper
    every exporter stamps onto its output lines (groundwork for the
    multi-host runtime: a fleet's scraped series aggregate by process
    without any per-call-site label plumbing). The first SUCCESSFUL
    read is cached; a failure (jax unavailable / backend not yet
    initialized) falls back to ``"0"`` WITHOUT caching, so an export
    that runs before ``jax.distributed.initialize()`` does not pin
    every later export on this host to process 0."""
    if _process_label[0] is None:
        try:
            import jax
            idx = str(int(jax.process_index()))
        except Exception:
            return ("process_index", "0")    # transient: retry next call
        _process_label[0] = ("process_index", idx)
    return _process_label[0]


def parse_label_string(s: str):
    """Inverse of ``label_string``: ``[(key, value), ...]``."""
    if not s:
        return []
    pairs, field, fields, i = [], [], [], 0
    while i < len(s):
        ch = s[i]
        if ch == "\\" and i + 1 < len(s):
            field.append(s[i + 1])
            i += 2
            continue
        if ch == "=" and not fields:        # first unescaped = splits k/v
            fields.append("".join(field))
            field = []
        elif ch == ",":                     # unescaped , ends the pair
            fields.append("".join(field))
            pairs.append(tuple(fields))
            field, fields = [], []
        else:
            field.append(ch)
        i += 1
    fields.append("".join(field))
    pairs.append(tuple(fields))
    return [(k, v) for k, v in pairs]


class _Metric:
    """Shared series bookkeeping; subclasses define the per-series cell."""

    kind = "metric"

    def __init__(self, name: str, registry: "MetricsRegistry",
                 max_series: int):
        self.name = name
        self._registry = registry
        self._lock = registry._lock
        self._series: Dict[Tuple, object] = {}
        self._max_series = max_series
        self._overflow_warned = False

    def _new_cell(self):
        raise NotImplementedError

    def _cell(self, labels: Optional[Dict] = None):
        key = _label_key(labels) if labels else ()
        cell = self._series.get(key)
        if cell is None:
            if len(self._series) >= self._max_series \
                    and key not in self._series:
                if not self._overflow_warned:
                    self._overflow_warned = True
                    warnings.warn(
                        f"metric {self.name!r} exceeded max_series="
                        f"{self._max_series} label sets; further label "
                        "sets fold into the overflow series "
                        "(check for per-request/per-step label values)",
                        stacklevel=4)
                key = _OVERFLOW_KEY
                cell = self._series.get(key)
                if cell is not None:
                    return cell
            cell = self._series[key] = self._new_cell()
        return cell

    def series_keys(self) -> Iterable[Tuple]:
        with self._lock:
            return list(self._series)


class Counter(_Metric):
    """Monotonically increasing float per label set."""

    kind = "counter"

    def _new_cell(self):
        return [0.0]

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        with self._lock:
            self._cell(labels)[0] += amount

    def value(self, **labels) -> float:
        with self._lock:
            key = _label_key(labels) if labels else ()
            cell = self._series.get(key)
            return cell[0] if cell else 0.0

    def values(self) -> Dict[str, float]:
        with self._lock:
            return {label_string(k): v[0] for k, v in self._series.items()}


class Gauge(_Metric):
    """Last-set value per label set; ``track_max`` keeps the watermark."""

    kind = "gauge"

    def _new_cell(self):
        return [0.0, float("-inf")]        # value, watermark

    def set(self, value: float, **labels) -> None:
        with self._lock:
            cell = self._cell(labels)
            cell[0] = float(value)
            if value > cell[1]:
                cell[1] = float(value)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            key = _label_key(labels) if labels else ()
            cell = self._series.get(key)
            return cell[0] if cell else None

    def max(self, **labels) -> Optional[float]:
        with self._lock:
            key = _label_key(labels) if labels else ()
            cell = self._series.get(key)
            return cell[1] if cell else None

    def values(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {label_string(k): {"value": c[0], "max": c[1]}
                    for k, c in self._series.items()}


class _HistCell:
    __slots__ = ("count", "sum", "min", "max", "reservoir", "rng")

    def __init__(self, seed: int):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.reservoir = []
        # deterministic per-series stream: snapshots are reproducible
        # under a fixed observation sequence (test requirement)
        self.rng = random.Random(seed)


class Histogram(_Metric):
    """Exact streaming count/sum/min/max + fixed-size uniform reservoir
    (algorithm R) for percentile estimates. Memory per series is
    O(``reservoir_size``) regardless of observation count."""

    kind = "histogram"

    def __init__(self, name, registry, max_series,
                 reservoir_size: int = DEFAULT_RESERVOIR):
        super().__init__(name, registry, max_series)
        if reservoir_size < 1:
            raise ValueError(f"reservoir_size must be >= 1, got "
                             f"{reservoir_size}")
        self.reservoir_size = int(reservoir_size)

    def _new_cell(self):
        # crc32, not hash(): str hashing is salted per process
        # (PYTHONHASHSEED), which would break cross-process
        # reproducibility of which samples survive a full reservoir
        return _HistCell(seed=zlib.crc32(
            f"{self.name}:{len(self._series)}".encode()))

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        with self._lock:
            cell = self._cell(labels)
            cell.count += 1
            cell.sum += value
            if value < cell.min:
                cell.min = value
            if value > cell.max:
                cell.max = value
            if len(cell.reservoir) < self.reservoir_size:
                cell.reservoir.append(value)
            else:
                j = cell.rng.randrange(cell.count)
                if j < self.reservoir_size:
                    cell.reservoir[j] = value

    def samples(self, **labels):
        """Reservoir contents (a copy) — exact until the reservoir
        fills, a uniform sample after."""
        with self._lock:
            key = _label_key(labels) if labels else ()
            cell = self._series.get(key)
            return list(cell.reservoir) if cell else []

    def stats(self, ps=(50.0, 99.0), **labels) -> Optional[Dict]:
        with self._lock:
            key = _label_key(labels) if labels else ()
            cell = self._series.get(key)
            if cell is None or cell.count == 0:
                return None
            return self._stats_locked(cell, ps)

    @staticmethod
    def _stats_locked(cell: _HistCell, ps=(50.0, 99.0)) -> Dict:
        out = {"count": cell.count, "sum": cell.sum,
               "mean": cell.sum / cell.count,
               "min": cell.min, "max": cell.max}
        pct = percentiles(cell.reservoir, ps)
        if pct:
            out.update(pct)
        return out


class MetricsRegistry:
    """Named instruments, one per (name, kind); re-asking returns the
    same object, asking with a different kind raises (the classic
    metrics-registry contract)."""

    def __init__(self, max_series: int = DEFAULT_MAX_SERIES,
                 reservoir_size: int = DEFAULT_RESERVOIR):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self.max_series = int(max_series)
        self.reservoir_size = int(reservoir_size)

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, self,
                                              self.max_series, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {cls.kind}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str,
                  reservoir_size: Optional[int] = None) -> Histogram:
        return self._get(name, Histogram,
                         reservoir_size=reservoir_size
                         or self.reservoir_size)

    def instruments(self) -> Dict[str, _Metric]:
        """Live ``{name: instrument}`` map (a shallow copy). The
        time-series scraper (``obs.timeseries``) and the report-series
        lint walk this to see which series exist and, for histograms,
        to diff reservoirs between scrapes — read-only access; mutate
        through the instruments themselves."""
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict:
        """``{"counters": {name: {labels: v}}, "gauges": ...,
        "histograms": {name: {labels: stats}}}`` — the one shape every
        exporter consumes and ``exporters.read_jsonl`` reconstructs."""
        with self._lock:
            out = {"counters": {}, "gauges": {}, "histograms": {}}
            for name, m in sorted(self._metrics.items()):
                if isinstance(m, Counter):
                    out["counters"][name] = m.values()
                elif isinstance(m, Gauge):
                    out["gauges"][name] = m.values()
                elif isinstance(m, Histogram):
                    out["histograms"][name] = {
                        label_string(k): Histogram._stats_locked(c)
                        for k, c in m._series.items() if c.count}
            return out
