"""``distkeras_tpu.obs`` — the unified telemetry layer.

One subsystem answering, from a single snapshot: where did the step
time go (spans + the training tape's data/host/device breakdown), did
we recompile (``collectors.RecompileDetector`` + process-global compile
totals), are we data-stalled (``Prefetcher`` queue-depth/stall gauges),
and what is the serving fleet doing (``ServingMetrics`` re-expressed on
the registry). Exporters: JSONL event log, Prometheus text, and the
in-process ``telemetry_snapshot()``.

Quick tour::

    from distkeras_tpu import obs

    with obs.span("epoch"):
        ...                        # nested spans build a tree

    reqs = obs.get_registry().counter("myapp.requests")
    reqs.inc(route="predict")

    snap = obs.telemetry_snapshot()          # everything, one dict
    obs.exporters.JsonlExporter("t.jsonl").export()
    print(obs.exporters.prometheus_text())

Global switch: ``obs.disable()`` (or env ``DKT_TELEMETRY=0``) turns the
instrumentation points — spans, tapes, prefetch gauges, bench hooks —
into no-ops. Explicit registry use (e.g. ``ServingMetrics``, whose
``summary()`` is a functional API, not telemetry) keeps recording
regardless; the switch gates overhead, not correctness.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Callable, Dict, Optional

from distkeras_tpu.obs.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry)
from distkeras_tpu.obs.spans import (  # noqa: F401
    current_path, reset_spans, span, span_records, span_summary)
from distkeras_tpu.obs import collectors, exporters  # noqa: F401
from distkeras_tpu.obs.collectors import (  # noqa: F401
    RecompileDetector, RecompileWarning, compile_totals,
    memory_watermark)
from distkeras_tpu.obs.exporters import SCHEMA_VERSION  # noqa: F401
from distkeras_tpu.obs.tape import (  # noqa: F401
    NULL_TAPE, TrainingTape, detect_peak_flops, resolve_tape,
    timed_stream)
from distkeras_tpu.obs.tracing import (  # noqa: F401
    NULL_TRACER, RequestTracer, resolve_tracer)
from distkeras_tpu.obs.recorder import (  # noqa: F401
    NULL_RECORDER, FlightRecorder, get_recorder, resolve_recorder)
from distkeras_tpu.obs.timeseries import Ring, TimeSeries  # noqa: F401
from distkeras_tpu.obs.slo import Objective, SLOEngine  # noqa: F401

_enabled = [os.environ.get("DKT_TELEMETRY", "1") not in ("0", "false")]
_registry_lock = threading.Lock()
_registry: Optional[MetricsRegistry] = None
_components: Dict[str, Callable] = {}


def enabled() -> bool:
    return _enabled[0]


def enable() -> None:
    _enabled[0] = True


def disable() -> None:
    """No-op the instrumentation points (spans/tapes/gauges)."""
    _enabled[0] = False


def get_registry() -> MetricsRegistry:
    """The process-global default registry (created on first use)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def reset_registry() -> MetricsRegistry:
    """Swap in a fresh global registry (test isolation / new reporting
    window); returns the new one. Existing instrument handles keep
    writing to the OLD registry — re-fetch instruments after a reset."""
    global _registry
    with _registry_lock:
        _registry = MetricsRegistry()
        return _registry


def attach(name: str, provider, owner=None) -> None:
    """Register a component snapshot provider (a zero-arg callable
    returning a dict) under ``name`` — how subsystem-local state (e.g.
    the serving engine's current ``ServingMetrics`` window) joins
    ``telemetry_snapshot()`` without living on the global registry.

    With ``owner``, the registration auto-detaches when ``owner`` is
    garbage-collected, so short-lived engines don't leak. A BOUND
    METHOD provider (``obs.attach(n, self.snapshot, owner=self)`` — the
    natural pattern) is held via ``weakref.WeakMethod`` so the registry
    never keeps ``owner`` alive; any other callable is held strongly,
    so it must not capture ``owner`` itself (close over a
    ``weakref.ref`` instead)."""
    import types
    if owner is not None:
        box = {}
        if isinstance(provider, types.MethodType):
            wm = weakref.WeakMethod(provider)

            def wrapped():
                fn = wm()
                return (fn() if fn is not None
                        and box["ref"]() is not None else None)
        else:
            fn = provider

            def wrapped():
                return fn() if box["ref"]() is not None else None

        def _cleanup(_ref, n=name):
            # pop only OUR registration: a newer attach under the same
            # name must survive an older owner's garbage collection
            if _components.get(n) is wrapped:
                _components.pop(n, None)

        box["ref"] = weakref.ref(owner, _cleanup)
        provider = wrapped
    _components[name] = provider


def detach(name: str) -> None:
    _components.pop(name, None)


def components() -> list:
    """Currently attached component names (registration order)."""
    return list(_components)


def aggregate_serving(snapshot: Optional[Dict] = None) -> Dict:
    """Cross-replica serving aggregation (serving-router PR): collect
    every serving component from a ``telemetry_snapshot()`` — with N
    live engines each attaches under its own name (``"serving"`` /
    ``"serving[<engine_id>]"``) — and sum the fleet-wide counters.
    Returns ``{"replicas": {component name: summary}, "totals":
    {counter: fleet sum}}``; per-replica detail (percentiles, pages,
    SLO status, request timelines — each timeline tagged with its
    engine id) stays under ``"replicas"`` because percentiles do not
    sum."""
    snap = snapshot if snapshot is not None else telemetry_snapshot()
    replicas = {
        name: comp
        for name, comp in (snap.get("components") or {}).items()
        if name == "serving" or name.startswith("serving[")}
    keys = ("requests_finished", "requests_rejected",
            "requests_timed_out", "requests_cancelled",
            "requests_preempted", "requests_transferred",
            "tokens_generated", "prefill_chunks")
    totals: Dict[str, float] = {k: 0 for k in keys}
    for comp in replicas.values():
        if not isinstance(comp, dict):
            continue
        for k in keys:
            v = comp.get(k)
            if isinstance(v, (int, float)):
                totals[k] += v
    return {"replicas": replicas, "totals": totals}


def telemetry_snapshot(registry: Optional[MetricsRegistry] = None) -> Dict:
    """THE unified view: registry metrics + span tree + compile totals
    + device-memory stats + every attached component's snapshot."""
    registry = registry if registry is not None else get_registry()
    components = {}
    for name, provider in list(_components.items()):
        try:
            snap = provider()
        except Exception as e:       # a dying component must not take
            snap = {"error": repr(e)}  # the whole snapshot down
        if snap is not None:
            components[name] = snap
    # watermark BEFORE the metrics snapshot: it writes the
    # device.bytes_in_use gauges on this registry, and the "metrics"
    # view must include the reading taken in this same call
    mem = memory_watermark(registry)
    return {
        "schema_version": SCHEMA_VERSION,
        "metrics": registry.snapshot(),
        "spans": span_summary(),
        "compile": compile_totals(),
        "device_memory": mem,
        "components": components,
    }
