"""Windowed time-series telemetry: metrics *over time*, bounded.

The registry (``obs.registry``) is cumulative by design — counters only
go up, histograms pool every observation since the window began. That
answers "how much, total?" but not the questions a scenario replay or a
capacity review actually asks: when did queue depth start growing, what
was TTFT p99 *during the burst*, how fast was the error budget burning
at minute three. This module adds the missing axis:

* ``Ring`` — a bounded deque of ``(t, payload)`` samples. It is the one
  timestamped-history primitive in the repo: ``TimeSeries`` stores
  scrapes in one, and ``SLOEngine`` keeps its burn-rate history in one
  (so ``obs.report`` and ``ServingEngine.health()`` read the *same*
  trajectory — no duplicate bookkeeping).
* ``TimeSeries`` — a periodic scraper over a live ``MetricsRegistry``.
  Each scrape converts the cumulative state into windowed form:

  - **counters → rates**: per-series delta since the previous scrape
    divided by elapsed time (reset-clamped: a value that went *down*
    means the registry was swapped — e.g. the serving engine's
    per-window ``metrics`` setter — and the delta restarts from zero);
  - **gauges → levels**: the instantaneous value;
  - **histograms → windowed percentiles**: observations that arrived
    since the previous scrape, recovered by diffing the fixed-size
    reservoir (appended tail while it is still filling, replaced slots
    once full — a uniform subsample of the window when the reservoir
    has wrapped), with the exact window count from the streaming
    counter.

Scrapes are pure host-side Python — no ``np.asarray``, no device reads
— so the serving/router step loops can sample on their existing
deferred host-window cadence without adding host syncs
(``tools/lint_host_sync.py`` stays green).

Exports follow the ``obs.exporters`` conventions: a JSONL form using a
new ``"timeseries"`` record type (additive — forward-compatible readers
skip it, no ``SCHEMA_VERSION`` bump needed) and a *timestamped*
Prometheus exposition form (trailing epoch-milliseconds per line, the
optional timestamp the text format allows).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple, Union

from distkeras_tpu.obs.exporters import (SCHEMA_VERSION, _prom_labels,
                                         _prom_name)
from distkeras_tpu.obs.registry import (Counter, Gauge, Histogram,
                                        MetricsRegistry)
from distkeras_tpu.utils.profiling import now as _now
from distkeras_tpu.utils.profiling import percentiles
from distkeras_tpu.utils.profiling import wall as _wall

#: default bound on retained samples (per TimeSeries / Ring)
DEFAULT_CAPACITY = 512

#: ``series()`` field fallback per instrument kind
_DEFAULT_FIELD = {"counters": "rate", "gauges": "value",
                  "histograms": "p50"}

#: percentiles computed for each histogram window
_WINDOW_PS = (50.0, 90.0, 99.0)


class Ring:
    """Bounded timestamped history: ``(t, payload)`` pairs, oldest
    evicted first. Thread-safe; iteration yields a point-in-time copy."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._entries: deque = deque(maxlen=self.capacity)

    def append(self, t: float, payload) -> None:
        with self._lock:
            self._entries.append((float(t), payload))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __iter__(self):
        with self._lock:
            return iter(list(self._entries))

    def last(self) -> Optional[Tuple[float, object]]:
        with self._lock:
            return self._entries[-1] if self._entries else None

    def window(self, t0: Optional[float] = None,
               t1: Optional[float] = None) -> List[Tuple[float, object]]:
        """Entries with ``t0 <= t <= t1`` (either bound optional)."""
        with self._lock:
            entries = list(self._entries)
        return [(t, p) for t, p in entries
                if (t0 is None or t >= t0) and (t1 is None or t <= t1)]

    def span_s(self) -> float:
        with self._lock:
            if len(self._entries) < 2:
                return 0.0
            return self._entries[-1][0] - self._entries[0][0]


class TimeSeries:
    """Periodic registry scraper feeding a bounded :class:`Ring`.

    ``registry`` is either a :class:`MetricsRegistry` or a zero-arg
    callable returning one (or ``None`` to skip) — the callable form
    lets the serving engine's scraper follow its *live* registry across
    the per-window ``metrics`` swaps without re-wiring.

    ``clock`` defaults to the profiling monotonic clock; a replay
    installs a virtual iteration clock here so sample timestamps (and
    therefore every rate) are deterministic. ``tags`` annotate exports
    and ``summary()`` (the router fleet uses ``{"engine": <id>}`` so
    per-replica series separate cleanly).
    """

    def __init__(self,
                 registry: Union[MetricsRegistry,
                                 Callable[[], Optional[MetricsRegistry]]],
                 *,
                 capacity: int = DEFAULT_CAPACITY,
                 interval_s: float = 0.0,
                 clock: Callable[[], float] = _now,
                 tags: Optional[Dict[str, str]] = None):
        if interval_s < 0:
            raise ValueError(f"interval_s must be >= 0, got {interval_s}")
        self._registry_src = registry
        self.interval_s = float(interval_s)
        self.clock = clock
        self.tags = dict(tags or {})
        self.ring = Ring(capacity)
        self._lock = threading.Lock()
        self._last_t: Optional[float] = None
        # per-(name, labels) scrape state for windowed conversion
        self._prev_counter: Dict[Tuple[str, str], float] = {}
        self._prev_hist: Dict[Tuple[str, str], Tuple[int, list]] = {}
        # wall anchor for the timestamped Prometheus form: monotonic /
        # virtual offsets map onto epoch time captured at construction
        self._t0 = clock()
        self._wall0 = _wall()

    # -- scraping ----------------------------------------------------

    def _registry(self) -> Optional[MetricsRegistry]:
        src = self._registry_src
        if callable(src):
            return src()
        return src

    def maybe_sample(self, **extra) -> Optional[Dict]:
        """Scrape iff ``interval_s`` has elapsed since the last scrape
        (always scrapes when ``interval_s == 0``). The serving loops
        call this unconditionally on their host-window cadence and let
        the interval gate do the rest."""
        t = self.clock()
        with self._lock:
            if (self._last_t is not None
                    and t - self._last_t < self.interval_s):
                return None
        return self.sample(**extra)

    def sample(self, **extra) -> Optional[Dict]:
        """Force one scrape; returns the sample dict (also appended to
        the ring) or ``None`` when the registry provider yields none.
        Keyword extras (e.g. ``iteration=...``) are stored on the
        sample so reports can join samples to trace phases."""
        reg = self._registry()
        if reg is None:
            return None
        t = self.clock()
        with self._lock:
            dt = None if self._last_t is None else t - self._last_t
            sample: Dict = dict(extra)
            sample["t"] = t
            sample["counters"] = {}
            sample["gauges"] = {}
            sample["histograms"] = {}
            for name, metric in sorted(reg.instruments().items()):
                if isinstance(metric, Counter):
                    out = {}
                    for labels, v in metric.values().items():
                        key = (name, labels)
                        prev = self._prev_counter.get(key)
                        # reset clamp: a shrinking counter means the
                        # backing registry was swapped — restart at 0
                        delta = v - prev if (prev is not None
                                             and v >= prev) else v
                        rate = (delta / dt) if dt else None
                        self._prev_counter[key] = v
                        out[labels] = {"value": v, "delta": delta,
                                       "rate": rate}
                    if out:
                        sample["counters"][name] = out
                elif isinstance(metric, Gauge):
                    out = {ls: {"value": c["value"]}
                           for ls, c in metric.values().items()}
                    if out:
                        sample["gauges"][name] = out
                elif isinstance(metric, Histogram):
                    out = self._scrape_histogram(name, metric)
                    if out:
                        sample["histograms"][name] = out
            self._last_t = t
        self.ring.append(t, sample)
        return sample

    def reset_baseline(self) -> None:
        """Forget per-instrument scrape state so the next sample treats
        every counter/histogram as starting from zero. Callers that
        deliberately swap the backing registry (e.g. the trace replayer
        opening a fresh per-phase metrics window) must call this: the
        automatic reset clamp only detects a swap when the new value is
        *smaller* than the old one, which a coincidentally equal new
        window defeats."""
        with self._lock:
            self._prev_counter.clear()
            self._prev_hist.clear()

    def _scrape_histogram(self, name: str, metric: Histogram) -> Dict:
        """Windowed stats per label set via reservoir deltas. Cells
        whose observation count is unchanged since the last scrape are
        skipped BEFORE their reservoir is copied — the scraper rides
        the serving loop's host-window cadence, so an idle histogram
        must cost O(1) per scrape, not O(reservoir)."""
        from distkeras_tpu.obs.registry import label_string
        out = {}
        with metric._lock:
            cells = []
            for k, c in metric._series.items():
                labels = label_string(k)
                prev = self._prev_hist.get((name, labels))
                if prev is not None and c.count == prev[0]:
                    continue
                cells.append((labels, c.count, list(c.reservoir)))
        for labels, count, res in cells:
            key = (name, labels)
            prev_count, prev_res = self._prev_hist.get(key, (0, []))
            if count < prev_count:          # registry swap / reset
                prev_count, prev_res = 0, []
            self._prev_hist[key] = (count, res)
            wcount = count - prev_count
            if wcount <= 0:
                continue
            # window values: appended tail while the reservoir fills,
            # replaced slots once full (uniform subsample of the window)
            vals = res[len(prev_res):]
            for i in range(min(len(prev_res), len(res))):
                if res[i] != prev_res[i]:
                    vals.append(res[i])
            stats = {"count": wcount}
            if vals:
                stats["mean"] = sum(vals) / len(vals)
                stats["min"] = min(vals)
                stats["max"] = max(vals)
                stats.update(percentiles(vals, _WINDOW_PS))
            out[labels] = stats
        return out

    # -- views -------------------------------------------------------

    def samples(self) -> List[Tuple[float, Dict]]:
        return list(self.ring)

    def latest(self) -> Optional[Dict]:
        last = self.ring.last()
        return last[1] if last else None

    def series(self, name: str, labels: str = "",
               field: Optional[str] = None) -> List[Tuple[float, float]]:
        """``[(t, value), ...]`` for one series across all samples.
        ``field`` defaults per kind: counter ``rate``, gauge ``value``,
        histogram ``p50`` (ask for ``p99``/``mean``/``count``/...)."""
        out = []
        for t, s in self.ring:
            for kind in ("counters", "gauges", "histograms"):
                entry = s.get(kind, {}).get(name, {}).get(labels)
                if entry is None:
                    continue
                v = entry.get(field or _DEFAULT_FIELD[kind])
                if v is not None:
                    out.append((t, v))
                break
        return out

    def summary(self) -> Dict:
        """Compact descriptor for ``telemetry_snapshot()`` components
        (deliberately not the full ring — bounded output)."""
        last = self.ring.last()
        out = {"capacity": self.ring.capacity,
               "interval_s": self.interval_s,
               "n_samples": len(self.ring),
               "span_s": self.ring.span_s(),
               "tags": dict(self.tags)}
        if last is not None:
            t, s = last
            out["last_t"] = t
            if "iteration" in s:
                out["last_iteration"] = s["iteration"]
            out["n_series"] = sum(
                len(by_name) for kind in ("counters", "gauges",
                                          "histograms")
                for by_name in s.get(kind, {}).values())
        return out

    # -- exports -----------------------------------------------------

    def jsonl_lines(self, seq: int = 0) -> List[str]:
        """One ``meta`` header + one ``"timeseries"`` record per
        (sample, series) — an additive record type under the
        ``SCHEMA_VERSION`` forward-compat contract (old readers skip
        it; no version bump required)."""
        lines = [json.dumps({"type": "meta", "seq": seq,
                             "schema_version": SCHEMA_VERSION,
                             "kind": "timeseries",
                             "interval_s": self.interval_s,
                             "capacity": self.ring.capacity,
                             "tags": self.tags})]
        kinds = (("counters", "counter"), ("gauges", "gauge"),
                 ("histograms", "histogram"))
        for t, s in self.ring:
            extras = {k: v for k, v in s.items()
                      if k not in ("t", "counters", "gauges",
                                   "histograms")}
            for plural, singular in kinds:
                for name, by_label in s.get(plural, {}).items():
                    for labels, entry in by_label.items():
                        rec = {"type": "timeseries", "seq": seq,
                               "t": t, "kind": singular, "name": name,
                               "labels": labels}
                        rec.update(extras)
                        rec.update(entry)
                        lines.append(json.dumps(rec))
        return lines

    def export_jsonl(self, path: str, seq: int = 0) -> None:
        with open(path, "a") as f:
            for line in self.jsonl_lines(seq=seq):
                f.write(line + "\n")

    def prometheus_text(self, prefix: str = "distkeras_") -> str:
        """The LATEST sample in Prometheus text exposition format with
        trailing epoch-millisecond timestamps (the optional per-line
        timestamp the format allows). Counter lines carry the cumulative
        value (Prometheus computes its own rates); gauge lines the
        level; histogram windows render as quantile/sum-less summary
        lines plus a ``_window_count``."""
        last = self.ring.last()
        if last is None:
            return ""
        t, s = last
        ts_ms = int((self._wall0 + (t - self._t0)) * 1000)
        out = []
        for name, by_label in sorted(s.get("counters", {}).items()):
            pname = prefix + _prom_name(name) + "_total"
            out.append(f"# TYPE {pname} counter")
            for labels, entry in sorted(by_label.items()):
                out.append(f"{pname}{_prom_labels(labels)} "
                           f"{entry['value']} {ts_ms}")
        for name, by_label in sorted(s.get("gauges", {}).items()):
            pname = prefix + _prom_name(name)
            out.append(f"# TYPE {pname} gauge")
            for labels, entry in sorted(by_label.items()):
                out.append(f"{pname}{_prom_labels(labels)} "
                           f"{entry['value']} {ts_ms}")
        for name, by_label in sorted(s.get("histograms", {}).items()):
            pname = prefix + _prom_name(name) + "_window"
            out.append(f"# TYPE {pname} summary")
            for labels, entry in sorted(by_label.items()):
                for q in ("p50", "p99"):
                    if q in entry:
                        quant = f'quantile="{float(q[1:]) / 100:g}"'
                        out.append(
                            f"{pname}{_prom_labels(labels, quant)} "
                            f"{entry[q]} {ts_ms}")
                out.append(f"{pname}_count{_prom_labels(labels)} "
                           f"{entry['count']} {ts_ms}")
        return "\n".join(out) + "\n"
