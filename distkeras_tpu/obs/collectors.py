"""JAX-specific telemetry collectors.

Three signals XLA-land owns that generic counters can't see:

* **Backend compiles** — every ``/jax/core/compile/backend_compile_
  duration`` event from ``jax.monitoring`` feeds process-global totals
  (count + seconds). Compile seconds are the "unproductive" term in the
  goodput accounting (``obs.tape``).
* **Per-function recompiles** — ``RecompileDetector.watch(name, fn)``
  tracks a jitted function's executable-cache size
  (``fn._cache_size()``). After ``mark_warm()`` any growth means the
  hot step recompiled — the classic shape-leak bug (a Python int
  promoted to a fresh traced shape, a ragged batch, a dtype drift) —
  and ``check()`` raises a ``RecompileWarning`` naming the function.
  Growth BEFORE warm-up is normal (first-call compiles, one program per
  legitimate shape bucket).
* **Device-memory watermarks** — ``memory_watermark()`` folds
  ``utils.profiling.device_memory_stats`` into per-device gauges whose
  ``max`` field is the high-water mark across calls.
"""

from __future__ import annotations

import threading
import warnings
import weakref
from typing import Dict, Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_totals = {"count": 0, "seconds": 0.0}
_listener_installed = [False]


class RecompileWarning(UserWarning):
    """A watched jitted function recompiled after warm-up."""


def _on_event_duration(name: str, duration: float, **kw) -> None:
    if name != _COMPILE_EVENT:
        return
    with _lock:
        _totals["count"] += 1
        _totals["seconds"] += float(duration)


def install_compile_listener() -> None:
    """Idempotent: register the ``jax.monitoring`` duration listener
    feeding the process-global compile totals."""
    if _listener_installed[0]:
        return
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(
        _on_event_duration)
    _listener_installed[0] = True


def compile_totals() -> Dict[str, float]:
    """Process-global ``{"count", "seconds"}`` of backend compiles
    since the listener was installed."""
    install_compile_listener()
    with _lock:
        return dict(_totals)


class RecompileDetector:
    """Tracks executable-cache growth of named jitted functions.

    Lifecycle: ``watch`` each hot function right after building it,
    ``mark_warm()`` once the warm-up call(s) ran, then ``check()``
    periodically (each epoch / every N serving iterations). ``check``
    warns ONCE per observed growth step, so a leak that recompiles
    every step does not also flood stderr every step.

    Holds jitted functions via weakref where the callable supports it
    (falling back to a strong reference otherwise) so watching never
    extends an executable's lifetime.
    """

    def __init__(self, registry=None):
        install_compile_listener()
        from distkeras_tpu.obs import get_registry
        self.registry = registry if registry is not None else get_registry()
        self._watched: Dict[str, Dict] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        try:
            return int(fn._cache_size())
        except Exception:
            return None

    def watch(self, name: str, fn) -> None:
        """Track ``fn`` (a ``jax.jit`` result) under ``name``. Raises
        if it exposes no ``_cache_size`` (nothing to track)."""
        if not hasattr(fn, "_cache_size"):
            raise TypeError(
                f"{name}: object has no _cache_size(); pass the "
                "jax.jit-wrapped callable itself")
        try:
            ref = weakref.ref(fn)
        except TypeError:
            ref = lambda fn=fn: fn          # not weakref-able: strong
        with self._lock:
            self._watched[name] = {
                "ref": ref,
                "warm": None,                # cache size at mark_warm
                "warned_at": None,           # size already warned about
                "last": None,                # last observed size (kept
            }                                # after the fn is GC'd)

    def mark_warm(self, name: Optional[str] = None) -> None:
        """Freeze the current cache size(s) as the expected steady
        state; growth past it is a recompile."""
        with self._lock:
            entries = ([self._watched[name]] if name is not None
                       else list(self._watched.values()))
            for e in entries:
                fn = e["ref"]()
                if fn is not None:
                    e["warm"] = self._cache_size(fn)

    def counts(self) -> Dict[str, int]:
        """Compile count per watched function — live cache size, or the
        last observed size once the function has been GC'd (a finished
        trainer's epoch program stays visible in the final snapshot)."""
        out = {}
        with self._lock:
            items = list(self._watched.items())
        for name, e in items:
            fn = e["ref"]()
            size = self._cache_size(fn) if fn is not None else None
            if size is not None:
                e["last"] = size
            if size is not None or e["last"] is not None:
                out[name] = size if size is not None else e["last"]
        return out

    def check(self, warn: bool = True) -> Dict[str, int]:
        """Poll watched functions; returns ``{name:
        recompiles_after_warm}`` for those that grew past their warm
        size (empty when all quiet). Updates the registry counters
        either way."""
        grew: Dict[str, int] = {}
        with self._lock:
            items = list(self._watched.items())
        gauge = self.registry.gauge("jit.compile_count")
        for name, e in items:
            fn = e["ref"]()
            if fn is None:
                continue
            size = self._cache_size(fn)
            if size is None:
                continue
            e["last"] = size
            gauge.set(size, fn=name)
            warm = e["warm"]
            if warm is None or size <= warm:
                continue
            grew[name] = size - warm
            if warn and e["warned_at"] != size:
                e["warned_at"] = size
                warnings.warn(
                    f"jitted function {name!r} recompiled after "
                    f"warm-up ({size - warm} new executable(s), cache "
                    f"size {warm} -> {size}) — a hot step retracing "
                    "usually means unstable shapes/dtypes (shape leak)",
                    RecompileWarning, stacklevel=2)
        return grew


def memory_watermark(registry=None):
    """Record per-device ``bytes_in_use`` gauges (watermark = ``max``
    across calls). Returns the stats list, or None where the backend
    exposes none (virtual CPU devices)."""
    from distkeras_tpu.obs import get_registry
    from distkeras_tpu.utils.profiling import device_memory_stats
    registry = registry if registry is not None else get_registry()
    stats = device_memory_stats()
    if not stats:
        return None
    gauge = registry.gauge("device.bytes_in_use")
    for s in stats:
        if s.get("bytes_in_use") is not None:
            gauge.set(s["bytes_in_use"], device=s["device"])
    return stats
