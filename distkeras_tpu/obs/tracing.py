"""Request-level tracing: a bounded per-request event timeline.

The telemetry layer's registry/spans answer "how is the system doing
on average"; a production serving incident needs "what happened to
*this request*" — where did its latency go (queued behind a burst?
chunked prefill of a long neighbour? slow decode?), which slot served
it, how deep was the queue when it arrived. dist-keras shipped
per-worker training histories as first-class artifacts; the
serving-engine equivalent is the per-request timeline this module
records.

Event vocabulary (every event carries a ``utils.profiling.now``
timestamp on the engine clock):

* ``submitted`` — entered the admission queue (queue depth attached);
* ``admitted`` — took a KV slot (slot id + remaining queue depth);
* ``prefix_hit`` — the paged engine served the first N context tokens
  off shared prefix-cache pages (prefill skipped them);
* ``prefill_chunk`` — one prompt chunk ingested (bounded by
  ``ceil(max_len / prefill_chunk)`` per request);
* ``first_token`` — prefill complete, first sample emitted (the TTFT
  edge);
* ``decode`` — AGGREGATED: one event per ``decode_agg`` decode ticks
  (not per token — the hot loop stays cheap), plus a final flush at
  terminal. Since the zero-bubble serving loop, the engine delivers
  ticks in deferred batches (``on_decode_batch``, one call per host
  window rather than one ``on_decode`` per iteration), back-dated to
  the window start — totals are exact, event timestamps are
  window-granular;
* ``spec_verify`` — AGGREGATED like ``decode`` (flushed on the same
  cadence): draft tokens proposed vs accepted for this request's
  speculative verify steps since the last flush;
* ``moe_route`` — AGGREGATED like ``decode`` (flushed on the same
  cadence, MoE engines only): mean router entropy and the max
  top-expert share over the iterations this request decoded since the
  last flush — per-request visibility into the routing concentration
  that shapes MoE decode cost;
* ``preempted`` / ``resumed`` — the paged engine evicted the
  request's pages back to the queue under budget pressure / brought
  it back after the recompute prefill or the host-page swap-in
  (tokens generated so far attached; the request stays live —
  ``admitted`` fires again on re-admission);
* ``swap_out`` / ``swap_in`` — the victim's KV pages moved D2H into
  the host pool at eviction / back H2D at re-admission (offload PR:
  ``n_pages`` attached; a preemption WITHOUT ``swap_out`` resumes by
  re-prefill instead);
* ``finished`` / ``timed_out`` / ``cancelled`` — terminal.

Memory is bounded everywhere: completed timelines live in a
``deque(maxlen=max_requests)``, each timeline caps its event list at
``max_events`` (overflow counted, not stored), and in-flight state is
evicted at terminal.

Two export views:

* ``summaries()`` — compact per-request dicts (phase durations that
  sum exactly to the request's measured latency); the serving engine
  merges them into
  ``telemetry_snapshot()["components"]["serving"]["requests"]``.
* ``chrome_trace()`` / ``dump_chrome_trace(path)`` — Chrome
  trace-event JSON loadable in Perfetto (https://ui.perfetto.dev):
  one track per KV slot (slot occupancy intervals), one track per
  request (queued/prefill/decode phases), and one flow arrow per
  request linking its submission to its completion.

``NULL_TRACER`` is the disabled path (``obs.disable()`` /
``DKT_TELEMETRY=0``): every hook a no-op, resolved once at engine
construction via ``resolve_tracer``.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional

from distkeras_tpu.utils.profiling import now

__all__ = ["NULL_TRACER", "RequestTimeline", "RequestTracer",
           "resolve_tracer"]

#: completed timelines retained (ring; oldest evicted)
DEFAULT_MAX_REQUESTS = 256
#: engine iterations folded into one aggregated ``decode`` event
DEFAULT_DECODE_AGG = 16
#: events stored per timeline before overflow counting kicks in
DEFAULT_MAX_EVENTS = 256

#: terminal states a timeline can end in (mirrors the scheduler's
#: ``TERMINAL_STATES`` without importing serving from obs)
TERMINAL_EVENTS = ("finished", "timed_out", "cancelled")


class RequestTimeline:
    """One request's event list plus the landmark timestamps the
    summary durations derive from. Host-side bookkeeping only."""

    __slots__ = ("rid", "submit_t", "admit_t", "first_token_t", "end_t",
                 "state", "slot", "queue_depth_at_submit",
                 "queue_depth_at_admit", "prefill_chunks", "decode_iters",
                 "n_tokens", "events", "dropped_events", "_agg_count",
                 "_agg_t0", "n_preempted", "prefix_hit_tokens",
                 "spec_proposed", "spec_accepted", "_spec_agg_proposed",
                 "_spec_agg_accepted", "_spec_agg_width",
                 "_spec_agg_path", "_moe_agg_n", "_moe_agg_entropy",
                 "_moe_agg_top")

    def __init__(self, rid: int):
        self.rid = rid
        self.submit_t: Optional[float] = None
        self.admit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.end_t: Optional[float] = None
        self.state = "in_flight"
        self.slot: Optional[int] = None
        self.queue_depth_at_submit: Optional[int] = None
        self.queue_depth_at_admit: Optional[int] = None
        self.prefill_chunks = 0
        self.decode_iters = 0
        self.n_tokens = 0
        self.events: List[Dict] = []
        self.dropped_events = 0
        self._agg_count = 0          # decode iters since last flush
        self._agg_t0: Optional[float] = None
        self.n_preempted = 0         # page-budget evictions survived
        self.prefix_hit_tokens = 0   # context tokens off shared pages
        self.spec_proposed = 0       # draft tokens offered to verify
        self.spec_accepted = 0       # drafts the target accepted
        self._spec_agg_proposed = 0  # since last spec_verify flush
        self._spec_agg_accepted = 0
        self._spec_agg_width = 0     # max tree width in the window
        self._spec_agg_path = 0      # max accepted root-path length
        self._moe_agg_n = 0          # MoE iters since last flush
        self._moe_agg_entropy = 0.0  # summed router entropy (nats)
        self._moe_agg_top = 0.0      # max top-expert share seen

    def add_event(self, name: str, t: float, max_events: int,
                  **fields) -> None:
        if len(self.events) >= max_events:
            self.dropped_events += 1
            return
        ev = {"name": name, "t": t}
        if fields:
            ev.update(fields)
        self.events.append(ev)

    def flush_decode(self, t: float, max_events: int) -> None:
        """Close the open aggregated-decode window (if any), and the
        speculative-verify aggregation riding on the same cadence."""
        if self._agg_count:
            self.add_event("decode", t, max_events,
                           iters=self._agg_count, t0=self._agg_t0)
            self._agg_count = 0
            self._agg_t0 = None
        if self._spec_agg_proposed:
            extra = {}
            if self._spec_agg_width:
                # tree speculation (tree-speculation PR): the widest
                # tree and longest accepted root path in the window
                extra = {"tree_width": self._spec_agg_width,
                         "accepted_path_len": self._spec_agg_path}
            self.add_event("spec_verify", t, max_events,
                           proposed=self._spec_agg_proposed,
                           accepted=self._spec_agg_accepted, **extra)
            self._spec_agg_proposed = 0
            self._spec_agg_accepted = 0
            self._spec_agg_width = 0
            self._spec_agg_path = 0
        if self._moe_agg_n:
            self.add_event(
                "moe_route", t, max_events,
                entropy=round(self._moe_agg_entropy / self._moe_agg_n,
                              4),
                top_share=round(self._moe_agg_top, 4),
                iters=self._moe_agg_n)
            self._moe_agg_n = 0
            self._moe_agg_entropy = 0.0
            self._moe_agg_top = 0.0

    def durations(self) -> Dict[str, float]:
        """Per-phase durations. By construction the emitted phases
        partition the request's life exactly — ``queued_s +
        prefill_s + decode_s == total_s`` (missing phases contribute
        nothing: same landmark timestamps on both sides) — so a
        timeline is token-exact against the measured latency. A
        request terminated while still QUEUED is all queued phase; one
        terminated after admission but before its first token gets the
        admit->end span as ``prefill_s`` (that is the work it died
        in), with no ``ttft_s``/``decode_s``."""
        out: Dict[str, float] = {}
        sub, adm = self.submit_t, self.admit_t
        first, end = self.first_token_t, self.end_t
        if sub is None:
            return out
        if adm is not None:
            out["queued_s"] = adm - sub
            if first is not None:
                out["prefill_s"] = first - adm
                out["ttft_s"] = first - sub
                if end is not None:
                    out["decode_s"] = end - first
            elif end is not None:
                out["prefill_s"] = end - adm
        elif end is not None:
            out["queued_s"] = end - sub
        if end is not None:
            out["total_s"] = end - sub
        return out

    def summary(self) -> Dict:
        out = {
            "rid": self.rid,
            "state": self.state,
            "slot": self.slot,
            "queue_depth_at_submit": self.queue_depth_at_submit,
            "queue_depth_at_admit": self.queue_depth_at_admit,
            "prefill_chunks": self.prefill_chunks,
            "decode_iters": self.decode_iters,
            "n_tokens": self.n_tokens,
            "durations": self.durations(),
        }
        if self.n_preempted:
            out["n_preempted"] = self.n_preempted
        if self.prefix_hit_tokens:
            out["prefix_hit_tokens"] = self.prefix_hit_tokens
        if self.spec_proposed:
            out["spec_proposed"] = self.spec_proposed
            out["spec_accepted"] = self.spec_accepted
        if self.dropped_events:
            out["dropped_events"] = self.dropped_events
        return out


class _NullTracer:
    """Disabled tracing: every hook a no-op (single shared instance)."""

    enabled = False
    engine = None

    def on_submit(self, rid, queue_depth):
        pass

    def on_admit(self, rid, slot, queue_depth):
        pass

    def on_prefill_chunk(self, rid, t0, q_len):
        pass

    def on_prefix_hit(self, rid, n_tokens):
        pass

    def on_first_token(self, rid):
        pass

    def on_decode(self, rids):
        pass

    def on_decode_batch(self, ticks, t0=None):
        pass

    def on_spec_verify(self, items):
        pass

    def on_moe_route(self, rids, entropy, top_share):
        pass

    def on_preempt(self, rid, n_generated=0):
        pass

    def on_swap_out(self, rid, n_pages):
        pass

    def on_swap_in(self, rid, n_pages):
        pass

    def on_resume(self, rid):
        pass

    def on_terminal(self, rid, state, n_tokens=0):
        pass

    def summaries(self):
        return {}

    def timelines(self):
        return []

    def chrome_trace(self):
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    def dump_chrome_trace(self, path):
        return None


NULL_TRACER = _NullTracer()


class RequestTracer:
    """Thread-safe, bounded per-request timeline recorder (module doc
    has the event vocabulary and bounds). ``clock`` must be the SAME
    clock the engine's ``ServingMetrics`` uses, so timeline durations
    and measured latencies are directly comparable — the engine passes
    ``metrics.clock`` when it auto-creates a tracer."""

    enabled = True

    def __init__(self, clock=now, max_requests: int = DEFAULT_MAX_REQUESTS,
                 decode_agg: int = DEFAULT_DECODE_AGG,
                 max_events: int = DEFAULT_MAX_EVENTS):
        if max_requests < 1 or decode_agg < 1 or max_events < 8:
            raise ValueError(
                f"max_requests/decode_agg must be >= 1 and max_events "
                f">= 8, got {max_requests}/{decode_agg}/{max_events}")
        self.clock = clock
        self.max_requests = int(max_requests)
        self.decode_agg = int(decode_agg)
        self.max_events = int(max_events)
        #: engine/replica tag (serving router): with N live engines,
        #: each engine's tracer stamps its summaries and Chrome-trace
        #: tracks with the engine id so cross-replica aggregations stay
        #: separable. Set by the engine at construction
        #: (``resolve_tracer(engine=...)``); None on a standalone
        #: tracer.
        self.engine: Optional[str] = None
        self._lock = threading.Lock()
        self._live: Dict[int, RequestTimeline] = {}
        self._done: deque = deque(maxlen=self.max_requests)
        self._origin = clock()        # chrome-trace time zero
        self.rejected = 0             # shed submits (no timeline)

    # -- recording hooks (engine/scheduler call sites) --------------------

    def on_submit(self, rid: int, queue_depth: int) -> None:
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                tl = self._live[rid] = RequestTimeline(rid)
            tl.submit_t = t
            tl.queue_depth_at_submit = int(queue_depth)
            tl.add_event("submitted", t, self.max_events,
                         queue_depth=int(queue_depth))

    def on_admit(self, rid: int, slot: int, queue_depth: int) -> None:
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.admit_t = t
            tl.slot = int(slot)
            tl.queue_depth_at_admit = int(queue_depth)
            tl.add_event("admitted", t, self.max_events, slot=int(slot),
                         queue_depth=int(queue_depth))

    def on_prefill_chunk(self, rid: int, t0: int, q_len: int) -> None:
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.prefill_chunks += 1
            tl.add_event("prefill_chunk", t, self.max_events,
                         pos=int(t0), len=int(q_len))

    def on_prefix_hit(self, rid: int, n_tokens: int) -> None:
        """The paged engine served ``n_tokens`` of this request's
        context off shared prefix-cache pages (their prefill compute
        was skipped)."""
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.prefix_hit_tokens += int(n_tokens)
            tl.add_event("prefix_hit", t, self.max_events,
                         tokens=int(n_tokens))

    def on_first_token(self, rid: int) -> None:
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.first_token_t = t
            tl.add_event("first_token", t, self.max_events)

    def on_preempt(self, rid: int, n_generated: int = 0) -> None:
        """Page-budget eviction: the request left its slot but stays
        LIVE (its timeline keeps accumulating through re-admission —
        ``admitted`` fires again; latency still measures to the real
        terminal)."""
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.flush_decode(t, self.max_events)
            tl.n_preempted += 1
            tl.add_event("preempted", t, self.max_events,
                         n_generated=int(n_generated))

    def on_swap_out(self, rid: int, n_pages: int) -> None:
        """The preemption victim's KV pages were offloaded D2H to the
        host pool (offload PR) — its resume will be a page swap-in,
        not a re-prefill."""
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.add_event("swap_out", t, self.max_events,
                         n_pages=int(n_pages))

    def on_swap_in(self, rid: int, n_pages: int) -> None:
        """Host pages restored H2D into fresh pool pages; the request
        rejoined decode without recomputing its context."""
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.add_event("swap_in", t, self.max_events,
                         n_pages=int(n_pages))

    def on_resume(self, rid: int) -> None:
        """Recompute prefill (or a page swap-in) finished after a
        preemption; the request rejoined the decode batch."""
        t = self.clock()
        with self._lock:
            tl = self._live.get(rid)
            if tl is None:
                return
            tl.add_event("resumed", t, self.max_events)

    def on_decode(self, rids) -> None:
        """One engine decode iteration over ``rids`` (the decoding
        batch). Aggregated: one stored event per ``decode_agg``
        iterations per request. One tick per rid — the aggregation
        rule lives in :meth:`on_decode_batch`."""
        ticks: Dict[int, int] = {}
        for rid in rids:
            ticks[rid] = ticks.get(rid, 0) + 1
        self.on_decode_batch(ticks)

    def on_decode_batch(self, ticks: Dict[int, int],
                        t0: Optional[float] = None) -> None:
        """Deferred decode ticks (zero-bubble serving loop): ``ticks``
        maps ``rid -> n`` decode ticks accumulated since the engine's
        last host-window flush (one tick per emitted token — for plain
        decode that IS one per iteration; a fused K-step window ticks
        once per token it emitted). ``t0`` back-dates the window start
        so the aggregated ``decode`` events still bracket the real
        span. Equivalent to ``n`` single-rid ``on_decode`` calls,
        batched so the serving hot loop pays one lock/clock per window
        instead of one per iteration."""
        t = self.clock()
        with self._lock:
            for rid, n in ticks.items():
                tl = self._live.get(rid)
                if tl is None:
                    continue
                tl.decode_iters += int(n)
                if tl._agg_count == 0:
                    tl._agg_t0 = t0 if t0 is not None else t
                tl._agg_count += int(n)
                if tl._agg_count >= self.decode_agg:
                    tl.flush_decode(t, self.max_events)

    def on_spec_verify(self, items) -> None:
        """One speculative verify step's per-request outcomes:
        ``items`` is an iterable of ``(rid, proposed, accepted)`` —
        or, for TREE verifies (tree-speculation PR), ``(rid, proposed,
        accepted, tree_width, accepted_path_len)``. Aggregated onto
        the decode-event cadence (flushed together), so speculation
        adds no per-iteration event volume; the tree fields aggregate
        as window maxima."""
        with self._lock:
            for item in items:
                rid, proposed, accepted = item[0], item[1], item[2]
                tl = self._live.get(rid)
                if tl is None:
                    continue
                tl.spec_proposed += int(proposed)
                tl.spec_accepted += int(accepted)
                tl._spec_agg_proposed += int(proposed)
                tl._spec_agg_accepted += int(accepted)
                if len(item) > 3:
                    tl._spec_agg_width = max(tl._spec_agg_width,
                                             int(item[3]))
                    tl._spec_agg_path = max(tl._spec_agg_path,
                                            int(item[4]))

    def on_moe_route(self, rids, entropy: float,
                     top_share: float) -> None:
        """One MoE decode iteration's routing picture for the decoding
        batch ``rids``: mean router entropy (nats) and the top
        expert's share of routing assignments. Aggregated onto the
        decode-event cadence (flushed with ``decode``), so MoE
        telemetry adds no per-iteration event volume."""
        with self._lock:
            for rid in rids:
                tl = self._live.get(rid)
                if tl is None:
                    continue
                tl._moe_agg_n += 1
                tl._moe_agg_entropy += float(entropy)
                if top_share > tl._moe_agg_top:
                    tl._moe_agg_top = float(top_share)

    def on_terminal(self, rid: int, state: str, n_tokens: int = 0) -> None:
        t = self.clock()
        with self._lock:
            tl = self._live.pop(rid, None)
            if tl is None:
                return
            tl.flush_decode(t, self.max_events)
            tl.end_t = t
            tl.state = str(state)
            tl.n_tokens = int(n_tokens)
            tl.add_event(str(state), t, self.max_events)
            self._done.append(tl)

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    # -- views -------------------------------------------------------------

    def timelines(self) -> List[RequestTimeline]:
        """Completed timelines, oldest first, then in-flight ones."""
        with self._lock:
            return list(self._done) + list(self._live.values())

    def summaries(self) -> Dict[int, Dict]:
        """``{rid: compact summary}`` for every retained timeline —
        the view the serving engine merges into
        ``telemetry_snapshot()["components"]["serving"]``. Each
        summary carries the tracer's ``engine`` tag when set, so
        cross-replica aggregations can tell whose request rid 3 was."""
        out = {}
        for tl in self.timelines():
            s = tl.summary()
            if self.engine is not None:
                s["engine"] = self.engine
            out[tl.rid] = s
        return out

    # -- Chrome trace export ----------------------------------------------

    def _us(self, t: float) -> float:
        return (t - self._origin) * 1e6

    def chrome_trace(self) -> Dict:
        """The timelines as Chrome trace-event JSON (the
        ``chrome://tracing`` / Perfetto format): pid 0 = one thread
        per KV slot (occupancy intervals), pid 1 = one thread per
        request (queued/prefill/decode complete events), plus one
        ``s``/``f`` flow pair per request tying its submission to its
        completion across tracks. Durations in microseconds."""
        tag = f"[{self.engine}]" if self.engine is not None else ""
        events: List[Dict] = [
            {"ph": "M", "pid": 0, "name": "process_name",
             "args": {"name": f"kv_slots{tag}"}},
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": f"requests{tag}"}},
        ]
        slots_seen = set()
        for tl in self.timelines():
            rid = tl.rid
            end_t = tl.end_t if tl.end_t is not None else self.clock()
            events.append({"ph": "M", "pid": 1, "tid": rid,
                           "name": "thread_name",
                           "args": {"name": f"req {rid}"}})
            if tl.submit_t is None:
                continue
            args = {"state": tl.state, "slot": tl.slot,
                    "queue_depth_at_submit": tl.queue_depth_at_submit,
                    "n_tokens": tl.n_tokens}
            # request track: the three phases as complete ("X") slices
            adm = tl.admit_t
            events.append({
                "ph": "X", "pid": 1, "tid": rid, "name": "queued",
                "cat": "request", "ts": self._us(tl.submit_t),
                "dur": max(self._us(adm if adm is not None else end_t)
                           - self._us(tl.submit_t), 0.0),
                "args": args})
            if adm is not None:
                first = tl.first_token_t
                events.append({
                    "ph": "X", "pid": 1, "tid": rid, "name": "prefill",
                    "cat": "request", "ts": self._us(adm),
                    "dur": max(self._us(first if first is not None
                                        else end_t) - self._us(adm), 0.0),
                    "args": {"chunks": tl.prefill_chunks}})
                if first is not None:
                    events.append({
                        "ph": "X", "pid": 1, "tid": rid, "name": "decode",
                        "cat": "request", "ts": self._us(first),
                        "dur": max(self._us(end_t) - self._us(first), 0.0),
                        "args": {"iters": tl.decode_iters,
                                 "tokens": tl.n_tokens}})
            # slot track: this request's occupancy interval
            if tl.slot is not None and adm is not None:
                if tl.slot not in slots_seen:
                    slots_seen.add(tl.slot)
                    events.append({"ph": "M", "pid": 0, "tid": tl.slot,
                                   "name": "thread_name",
                                   "args": {"name": f"slot {tl.slot}"}})
                events.append({
                    "ph": "X", "pid": 0, "tid": tl.slot,
                    "name": f"req {rid}", "cat": "slot",
                    "ts": self._us(adm),
                    "dur": max(self._us(end_t) - self._us(adm), 0.0),
                    "args": {"rid": rid, "state": tl.state}})
            # ONE complete flow per request: submission -> completion
            # (crosses tracks when the request held a slot)
            f_pid, f_tid = ((0, tl.slot)
                            if tl.slot is not None and adm is not None
                            else (1, rid))
            events.append({"ph": "s", "pid": 1, "tid": rid,
                           "name": "req_flow", "cat": "flow", "id": rid,
                           "ts": self._us(tl.submit_t)})
            events.append({"ph": "f", "bp": "e", "pid": f_pid,
                           "tid": f_tid, "name": "req_flow",
                           "cat": "flow", "id": rid,
                           "ts": self._us(end_t)})
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_chrome_trace(self, path: str) -> str:
        """Write ``chrome_trace()`` as JSON; returns ``path``. Load in
        Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


def resolve_tracer(tracer=None, clock=now, engine=None):
    """THE engine ``tracer=`` kwarg policy (mirrors
    ``obs.resolve_tape``): ``False`` (or obs disabled) ->
    ``NULL_TRACER``; ``None`` -> a fresh auto tracer on ``clock``;
    anything else is a user-configured tracer used as-is.

    ``engine`` stamps the tracer's engine/replica tag: a fresh auto
    tracer always takes it; a user-configured tracer takes it only if
    it has none yet (the first engine a shared tracer sees names it —
    sharing one tracer across engines is not separable per request
    and a router deployment should give each replica its own)."""
    from distkeras_tpu import obs
    if tracer is False or not obs.enabled():
        return NULL_TRACER
    if tracer is None:
        t = RequestTracer(clock=clock)
        t.engine = engine
        return t
    if engine is not None and tracer.enabled \
            and getattr(tracer, "engine", None) is None:
        tracer.engine = engine
    return tracer
