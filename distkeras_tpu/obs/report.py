"""Scenario SLO reports: trace phases joined against the time series.

``serving.loadgen.replay`` produces per-phase metrics windows, a
per-engine :class:`~distkeras_tpu.obs.timeseries.TimeSeries`, and
per-engine ``SLOEngine`` burn-history rings. This module joins them
into the artifact a capacity review actually reads:

* **per-phase SLO attainment** and **max burn rate** — the worst
  good-fraction across engines per objective, and the peak of the
  burn trajectory inside the phase's virtual-time span (sliced from
  the SAME ring ``SLOEngine.status()`` computes its window-max from);
* **saturation detection** — sustained queue-depth growth inside a
  phase, and the first sample where admission started shedding
  (``serving.requests_rejected`` rate > 0): "queue grew while sheds
  were zero" (under-provisioned but absorbing) reads differently from
  "shed onset at t=X" (actively refusing);
* **TTFT/TPOT percentile timelines per phase** from the windowed
  histogram scrapes, plus **per-replica divergence** for fleet runs
  (a straggler replica hides inside fleet totals; the spread doesn't);
* renderers: JSON (machine), markdown (review comment), and a
  self-contained HTML timeline dashboard (inline SVG, no external
  assets — attachable to a ticket as one file).

Every number in the report derives from the virtual iteration clock
and exact counters, so two replays of the same seeded scenario yield
byte-identical reports (the tier-1 determinism assertion). Wall-clock
values (``StepTimer`` phase seconds, ``fetch_seconds``) are
deliberately excluded.

``REPORT_SERIES`` names every registry series this module reads —
``tools/lint_report_series.py`` asserts each one exists in a live
registry after a smoke scenario, so renaming a metric fails tier-1
instead of silently emptying a report panel.
"""

from __future__ import annotations

import html as _html
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from distkeras_tpu.obs.exporters import SCHEMA_VERSION

#: every registry series name this report reads (via time-series
#: scrapes or the SLO engine's gauges) — the lint contract surface
REPORT_SERIES = (
    "serving.queue_depth",
    "serving.slot_occupancy",
    "serving.requests_finished",
    "serving.requests_rejected",
    "serving.tokens_generated",
    "serving.ttft_s",
    "serving.tpot_s",
    "serving.latency_s",
    "slo.good_fraction",
    "slo.burn_rate",
    "slo.breach",
)

#: metrics-summary keys copied into per-phase engine rows — the
#: deterministic subset (virtual-clock or exact-count derived); the
#: wall-clock keys ("phases" StepTimer seconds) are excluded so two
#: replays report byte-identical numbers
_DET_SUMMARY_KEYS = (
    "requests_submitted", "requests_finished", "requests_rejected",
    "requests_timed_out", "requests_cancelled", "requests_preempted",
    "requests_transferred", "tokens_generated", "tokens_per_sec",
    "prefill_chunks", "ttft_s", "tpot_s", "latency_s", "queue_depth",
    "slot_occupancy", "acceptance_rate", "speculation", "prefix_cache",
    "pages")


# --- joins ------------------------------------------------------------------


def _phase_samples(ts, t0: float, t1: float) -> List[Tuple[float, Dict]]:
    # half-open (t0, t1]: the replayer forces a closing scrape at each
    # phase boundary, so the sample at exactly t0 summarizes the
    # *previous* phase and must not be re-attributed to this one
    return [(t, s) for t, s in ts.ring.window(t0, t1) if t > t0]


def _series_from(samples, kind: str, name: str, field: str,
                 labels: str = "") -> List[Tuple[float, float]]:
    out = []
    for t, s in samples:
        entry = s.get(kind, {}).get(name, {}).get(labels)
        if entry is None:
            continue
        v = entry.get(field)
        if v is not None:
            out.append((t, v))
    return out


def _detect_growth(vals: Sequence[float], min_run: int = 3,
                   min_rise: float = 1.0) -> bool:
    """Sustained growth: a non-decreasing run of >= ``min_run``
    consecutive samples rising by >= ``min_rise`` total."""
    run_start = 0
    for i in range(1, len(vals)):
        if vals[i] < vals[i - 1]:
            run_start = i
        elif (i - run_start + 1 >= min_run
              and vals[i] - vals[run_start] >= min_rise):
            return True
    return False


def _saturation(samples) -> Dict:
    """Queue-growth vs admission-shed onset within one phase."""
    qd = _series_from(samples, "histograms", "serving.queue_depth",
                      "mean")
    shed = _series_from(samples, "counters", "serving.requests_rejected",
                        "delta")
    onset = next((t for t, d in shed if d > 0), None)
    return {
        "queue_growth": _detect_growth([v for _, v in qd]),
        "max_queue_depth": max((v for _, v in qd), default=0.0),
        "shed_onset_t": onset,
    }


def _phase_timeline(samples) -> Dict[str, List]:
    """Compact per-phase series for the dashboard charts."""
    specs = (("queue_depth", "histograms", "serving.queue_depth",
              "mean"),
             ("ttft_p99", "histograms", "serving.ttft_s", "p99"),
             ("tpot_p99", "histograms", "serving.tpot_s", "p99"),
             ("tokens_rate", "counters", "serving.tokens_generated",
              "rate"),
             ("rejected_rate", "counters", "serving.requests_rejected",
              "rate"))
    out: Dict[str, List] = {"t": [round(t, 9) for t, _ in samples]}
    for key, kind, name, field in specs:
        by_t = dict(_series_from(samples, kind, name, field))
        out[key] = [by_t.get(t) for t, _ in samples]
    return out


def _merged_burn_history(result) -> List[Tuple[float, float]]:
    """Fleet-wide burn trajectory: (t, max burn across engines and
    objectives), merged from every engine's burn-history ring. All
    engines scrape on the same virtual clock, so samples group by t."""
    by_t: Dict[float, float] = {}
    for eid, slo in (result.slo or {}).items():
        if slo is None:
            continue
        for t, burns in slo.burn_history():
            if burns:
                by_t[t] = max(by_t.get(t, 0.0), max(burns.values()))
    return sorted(by_t.items())


def _recovery(result) -> Optional[Dict]:
    """Per-incident recovery SLOs for chaos replays.

    For each fault trigger recorded by the replayer:

    * **time_to_first_action** — virtual seconds from the trigger to
      the first non-blocked autoscale decision (scale_up/scale_down/gc)
      at or after it; None when no controller acted.
    * **mttr** — mean-time-to-recovery from the SLO burn-history
      rings: the first post-incident sample where the fleet-max burn
      rate exceeds 1.0 (the budget-neutral line) marks the outage;
      recovery is the first later sample back at <= 1.0. ``mttr`` is
      recovery-t minus incident-t; None while still burning at the end
      of the replay, and absent entirely if the incident never pushed
      burn past 1.0.

    Request accounting splits terminal outcomes into **lost**
    (timed out / cancelled), **replayed** (finished after replica
    failover — tokens re-derived from the seed ledger), and
    **degraded** (finished after a prefill->decode handoff only).
    """
    incidents = getattr(result, "incidents", None) or []
    timeline = getattr(result, "fleet_timeline", None) or []
    events = getattr(result, "autoscale_events", None) or []
    if not incidents and not timeline and not events:
        return None
    burn = _merged_burn_history(result)
    rows: List[Dict] = []
    for inc in incidents:
        t_inc = inc["t"]
        row = dict(inc)
        act = next((e for e in events
                    if e["t"] >= t_inc and e.get("action") != "blocked"),
                   None)
        row["time_to_first_action"] = (
            None if act is None else round(act["t"] - t_inc, 9))
        breach_t = next((t for t, b in burn if t >= t_inc and b > 1.0),
                        None)
        if breach_t is not None:
            rec_t = next((t for t, b in burn
                          if t > breach_t and b <= 1.0), None)
            row["breach_t"] = round(breach_t, 9)
            row["mttr"] = (None if rec_t is None
                           else round(rec_t - t_inc, 9))
        rows.append(row)
    lost = replayed = degraded = 0
    for o in result.outcomes:
        st = o.get("state")
        if st in ("timed_out", "cancelled"):
            lost += 1
        elif st == "finished" and o.get("failovers", 0) > 0:
            replayed += 1
        elif st == "finished" and o.get("handoffs", 0) > 0:
            degraded += 1
    sizes = [e.get("total", 0) for e in timeline]
    actions: Dict[str, int] = {}
    for e in events:
        a = e.get("action", "?")
        actions[a] = actions.get(a, 0) + 1
    out: Dict = {
        "incidents": rows,
        "requests": {"lost": lost, "replayed": replayed,
                     "degraded": degraded},
        "fleet_timeline": timeline,
        "autoscale_actions": actions,
    }
    if sizes:
        out["fleet_size"] = {"min": min(sizes), "max": max(sizes),
                             "final": sizes[-1]}
    mttrs = [r["mttr"] for r in rows if r.get("mttr") is not None]
    if mttrs:
        out["max_mttr"] = max(mttrs)
    return out


def build_report(result) -> Dict:
    """Join a ``loadgen.ReplayResult`` into the scenario report dict
    (JSON-serializable; see the renderers for markdown/HTML forms)."""
    trace = result.trace
    phases_out: List[Dict] = []
    all_att: List[Tuple[str, str, float]] = []   # (phase, objective, v)
    all_burn: List[Tuple[str, str, float]] = []
    for ph in result.phases:
        row: Dict = {
            "name": ph.name, "span": [ph.start, ph.end],
            "t": [round(ph.t0, 9), round(ph.t1, 9)],
            "submitted": ph.submitted, "shed": ph.shed,
        }
        # SLO attainment: worst good-fraction across engines, per
        # objective; max burn from the burn-history ring slice
        attain: Dict[str, float] = {}
        breach = False
        for eid, statuses in (ph.slo or {}).items():
            for name, st in statuses.items():
                v = st["good_fraction"]
                attain[name] = min(attain.get(name, 1.0), v)
                breach = breach or st["breach"]
        if attain:
            row["attainment"] = attain
            row["breach"] = breach
            for name, v in attain.items():
                all_att.append((ph.name, name, v))
        max_burn: Dict[str, float] = {}
        for eid, slo in (result.slo or {}).items():
            if slo is None:
                continue
            for t, burns in slo.burn_history(ph.t0, ph.t1):
                for name, b in burns.items():
                    max_burn[name] = max(max_burn.get(name, 0.0), b)
        if max_burn:
            row["max_burn_rate"] = max_burn
            for name, b in max_burn.items():
                all_burn.append((ph.name, name, b))
        # per-engine deterministic summary subset + fleet sums
        engines: Dict[str, Dict] = {}
        for eid, summary in ph.summaries.items():
            engines[eid] = {k: summary[k] for k in _DET_SUMMARY_KEYS
                            if k in summary}
        row["engines"] = engines
        totals: Dict[str, float] = {}
        for eid, e in engines.items():
            for k in ("requests_finished", "requests_rejected",
                      "requests_timed_out", "requests_preempted",
                      "tokens_generated", "prefill_chunks"):
                v = e.get(k)
                if isinstance(v, (int, float)):
                    totals[k] = totals.get(k, 0) + v
        row["totals"] = totals
        if result.fleet and len(engines) > 1:
            div: Dict[str, Dict] = {}
            for k in ("requests_finished", "tokens_generated"):
                vals = [e.get(k, 0) for e in engines.values()]
                div[k] = {"min": min(vals), "max": max(vals),
                          "spread": max(vals) - min(vals)}
            row["divergence"] = div
        # saturation + timelines from each engine's phase samples
        sat: Dict[str, Dict] = {}
        tl: Dict[str, Dict] = {}
        for eid in result.engine_ids:
            ts = result.timeseries.get(eid)
            if ts is None:
                continue
            samples = _phase_samples(ts, ph.t0, ph.t1)
            if not samples:
                continue
            sat[eid] = _saturation(samples)
            tl[eid] = _phase_timeline(samples)
        row["saturation"] = sat
        row["timeline"] = tl
        phases_out.append(row)

    headline: Dict = {}
    if all_att:
        phname, obj, v = min(all_att, key=lambda x: x[2])
        headline["min_attainment"] = v
        headline["worst_phase"] = phname
        headline["worst_objective"] = obj
    if all_burn:
        phname, obj, b = max(all_burn, key=lambda x: x[2])
        headline["max_burn_rate"] = b
        headline["max_burn_phase"] = phname
    # fleet-wide burn trajectories for the dashboard
    burn_tl: Dict[str, Dict] = {}
    for eid, slo in (result.slo or {}).items():
        if slo is None:
            continue
        hist = slo.burn_history()
        if not hist:
            continue
        objs = sorted({n for _, burns in hist for n in burns})
        burn_tl[eid] = {"t": [round(t, 9) for t, _ in hist]}
        for n in objs:
            burn_tl[eid][n] = [burns.get(n) for _, burns in hist]

    out = {
        "schema_version": SCHEMA_VERSION,
        "kind": "scenario_report",
        "scenario": {
            "seed": trace.meta.get("seed"),
            "n_requests": len(trace.requests),
            "total_iterations": trace.meta.get("total_iterations"),
            "phases": [[p.name, p.start, p.end] for p in trace.phases],
        },
        "dt": result.dt,
        "iterations": result.iterations,
        "fleet": result.fleet,
        "engines": result.engine_ids,
        "requests": result.totals,
        "headline": headline,
        "phases": phases_out,
        "burn": burn_tl,
    }
    # recovery SLOs — only for chaos/autoscale replays (additive key:
    # readers of plain scenario reports see no change)
    rec = _recovery(result)
    if rec is not None:
        out["recovery"] = rec
        if "max_mttr" in rec:
            headline["max_mttr"] = rec["max_mttr"]
    return out


# --- renderers --------------------------------------------------------------


def to_json(report: Dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True)


# --- weight-quantization accuracy (quantized-decode PR) ---------------------


def weight_quant_report(source, weight_quant=None) -> Dict:
    """The accuracy-drift artifact of serving quantized weights: one
    deterministic dict from the per-leaf reconstruction errors a
    ``ServingEngine(weight_quant=...)`` computes at construction
    (``engine.weight_quant_error`` — ``ops.quant_matmul.quant_error``
    per quantized leaf). ``source`` is the engine itself or the raw
    path-keyed error dict."""
    errors = getattr(source, "weight_quant_error", source)
    if weight_quant is None:
        weight_quant = getattr(source, "weight_quant", None)
    if not errors:
        raise ValueError(
            "no weight-quantization errors to report (engine built "
            "without weight_quant?)")
    worst = max(errors, key=lambda k: errors[k]["rel_rms"])
    return {
        "schema_version": SCHEMA_VERSION,
        "weight_quant": weight_quant,
        "num_leaves": len(errors),
        "mean_rel_rms": (sum(v["rel_rms"] for v in errors.values())
                         / len(errors)),
        "worst_leaf": worst,
        "worst_rel_rms": errors[worst]["rel_rms"],
        "max_abs_err": max(v["max_abs_err"] for v in errors.values()),
        "leaves": {k: dict(v) for k, v in sorted(errors.items())},
    }


def weight_quant_markdown(report: Dict) -> str:
    """Review-comment form of :func:`weight_quant_report`: headline +
    one row per quantized leaf."""
    lines = [
        f"# Weight quantization accuracy ({report['weight_quant']})", "",
        f"{report['num_leaves']} quantized leaves — mean rel-RMS "
        f"{_fmt(report['mean_rel_rms'])}, worst "
        f"{_fmt(report['worst_rel_rms'])} at `{report['worst_leaf']}`.",
        "",
        "| leaf | rel RMS | max abs err |", "|---|---|---|"]
    for k, v in report["leaves"].items():
        lines.append(f"| `{k}` | {_fmt(v['rel_rms'])} "
                     f"| {_fmt(v['max_abs_err'], 4)} |")
    return "\n".join(lines) + "\n"


def _fmt(v, nd: int = 3) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def to_markdown(report: Dict) -> str:
    """The review-comment form: headline + one row per phase."""
    lines = [f"# Scenario report ({report['requests'].get('total', 0)} "
             f"requests, {len(report['phases'])} phases)", ""]
    h = report.get("headline") or {}
    if "min_attainment" in h:
        lines.append(
            f"**Headline:** min SLO attainment "
            f"**{_fmt(h['min_attainment'])}** "
            f"({h['worst_objective']} during {h['worst_phase']}); "
            f"max burn rate {_fmt(h.get('max_burn_rate'))} "
            f"(during {h.get('max_burn_phase', '-')}).")
        lines.append("")
    lines += ["| phase | span | submitted | shed | finished | "
              "attainment | max burn | max queue | shed onset |",
              "|---|---|---:|---:|---:|---|---|---:|---|"]
    for ph in report["phases"]:
        att = ph.get("attainment") or {}
        att_s = " ".join(f"{k}={_fmt(v)}" for k, v in sorted(att.items())) \
            or "-"
        burn = ph.get("max_burn_rate") or {}
        burn_s = _fmt(max(burn.values())) if burn else "-"
        sat = ph.get("saturation") or {}
        maxq = max((s.get("max_queue_depth", 0.0)
                    for s in sat.values()), default=0.0)
        onset = next((s["shed_onset_t"] for s in sat.values()
                      if s.get("shed_onset_t") is not None), None)
        fin = ph.get("totals", {}).get("requests_finished", 0)
        lines.append(
            f"| {ph['name']} | {ph['span'][0]}-{ph['span'][1]} | "
            f"{ph['submitted']} | {ph['shed']} | {int(fin)} | {att_s} | "
            f"{burn_s} | {_fmt(maxq, 1)} | {_fmt(onset)} |")
    if report.get("fleet"):
        lines += ["", "## Per-replica divergence", ""]
        for ph in report["phases"]:
            div = ph.get("divergence")
            if div:
                spread = " ".join(
                    f"{k}: {_fmt(v['spread'], 0)}"
                    for k, v in sorted(div.items()))
                lines.append(f"- {ph['name']}: {spread}")
    rec = report.get("recovery")
    if rec:
        lines += ["", "## Recovery", ""]
        reqs = rec.get("requests", {})
        lines.append(
            f"Requests: **{reqs.get('lost', 0)} lost**, "
            f"{reqs.get('replayed', 0)} replayed (failover), "
            f"{reqs.get('degraded', 0)} degraded (handoff).")
        fs = rec.get("fleet_size")
        if fs:
            lines.append(
                f"Fleet size: {fs['min']}-{fs['max']} "
                f"(final {fs['final']}). Autoscale actions: "
                + (" ".join(f"{k}={v}" for k, v in
                            sorted(rec.get("autoscale_actions",
                                           {}).items())) or "none")
                + ".")
        if rec.get("incidents"):
            lines += ["", "| incident | t | first action | MTTR |",
                      "|---|---:|---:|---:|"]
            for inc in rec["incidents"]:
                lines.append(
                    f"| {inc.get('point', '?')} | {_fmt(inc.get('t'))} "
                    f"| {_fmt(inc.get('time_to_first_action'))} "
                    f"| {_fmt(inc.get('mttr')) if 'breach_t' in inc else 'no breach'} |")
    return "\n".join(lines) + "\n"


_CHART_COLORS = ("#2563eb", "#dc2626", "#059669", "#d97706", "#7c3aed",
                 "#0891b2")
_PHASE_COLORS = ("#93c5fd", "#fca5a5", "#86efac", "#fcd34d", "#c4b5fd",
                 "#67e8f9")


def _svg_chart(title: str, series: List[Tuple[str, List[Tuple[float, float]]]],
               phases: List[Tuple[str, float, float]],
               width: int = 880, height: int = 150) -> str:
    """One inline-SVG line chart: phase bands + polylines. Pure
    string-building — the dashboard must stay a single self-contained
    file with no JS/CSS/image dependencies."""
    pad_l, pad_r, pad_t, pad_b = 46, 8, 18, 16
    iw, ih = width - pad_l - pad_r, height - pad_t - pad_b
    pts = [p for _, s in series for p in s if p[1] is not None]
    t_min = min((p[0] for p in pts), default=0.0)
    t_max = max((p[0] for p in pts), default=1.0)
    if phases:
        t_min = min(t_min, min(p[1] for p in phases))
        t_max = max(t_max, max(p[2] for p in phases))
    v_max = max((p[1] for p in pts), default=1.0) or 1.0
    t_span = (t_max - t_min) or 1.0

    def sx(t):
        return pad_l + (t - t_min) / t_span * iw

    def sy(v):
        return pad_t + ih - (v / v_max) * ih

    parts = [f'<svg width="{width}" height="{height}" '
             f'xmlns="http://www.w3.org/2000/svg" '
             f'style="background:#fff;border:1px solid #e5e7eb">']
    for i, (name, p0, p1) in enumerate(phases):
        c = _PHASE_COLORS[i % len(_PHASE_COLORS)]
        parts.append(
            f'<rect x="{sx(p0):.1f}" y="{pad_t}" '
            f'width="{max(sx(p1) - sx(p0), 1):.1f}" height="{ih}" '
            f'fill="{c}" fill-opacity="0.18"/>')
        parts.append(
            f'<text x="{sx(p0) + 2:.1f}" y="{pad_t + 10}" '
            f'font-size="8" fill="#6b7280">{_html.escape(name)}</text>')
    for i, (label, s) in enumerate(series):
        c = _CHART_COLORS[i % len(_CHART_COLORS)]
        path = " ".join(f"{sx(t):.1f},{sy(v):.1f}"
                        for t, v in s if v is not None)
        if path:
            parts.append(f'<polyline points="{path}" fill="none" '
                         f'stroke="{c}" stroke-width="1.3"/>')
        parts.append(
            f'<text x="{pad_l + 4 + i * 130}" y="{height - 4}" '
            f'font-size="9" fill="{c}">{_html.escape(label)}</text>')
    parts.append(f'<text x="2" y="{pad_t + 8}" font-size="9" '
                 f'fill="#374151">{v_max:.3g}</text>')
    parts.append(f'<text x="2" y="{pad_t + ih}" font-size="9" '
                 f'fill="#374151">0</text>')
    parts.append(f'<text x="{pad_l}" y="{pad_t - 6}" font-size="11" '
                 f'font-weight="bold" fill="#111827">'
                 f'{_html.escape(title)}</text>')
    parts.append("</svg>")
    return "".join(parts)


def to_html(report: Dict) -> str:
    """The self-contained timeline dashboard: headline, per-phase
    table (as rendered markdown-ish HTML), and per-engine SVG charts
    for queue depth, TTFT/TPOT p99, token/shed rates and SLO burn."""
    phases = [(ph["name"], ph["t"][0], ph["t"][1])
              for ph in report["phases"]]
    # stitch per-phase timelines back into full-run series per engine
    per_engine: Dict[str, Dict[str, List[Tuple[float, float]]]] = {}
    for ph in report["phases"]:
        for eid, tl in (ph.get("timeline") or {}).items():
            eng = per_engine.setdefault(eid, {})
            for key in ("queue_depth", "ttft_p99", "tpot_p99",
                        "tokens_rate", "rejected_rate"):
                eng.setdefault(key, []).extend(
                    (t, v) for t, v in zip(tl["t"], tl.get(key, []))
                    if v is not None)
    h = report.get("headline") or {}
    head = ""
    if "min_attainment" in h:
        head = (f"min attainment <b>{_fmt(h['min_attainment'])}</b> "
                f"({_html.escape(str(h['worst_objective']))} during "
                f"{_html.escape(str(h['worst_phase']))}), max burn "
                f"{_fmt(h.get('max_burn_rate'))}")
    rows = []
    for ph in report["phases"]:
        att = ph.get("attainment") or {}
        att_s = " ".join(f"{k}={_fmt(v)}"
                         for k, v in sorted(att.items())) or "-"
        fin = ph.get("totals", {}).get("requests_finished", 0)
        rows.append(
            f"<tr><td>{_html.escape(ph['name'])}</td>"
            f"<td>{ph['span'][0]}&ndash;{ph['span'][1]}</td>"
            f"<td>{ph['submitted']}</td><td>{ph['shed']}</td>"
            f"<td>{int(fin)}</td><td>{_html.escape(att_s)}</td></tr>")
    charts = []
    for eid, series in sorted(per_engine.items()):
        charts.append(f"<h3>engine {_html.escape(eid)}</h3>")
        charts.append(_svg_chart(
            "queue depth (window mean)",
            [("queue_depth", series.get("queue_depth", []))], phases))
        charts.append(_svg_chart(
            "latency p99 (s, windowed)",
            [("ttft_p99", series.get("ttft_p99", [])),
             ("tpot_p99", series.get("tpot_p99", []))], phases))
        charts.append(_svg_chart(
            "rates (/s)",
            [("tokens_rate", series.get("tokens_rate", [])),
             ("rejected_rate", series.get("rejected_rate", []))],
            phases))
    for eid, tl in sorted((report.get("burn") or {}).items()):
        objs = [k for k in tl if k != "t"]
        charts.append(_svg_chart(
            f"SLO burn rate — {eid}",
            [(o, [(t, v) for t, v in zip(tl["t"], tl[o])
                  if v is not None]) for o in objs], phases))
    rec = report.get("recovery")
    if rec and rec.get("fleet_timeline"):
        # step-function fleet-size series: repeat each size until the
        # next mutation so the chart reads as levels, not ramps
        tl = rec["fleet_timeline"]
        series = []
        for key in ("total", "serving", "dead"):
            pts: List[Tuple[float, float]] = []
            for i, e in enumerate(tl):
                if i > 0:
                    pts.append((e["t"], tl[i - 1].get(key, 0)))
                pts.append((e["t"], e.get(key, 0)))
            series.append((key, pts))
        charts.append("<h3>fleet</h3>")
        charts.append(_svg_chart("fleet size", series, phases))
        inc_s = " ".join(
            f"{_html.escape(str(i.get('point')))}@t={_fmt(i.get('t'))}"
            f" (first action {_fmt(i.get('time_to_first_action'))}, "
            f"MTTR {_fmt(i.get('mttr')) if 'breach_t' in i else 'no breach'})"
            for i in rec.get("incidents", []))
        reqs = rec.get("requests", {})
        charts.append(
            f"<p>incidents: {inc_s or 'none'}<br>requests: "
            f"{reqs.get('lost', 0)} lost, {reqs.get('replayed', 0)} "
            f"replayed, {reqs.get('degraded', 0)} degraded</p>")
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<title>scenario report</title></head>"
        "<body style='font-family:system-ui,sans-serif;max-width:960px;"
        "margin:24px auto'>"
        f"<h1>Scenario report</h1><p>{head}</p>"
        "<table border='1' cellspacing='0' cellpadding='4' "
        "style='border-collapse:collapse;font-size:13px'>"
        "<tr><th>phase</th><th>span</th><th>submitted</th><th>shed</th>"
        "<th>finished</th><th>attainment</th></tr>"
        + "".join(rows) + "</table>"
        + "".join(charts)
        + "</body></html>")


def save_report(report: Dict, out_dir: str,
                basename: str = "scenario") -> Dict[str, str]:
    """Write the JSON + markdown + HTML artifacts; returns their
    paths (the bench record carries these)."""
    os.makedirs(out_dir, exist_ok=True)
    paths = {}
    for ext, render in (("json", to_json), ("md", to_markdown),
                        ("html", to_html)):
        p = os.path.join(out_dir, f"{basename}.{ext}")
        with open(p, "w") as f:
            f.write(render(report))
        paths[ext] = p
    return paths
