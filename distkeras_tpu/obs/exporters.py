"""Exporters: the registry/span state rendered for the outside world.

Three formats, deliberately boring:

* **JSONL event log** — one self-describing line per series
  (``{"type": "counter"|"gauge"|"histogram"|"span", ...}``) plus a
  ``meta`` header carrying ``schema_version``. Append-oriented (a
  long-running job re-exports snapshots under increasing ``seq``), and
  lossless for the snapshot shape: ``read_jsonl(path)`` reconstructs
  exactly what ``registry.snapshot()`` produced (the round-trip test).
  FORWARD-compatible by contract: readers skip record types they don't
  know and ignore unknown top-level keys, so the format can grow
  (new ``type`` lines, new fields) without breaking old consumers —
  bump ``SCHEMA_VERSION`` on any change an old reader must not
  silently misread.
* **Prometheus text** — the ``# TYPE``-annotated exposition format, for
  scraping or file-based node-exporter pickup. Histograms render as
  summaries (quantile series + ``_sum``/``_count``); metric names are
  sanitized (dots -> underscores). Every line carries a
  ``process_index`` label (``registry.process_label()``) so multi-host
  fleets aggregate without per-call-site label plumbing.
* **In-process snapshot** — ``obs.telemetry_snapshot()`` (the
  ``obs/__init__`` API) returns the unified dict; these functions only
  serialize it.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from distkeras_tpu.obs import spans as _spans

#: telemetry format version, stamped into ``telemetry_snapshot()``,
#: every JSONL ``meta`` header and flight-recorder dump. Version 2 =
#: this scheme's introduction (version 1 is the implicit, unstamped
#: telemetry-PR format). Bump on changes an old reader must not
#: silently misread; additive keys/record types do NOT need a bump
#: (readers tolerate them by contract).
SCHEMA_VERSION = 2

_QUANTILE_KEYS = ("p50", "p99")


def snapshot_lines(snapshot: Dict, spans: Optional[List] = None,
                   seq: int = 0) -> List[str]:
    """Decompose a registry snapshot (+ optional
    ``spans.span_records()`` list) into JSONL lines."""
    lines = [json.dumps({"type": "meta", "seq": seq,
                         "schema_version": SCHEMA_VERSION})]
    for name, series in snapshot.get("counters", {}).items():
        for labels, value in series.items():
            lines.append(json.dumps(
                {"type": "counter", "seq": seq, "name": name,
                 "labels": labels, "value": value}))
    for name, series in snapshot.get("gauges", {}).items():
        for labels, cell in series.items():
            lines.append(json.dumps(
                {"type": "gauge", "seq": seq, "name": name,
                 "labels": labels, "value": cell["value"],
                 "max": cell["max"]}))
    for name, series in snapshot.get("histograms", {}).items():
        for labels, stats in series.items():
            lines.append(json.dumps(
                {"type": "histogram", "seq": seq, "name": name,
                 "labels": labels, **stats}))
    for path, total_s, count in (spans or []):
        lines.append(json.dumps(
            {"type": "span", "seq": seq, "path": list(path),
             "total_s": total_s, "count": count}))
    return lines


def read_jsonl(path: str, seq: Optional[int] = None
               ) -> Tuple[Dict, List]:
    """Parse a JSONL export back into ``(snapshot, span_records)``.
    With ``seq=None`` the LATEST sequence in the file wins (the
    append-log read convention). Forward-compatible: record types this
    reader doesn't know are skipped and unknown top-level keys are
    ignored, so a newer writer's log (higher ``schema_version``, extra
    line types) still yields the series this version understands."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    if seq is None:
        seq = max((r.get("seq", 0) for r in records), default=0)
    snapshot: Dict = {"counters": {}, "gauges": {}, "histograms": {}}
    span_records = []
    for r in records:
        if r.get("seq", 0) != seq:
            continue
        t = r.get("type")
        if t == "counter":
            snapshot["counters"].setdefault(r["name"], {})[
                r["labels"]] = r["value"]
        elif t == "gauge":
            snapshot["gauges"].setdefault(r["name"], {})[r["labels"]] = \
                {"value": r["value"], "max": r["max"]}
        elif t == "histogram":
            stats = {k: v for k, v in r.items()
                     if k not in ("type", "seq", "name", "labels")}
            snapshot["histograms"].setdefault(r["name"], {})[
                r["labels"]] = stats
        elif t == "span":
            span_records.append((tuple(r["path"]), r["total_s"],
                                 r["count"]))
    return snapshot, span_records


class JsonlExporter:
    """Append-only JSONL event log. Each ``export()`` call writes one
    full snapshot under the next ``seq`` — a reporting-interval tick."""

    def __init__(self, path: str):
        self.path = str(path)
        self._seq = 0

    def export(self, snapshot: Optional[Dict] = None,
               spans: Optional[List] = None) -> int:
        """Append one snapshot (default: the global registry + span
        tree); returns the sequence number written."""
        if snapshot is None:
            from distkeras_tpu.obs import get_registry
            snapshot = get_registry().snapshot()
        if spans is None:
            spans = _spans.span_records()
        seq = self._seq
        self._seq += 1
        with open(self.path, "a") as f:
            for line in snapshot_lines(snapshot, spans, seq=seq):
                f.write(line + "\n")
        return seq


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_value(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_labels(labels: str, extra: str = "") -> str:
    from distkeras_tpu.obs.registry import (parse_label_string,
                                            process_label)
    pk, pv = process_label()
    pairs = parse_label_string(labels)
    # process_index first on EVERY line (multi-host groundwork; the
    # single registry.process_label() helper is the only source) —
    # unless the series carries its own, which wins (a duplicate label
    # name is invalid exposition format and fails the whole scrape)
    parts = ([] if any(_prom_name(k) == pk for k, _ in pairs)
             else [f'{pk}="{_prom_value(pv)}"'])
    parts += [f'{_prom_name(k)}="{_prom_value(v)}"' for k, v in pairs]
    if extra:
        parts.append(extra)            # quantile goes last, per convention
    return "{" + ",".join(parts) + "}"


def prometheus_text(snapshot: Optional[Dict] = None,
                    prefix: str = "distkeras_") -> str:
    """Render a snapshot in the Prometheus text exposition format."""
    if snapshot is None:
        from distkeras_tpu.obs import get_registry
        snapshot = get_registry().snapshot()
    out = []
    for name, series in sorted(snapshot.get("counters", {}).items()):
        pname = prefix + _prom_name(name) + "_total"
        out.append(f"# TYPE {pname} counter")
        for labels, value in sorted(series.items()):
            out.append(f"{pname}{_prom_labels(labels)} {value}")
    for name, series in sorted(snapshot.get("gauges", {}).items()):
        pname = prefix + _prom_name(name)
        out.append(f"# TYPE {pname} gauge")
        for labels, cell in sorted(series.items()):
            out.append(f"{pname}{_prom_labels(labels)} {cell['value']}")
    for name, series in sorted(snapshot.get("histograms", {}).items()):
        pname = prefix + _prom_name(name)
        out.append(f"# TYPE {pname} summary")
        for labels, stats in sorted(series.items()):
            for q in _QUANTILE_KEYS:
                if q in stats:
                    quant = f'quantile="{float(q[1:]) / 100:g}"'
                    out.append(f"{pname}{_prom_labels(labels, quant)} "
                               f"{stats[q]}")
            out.append(f"{pname}_sum{_prom_labels(labels)} "
                       f"{stats['sum']}")
            out.append(f"{pname}_count{_prom_labels(labels)} "
                       f"{stats['count']}")
    return "\n".join(out) + "\n"


def dump_prometheus(path: str, snapshot: Optional[Dict] = None) -> None:
    with open(path, "w") as f:
        f.write(prometheus_text(snapshot))
