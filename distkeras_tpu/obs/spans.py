"""Tracing spans: a zero-dependency ``with span("epoch"):`` tree.

Spans answer "where did the wall time go" at the orchestration level —
epoch / data_wait / prefill / decode — the layer ABOVE what an XLA
trace shows. Each ``span(name)`` pushes onto a thread-local stack, so
nesting builds a path tree (``("train", "epoch", "device")``) without
any caller plumbing; aggregation (total seconds + count per path) is
process-global and lock-protected, so worker threads (serving engine,
``StreamingPredictor``, ``Prefetcher``) land in the same tree.

Bridged to ``jax.profiler.TraceAnnotation`` when available: the same
span names show up on the host timeline in XProf/TensorBoard next to
the device ops they enclose, so a span table (``tools/xprof_op_table.py
--spans``) and an xprof trace cross-reference by name.

Disabled path (``obs.disable()``): one predicate check, no clock reads,
no allocation — the overhead contract for production hot loops.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional, Tuple

from distkeras_tpu.utils.profiling import now

#: distinct span paths kept before new paths are dropped (a span name
#: built from a request id would otherwise grow without bound)
MAX_PATHS = 4096

_lock = threading.RLock()
_agg: Dict[Tuple[str, ...], list] = {}   # path -> [total_s, count]
_tls = threading.local()
_overflow_warned = [False]

# the xprof bridge is best-effort: jax is always importable in this
# repo, but TraceAnnotation construction can fail on exotic backends —
# one failure disables the bridge rather than taxing every span
_trace_annotation = [None]


def _get_annotation_cls():
    if _trace_annotation[0] is None:
        try:
            import jax
            _trace_annotation[0] = jax.profiler.TraceAnnotation
        except Exception:
            _trace_annotation[0] = False
    return _trace_annotation[0]


def _enabled() -> bool:
    from distkeras_tpu import obs
    return obs.enabled()


@contextlib.contextmanager
def span(name: str):
    """Time the enclosed block under ``name``, nested inside whatever
    span is active on this thread. Exception-safe: the stack pops and
    the (partial) duration records on every exit path."""
    if not _enabled():
        yield
        return
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    stack.append(str(name))
    path = tuple(stack)
    ann_cls = _get_annotation_cls()
    ann = None
    if ann_cls:
        try:
            ann = ann_cls(name)
            ann.__enter__()
        except Exception:
            _trace_annotation[0] = False
            ann = None
    t0 = now()
    try:
        yield
    finally:
        dt = now() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        stack.pop()
        with _lock:
            rec = _agg.get(path)
            if rec is not None:
                rec[0] += dt
                rec[1] += 1
            elif len(_agg) < MAX_PATHS:
                _agg[path] = [dt, 1]
            elif not _overflow_warned[0]:
                _overflow_warned[0] = True
                import warnings
                warnings.warn(
                    f"span tree exceeded {MAX_PATHS} distinct paths; "
                    "further paths are dropped (span names should be "
                    "static, not per-request values)", stacklevel=3)


def current_path() -> Tuple[str, ...]:
    """The active span path on THIS thread (empty outside any span)."""
    return tuple(getattr(_tls, "stack", ()) or ())


def reset_spans() -> None:
    with _lock:
        _agg.clear()
        _overflow_warned[0] = False


def span_records():
    """Flat ``[(path_tuple, total_s, count)]`` — the exporter view."""
    with _lock:
        return [(path, rec[0], rec[1]) for path, rec in _agg.items()]


def span_summary() -> Dict:
    """Nested tree: ``{name: {"count", "total_s", "self_s",
    "children": {...}}}``. ``self_s`` is wall time not accounted to any
    child span (the "accounted time" view: a large ``self_s`` on a
    parent means untraced work inside it)."""
    with _lock:
        items = sorted(_agg.items())
    root: Dict = {}
    for path, (total, count) in items:
        node_map = root
        node = None
        for part in path:
            node = node_map.setdefault(
                part, {"count": 0, "total_s": 0.0, "children": {}})
            node_map = node["children"]
        node["count"] += count
        node["total_s"] += total

    def finish(node_map):
        for node in node_map.values():
            child_total = sum(c["total_s"]
                              for c in node["children"].values())
            node["self_s"] = max(node["total_s"] - child_total, 0.0)
            finish(node["children"])
    finish(root)
    return root
