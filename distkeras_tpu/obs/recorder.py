"""Flight recorder: a fixed-size ring of recent engine/trainer activity,
dumped to JSONL when something goes wrong.

Post-incident forensics need the iterations *leading up to* a failure
— batch composition, occupancy, scheduler decisions, admission
rejections, trainer epochs — which steady-state metrics have already
aggregated away by the time anyone looks. The recorder keeps the last
``capacity`` records in memory (O(ring), no per-record IO) and writes
them out only on a trigger:

* any armed ``resilience.faults`` point firing (the chaos/crash path;
  installed via ``faults.add_trigger_listener``);
* an admission-rejection storm (``reject_storm`` sheds since the last
  dump — sustained overload, not one unlucky submit);
* a ``DegradedRequest`` surfacing from ``ServingEngine.run()``;
* ``TrainingSupervisor`` restarts/rollbacks;
* an explicit ``dump()`` call.

Record vocabulary (each line carries ``seq``, ``t`` —
``utils.profiling.wall`` epoch seconds — and ``kind``):

* ``serving.iteration`` — per engine ``step()``: queue depth,
  occupancy, decoding/prefilling/admitted rids, and — paged engines —
  ``pages_free`` (written BEFORE the iteration's prefill/decode run,
  so a mid-iteration fault dump contains the failing iteration
  itself; an admission stall reads directly as queue growth against a
  starved page budget);
* ``serving.rejected`` — one shed submit;
* ``serving.preempted`` — a decoding request's pages evicted back to
  the queue (rid, slot, tokens generated so far, pages freed);
* ``train.epoch`` — per epoch-loop iteration of any trainer
  (``parallel.trainers.epoch_exit``, the shared exit point);
* ``supervisor.restart`` / ``supervisor.rollback`` — interventions;
* ``fault.triggered`` — an injection point fired.

Dumps are JSONL: a ``{"type": "meta", "schema_version": ...}`` header
(same versioning as ``obs.exporters``) followed by the ring, oldest
first. Auto-triggered dumps are throttled (``min_auto_interval_s``) so
a fault firing every iteration produces one dump, not one per step.

One PROCESS-GLOBAL recorder (``get_recorder()``) is shared by serving
engines, trainers and the supervisor — a crash dump shows what *all*
of them were doing. ``obs.disable()`` (or ``DKT_TELEMETRY=0``) routes
every instrumentation site to ``NULL_RECORDER`` instead (resolved at
engine/loop setup via ``resolve_recorder``): the steady-state cost of
a disabled recorder is one attribute check.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
from collections import deque
from typing import Dict, List, Optional

from distkeras_tpu.utils.profiling import now, wall

__all__ = ["FlightRecorder", "NULL_RECORDER", "get_recorder",
           "read_flight_dump", "reset_recorder", "resolve_recorder"]

#: ring slots (records) a recorder retains
DEFAULT_CAPACITY = 256
#: admission rejections since the last dump that count as a storm
DEFAULT_REJECT_STORM = 8
#: minimum seconds between AUTO dumps (explicit ``dump()`` ignores it)
DEFAULT_MIN_AUTO_INTERVAL_S = 1.0


class _NullRecorder:
    """Disabled path: every hook a no-op (single shared instance)."""

    enabled = False

    def record(self, kind, **fields):
        pass

    def note_rejection(self, **fields):
        pass

    def auto_dump(self, reason):
        return None

    def dump(self, reason="manual", path=None):
        return None

    def records(self):
        return []

    def clear(self):
        pass


NULL_RECORDER = _NullRecorder()


class FlightRecorder:
    """Bounded ring + trigger-driven JSONL dumps (module doc).

    ``dump_dir`` defaults to ``$DKT_FLIGHT_DIR`` or a per-process
    directory under the system temp dir; each dump is one new file
    ``flight_<seq>_<reason>.jsonl`` (paths retained on ``dumps``)."""

    enabled = True

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: Optional[str] = None,
                 reject_storm: int = DEFAULT_REJECT_STORM,
                 min_auto_interval_s: float = DEFAULT_MIN_AUTO_INTERVAL_S):
        if capacity < 1 or reject_storm < 1:
            raise ValueError(
                f"capacity/reject_storm must be >= 1, got "
                f"{capacity}/{reject_storm}")
        self.capacity = int(capacity)
        self.reject_storm = int(reject_storm)
        self.min_auto_interval_s = float(min_auto_interval_s)
        self.dump_dir = (dump_dir
                         or os.environ.get("DKT_FLIGHT_DIR")
                         or os.path.join(tempfile.gettempdir(),
                                         f"dkt_flight_{os.getpid()}"))
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = itertools.count()
        self._dump_seq = itertools.count()
        self._rejects_since_dump = 0
        self._last_auto: Optional[float] = None
        self.dumps: List[str] = []       # paths written, oldest first

    # -- recording ---------------------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one ring record. Cheap: a dict build + deque append
        under a lock; callers gate any expensive field ASSEMBLY on
        ``recorder.enabled`` (the engine builds its rid lists only when
        a live recorder will keep them)."""
        rec = {"seq": next(self._seq), "t": wall(), "kind": str(kind)}
        if fields:
            rec.update(fields)
        with self._lock:
            self._ring.append(rec)

    def note_rejection(self, **fields) -> None:
        """One shed submit; dumps automatically when sheds since the
        last dump reach ``reject_storm`` (sustained overload)."""
        self.record("serving.rejected", **fields)
        with self._lock:
            self._rejects_since_dump += 1
            storm = self._rejects_since_dump >= self.reject_storm
        if storm:
            self.auto_dump("admission_storm")

    def records(self) -> List[Dict]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._rejects_since_dump = 0

    # -- dumping -----------------------------------------------------------

    def auto_dump(self, reason: str) -> Optional[str]:
        """Trigger-path dump, throttled to one per
        ``min_auto_interval_s`` (a fault storm firing every iteration
        writes one forensic file, not one per step). Returns the path,
        or None when throttled."""
        t = now()
        with self._lock:
            if self._last_auto is not None \
                    and t - self._last_auto < self.min_auto_interval_s:
                return None
            self._last_auto = t
        return self.dump(reason)

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> str:
        """Write the ring (oldest first) as JSONL under a meta header;
        returns the path written. Resets the rejection-storm counter —
        the next storm counts from this dump."""
        from distkeras_tpu.obs.exporters import SCHEMA_VERSION
        with self._lock:
            records = list(self._ring)
            self._rejects_since_dump = 0
            dseq = next(self._dump_seq)
        if path is None:
            os.makedirs(self.dump_dir, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "._-" else "_"
                           for c in str(reason))[:64]
            path = os.path.join(self.dump_dir,
                                f"flight_{dseq:04d}_{safe}.jsonl")
        header = {"type": "meta", "schema_version": SCHEMA_VERSION,
                  "reason": str(reason), "dumped_at": wall(),
                  "capacity": self.capacity, "n_records": len(records)}
        with open(path, "w") as f:
            f.write(json.dumps(header) + "\n")
            for rec in records:
                f.write(json.dumps(rec) + "\n")
        self.dumps.append(path)
        return path


def read_flight_dump(path: str):
    """Parse a dump back into ``(header, records)`` — unknown record
    kinds and extra keys pass through untouched (the same
    forward-compatibility contract as ``exporters.read_jsonl``)."""
    header: Dict = {}
    records: List[Dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("type") == "meta" and not header:
                header = rec
            else:
                records.append(rec)
    return header, records


_global_lock = threading.Lock()
_global: List[Optional[FlightRecorder]] = [None]
_hook_installed = [False]


def _fault_listener(point: str) -> None:
    rec = _global[0]
    if rec is None:
        return
    rec.record("fault.triggered", point=point)
    rec.auto_dump(f"fault:{point}")


def get_recorder() -> FlightRecorder:
    """The process-global recorder (created on first use). Creation
    installs the ``resilience.faults`` trigger listener, so every
    armed fault that fires from then on snapshots the ring."""
    with _global_lock:
        if _global[0] is None:
            _global[0] = FlightRecorder()
        if not _hook_installed[0]:
            from distkeras_tpu.resilience import faults
            faults.add_trigger_listener(_fault_listener)
            _hook_installed[0] = True
        return _global[0]


def reset_recorder() -> None:
    """Drop the global recorder and its fault hook (test isolation)."""
    with _global_lock:
        if _hook_installed[0]:
            from distkeras_tpu.resilience import faults
            faults.remove_trigger_listener(_fault_listener)
            _hook_installed[0] = False
        _global[0] = None


def resolve_recorder():
    """The instrumentation-site policy: the global recorder while obs
    is enabled, ``NULL_RECORDER`` otherwise (NULL-object path — the
    disabled steady state costs one attribute check per site)."""
    from distkeras_tpu import obs
    return get_recorder() if obs.enabled() else NULL_RECORDER
