"""Per-step training telemetry tape: where did the step time go.

The reference's entire training telemetry was two wall-clock stamps
(``Trainer.record_training_start/stop``). The tape keeps that number
but decomposes it the way an MLPerf-style report does:

* **phase breakdown** — ``data_wait`` (host blocked on the input
  pipeline), ``device`` (dispatch + epoch scan + result fetch),
  ``validation``, ``checkpoint``, and the derived ``host`` remainder;
* **rates** — examples (imgs/tokens) per second per epoch;
* **MFU** — ``rate x flops_per_example / peak_flops`` when both terms
  are known (``flops_per_example`` from XLA cost analysis of the
  compiled step, ``peak_flops`` from ``detect_peak_flops``);
* **goodput** — productive device seconds (device phase minus backend
  compile seconds that landed inside it) over TOTAL wall seconds since
  ``train_begin``, checkpoint/restore/compile included. A run that
  spends half its wall clock compiling or checkpointing has goodput
  ~0.5 no matter how fast its steps are.

Every ``epoch_end`` returns a flat ``logs`` dict the trainers merge
into the callback logs, so ``CSVLogger``/``TensorBoardLogger`` pick the
breakdown up with zero new wiring. ``NULL_TAPE`` is the disabled-path
object: every method a no-op, so instrumented loops stay branch-free.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Optional

from distkeras_tpu.obs import collectors
from distkeras_tpu.utils.profiling import now

#: bf16 peak matmul throughput per chip, by device_kind substring —
#: published TPU spec sheets (v4: 275, v5e: 197, v5p: 459,
#: v6e/Trillium: 918 TFLOP/s bf16). Previously bench.py-private; the
#: tape needs the same table, so bench imports it from here.
BF16_PEAK_FLOPS = (
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5litepod", 197e12),
    ("v5", 459e12),
    ("v4", 275e12),
)


def detect_peak_flops():
    """``(peak_flops_or_None, device_kind)`` of device 0."""
    import jax
    kind = jax.devices()[0].device_kind
    low = kind.lower()
    for sub, peak in BF16_PEAK_FLOPS:
        if sub in low:
            return peak, kind
    return None, kind


class _NullTape:
    """Disabled telemetry: every hook a no-op (single shared instance)."""

    enabled = False

    def phase(self, name):
        return contextlib.nullcontext()

    def train_begin(self):
        pass

    def train_end(self):
        pass

    def epoch_end(self, examples, steps=None):
        return {}

    def watch(self, name, fn):
        pass

    def mark_warm(self, name=None):
        pass

    def check_recompiles(self):
        return {}

    def set_flops_per_example(self, flops):
        pass

    def snapshot(self):
        return {}


NULL_TAPE = _NullTape()


class TrainingTape:
    """One tape per ``train()`` run. ``unit`` names the example axis in
    the logs keys (``examples``/``imgs``/``tokens`` ->
    ``examples_per_sec``/...). All state also lands on the registry
    (histograms ``<name>.phase_s{phase=}``, gauges ``<name>.goodput``
    etc.) so the unified snapshot carries it."""

    enabled = True

    def __init__(self, name: str = "train", unit: str = "examples",
                 registry=None, flops_per_example: Optional[float] = None,
                 peak_flops="auto"):
        from distkeras_tpu.obs import get_registry
        self.name = name
        self.unit = unit
        self.registry = registry if registry is not None else get_registry()
        self.flops_per_example = flops_per_example
        if peak_flops == "auto":
            peak_flops, _ = detect_peak_flops()
        self.peak_flops = peak_flops
        self.detector = collectors.RecompileDetector(self.registry)
        self._lock = threading.Lock()
        self._phase_totals: Dict[str, float] = {}
        self._epoch_phase: Dict[str, float] = {}
        #: compile seconds observed DURING the device phase (per-phase
        #: deltas of the process-global totals) — the deduction that
        #: makes goodput's "productive device time" honest without
        #: charging validator/serving compiles against the device phase
        self._device_compile = 0.0
        self._t0 = None
        self._t_epoch = None
        self._t_end = None
        self._compile0 = None
        self._compile_end = None
        self._device_total = 0.0
        self._examples_total = 0
        self._epochs = 0
        # the prefix is a trainer CLASS name — a bounded, code-defined
        # set, not runtime data (lint_metric_names.py)
        self._hist = self.registry.histogram(  # lint: allow-dynamic-metric-name
            f"{name}.phase_s")

    # -- phases -----------------------------------------------------------
    @contextlib.contextmanager
    def phase(self, phase: str):
        device = phase == "device"
        if device:
            c0 = collectors.compile_totals()["seconds"]
        t0 = now()
        try:
            yield
        finally:
            dt = now() - t0
            with self._lock:
                self._phase_totals[phase] = \
                    self._phase_totals.get(phase, 0.0) + dt
                self._epoch_phase[phase] = \
                    self._epoch_phase.get(phase, 0.0) + dt
                if device:
                    self._device_total += dt
                    # global-totals delta over the phase window: a
                    # concurrent thread's compile can still land here,
                    # but a validator/serving compile OUTSIDE the phase
                    # no longer deflates productive device time
                    self._device_compile += (
                        collectors.compile_totals()["seconds"] - c0)
            self._hist.observe(dt, phase=phase)

    # -- recompile plumbing (delegates to the detector) -------------------
    def watch(self, name, fn):
        try:
            self.detector.watch(name, fn)
        except TypeError:
            pass                    # not a jitted callable: nothing to do

    def mark_warm(self, name=None):
        self.detector.mark_warm(name)

    def check_recompiles(self):
        return self.detector.check()

    def set_flops_per_example(self, flops: Optional[float]):
        if flops:
            self.flops_per_example = float(flops)

    # -- lifecycle --------------------------------------------------------
    def train_begin(self):
        self._t0 = self._t_epoch = now()
        self._t_end = self._compile_end = None
        self._compile0 = collectors.compile_totals()["seconds"]

    def train_end(self):
        """Freeze the goodput window: ``snapshot()`` after this stops
        charging wall time (and other subsystems' compiles) that
        accrued AFTER training finished to this run's goodput."""
        self._t_end = now()
        self._compile_end = collectors.compile_totals()["seconds"]

    def epoch_end(self, examples: int, steps: Optional[int] = None) -> Dict:
        """Close out one epoch; returns the logs dict (floats only —
        unknown values are OMITTED, not None, so CSV/TensorBoard
        loggers never see non-numeric cells)."""
        if self._t0 is None:
            self.train_begin()
        t = now()
        epoch_wall = max(t - self._t_epoch, 1e-12)
        self._t_epoch = t
        with self._lock:
            phases = dict(self._epoch_phase)
            self._epoch_phase = {}
            self._examples_total += int(examples)
            self._epochs += 1
            device_total = self._device_total
            device_compile = self._device_compile
        accounted = sum(phases.values())
        host = max(epoch_wall - accounted, 0.0)

        wall = max(t - self._t0, 1e-12)
        compile_s = collectors.compile_totals()["seconds"] - self._compile0
        # productive device time excludes only the compile seconds that
        # landed INSIDE the device phase (first-epoch step compiles) —
        # validator/serving compiles elsewhere in the process charge
        # the wall denominator, not the device numerator
        productive = max(device_total - device_compile, 0.0)
        goodput = min(productive / wall, 1.0)

        rate = examples / epoch_wall
        # checkpoint/validation emit 0.0 on epochs where the phase
        # didn't run: CSVLogger freezes its header on the FIRST epoch's
        # keys, so a key appearing only on checkpoint epochs would be
        # silently dropped from the whole CSV
        logs = {f"{self.unit}_per_sec": rate,
                "data_wait_s": phases.get("data_wait", 0.0),
                "device_s": phases.get("device", 0.0),
                "host_s": host,
                "checkpoint_s": phases.get("checkpoint", 0.0),
                "validation_s": phases.get("validation", 0.0),
                "goodput": goodput}
        if self.flops_per_example and self.peak_flops:
            logs["mfu"] = rate * self.flops_per_example / self.peak_flops
            # bounded prefix: the tape/trainer class name (see _hist)
            self.registry.gauge(  # lint: allow-dynamic-metric-name
                f"{self.name}.mfu").set(logs["mfu"])
        g = self.registry.gauge
        g(f"{self.name}.{self.unit}_per_sec").set(rate)
        g(f"{self.name}.goodput").set(goodput)
        g(f"{self.name}.compile_s").set(compile_s)
        self.check_recompiles()
        collectors.memory_watermark(self.registry)
        return logs

    # -- views ------------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            phases = dict(self._phase_totals)
            device_compile = self._device_compile
        t_end = self._t_end if self._t_end is not None else now()
        wall = (t_end - self._t0) if self._t0 is not None else 0.0
        compile_now = (self._compile_end if self._compile_end is not None
                       else collectors.compile_totals()["seconds"])
        compile_s = (compile_now - self._compile0
                     if self._compile0 is not None else 0.0)
        productive = max(phases.get("device", 0.0) - device_compile, 0.0)
        out = {"unit": self.unit, "epochs": self._epochs,
               "examples": self._examples_total,
               "wall_s": wall, "phases_s": phases,
               "compile_s": compile_s,
               "goodput": (min(productive / wall, 1.0) if wall > 0
                           else None),
               "recompiles": self.detector.counts()}
        if (self.flops_per_example and self.peak_flops and wall > 0
                and self._examples_total):
            out["mfu"] = (self._examples_total / wall
                          * self.flops_per_example / self.peak_flops)
        return out


def resolve_tape(telemetry, name: str, unit: str = "examples"):
    """THE trainer `telemetry=` kwarg policy, in one place:
    ``False`` (or obs disabled) -> ``NULL_TAPE``; ``None`` -> a fresh
    auto tape; anything else is a user-configured tape used as-is."""
    from distkeras_tpu import obs
    if telemetry is False or not obs.enabled():
        return NULL_TAPE
    if telemetry is None:
        return TrainingTape(name=name, unit=unit)
    return telemetry


def timed_stream(iterable, tape):
    """Iterate while charging time blocked on ``next()`` to the tape's
    ``data_wait`` phase — the input-pipeline stall signal, wrapped
    around any trainer stream (Prefetcher or plain generator)."""
    it = iter(iterable)
    while True:
        with tape.phase("data_wait"):
            try:
                item = next(it)
            except StopIteration:
                return
        yield item
