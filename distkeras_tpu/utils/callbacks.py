"""Keras-style training callbacks (epoch granularity).

The reference delegates its entire callback story to Keras — dist-keras
workers call ``model.train_on_batch`` in a bare loop and ship histories
home (``workers.py :: Worker.train``), so per-epoch control (early
stopping, best-weights checkpointing) simply doesn't exist there. Here
callbacks are first-class on every epoch-loop trainer (Single / SPMD /
engine-distributed / host-async).

Granularity is deliberately per-EPOCH, not per-batch: a trainer's epoch is
ONE compiled ``lax.scan`` on device — a per-batch host callback would force
a device→host sync every step and destroy throughput. Anything that needs
per-step behavior belongs inside the jitted step (see ``ops.schedules``
for per-step learning-rate control).

Contract:
  * ``logs`` passed to ``on_epoch_end`` holds python floats: ``loss``
    (epoch mean), each configured metric's epoch mean, and ``val_*``
    entries when the trainer has ``validation_data``.
  * Callbacks may read/replace weights through the ``trainer`` handle:
    ``trainer.get_weights() -> (params, state)`` (host pytrees) and
    ``trainer.set_weights(params, state)`` (applied to the model the
    trainer returns).
  * Setting ``trainer.stop_training = True`` ends training after the
    current epoch (the engine-distributed trainers stop ALL workers — the
    center model is shared, there is no per-worker stop).
"""

from __future__ import annotations

import csv
import math
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class Callback:
    """Base class. Subclasses override any subset of the hooks."""

    trainer = None

    def set_trainer(self, trainer) -> None:
        self.trainer = trainer

    def on_train_begin(self, logs: Optional[Dict] = None) -> None:
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None) -> None:
        pass

    def on_train_end(self, logs: Optional[Dict] = None) -> None:
        pass


class CallbackList:
    """Internal dispatcher the trainers drive. Not user-facing."""

    def __init__(self, callbacks: Sequence[Callback], trainer):
        self.callbacks = list(callbacks)
        self.trainer = trainer
        self._ended = False
        for cb in self.callbacks:
            if not isinstance(cb, Callback):
                raise TypeError(
                    f"callbacks must be utils.callbacks.Callback instances, "
                    f"got {type(cb).__name__}")
            cb.set_trainer(trainer)

    def train_begin(self) -> None:
        for cb in self.callbacks:
            cb.on_train_begin({})

    def epoch_end(self, epoch: int, logs: Dict) -> None:
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, dict(logs))

    def train_end(self, logs: Optional[Dict] = None) -> None:
        """Idempotent (trainers call it from ``finally`` so callback
        resources — open log files etc. — are released on the exception
        path too). Afterwards the weight accessors go stale: clear them so
        a post-train get_weights() fails loudly instead of fetching from a
        dead training loop (a collective hazard under multi-process)."""
        if self._ended:
            return
        self._ended = True
        first_err = None
        for cb in self.callbacks:  # one failing hook must not leak the rest
            try:
                cb.on_train_end(dict(logs or {}))
            except BaseException as e:  # lint: allow-swallow — re-raised below
                if first_err is None:
                    first_err = e
        self.trainer._weights_fn = None
        if first_err is not None:
            raise first_err


def _monitor_value(logs: Dict, monitor: str) -> Optional[float]:
    if monitor in logs:
        return float(logs[monitor])
    return None


def _improved(value: float, best: float, mode: str, min_delta: float) -> bool:
    if mode == "min":
        return value < best - min_delta
    return value > best + min_delta


def _infer_mode(monitor: str, mode: str) -> str:
    if mode in ("min", "max"):
        return mode
    if mode != "auto":
        raise ValueError(f"mode must be 'auto', 'min' or 'max', got {mode!r}")
    # accuracy-like monitors go up; losses/errors go down
    up = ("acc", "accuracy", "auc", "precision", "recall", "f1", "top")
    name = monitor.rsplit("val_", 1)[-1]
    return "max" if any(k in name for k in up) else "min"


class EarlyStopping(Callback):
    """Stop when ``monitor`` hasn't improved for ``patience`` epochs.

    ``restore_best_weights`` puts the best epoch's weights back on the
    trainer at train end (host-side copies — snapshot cost is one
    device→host fetch per improving epoch).
    """

    def __init__(self, monitor: str = "val_loss", min_delta: float = 0.0,
                 patience: int = 0, mode: str = "auto",
                 restore_best_weights: bool = False, verbose: bool = False):
        self.monitor = monitor
        self.min_delta = abs(float(min_delta))
        self.patience = int(patience)
        self.mode = _infer_mode(monitor, mode)
        self.restore_best_weights = bool(restore_best_weights)
        self.verbose = bool(verbose)

    def on_train_begin(self, logs=None):
        self.best = math.inf if self.mode == "min" else -math.inf
        self.wait = 0
        self.best_epoch = -1
        self.best_weights = None
        self.stopped_epoch = -1

    def on_epoch_end(self, epoch, logs=None):
        value = _monitor_value(logs or {}, self.monitor)
        if value is None:
            raise KeyError(
                f"EarlyStopping monitor {self.monitor!r} not in epoch logs "
                f"{sorted((logs or {}))}; configure the trainer's metrics/"
                "validation_data to produce it")
        if _improved(value, self.best, self.mode, self.min_delta):
            self.best, self.best_epoch, self.wait = value, epoch, 0
            if self.restore_best_weights:
                self.best_weights = self.trainer.get_weights()
        else:
            self.wait += 1
            if self.wait >= self.patience:  # Keras semantics: patience
                self.stopped_epoch = epoch  # non-improving epochs, then stop
                self.trainer.stop_training = True

    def on_train_end(self, logs=None):
        if self.restore_best_weights and self.best_weights is not None:
            self.trainer.set_weights(*self.best_weights)
        if self.verbose and self.stopped_epoch >= 0:
            print(f"EarlyStopping: stopped at epoch {self.stopped_epoch} "
                  f"(best {self.monitor}={self.best:.6g} "
                  f"@ epoch {self.best_epoch})")


class ModelCheckpoint(Callback):
    """Save the model to ``filepath`` each epoch (or only on improvement).

    ``filepath`` may contain ``{epoch}`` and any logs key, e.g.
    ``"ckpt-{epoch:03d}-{val_loss:.3f}.dkt"``. Files are written with
    ``models.serialization.save_model`` — loadable by ``load_model``.
    (Distinct from the trainers' own ``checkpoint_dir``, which snapshots
    raw training state for crash RESUME; this one exports serving models.)
    """

    def __init__(self, filepath: str, monitor: str = "val_loss",
                 save_best_only: bool = False, mode: str = "auto",
                 verbose: bool = False):
        self.filepath = str(filepath)
        self.monitor = monitor
        self.save_best_only = bool(save_best_only)
        self.mode = _infer_mode(monitor, mode)
        self.verbose = bool(verbose)

    def on_train_begin(self, logs=None):
        self.best = math.inf if self.mode == "min" else -math.inf

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        if self.save_best_only:
            value = _monitor_value(logs, self.monitor)
            if value is None:
                raise KeyError(
                    f"ModelCheckpoint monitor {self.monitor!r} not in epoch "
                    f"logs {sorted(logs)}")
            if not _improved(value, self.best, self.mode, 0.0):
                return
            self.best = value
        # snapshot on EVERY process — the weight fetch is a collective
        # under multi-process sharding; only the file write is process-0's
        # (same invariant as the trainers' own checkpoint_dir saves)
        model = self.trainer.snapshot_model()
        import jax
        if jax.process_index() != 0:
            return
        path = self.filepath.format(epoch=epoch, **logs)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from distkeras_tpu.models.serialization import save_model
        save_model(model, path)
        if self.verbose:
            print(f"ModelCheckpoint: wrote {path}")


class CSVLogger(Callback):
    """Append one row per epoch (``epoch`` + sorted logs keys) to a CSV."""

    def __init__(self, filename: str, append: bool = False):
        self.filename = str(filename)
        self.append = bool(append)
        self._file = None
        self._writer = None

    def on_train_begin(self, logs=None):
        import jax
        if jax.process_index() != 0:  # one writer under multi-process
            return
        d = os.path.dirname(self.filename)
        if d:
            os.makedirs(d, exist_ok=True)
        # appending to a file that already has content ⇒ its header is
        # already there; don't write a second one mid-file
        self._has_header = (self.append and os.path.exists(self.filename)
                            and os.path.getsize(self.filename) > 0)
        self._file = open(self.filename, "a" if self.append else "w",
                          newline="")
        self._writer = None  # header keys fixed on first epoch

    def on_epoch_end(self, epoch, logs=None):
        if self._file is None:
            return  # non-zero process
        logs = logs or {}
        if self._writer is None:
            self._keys = sorted(logs)
            self._writer = csv.writer(self._file)
            if not self._has_header:
                self._writer.writerow(["epoch"] + self._keys)
        self._writer.writerow(
            [epoch] + [logs.get(k, "") for k in self._keys])
        self._file.flush()

    def on_train_end(self, logs=None):
        if self._file is not None:
            self._file.close()
            self._file = None


class TensorBoardLogger(Callback):
    """Write per-epoch scalars as TensorBoard event files (the
    observability ADD over the reference's stdout-only logging — SURVEY
    §5.5). Uses ``tf.summary`` from the installed TensorFlow; a missing
    TF degrades to a warning, not a crash, so training scripts stay
    portable. One writer per run directory, process 0 only."""

    def __init__(self, log_dir: str):
        self.log_dir = str(log_dir)
        self._writer = None

    def on_train_begin(self, logs=None):
        import jax
        if jax.process_index() != 0:
            return
        try:
            import tensorflow as tf
        except ImportError:
            import warnings
            warnings.warn("TensorBoardLogger: tensorflow not available; "
                          "no event files will be written", stacklevel=2)
            return
        self._writer = tf.summary.create_file_writer(self.log_dir)

    def on_epoch_end(self, epoch, logs=None):
        if self._writer is None:
            return
        import tensorflow as tf
        with self._writer.as_default(step=epoch):
            for key, value in sorted((logs or {}).items()):
                try:
                    tf.summary.scalar(key, float(value))
                except (TypeError, ValueError):
                    continue  # non-scalar log entries are skipped
        self._writer.flush()

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
            self._writer = None


class TerminateOnNaN(Callback):
    """Stop training as soon as the epoch loss is NaN/inf."""

    def on_epoch_end(self, epoch, logs=None):
        loss = (logs or {}).get("loss")
        if loss is not None and not np.isfinite(loss):
            print(f"TerminateOnNaN: non-finite loss {loss} at epoch {epoch}")
            self.trainer.stop_training = True


class EMAWeights(Callback):
    """Keep an exponential moving average of the weights across EPOCHS and
    install it on the trained model at train end (Polyak averaging — the
    eval-quality trick ResNet/EfficientNet recipes rely on).

    Per-EPOCH on purpose: per-step EMA would force a device→host fetch
    every step (see the module docstring); with E epochs an epoch-decay of
    ``decay`` behaves like a per-step decay of ``decay**(1/steps_per_epoch)``.
    Set ``install=False`` to keep the trained weights and only expose the
    average on ``.ema_weights``.
    """

    def __init__(self, decay: float = 0.9, install: bool = True):
        if not 0.0 < decay < 1.0:
            raise ValueError(f"decay must be in (0, 1), got {decay}")
        self.decay = float(decay)
        self.install = bool(install)

    def on_train_begin(self, logs=None):
        self.ema_weights = None
        if self.install:
            clash = [cb for cb in self.trainer.callbacks
                     if isinstance(cb, EarlyStopping)
                     and cb.restore_best_weights]
            if clash:
                raise ValueError(
                    "EMAWeights(install=True) and EarlyStopping("
                    "restore_best_weights=True) both replace the final "
                    "weights — whichever runs last silently wins. Pick "
                    "one, or use EMAWeights(install=False) and read "
                    ".ema_weights yourself")

    def on_epoch_end(self, epoch, logs=None):
        params, state = self.trainer.get_weights()
        if self.ema_weights is None:
            self.ema_weights = (params, state)
            return
        d = self.decay

        def mix(a, b):
            a = np.asarray(a)
            if not np.issubdtype(a.dtype, np.floating):
                return b  # counters/ints track the live value
            return (d * a + (1 - d) * np.asarray(b)).astype(a.dtype)

        import jax

        ep, es = self.ema_weights
        self.ema_weights = (jax.tree_util.tree_map(mix, ep, params),
                            jax.tree_util.tree_map(mix, es, state))

    def on_train_end(self, logs=None):
        if self.install and self.ema_weights is not None:
            self.trainer.set_weights(*self.ema_weights)


class LambdaCallback(Callback):
    """Ad-hoc hooks: ``LambdaCallback(on_epoch_end=lambda e, logs: ...)``."""

    def __init__(self,
                 on_train_begin: Optional[Callable] = None,
                 on_epoch_end: Optional[Callable] = None,
                 on_train_end: Optional[Callable] = None):
        self._begin = on_train_begin
        self._epoch = on_epoch_end
        self._end = on_train_end

    def on_train_begin(self, logs=None):
        if self._begin:
            self._begin(logs)

    def on_epoch_end(self, epoch, logs=None):
        if self._epoch:
            self._epoch(epoch, logs)

    def on_train_end(self, logs=None):
        if self._end:
            self._end(logs)
