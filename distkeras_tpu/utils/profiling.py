"""Profiling hooks: jax.profiler traces + step timing.

The reference's only instrumentation is wall-clock bookkeeping on the
trainer (SURVEY §5.1: ``record_training_start/stop`` + collected Keras
histories). Here profiling is first-class: XLA-level traces via
``jax.profiler`` (viewable in TensorBoard/XProf) and cheap step timers that
feed ``History.steps_per_second``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional

import jax

#: the repo's clock access points. Library code must not call
#: ``time.time()``/``time.perf_counter()`` directly (enforced by
#: ``tools/lint_timing.py``): routing every read through here keeps ONE
#: place that owns clock semantics — ``now`` is the monotonic
#: high-resolution timer every duration in the telemetry layer uses,
#: ``wall`` the epoch-seconds wall clock for timestamps.
now = time.perf_counter
wall = time.time


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA/device trace for the enclosed block.

    Usage::
        with profiling.trace("/tmp/xprof"):
            trainer.train(dataset)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Accumulates wall-clock per named phase; negligible overhead (two
    ``perf_counter`` calls plus one lock acquire per phase).

    THREAD-SAFE: the serving engine and ``StreamingPredictor`` touch
    phase timers from worker threads, so the accumulate and every read
    hold a lock (concurrent phases of the same name interleave
    correctly; totals never tear). ``reset()`` clears accumulated
    phases so long-running engines can treat the timer as a reporting
    window instead of accumulating stale phases forever."""

    def __init__(self):
        self._lock = threading.Lock()
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = now()
        try:
            yield
        finally:
            dt = now() - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1

    def reset(self) -> None:
        with self._lock:
            self.totals.clear()
            self.counts.clear()

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {"total_s": self.totals[name],
                       "count": self.counts[name],
                       "mean_s": self.totals[name] / self.counts[name]}
                for name in self.totals
            }


def percentiles(values, ps=(50.0, 99.0)) -> Optional[Dict[str, float]]:
    """``{"p50": ..., "p99": ...}`` of a sample list via linear
    interpolation (numpy's default) — the latency-summary convention the
    serving metrics and ``bench.py --model serving`` share. None for an
    empty sample."""
    if not len(values):
        return None
    import numpy as np
    arr = np.asarray(list(values), np.float64)
    return {f"p{g:g}": float(np.percentile(arr, g)) for g in ps}


def device_memory_stats() -> Optional[List[Dict]]:
    """Per-device memory stats where the backend exposes them (TPU does;
    virtual CPU devices usually return None)."""
    stats = []
    for d in jax.devices():
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if s:
            stats.append({"device": str(d),
                          "bytes_in_use": s.get("bytes_in_use"),
                          "bytes_limit": s.get("bytes_limit")})
    return stats or None
