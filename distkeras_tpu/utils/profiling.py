"""Profiling hooks: jax.profiler traces + step timing.

The reference's only instrumentation is wall-clock bookkeeping on the
trainer (SURVEY §5.1: ``record_training_start/stop`` + collected Keras
histories). Here profiling is first-class: XLA-level traces via
``jax.profiler`` (viewable in TensorBoard/XProf) and cheap step timers that
feed ``History.steps_per_second``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str):
    """Capture an XLA/device trace for the enclosed block.

    Usage::
        with profiling.trace("/tmp/xprof"):
            trainer.train(dataset)
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StepTimer:
    """Accumulates wall-clock per named phase; negligible overhead (two
    ``perf_counter`` calls per phase)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            name: {"total_s": self.totals[name],
                   "count": self.counts[name],
                   "mean_s": self.totals[name] / self.counts[name]}
            for name in self.totals
        }


def percentiles(values, ps=(50.0, 99.0)) -> Optional[Dict[str, float]]:
    """``{"p50": ..., "p99": ...}`` of a sample list via linear
    interpolation (numpy's default) — the latency-summary convention the
    serving metrics and ``bench.py --model serving`` share. None for an
    empty sample."""
    if not len(values):
        return None
    import numpy as np
    arr = np.asarray(list(values), np.float64)
    return {f"p{g:g}": float(np.percentile(arr, g)) for g in ps}


def device_memory_stats() -> Optional[List[Dict]]:
    """Per-device memory stats where the backend exposes them (TPU does;
    virtual CPU devices usually return None)."""
    stats = []
    for d in jax.devices():
        try:
            s = d.memory_stats()
        except Exception:
            s = None
        if s:
            stats.append({"device": str(d),
                          "bytes_in_use": s.get("bytes_in_use"),
                          "bytes_limit": s.get("bytes_limit")})
    return stats or None
