"""Checkpoint/resume for training state.

The reference has NO checkpointing (SURVEY §5.4: model persistence is manual
after ``train()`` returns; a mid-run driver crash loses the PS center). This
module is the capability ADD justified by the ImageNet north-star config:
periodic atomic snapshots of the center/parameters plus resume.

Format: one directory per step — ``step_<N>/manifest.json`` +
``step_<N>/arrays.npz`` (flattened pytree paths -> numpy arrays), written to
a temp dir and atomically renamed, so a crash mid-write never corrupts the
latest snapshot. ``CheckpointManager`` keeps the newest ``max_to_keep``.

Zero-stall saves (overlap PR, docs/overlap.md): the device->host
snapshot runs as per-leaf ASYNC transfers fenced into snapshot-owned
host memory before ``save()`` returns (``_snapshot_flat`` — safe
against the epoch loop donating the checkpointed buffers right after),
and with ``async_writes=True`` the serialize+rename overlaps the next
epoch's compute through an ordered, bounded background write queue.
Chaos hooks: ``ckpt.d2h`` (mid-transfer), ``ckpt.write``,
``ckpt.rename``, ``ckpt.restore`` (resilience.faults).
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import zlib
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from distkeras_tpu.models.serialization import leaf_key
from distkeras_tpu.resilience import faults
from distkeras_tpu.resilience.retry import RetryPolicy, io_retry


def _enqueue_d2h(paths_leaves) -> None:
    """Enqueue ``copy_to_host_async`` for every device leaf (ONE copy of
    the enqueue contract, shared by the dense and sharded save paths)
    and hit the ``ckpt.d2h`` chaos point — the crash-mid-transfer site,
    after the copies are in flight, before any is fenced."""
    for _, leaf in paths_leaves:
        if isinstance(leaf, jax.Array):
            try:
                leaf.copy_to_host_async()
            except Exception:  # lint: allow-swallow — an array type
                pass           # without async D2H just fetches synchronously
    faults.point("ckpt.d2h")


def _snapshot_flat(tree: Any) -> Dict[str, np.ndarray]:
    """Flattened ``{path: host array}`` snapshot of a pytree via
    per-leaf ASYNC device->host transfer (overlap PR, docs/overlap.md):

    1. ``copy_to_host_async`` is enqueued for EVERY device leaf first,
       so all D2H transfers run concurrently instead of each leaf
       paying its own serial round trip (what a leaf-by-leaf
       ``jax.device_get`` costs);
    2. then each leaf is fenced to host memory — ``np.asarray`` on a
       CPU-backend jax array (and on numpy views) is zero-copy, so any
       result that does not own its buffer is copied. This is the
       snapshot-before-donate contract: once this function returns, no
       DEVICE buffer is read again — every ``jax.Array`` leaf lands in
       snapshot-owned memory, so the epoch loop may immediately
       donate/overwrite the checkpointed carry while the
       serialize+fsync proceeds in the background. (A plain
       owning-numpy leaf stays aliased, not copied — host trees are
       caller-owned, and callers must not mutate them before
       ``wait()``; same contract as the old ``device_get`` path.)

    ``ckpt.d2h`` is the chaos hook for a crash mid-transfer (after the
    copies are enqueued, before the ready-fence).
    """
    paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    _enqueue_d2h(paths_leaves)
    flat = {}
    for path, leaf in paths_leaves:
        key = leaf_key(path)
        arr = np.asarray(leaf)
        if not arr.flags["OWNDATA"]:
            arr = arr.copy()   # fence into snapshot-owned host memory
        flat[key] = arr
    return flat


def _unflatten_like(template, flat):
    """Checkpoint restore stays in HOST numpy with the STORED dtype:
    device placement (and any dtype policy) belongs to the trainer that
    restores, and converting through jax here would silently truncate
    f64 host arrays to f32 (x64 is disabled). Shapes are validated
    against the template like the serialization helper; a stored-vs-
    template DTYPE mismatch is allowed but warned (a changed
    mixed-precision policy between save and resume should be visible,
    not silent)."""
    import warnings
    paths_leaves = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in paths_leaves[0]:
        key = leaf_key(path)
        arr = flat[key]
        if tuple(arr.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint leaf {key!r} shape {arr.shape} != expected "
                f"{np.shape(leaf)}")
        want = getattr(leaf, "dtype", None)
        if want is not None and np.dtype(want) != arr.dtype:
            warnings.warn(
                f"checkpoint leaf {key!r} restores as stored dtype "
                f"{arr.dtype} but the template expects {np.dtype(want)} "
                f"(precision policy changed between save and resume?)",
                stacklevel=3)
        leaves.append(np.asarray(arr))
    return jax.tree_util.tree_unflatten(paths_leaves[1], leaves)

MANIFEST = "manifest.json"
ARRAYS = "arrays.npz"


class CheckpointManager:
    """Step-indexed atomic checkpoints of arbitrary pytrees.

    Zero-stall save path (overlap PR): ``save()`` always snapshots via
    per-leaf async D2H (``_snapshot_flat`` — transfers overlap, and the
    returned host copies are snapshot-owned, so the caller's donated
    device buffers are never read after ``save()`` returns). With
    ``async_writes=True`` the disk write (npz serialize + fsync-ish
    atomic rename) then runs on a background worker thread OVERLAPPED
    with the caller's next epoch: ``save()`` no longer blocks on the
    PREVIOUS write either — writes queue in order through one worker,
    bounded by ``max_pending`` in-flight snapshots (backpressure: a
    disk slower than the epoch cadence stalls the loop at the bound
    instead of growing host memory without limit). A queued write's
    error surfaces on the next ``save()``/``wait()``. ``wait()`` blocks
    until every queued snapshot is durable (reads — ``restore``/
    ``latest_step`` — call it implicitly so they observe queued writes).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_writes: bool = False,
                 retry: Optional[RetryPolicy] = None,
                 max_pending: int = 2):
        self.directory = directory
        self.max_to_keep = int(max_to_keep)
        if self.max_to_keep < 1:
            raise ValueError(
                f"max_to_keep must be >= 1, got {max_to_keep}")
        if int(max_pending) < 1:
            raise ValueError(
                f"max_pending must be >= 1, got {max_pending}")
        os.makedirs(directory, exist_ok=True)
        # transient-IO retry (resilience.retry): a flaky write/read costs
        # a jittered backoff, not the snapshot; non-IO errors surface raw
        self.retry = io_retry() if retry is None else retry
        self._sweep_stale_tmp()
        self.async_writes = bool(async_writes)
        self.max_pending = int(max_pending)
        self._q: Optional[queue.Queue] = None
        self._worker: Optional[threading.Thread] = None
        self._slots = threading.Semaphore(self.max_pending)
        self._err_lock = threading.Lock()
        self._write_errors: List[BaseException] = []

    def _sweep_stale_tmp(self) -> None:
        """Remove ``step_*.tmp`` dirs left by a crash mid-write: they
        were never published (publish is the atomic rename), so they are
        garbage that would otherwise accumulate forever — and a later
        save of the SAME step must not inherit a half-written temp."""
        for name in os.listdir(self.directory):
            if name.startswith("step_") and name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- write ------------------------------------------------------------
    def save(self, step: int, tree: Any,
             metadata: Optional[Dict] = None) -> str:
        """Snapshot ``tree`` (any pytree of arrays) at ``step``.

        The device->host snapshot is fenced BEFORE return (see
        ``_snapshot_flat`` — the caller may donate the tree's device
        buffers immediately after); the disk write is synchronous or
        queued per ``async_writes``. Prior queued-write errors re-raise
        here (without blocking on writes still in flight)."""
        self._raise_write_errors()
        flat = _snapshot_flat(tree)
        final = os.path.join(self.directory, f"step_{step}")
        if not self.async_writes:
            self.retry.call(self._write, step, flat, metadata, final,
                            op="ckpt.write")
            return final
        # bounded in-flight snapshots: acquire a slot (backpressure),
        # released by the worker once this write is durable
        self._slots.acquire()
        self._ensure_worker()
        self._q.put((step, flat, metadata, final))
        return final

    def wait(self) -> None:
        """Block until every queued async write is durable; re-raise the
        first queued error in the caller."""
        if self._q is not None:
            self._q.join()
        self._raise_write_errors()

    def _raise_write_errors(self) -> None:
        with self._err_lock:
            if not self._write_errors:
                return
            err = self._write_errors[0]
            self._write_errors = []
        raise err

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._q = self._q or queue.Queue()
        self._worker = threading.Thread(target=self._drain_writes,
                                        daemon=True)
        self._worker.start()

    def _drain_writes(self) -> None:
        """The single writer thread: writes publish in submission order
        (atomic-rename ordering and ``_gc`` stay race-free)."""
        while True:
            step, flat, metadata, final = self._q.get()
            try:
                self.retry.call(self._write, step, flat, metadata, final,
                                op="ckpt.write")
            except BaseException as e:  # lint: allow-swallow — surfaced
                with self._err_lock:    # on the next wait()/save()
                    self._write_errors.append(e)
            finally:
                self._slots.release()
                self._q.task_done()

    @staticmethod
    def _crc(arr: np.ndarray) -> int:
        return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF

    def _write(self, step, flat, metadata, final):
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        faults.point("ckpt.write")
        np.savez(os.path.join(tmp, ARRAYS), **flat)
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump({"step": int(step),
                       "keys": sorted(flat),
                       # per-leaf payload checksums, verified on restore:
                       # a truncated/corrupted arrays.npz fails loudly
                       # with the leaf name instead of deep inside numpy
                       "crc32": {k: self._crc(v) for k, v in flat.items()},
                       "metadata": metadata or {}}, f, indent=2)
        faults.point("ckpt.rename")
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic publish
        self._gc()

    def _gc(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"),
                          ignore_errors=True)

    # -- read -------------------------------------------------------------
    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    steps.append(int(name[len("step_"):]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        self.wait()  # reads observe every queued async write
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure of ``template`` (shapes validated,
        per-leaf crc32 verified against the manifest)."""
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints in {self.directory!r}")
        path = os.path.join(self.directory, f"step_{step}")
        flat = self.retry.call(self._read_verified, path,
                               op="ckpt.restore")
        return _unflatten_like(template, flat)

    def _read_verified(self, path: str) -> Dict[str, np.ndarray]:
        """Load ``arrays.npz`` with integrity checking: a truncated or
        corrupted snapshot fails loudly with the checkpoint path and the
        offending LEAF name — never an opaque zlib/zipfile traceback
        from deep inside numpy. Checkpoints written before the checksum
        format (no ``crc32`` in the manifest) load unverified."""
        faults.point("ckpt.restore")
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
        crcs = manifest.get("crc32", {})
        try:
            arrays = np.load(os.path.join(path, ARRAYS))
        except Exception as e:
            raise ValueError(
                f"checkpoint {path!r}: {ARRAYS} unreadable (truncated "
                f"or corrupt): {e}") from e
        flat = {}
        for k in arrays.files:
            try:
                arr = arrays[k]
            except Exception as e:
                raise ValueError(
                    f"checkpoint {path!r}: leaf {k!r} unreadable "
                    f"(truncated or corrupt {ARRAYS}): {e}") from e
            want = crcs.get(k)
            if want is not None and self._crc(arr) != int(want):
                raise ValueError(
                    f"checkpoint {path!r}: leaf {k!r} failed its crc32 "
                    f"check (manifest {want}, payload {self._crc(arr)}) "
                    "— the snapshot is corrupt; restore an older step")
            flat[k] = arr
        missing = [k for k in manifest.get("keys", []) if k not in flat]
        if missing:
            raise ValueError(
                f"checkpoint {path!r}: leaves in the manifest but "
                f"missing from {ARRAYS}: {missing[:5]}"
                f"{'...' if len(missing) > 5 else ''}")
        return flat

    def delete(self, step: int) -> None:
        """Remove one step's snapshot (the supervisor's rollback path: a
        poisoned epoch's checkpoint must stop being resumable)."""
        self.wait()
        shutil.rmtree(os.path.join(self.directory, f"step_{step}"),
                      ignore_errors=True)

    def keys(self, step: Optional[int] = None) -> Optional[List[str]]:
        """Flat array keys stored in a checkpoint (format introspection —
        e.g. distinguishing params-only snapshots from full-carry ones)."""
        self.wait()  # an explicit step may still be in the write queue
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = os.path.join(self.directory, f"step_{step}")
        arrays = np.load(os.path.join(path, ARRAYS))
        return list(arrays.files)

    def metadata(self, step: Optional[int] = None) -> Dict:
        self.wait()  # an explicit step may still be in the write queue
        if step is None:
            step = self.latest_step()
        path = os.path.join(self.directory, f"step_{step}", MANIFEST)
        with open(path) as f:
            return json.load(f)["metadata"]


# ---------------------------------------------------------------------------
# Sharded checkpoints (models bigger than one host's memory)
# ---------------------------------------------------------------------------

def _encode_index(index, shape) -> str:
    """Shard index (tuple of slices, possibly open like ``slice(None)`` on
    replicated dims) -> normalized string, e.g. '0:4,8:16'."""
    return ",".join(f"{s.indices(d)[0]}:{s.indices(d)[1]}"
                    for s, d in zip(index, shape))


def _decode_index(s: str) -> tuple:
    """'0:4,8:16' -> ((0, 4), (8, 16)). Plain int pairs, not slices:
    these tuples key the per-leaf piece dicts, and ``slice`` is only
    hashable from Python 3.12."""
    if not s:
        return ()
    return tuple((int(a), int(b))
                 for a, b in (part.split(":") for part in s.split(",")))


def _as_slices(idx) -> tuple:
    """((lo, hi), ...) piece index -> numpy basic-indexing slices."""
    return tuple(slice(lo, hi) for lo, hi in idx)


class ShardedCheckpointManager(CheckpointManager):
    """Checkpoints for sharded (TP/FSDP/EP) models: every process writes
    ONLY its addressable shards; restore ``device_put``s each stored piece
    straight to its device. The full array is never materialized on any
    host in either direction — the point of SPMD sharding is that no one
    host can hold the model (SURVEY §5.4 build note; VERDICT r1 weak #4).

    Layout per step: ``step_<N>/arrays_p<proc>.npz`` where each entry key
    is ``<leaf-path>|<shard-index>`` (e.g. ``params/dense/kernel|0:512``),
    deduplicated across data-parallel replicas via ``shard.replica_id ==
    0``; plus the usual ``manifest.json`` (written by process 0) carrying
    every leaf's global shape/dtype. The plain ``restore(template)``
    compat path still works by stitching shards (and DOES materialize —
    use ``restore_sharded`` for big models).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_writes: bool = False):
        if async_writes:
            raise ValueError(
                "async_writes is not supported for sharded checkpoints: "
                "the save path runs multi-process barriers that must stay "
                "on the training thread")
        super().__init__(directory, max_to_keep=max_to_keep)

    def save(self, step: int, tree: Any,
             metadata: Optional[Dict] = None) -> str:
        self.wait()
        # per-shard async D2H first (same overlap as _snapshot_flat: all
        # shard transfers in flight before any is fenced by np.asarray
        # below); the write itself stays synchronous — multi-process
        # barriers must stay on the training thread
        paths_leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        _enqueue_d2h(paths_leaves)
        flat = {}
        leaves = {}
        for path, leaf in paths_leaves:
            key = leaf_key(path)
            shape = tuple(np.shape(leaf))
            dtype = (leaf.dtype if isinstance(leaf, jax.Array)
                     else np.asarray(leaf).dtype)
            leaves[key] = {"shape": list(shape), "dtype": str(dtype)}
            if isinstance(leaf, jax.Array) and hasattr(
                    leaf, "addressable_shards"):
                for shard in leaf.addressable_shards:
                    if shard.replica_id != 0:
                        continue  # one copy per distinct shard, job-wide
                    flat[f"{key}|{_encode_index(shard.index, shape)}"] = \
                        np.asarray(shard.data)
            else:
                if jax.process_index() == 0:
                    arr = np.asarray(leaf)
                    full = _encode_index(
                        tuple(slice(0, d) for d in shape), shape)
                    flat[f"{key}|{full}"] = arr
        final = os.path.join(self.directory, f"step_{step}")
        self._write_sharded(step, flat, leaves, metadata, final)
        return final

    def _write_sharded(self, step, flat, leaves, metadata, final):
        pid = jax.process_index()
        tmp = final + ".tmp"
        if pid == 0:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"dkt_ckpt_mkdir_{step}")
        # injection points only — NO retry here: the sharded save runs
        # multi-process barriers, and a single process retrying would
        # desynchronize them (the documented reason checkpoint_async is
        # rejected too)
        faults.point("ckpt.write")
        np.savez(os.path.join(tmp, f"arrays_p{pid}.npz"), **flat)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"dkt_ckpt_write_{step}")
        if pid == 0:
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump({"step": int(step), "format": "sharded",
                           "keys": sorted(leaves),
                           "leaves": leaves,
                           "num_processes": jax.process_count(),
                           "metadata": metadata or {}}, f, indent=2)
            faults.point("ckpt.rename")
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)  # atomic publish
            self._gc()

    # -- read ---------------------------------------------------------------
    def _load_shards(self, step):
        """{leaf path: {index tuple: LAZY piece loader}} + per-leaf specs.
        Only an index of (file, key) pairs is built here — array bytes are
        decompressed from the npz on first access of each piece, so a
        process restoring its own shards never pulls the rest of the model
        through host memory. Also reads dense-format checkpoints
        (``arrays.npz``, from the base manager) as single full-array
        pieces, so format migration is transparent."""
        path = os.path.join(self.directory, f"step_{step}")
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
        if "leaves" in manifest:
            leaves = manifest["leaves"]
            files = [n for n in sorted(os.listdir(path))
                     if n.startswith("arrays_p") and n.endswith(".npz")]
        else:  # dense checkpoint from the base CheckpointManager
            leaves, files = None, [ARRAYS]
        pieces: Dict[str, Dict] = {}
        specs = dict(leaves) if leaves else {}
        for name in files:
            arrays = np.load(os.path.join(path, name))  # lazy NpzFile
            for k in arrays.files:
                if "|" in k:
                    leaf_key, _, idxstr = k.rpartition("|")
                    idx = _decode_index(idxstr)
                else:  # dense entry: one piece spanning the whole leaf
                    leaf_key = k
                    if leaves is None and leaf_key not in specs:
                        # npy header only — shape/dtype without the payload
                        with arrays.zip.open(k + ".npy") as f:
                            np.lib.format.read_magic(f)
                            shp, _, dt = \
                                np.lib.format.read_array_header_1_0(f)
                        specs[leaf_key] = {"shape": list(shp),
                                           "dtype": str(dt)}
                    idx = tuple((0, d) for d in specs[leaf_key]["shape"])
                pieces.setdefault(leaf_key, {})[idx] = \
                    (lambda a=arrays, key=k: a[key])
        return pieces, specs

    @staticmethod
    def _stitch(norm, stored, dtype, key):
        """Assemble the requested index range ``norm`` from OVERLAPPING
        stored pieces (mesh-change restore, round 4): each stored piece
        contributes its intersection with the request, and is loaded,
        copied, and FREED one at a time — the host high-water stays one
        stored piece + one target shard (deliberately NOT the exact-match
        path's per-leaf cache: caching every overlapping piece would hold
        the whole leaf in host RAM, the regime sharded restore exists to
        avoid; a piece overlapping several target shards pays
        re-decompression instead). A gap (the stored tiling does not
        cover the request) is a loud error, not zeros."""
        out = np.empty(tuple(hi - lo for lo, hi in norm), dtype)
        got = 0
        for sidx, loader in stored.items():
            inter = []
            for a, b in zip(sidx, norm):
                lo, hi = max(a[0], b[0]), min(a[1], b[1])
                if lo >= hi:
                    inter = None
                    break
                inter.append((lo, hi))
            if inter is None:
                continue
            piece = loader()
            src = piece[tuple(
                slice(lo - a[0], hi - a[0])
                for (lo, hi), a in zip(inter, sidx))]
            out[tuple(slice(lo - b[0], hi - b[0])
                      for (lo, hi), b in zip(inter, norm))] = src
            got += src.size
            del piece
        if got != out.size:
            raise ValueError(
                f"checkpoint shard mismatch for {key!r}: stored pieces "
                f"cover only {got}/{out.size} elements of requested "
                f"index {norm} (stored indices: {list(stored)})")
        return out

    def restore_sharded(self, shardings: Any,
                        step: Optional[int] = None) -> Any:
        """Restore into device-resident arrays placed per ``shardings`` (a
        pytree of ``jax.sharding.Sharding``; structure = the saved tree).
        Each needed device shard is ``device_put`` from its stored piece —
        host memory high-water is ONE shard, never the global array.

        The restore sharding may tile each leaf DIFFERENTLY from how it
        was saved (round 4, VERDICT r3 weak #5): shards that don't match
        a stored piece exactly are STITCHED from the overlapping pieces,
        so an 8-device checkpoint restores bitwise onto 4- or 2-device
        meshes (elastic recovery / rescale) without ever assembling the
        dense array on the host."""
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory!r}")
        faults.point("ckpt.restore")
        pieces, leaves = self._load_shards(step)

        flat_sh, treedef = jax.tree_util.tree_flatten_with_path(shardings)
        out = []
        for path, sharding in flat_sh:
            key = leaf_key(path)
            if key not in leaves:
                raise KeyError(f"leaf {key!r} not in checkpoint {step}")
            shape = tuple(leaves[key]["shape"])
            dtype = np.dtype(leaves[key]["dtype"])
            stored = pieces[key]
            arrays = []
            full = tuple((0, d) for d in shape)
            cache = {}  # one decompression per distinct piece per leaf
            for dev, index in sharding.addressable_devices_indices_map(
                    shape).items():
                norm = tuple(
                    s.indices(d)[:2] for s, d in zip(index, shape))
                if norm in stored:
                    if norm not in cache:
                        cache[norm] = stored[norm]()
                    piece = cache[norm]
                elif full in stored:
                    # saved replicated/dense, restoring sharded: slice the
                    # stored full copy (still one shard on device)
                    if full not in cache:
                        cache[full] = stored[full]()
                    piece = cache[full][_as_slices(norm)]
                else:
                    # mesh-change restore: stitch the request from the
                    # overlapping stored pieces
                    piece = self._stitch(norm, stored, dtype, key)
                arrays.append(jax.device_put(
                    piece.astype(dtype, copy=False), dev))
            out.append(jax.make_array_from_single_device_arrays(
                shape, sharding, arrays))
        return jax.tree_util.tree_unflatten(treedef, out)

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Compat path: assemble FULL host arrays by stitching shards.
        Deliberately available (small models, format migration) but defeats
        the memory guarantee — big models use ``restore_sharded``."""
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory!r}")
        faults.point("ckpt.restore")
        pieces, leaves = self._load_shards(step)
        flat = {}
        for key, stored in pieces.items():
            shape = tuple(leaves[key]["shape"])
            dtype = np.dtype(leaves[key]["dtype"])
            full = np.empty(shape, dtype)
            for idx, piece in stored.items():
                full[_as_slices(idx)] = piece()
            flat[key] = full
        return _unflatten_like(template, flat)

    def keys(self, step: Optional[int] = None) -> Optional[List[str]]:
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = os.path.join(self.directory, f"step_{step}", MANIFEST)
        with open(path) as f:
            return list(json.load(f)["keys"])
