"""Checkpoint/resume for training state.

The reference has NO checkpointing (SURVEY §5.4: model persistence is manual
after ``train()`` returns; a mid-run driver crash loses the PS center). This
module is the capability ADD justified by the ImageNet north-star config:
periodic atomic snapshots of the center/parameters plus resume.

Format: one directory per step — ``step_<N>/manifest.json`` +
``step_<N>/arrays.npz`` (flattened pytree paths -> numpy arrays), written to
a temp dir and atomically renamed, so a crash mid-write never corrupts the
latest snapshot. ``CheckpointManager`` keeps the newest ``max_to_keep``.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from distkeras_tpu.models.serialization import (
    _flatten_with_paths, _unflatten_like)

MANIFEST = "manifest.json"
ARRAYS = "arrays.npz"


class CheckpointManager:
    """Step-indexed atomic checkpoints of arbitrary pytrees.

    ``async_writes=True`` moves the disk write (npz serialize + atomic
    rename) to a background thread so a large snapshot does not stall the
    training loop — the device->host fetch still happens synchronously at
    ``save()`` time (the arrays must be a consistent cut of training
    state). Writes are serialized through one worker thread; ``wait()``
    blocks until all queued snapshots are durable (called automatically on
    the next ``save``/``restore``/``latest_step`` to keep ordering simple).
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_writes: bool = False):
        self.directory = directory
        self.max_to_keep = int(max_to_keep)
        if self.max_to_keep < 1:
            raise ValueError(
                f"max_to_keep must be >= 1, got {max_to_keep}")
        os.makedirs(directory, exist_ok=True)
        self.async_writes = bool(async_writes)
        self._thread = None
        self._write_error: Optional[BaseException] = None

    # -- write ------------------------------------------------------------
    def save(self, step: int, tree: Any,
             metadata: Optional[Dict] = None) -> str:
        """Snapshot ``tree`` (any pytree of arrays) at ``step``."""
        self.wait()  # one in-flight write at a time; surfaces prior errors
        tree = jax.device_get(tree)
        flat = _flatten_with_paths(tree)
        final = os.path.join(self.directory, f"step_{step}")
        if not self.async_writes:
            self._write(step, flat, metadata, final)
            return final

        import threading
        self._thread = threading.Thread(
            target=self._write_guarded, args=(step, flat, metadata, final),
            daemon=True)
        self._thread.start()
        return final

    def wait(self) -> None:
        """Block until the in-flight async write (if any) is durable; re-
        raise its error in the caller."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._write_error is not None:
            err, self._write_error = self._write_error, None
            raise err

    def _write_guarded(self, step, flat, metadata, final):
        try:
            self._write(step, flat, metadata, final)
        except BaseException as e:  # surfaced on the next wait()/save()
            self._write_error = e

    def _write(self, step, flat, metadata, final):
        tmp = final + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        np.savez(os.path.join(tmp, ARRAYS), **flat)
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump({"step": int(step),
                       "keys": sorted(flat),
                       "metadata": metadata or {}}, f, indent=2)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic publish
        self._gc()

    def _gc(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep]:
            shutil.rmtree(os.path.join(self.directory, f"step_{s}"),
                          ignore_errors=True)

    # -- read -------------------------------------------------------------
    def all_steps(self) -> List[int]:
        steps = []
        for name in os.listdir(self.directory):
            if name.startswith("step_") and not name.endswith(".tmp"):
                try:
                    steps.append(int(name[len("step_"):]))
                except ValueError:
                    continue
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        self.wait()  # reads observe every queued async write
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, template: Any, step: Optional[int] = None) -> Any:
        """Restore into the structure of ``template`` (shapes validated)."""
        self.wait()
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(
                f"no checkpoints in {self.directory!r}")
        path = os.path.join(self.directory, f"step_{step}")
        arrays = np.load(os.path.join(path, ARRAYS))
        flat = {k: arrays[k] for k in arrays.files}
        return _unflatten_like(template, flat)

    def keys(self, step: Optional[int] = None) -> Optional[List[str]]:
        """Flat array keys stored in a checkpoint (format introspection —
        e.g. distinguishing params-only snapshots from full-carry ones)."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        path = os.path.join(self.directory, f"step_{step}")
        arrays = np.load(os.path.join(path, ARRAYS))
        return list(arrays.files)

    def metadata(self, step: Optional[int] = None) -> Dict:
        if step is None:
            step = self.latest_step()
        path = os.path.join(self.directory, f"step_{step}", MANIFEST)
        with open(path) as f:
            return json.load(f)["metadata"]
