"""Training history + wall-clock bookkeeping.

Reference parity: ``distkeras/trainers.py :: Trainer`` keeps
``record_training_start/stop``, ``get_training_time`` and per-worker Keras
``history`` objects collected to the driver (SURVEY §5.1). Here history is
a plain dict of numpy arrays filled from jitted scan outputs — one device →
host transfer per epoch, not one per batch.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from distkeras_tpu.utils.profiling import wall


class History:
    """Per-run training record: loss per step (per worker where relevant),
    epoch boundaries, wall-clock timings."""

    def __init__(self):
        self.epochs: List[Dict[str, np.ndarray]] = []
        self._start: Optional[float] = None
        self._stop: Optional[float] = None

    # -- wall clock (reference: Trainer.record_training_start/stop) -------
    def record_training_start(self) -> None:
        self._start = wall()

    def record_training_stop(self) -> None:
        self._stop = wall()

    def get_training_time(self) -> float:
        if self._start is None:
            return 0.0
        end = self._stop if self._stop is not None else wall()
        return end - self._start

    # -- metrics ----------------------------------------------------------
    def append_epoch(self, **metrics: np.ndarray) -> None:
        self.epochs.append({k: np.asarray(v) for k, v in metrics.items()})

    def losses(self) -> np.ndarray:
        """All per-step losses, concatenated across epochs. Shape
        ``[total_steps]`` (single worker) or ``[total_steps, num_workers]``."""
        if not self.epochs:
            return np.array([])
        return np.concatenate([e["loss"] for e in self.epochs], axis=0)

    def metric(self, name: str) -> np.ndarray:
        """Per-step values of a named training metric (constructor
        ``metrics=[...]``), concatenated across epochs — same shape contract
        as ``losses()``."""
        if not self.epochs:
            return np.array([])
        missing = [i for i, e in enumerate(self.epochs) if name not in e]
        if missing:
            raise KeyError(
                f"metric {name!r} not recorded (have: "
                f"{self.metric_names()})")
        return np.concatenate([e[name] for e in self.epochs], axis=0)

    def metric_names(self) -> List[str]:
        """Recorded training METRICS (loss is tracked separately via
        ``losses()``)."""
        if not self.epochs:
            return []
        return sorted(k for k in self.epochs[0] if k != "loss")

    def final_loss(self) -> float:
        losses = self.losses()
        if losses.size == 0:
            return float("nan")
        tail = losses[-max(1, len(losses) // 10):]
        return float(np.mean(tail))

    def steps_per_second(self) -> float:
        t = self.get_training_time()
        n = sum(len(e["loss"]) for e in self.epochs)
        return n / t if t > 0 else 0.0

    def summary(self) -> Dict[str, float]:
        return {
            "training_time": self.get_training_time(),
            "num_epochs": len(self.epochs),
            "num_steps": int(sum(len(e["loss"]) for e in self.epochs)),
            "final_loss": self.final_loss(),
            "steps_per_second": self.steps_per_second(),
        }
