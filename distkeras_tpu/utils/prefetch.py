"""Background epoch prefetching: overlap host data assembly with device
compute.

The reference gets pipelining for free from Spark (executors assemble the
next partition while others train). Here the per-epoch host work — the
permutation gather (``data/native.py``) and the ``[S, W, B, ...]`` stacking
— runs on a worker thread one epoch ahead, so the accelerator never waits
on the host between epochs.

Device staging (overlap PR, docs/overlap.md): with ``place=`` the
producer thread ALSO moves each assembled result onto device (e.g. a
sharded ``jax.device_put`` with the trainer's data sharding) before
queueing it, so the consumer's ``next()`` hands back a device-resident
batch — the H2D copy for chunk k+1 runs while the device computes
chunk k. The bounded queue is the device-side double buffer AND the
backpressure: ``place`` runs only once a queue slot is FREE (the
producer blocked on a full queue holds an assembled HOST chunk, never
a third device-resident one), so device memory for in-flight input
data is capped at ``depth`` queued chunks + the one the consumer
holds, no matter how far the host gets ahead.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Iterator, Optional, Tuple, TypeVar

from distkeras_tpu.utils.profiling import now

T = TypeVar("T")
U = TypeVar("U")

_SENTINEL = object()


class Prefetcher:
    """Iterate ``fn(item)`` over ``items`` with ``depth`` results computed
    ahead on a background thread. Exceptions in ``fn`` re-raise (original
    type) at the consuming ``next()`` call.

    ``place`` (optional) post-processes each ``fn`` result ON THE
    PRODUCER THREAD before it is queued — the device-staging hook (see
    module doc and ``device_stager``): the consumer then receives
    device-resident values and never pays the H2D copy on its own
    thread. ``place`` errors take the same consumer-side re-raise path
    as ``fn`` errors.

    The producer thread is cleaned up on EVERY exit path: normal
    exhaustion, consumer ``break``/exception (via ``GeneratorExit`` in the
    iterator), explicit ``close()``, or context-manager exit. The producer
    never blocks indefinitely on a full queue — its puts time out and
    re-check the stop flag, so ``close()`` cannot deadlock.
    """

    def __init__(self, fn: Callable[[T], U], items: Iterable[T],
                 depth: int = 1, name: str = "prefetch",
                 place: Optional[Callable[[U], U]] = None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._fn = fn
        self._place = place
        # LAZY: the iterable is consumed one item at a time ON THE
        # PRODUCER THREAD (predictors PR — the predictors.py:210
        # follow-up). The old ``list(items)`` materialized the whole
        # stream up front, which silently broke unbounded sources
        # (Kafka-style consumers, generators) and double-buffered
        # nothing for them; epoch-chunk callers pass small finite
        # iterables and are unaffected. A generator is therefore
        # advanced off-thread: it must not be consumed elsewhere
        # concurrently (none of the repo's sources are).
        self._items = items
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._stopped = threading.Event()
        # telemetry (obs registry): queue depth at each consume (a full
        # queue = loader ahead, an empty one = the consumer about to
        # stall) and per-item consumer stall seconds. A couple of
        # dict/float ops per ITEM — items are epoch chunks or shards,
        # so this is nowhere near any hot path. Instruments bind at
        # construction but recording checks obs.enabled() per consume,
        # so disable()/enable() toggles mid-run behave like every other
        # instrumentation point.
        self._name = name
        from distkeras_tpu import obs
        self._obs = obs
        reg = obs.get_registry()
        self._g_depth = reg.gauge("prefetch.queue_depth")
        self._h_stall = reg.histogram("prefetch.stall_s")
        self._thread = threading.Thread(target=self._produce, daemon=True)
        self._thread.start()

    def _put(self, out) -> bool:
        """Put with stop-flag polling; False means shutdown requested."""
        while not self._stopped.is_set():
            try:
                self._q.put(out, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _await_queue_space(self) -> bool:
        """Poll until the queue has a free slot (or shutdown). Safe as a
        reservation: this thread is the only producer, so a slot seen
        free stays free until our own put."""
        while not self._stopped.is_set():
            if not self._q.full():
                return True
            self._stopped.wait(0.05)
        return False

    def _produce(self):
        from distkeras_tpu.resilience import faults
        it = iter(self._items)
        while True:
            try:
                item = next(it)
            except StopIteration:
                break
            except Exception as e:
                # a LAZY source failing mid-stream (this PR) takes the
                # same consumer-side re-raise path as an fn error —
                # the eager list() used to surface it in __init__
                self._put((None, None, e))
                return
            if self._stopped.is_set():
                return
            try:
                # chaos hook (resilience.faults): an injected Exception
                # takes the same consumer-side re-raise path as a real
                # fn error; a stall models a wedged loader; an injected
                # BaseException kills the thread WITHOUT the sentinel —
                # the dead-producer case __iter__ must detect
                faults.point("prefetch.produce")
                value = self._fn(item)
                if self._place is not None:
                    # device staging happens HERE, on the loader thread
                    # — but only once the queue has room: a producer
                    # blocked on a full queue must hold an assembled
                    # HOST chunk, not an extra device-resident one (the
                    # depth-bounded device-memory cap, module doc)
                    if not self._await_queue_space():
                        return
                    value = self._place(value)
                out = (item, value, None)
            except Exception as e:  # re-raised consumer-side
                self._put((item, None, e))
                return
            if not self._put(out):
                return
        self._put(_SENTINEL)

    def _note_consume(self, waited_s: float) -> None:
        if self._obs.enabled():
            self._g_depth.set(self._q.qsize(), stream=self._name)
            self._h_stall.observe(waited_s, stream=self._name)

    def __iter__(self) -> Iterator[Tuple[T, U]]:
        try:
            # consumer stall clock: starts when we begin waiting for an
            # item and resets only on a successful get, so it spans the
            # whole polling wait, not one 50 ms poll slice
            t_wait = now()
            while True:
                try:
                    # POLLING get (this PR): a blocking get() deadlocked
                    # forever when close() ran mid-iteration — the old
                    # close() drained the queue (stealing queued results
                    # and the SENTINEL) to unblock the producer, and the
                    # consumer's next() then waited on a queue nothing
                    # would ever fill again
                    got = self._q.get(timeout=0.05)
                except queue.Empty:
                    # the q.empty() re-check closes a drop race: the
                    # producer may complete one last put between our
                    # get() timeout and its own stop-flag check — once
                    # the thread is dead AND the queue is empty, nothing
                    # can arrive anymore
                    if not self._thread.is_alive() and self._q.empty():
                        if self._stopped.is_set():
                            return   # closed mid-stream and fully drained
                        # dead producer, no sentinel, nothing queued and
                        # close() never ran: the thread died from a
                        # non-Exception BaseException (or was killed)
                        # before putting the sentinel. Without this check
                        # the consumer would poll this empty queue
                        # forever.
                        raise RuntimeError(
                            f"prefetch producer thread ({self._name!r}) "
                            "died without delivering a result or the "
                            "end-of-stream sentinel (non-Exception "
                            "BaseException in the producer, or the "
                            "thread was killed); the data stream is "
                            "broken")
                    continue
                if got is _SENTINEL:
                    return
                self._note_consume(now() - t_wait)
                item, value, err = got
                if err is not None:
                    raise err  # original type — callers match on it
                yield item, value
                t_wait = now()
        finally:
            # covers consumer break/exception (GeneratorExit) and normal end
            self.close()

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self):
        """Stop the producer and reap its thread; idempotent, never blocks
        indefinitely (the producer's puts poll the stop flag every 50 ms,
        so a put blocked on a full queue exits on its own — close() does
        NOT drain the queue: results computed before the close stay
        consumable, and the consumer's polling get() above terminates
        iteration once they are gone)."""
        self._stopped.set()
        self._thread.join(timeout=5.0)


def device_stager(sharding=None) -> Callable:
    """A ``place=`` callable for the trainers' ``(Xs, Ys, n_steps)``
    epoch chunks: dispatches ``jax.device_put`` of both stacked arrays
    (with ``sharding`` when given — the trainer's data sharding — or
    onto the default device otherwise) on the loader thread.
    ``device_put`` only ENQUEUES the transfer, so the loader is not
    serialized on the copy either; by the time the epoch loop consumes
    the chunk the data is on (or streaming to) device, and the jitted
    epoch program never blocks on a host->device copy of its inputs."""
    import jax

    def place(chunk):
        Xs, Ys, n_steps = chunk
        if sharding is None:
            return jax.device_put(Xs), jax.device_put(Ys), n_steps
        return (jax.device_put(Xs, sharding),
                jax.device_put(Ys, sharding), n_steps)

    return place
