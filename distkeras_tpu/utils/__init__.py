"""Utilities: history, checkpointing, profiling, callbacks."""

from distkeras_tpu.utils.callbacks import (  # noqa: F401
    Callback, CSVLogger, EarlyStopping, EMAWeights, LambdaCallback,
    ModelCheckpoint, TensorBoardLogger, TerminateOnNaN)
from distkeras_tpu.utils.checkpoint import (  # noqa: F401
    CheckpointManager, ShardedCheckpointManager)
from distkeras_tpu.utils.history import History  # noqa: F401
from distkeras_tpu.utils.prefetch import (  # noqa: F401
    Prefetcher, device_stager)
from distkeras_tpu.utils import profiling  # noqa: F401
