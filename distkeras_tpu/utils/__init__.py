"""Utilities: history, checkpointing, profiling."""

from distkeras_tpu.utils.history import History  # noqa: F401
