"""Utilities: history, checkpointing, profiling."""

from distkeras_tpu.utils.checkpoint import CheckpointManager  # noqa: F401
from distkeras_tpu.utils.history import History  # noqa: F401
from distkeras_tpu.utils import profiling  # noqa: F401
