"""distkeras_tpu — a TPU-native distributed training framework.

A ground-up JAX/XLA re-design of the capabilities of ``kunlqt/dist-keras``
(itself a fork of ``cerndb/dist-keras``): distributed Keras-style model
training with a family of synchronous/asynchronous SGD variants (DOWNPOUR,
EASGD, AEASGD, ADAG, DynSGD), model/feature transformers, predictors and
evaluators.

Where the reference distributes work over Apache Spark executors talking to a
socket parameter server on the driver (reference: ``distkeras/trainers.py``,
``distkeras/parameter_servers.py``, ``distkeras/networking.py``), this
framework maps the same algorithm family onto a single SPMD program over a
``jax.sharding.Mesh``: worker state lives as device-sharded pytrees, the
parameter-server "center" is a replicated pytree, and all pull/commit traffic
becomes XLA collectives (``psum``/``pmean``/``ppermute``) over ICI — zero
socket traffic, no central process.

Package layout:
    models/     Layer/Sequential model substrate + model zoo (MLP, LeNet-5,
                ResNet-50, BiLSTM, wide&deep, transformer)
    ops/        losses, metrics, optimizers, attention ops
    parallel/   mesh abstraction + trainer family (the reference's
                trainers.py/workers.py/parameter_servers.py equivalent)
    data/       columnar dataset + feature transformers (the reference's
                Spark-DataFrame ingest + transformers.py equivalent)
    inference/  predictors + evaluators (reference predictors.py/evaluators.py)
    serving/    continuous-batching LM serving engine (slot scheduler +
                pooled KV cache over the models/decoding machinery)
    obs/        unified telemetry: metrics registry, tracing spans,
                recompile/goodput accounting, JSONL/Prometheus exporters
    resilience/ fault tolerance: fault injection, retry policies,
                supervised auto-resume training (preemption, anomaly
                rollback); serving degradation lives in serving/
    utils/      serialization, checkpointing, history, profiling
"""

__version__ = "0.1.0"

from distkeras_tpu.models import Sequential, Model  # noqa: F401
