#!/usr/bin/env python
"""Static check: no ad-hoc host syncs inside the epoch-loop modules.

The overlap PR (docs/overlap.md) made the trainer epoch loops
non-blocking: batches are staged onto device by the loader thread,
per-step loss/metric arrays stay on device until ONE epoch-boundary
fetch, and checkpoint snapshots fence through the manager's async-D2H
path. A stray ``jax.device_get`` / ``.block_until_ready()`` /
``float(<traced scalar>)`` dropped into one of these loops silently
reintroduces a per-step device round trip — the regression class this
linter pins down, the way ``lint_timing.py`` pins raw clock reads.

Scope is the LIBRARY EPOCH-LOOP MODULES only (``EPOCH_LOOP_MODULES``
below): the trainer loops this discipline governs. Everything else —
inference, serving, bench/driver code — fetches freely. Flags:

  * ``jax.device_get(...)`` calls (and ``from jax import device_get``
    alias imports);
  * ``.block_until_ready()`` method calls on anything;
  * ``float(x)`` where ``x`` is not a constant and contains no
    ``np``/``numpy`` reference — ``float(device_scalar)`` is an
    implicit blocking transfer, while ``float(np.mean(host))`` is
    host-side arithmetic (the heuristic). ``__init__`` bodies are
    exempt (constructor scalar coercions are not syncs).

Sanctioned fetch points — ``parallel.engine.host_fetch``/``host_async``
internals, the shared ``trainers.val_logs`` validation fetch, the
epoch-boundary fetches, callback-API ``get_weights`` providers and
end-of-train result fetches — carry the marker comment
``# lint: allow-host-sync`` on the offending line.

Exit status 1 when findings exist (wired into tier-1 as
``tests/test_lint_host_sync.py``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

ALLOW_MARK = "lint: allow-host-sync"

#: the modules holding library epoch loops — the blocking-sync-free zone
EPOCH_LOOP_MODULES = (
    "distkeras_tpu/parallel/trainers.py",
    "distkeras_tpu/parallel/spmd.py",
    "distkeras_tpu/parallel/pipeline.py",
    "distkeras_tpu/parallel/distributed.py",
    "distkeras_tpu/parallel/engine.py",
)

Finding = Tuple[str, int, str]


def _allowed(line: str) -> bool:
    return ALLOW_MARK in line


def _mentions_numpy(node: ast.AST) -> bool:
    """Does the expression reference ``np``/``numpy`` anywhere? Host-side
    arithmetic routes through numpy; a bare traced value does not."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("np", "numpy"):
            return True
    return False


def _init_ranges(tree: ast.AST) -> List[Tuple[int, int]]:
    return [(n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "__init__"]


def check_source(src: str, rel: str) -> List[Finding]:
    """Findings for one file's source text."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:  # a broken file is its own finding
        return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    inits = _init_ranges(tree)
    out: List[Finding] = []

    def line_of(node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return lines[ln - 1] if 0 < ln <= len(lines) else ""

    def in_init(node: ast.AST) -> bool:
        ln = getattr(node, "lineno", 0)
        return any(lo <= ln <= hi for lo, hi in inits)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "device_get" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "jax":
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                "jax.device_get() in an epoch-loop module "
                                "— route through host_fetch/the "
                                "epoch-boundary fetch, or mark the "
                                "sanctioned site"))
            elif isinstance(f, ast.Attribute) \
                    and f.attr == "block_until_ready":
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                ".block_until_ready() in an epoch-loop "
                                "module — a blocking device sync; let the "
                                "boundary fetch bound the epoch"))
            elif isinstance(f, ast.Name) and f.id == "float" \
                    and node.args and not isinstance(node.args[0],
                                                     ast.Constant) \
                    and not _mentions_numpy(node.args[0]) \
                    and not in_init(node):
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                "float(<non-numpy value>) in an "
                                "epoch-loop module — on a traced/device "
                                "scalar this is an implicit blocking "
                                "transfer; fetch at the boundary (or go "
                                "through numpy) instead"))
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            bad = [a.name for a in node.names if a.name == "device_get"]
            if bad and not _allowed(line_of(node)):
                out.append((rel, node.lineno,
                            "from jax import device_get — aliasing the "
                            "banned fetch; use host_fetch or a marked "
                            "site"))
    return sorted(out, key=lambda f: f[1])


def check_tree(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for entry in EPOCH_LOOP_MODULES:
        p = root / entry
        if p.exists():
            findings.extend(check_source(p.read_text(), entry))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} host-sync finding(s); route through the "
              f"sanctioned fetch points or mark the line with "
              f"'# {ALLOW_MARK}'", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
