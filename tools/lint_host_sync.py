#!/usr/bin/env python
"""Static check: no ad-hoc host syncs inside the epoch-loop modules.

The overlap PR (docs/overlap.md) made the trainer epoch loops
non-blocking: batches are staged onto device by the loader thread,
per-step loss/metric arrays stay on device until ONE epoch-boundary
fetch, and checkpoint snapshots fence through the manager's async-D2H
path. A stray ``jax.device_get`` / ``.block_until_ready()`` /
``float(<traced scalar>)`` dropped into one of these loops silently
reintroduces a per-step device round trip — the regression class this
linter pins down, the way ``lint_timing.py`` pins raw clock reads.

Scope is the LIBRARY EPOCH-LOOP MODULES only (``EPOCH_LOOP_MODULES``
below): the trainer loops this discipline governs. Everything else —
inference, serving, bench/driver code — fetches freely. Flags:

  * ``jax.device_get(...)`` calls (and ``from jax import device_get``
    alias imports);
  * ``.block_until_ready()`` method calls on anything;
  * ``float(x)`` where ``x`` is not a constant and contains no
    ``np``/``numpy`` reference — ``float(device_scalar)`` is an
    implicit blocking transfer, while ``float(np.mean(host))`` is
    host-side arithmetic (the heuristic). ``__init__`` bodies are
    exempt (constructor scalar coercions are not syncs).

Sanctioned fetch points — ``parallel.engine.host_fetch``/``host_async``
internals, the shared ``trainers.val_logs`` validation fetch, the
epoch-boundary fetches, callback-API ``get_weights`` providers and
end-of-train result fetches — carry the marker comment
``# lint: allow-host-sync`` on the offending line.

THE SERVING ITERATION LOOP (zero-bubble PR, docs/serving.md
§Zero-bubble loop) is the second blocking-sync-free zone: the
step/decode-path methods of ``serving/engine.py`` listed in
``SERVING_LOOP_FUNCS``. There the pipelined-dispatch contract is that
the device NEVER waits on per-iteration Python, so on top of the three
rules above, ``np.asarray(...)``/``np.array(...)`` — the fetch idiom
that used to sync every decode iteration — is banned too. Exactly ONE
marked site is sanctioned: the lagged fetch in ``_fetch()``; zero
marks (someone deleted the contract) or a second mark (someone snuck a
new sync past review) are both findings.

THE SPECULATION PATH (tree-speculation PR) is the third zone: the
draft propose/accept call graph of ``serving/speculation.py``
(``SPECULATION_LOOP_FUNCS`` — ``propose``/``propose_tree``, the
n-gram lookups, the tree builders) runs inside the synchronous
speculative iteration, so the three base rules apply there too;
``np.asarray`` stays allowed (the draft-model step's per-step fetch
is the sources' sanctioned medium).

Exit status 1 when findings exist (wired into tier-1 as
``tests/test_lint_host_sync.py``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

ALLOW_MARK = "lint: allow-host-sync"

#: the modules holding library epoch loops — the blocking-sync-free zone
EPOCH_LOOP_MODULES = (
    "distkeras_tpu/parallel/trainers.py",
    "distkeras_tpu/parallel/spmd.py",
    "distkeras_tpu/parallel/pipeline.py",
    "distkeras_tpu/parallel/distributed.py",
    "distkeras_tpu/parallel/engine.py",
)

#: the serving engine module whose iteration loop is the second zone
SERVING_LOOP_MODULE = "distkeras_tpu/serving/engine.py"

#: the step/decode-path methods forming the serving iteration loop.
#: Out of scope by design: submit/prefill intake (one-off per-request
#: work), ``_note_moe_route`` (the throttled stats tap — it reads
#: arrays of an already-consumed step on a 1-in-16 cadence), and the
#: out-of-band control surface (cancel, health, telemetry summaries).
SERVING_LOOP_FUNCS = frozenset({
    "step", "_advance_decode", "_spec_step", "_launch_step",
    "_process_step", "_flush_pending", "_flush_host_window", "_fetch",
    "_fuse_window", "_inflight", "_merge_keys", "_ensure_decode_pages",
    "_fragmentation", "_record_iteration", "_finish", "_admit",
    "_expire_deadlines",
    # tree speculation (tree-speculation PR): the tree draft/accept
    # call graph runs inside the iteration too
    "_spec_tree_step", "_tree_shape", "_adapt_tree", "_drop_swap",
    "_consume_spec",
})

#: how many ``# lint: allow-host-sync`` marks the serving loop may
#: carry: exactly one — the lagged fetch in ``_fetch()``
SERVING_ALLOWED_MARKS = 1

#: the draft-source module (tree-speculation PR): proposal and the
#: tree helpers run INSIDE the (synchronous) speculative iteration, so
#: the three base rules apply — a stray ``jax.device_get`` /
#: ``block_until_ready`` / ``float(<traced>)`` in the propose path is
#: a per-iteration sync regression. ``np.asarray`` stays ALLOWED here
#: (unlike the engine zone): the draft-model step's per-step fetch is
#: the sources' sanctioned medium — drafting is host-driven by design.
SPECULATION_MODULE = "distkeras_tpu/serving/speculation.py"
SPECULATION_LOOP_FUNCS = frozenset({
    "propose", "propose_tree", "lookup", "continuations", "_grow",
    "build_token_tree", "tree_ancestors", "_draft_steps", "_heal",
    "_context",
})

Finding = Tuple[str, int, str]


def _allowed(line: str) -> bool:
    return ALLOW_MARK in line


def _mentions_numpy(node: ast.AST) -> bool:
    """Does the expression reference ``np``/``numpy`` anywhere? Host-side
    arithmetic routes through numpy; a bare traced value does not."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("np", "numpy"):
            return True
    return False


def _init_ranges(tree: ast.AST) -> List[Tuple[int, int]]:
    return [(n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == "__init__"]


def _func_ranges(tree: ast.AST, names) -> List[Tuple[int, int]]:
    return [(n.lineno, n.end_lineno or n.lineno)
            for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name in names]


def check_source(src: str, rel: str, only_funcs=None,
                 ban_np_fetch: bool = False,
                 allowed_marks: int = None) -> List[Finding]:
    """Findings for one file's source text. With ``only_funcs`` (a set
    of function names) only statements inside those functions are
    checked; ``ban_np_fetch`` adds the ``np.asarray``/``np.array`` rule
    (the serving-loop fetch idiom); ``allowed_marks`` asserts the exact
    number of ``# lint: allow-host-sync`` marks inside the scope."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:  # a broken file is its own finding
        return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    inits = _init_ranges(tree)
    scope = (None if only_funcs is None
             else _func_ranges(tree, only_funcs))
    if scope is not None and not scope:
        # the zone evaporated (e.g. the loop methods were renamed
        # without updating the func set) — that is a finding, not a
        # silently-green empty scope
        return [(rel, 0,
                 "none of the scoped serving-loop functions "
                 f"({', '.join(sorted(only_funcs))}) exist in this "
                 "file — update the lint's function set so the zone "
                 "keeps covering the loop")]
    out: List[Finding] = []

    def line_of(node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return lines[ln - 1] if 0 < ln <= len(lines) else ""

    def in_init(node: ast.AST) -> bool:
        ln = getattr(node, "lineno", 0)
        return any(lo <= ln <= hi for lo, hi in inits)

    def in_scope(node: ast.AST) -> bool:
        if scope is None:
            return True
        ln = getattr(node, "lineno", 0)
        return any(lo <= ln <= hi for lo, hi in scope)

    if allowed_marks is not None:
        n_marks = sum(
            1 for lo, hi in (scope or [(1, len(lines))])
            for ln in range(lo, hi + 1)
            if ln <= len(lines) and ALLOW_MARK in lines[ln - 1])
        if n_marks != allowed_marks:
            out.append((rel, 0,
                        f"{n_marks} '{ALLOW_MARK}' mark(s) in the "
                        f"serving loop scope, expected exactly "
                        f"{allowed_marks} (the _fetch lagged-fetch "
                        f"site) — a new sync needs a design review, "
                        f"not a marker"))

    for node in ast.walk(tree):
        if not in_scope(node):
            continue
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "device_get" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "jax":
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                "jax.device_get() in an epoch-loop module "
                                "— route through host_fetch/the "
                                "epoch-boundary fetch, or mark the "
                                "sanctioned site"))
            elif isinstance(f, ast.Attribute) \
                    and f.attr == "block_until_ready":
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                ".block_until_ready() in an epoch-loop "
                                "module — a blocking device sync; let the "
                                "boundary fetch bound the epoch"))
            elif ban_np_fetch and isinstance(f, ast.Attribute) \
                    and f.attr in ("asarray", "array") \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy"):
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                f"np.{f.attr}() in the serving iteration "
                                "loop — the fetch idiom blocks the host "
                                "on the device here; consume tokens "
                                "through the lagged _fetch() or defer "
                                "the work to a host-window buffer"))
            elif isinstance(f, ast.Name) and f.id == "float" \
                    and node.args and not isinstance(node.args[0],
                                                     ast.Constant) \
                    and not _mentions_numpy(node.args[0]) \
                    and not in_init(node):
                if not _allowed(line_of(node)):
                    out.append((rel, node.lineno,
                                "float(<non-numpy value>) in an "
                                "epoch-loop module — on a traced/device "
                                "scalar this is an implicit blocking "
                                "transfer; fetch at the boundary (or go "
                                "through numpy) instead"))
        elif isinstance(node, ast.ImportFrom) and node.module == "jax":
            bad = [a.name for a in node.names if a.name == "device_get"]
            if bad and not _allowed(line_of(node)):
                out.append((rel, node.lineno,
                            "from jax import device_get — aliasing the "
                            "banned fetch; use host_fetch or a marked "
                            "site"))
    return sorted(out, key=lambda f: f[1])


def check_tree(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for entry in EPOCH_LOOP_MODULES:
        p = root / entry
        if p.exists():
            findings.extend(check_source(p.read_text(), entry))
    p = root / SERVING_LOOP_MODULE
    if p.exists():
        findings.extend(check_source(
            p.read_text(), SERVING_LOOP_MODULE,
            only_funcs=SERVING_LOOP_FUNCS, ban_np_fetch=True,
            allowed_marks=SERVING_ALLOWED_MARKS))
    p = root / SPECULATION_MODULE
    if p.exists():
        findings.extend(check_source(
            p.read_text(), SPECULATION_MODULE,
            only_funcs=SPECULATION_LOOP_FUNCS))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} host-sync finding(s); route through the "
              f"sanctioned fetch points or mark the line with "
              f"'# {ALLOW_MARK}'", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
