#!/usr/bin/env python
"""Static check: fault-point names in code and docs/resilience.md agree.

``resilience.faults`` addresses injection sites BY NAME: a chaos
schedule (``loadgen.ChaosSpec``), a ``DKT_FAULTS`` env script, or a
test arming ``faults.inject("serving.decode", ...)`` all bind to the
string literal at the ``faults.point("...")`` site. Renaming a site
breaks none of them loudly — the injection simply never fires and the
chaos scenario silently tests nothing. The docs catalog
(docs/resilience.md, the fault-point table) is the contract surface
operators script against, so this linter holds the two sides equal:

  1. AST-walk ``distkeras_tpu/`` for every ``faults.point("...")`` /
     ``faults.corrupt("...", ...)`` call with a literal name;
  2. parse the docs/resilience.md catalog table (rows whose first
     cell is one backticked dotted name);
  3. finding for every name on one side only, either direction.

Dynamic point names (non-literal first args) are skipped — they are
not lintable statically and the catalog documents the static surface.
Wired into tier-1 via ``tests/test_lint_fault_points.py`` (with a
negative-injection case: an undocumented point must produce a
finding). The ``lint_report_series`` sibling covers metric names the
same way.

Exit status 1 when findings exist.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

Finding = Tuple[str, str]     # (point name, message)

#: the faults-module attributes that take a point NAME as their first
#: positional argument at an injection SITE (inject/clear take names
#: too, but those are *users* of points, not definitions)
_SITE_ATTRS = ("point", "corrupt")

_ROW_RE = re.compile(r"^\|\s*`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`\s*\|")


def code_points(root: Path) -> Dict[str, List[str]]:
    """Every literal ``faults.point/corrupt`` name under ``root`` ->
    the ``file:line`` sites that declare it."""
    out: Dict[str, List[str]] = {}
    for path in sorted(root.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute)
                    and fn.attr in _SITE_ATTRS
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "faults"):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue     # dynamic name: not statically lintable
            name = node.args[0].value
            rel = path.relative_to(root.parent)
            out.setdefault(name, []).append(
                f"{rel}:{node.lineno}")
    return out


def doc_points(doc: str) -> Set[str]:
    """Backticked dotted point names from the catalog table rows."""
    return {m.group(1) for line in doc.splitlines()
            if (m := _ROW_RE.match(line.strip()))}


def check(root=None, doc_text=None) -> List[Finding]:
    repo = Path(__file__).resolve().parent.parent
    root = Path(root) if root else repo / "distkeras_tpu"
    if doc_text is None:
        doc_text = (repo / "docs" / "resilience.md").read_text()
    in_code = code_points(root)
    in_doc = doc_points(doc_text)
    findings: List[Finding] = []
    for name in sorted(set(in_code) - in_doc):
        sites = ", ".join(in_code[name])
        findings.append((name, f"fault point {name!r} ({sites}) is not "
                               f"in the docs/resilience.md catalog — "
                               f"add a table row (chaos schedules bind "
                               f"to the documented name)"))
    for name in sorted(in_doc - set(in_code)):
        findings.append((name, f"docs/resilience.md catalogs fault "
                               f"point {name!r} but no faults.point/"
                               f"corrupt site declares it — renamed "
                               f"or removed? chaos schedules armed on "
                               f"it now silently no-op"))
    return findings


def main(argv=None) -> int:
    findings = check()
    for name, msg in findings:
        print(f"lint_fault_points: {msg}", file=sys.stderr)
    if findings:
        print(f"lint_fault_points: {len(findings)} finding(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
