#!/usr/bin/env python
"""Live check: every series name the scenario report reads exists.

``obs/report.py`` and the ``bench.py --model loadgen`` family read
registry series BY NAME out of time-series scrapes
(``REPORT_SERIES``). A metric rename in ``serving/metrics.py`` or
``obs/slo.py`` would not break any import — the report's joins just
come back empty and a dashboard panel silently flatlines. That is the
worst kind of observability regression: the system looks healthy
because the instrument reporting on it vanished.

This linter closes the loop dynamically (its siblings —
``lint_metric_names.py`` etc. — are static AST walks; name existence
is a runtime property, so this one runs a smoke scenario instead):

  1. instantiate the live instrument surface the report reads —
     a ``ServingMetrics`` window (the engine's per-request metric
     family) and an ``SLOEngine`` evaluation (the ``slo.*`` gauges
     + breach counter) — exactly as a replay would;
  2. assert every ``REPORT_SERIES`` name is registered in one of
     those live registries.

A renamed (or dropped) metric fails tier-1 via
``tests/test_lint_report_series.py``. Pure-CPU, no model build, no
JAX arrays — milliseconds, not seconds.

Exit status 1 when findings exist.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Tuple

Finding = Tuple[str, str]     # (series name, message)


def live_series() -> set:
    """Every series name registered by the instrument surfaces the
    scenario report reads: a fresh ``ServingMetrics`` window plus one
    ``SLOEngine`` evaluation against it."""
    from distkeras_tpu.obs.slo import SLOEngine, availability, ttft_p99
    from distkeras_tpu.serving.metrics import ServingMetrics

    metrics = ServingMetrics()
    slo = SLOEngine([ttft_p99(0.5), availability(0.9)],
                    registry=metrics.registry)
    slo.evaluate(metrics)
    return set(metrics.registry.instruments())


def check(names=None) -> List[Finding]:
    """Findings for the given series names (default: the report's
    ``REPORT_SERIES`` contract surface)."""
    if names is None:
        from distkeras_tpu.obs.report import REPORT_SERIES
        names = REPORT_SERIES
    live = live_series()
    return [(n, f"series {n!r} read by obs/report.py is not registered "
                f"by any live instrument surface (renamed or dropped?)")
            for n in names if n not in live]


def main(argv=None) -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    findings = check()
    for name, msg in findings:
        print(f"lint_report_series: {msg}", file=sys.stderr)
    if findings:
        print(f"lint_report_series: {len(findings)} finding(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
