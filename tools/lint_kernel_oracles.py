#!/usr/bin/env python
"""Static check: every Pallas kernel entry point has an
interpret-mode oracle test.

The repo-wide testing convention (docs/testing.md, PR 3 onward): a
Pallas kernel never ships on trust — some tier-1 test runs it under
``interpret=True`` (or the module's ``force_interpret()`` hook) and
pins it against a pure-XLA reference, bitwise or tolerance-matched.
The convention only protects kernels it actually covers, and nothing
structural used to enforce that: a new kernel module with no oracle
test would pass tier-1 silently and fail first on hardware, where a
miscompiled kernel is a wrong-NUMBERS bug, not a crash.

This linter closes the gap. It AST-parses ``distkeras_tpu/ops/*.py``
and finds every KERNEL ENTRY POINT — a public top-level function that
transitively (through same-module helpers) reaches a
``pl.pallas_call`` — then requires, for each, at least one
``tests/test_*.py`` that references the entry point by name AND
exercises interpreter mode (mentions ``interpret``; the
``force_interpret`` context managers and ``interpret=True`` kwargs
both match). A justified exception carries the marker comment
``lint: allow-no-oracle`` on the ``def`` line.

Exit status 1 when findings exist (wired into tier-1 as
``tests/test_lint_kernel_oracles.py``).
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import Dict, List, Set, Tuple

ALLOW_MARK = "lint: allow-no-oracle"

#: where kernels live and where their oracles live, repo-relative
OPS_DIR = "distkeras_tpu/ops"
TESTS_DIR = "tests"

Finding = Tuple[str, int, str]


def _calls_in(fn: ast.AST) -> Tuple[bool, Set[str]]:
    """(has a direct pallas_call, names of functions called)."""
    direct = False
    names: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "pallas_call":
            direct = True
        elif isinstance(f, ast.Name):
            names.add(f.id)
    return direct, names


def kernel_entry_points(src: str, rel: str) -> List[Tuple[str, int]]:
    """Public top-level functions of one module that transitively
    reach a ``pallas_call`` — ``(name, lineno)`` pairs. A private
    helper holding the actual ``pl.pallas_call`` (the ``_kernel`` /
    wrapper split every kernel module uses) attributes to whichever
    public function calls it."""
    tree = ast.parse(src, filename=rel)
    fns: Dict[str, ast.AST] = {
        n.name: n for n in tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    direct: Set[str] = set()
    edges: Dict[str, Set[str]] = {}
    for name, fn in fns.items():
        d, called = _calls_in(fn)
        if d:
            direct.add(name)
        edges[name] = called & set(fns)
    # transitive closure to the direct set
    reaches = set(direct)
    changed = True
    while changed:
        changed = False
        for name, called in edges.items():
            if name not in reaches and called & reaches:
                reaches.add(name)
                changed = True
    return sorted((n, fns[n].lineno) for n in reaches
                  if not n.startswith("_"))


def _exempt(src_lines: List[str], lineno: int) -> bool:
    line = src_lines[lineno - 1] if 0 < lineno <= len(src_lines) else ""
    return ALLOW_MARK in line


def check_tree(root: Path) -> List[Finding]:
    """Every kernel entry point across ``ops/`` without an
    interpret-mode oracle test referencing it by name."""
    test_texts: Dict[str, str] = {
        str(p.relative_to(root)): p.read_text()
        for p in sorted((root / TESTS_DIR).glob("test_*.py"))}
    findings: List[Finding] = []
    for mod in sorted((root / OPS_DIR).glob("*.py")):
        rel = str(mod.relative_to(root))
        src = mod.read_text()
        try:
            entries = kernel_entry_points(src, rel)
        except SyntaxError as e:
            findings.append((rel, e.lineno or 0,
                             f"syntax error: {e.msg}"))
            continue
        lines = src.splitlines()
        for name, lineno in entries:
            if _exempt(lines, lineno):
                continue
            pat = re.compile(rf"\b{re.escape(name)}\b")
            covered = any(
                pat.search(text) and "interpret" in text
                for text in test_texts.values())
            if not covered:
                findings.append((
                    rel, lineno,
                    f"kernel entry point '{name}' has no interpret-"
                    f"mode oracle test (no tests/test_*.py references "
                    f"it in a file exercising interpreter mode)"))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} kernel-oracle finding(s); add an "
              f"interpret-mode test pinning the kernel against its "
              f"XLA reference, or mark the def line with "
              f"'# {ALLOW_MARK}'", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
