#!/usr/bin/env python
"""Static check: library code must not swallow the un-catchable.

The resilience subsystem's contract is that failures are CLASSIFIED —
retryable IO errors heal, crashes restart from checkpoints, anomalies
roll back. A ``except:`` / ``except BaseException:`` handler that does
not re-raise breaks the whole chain silently: it eats
``KeyboardInterrupt``/``SystemExit`` (hangs instead of dying), hides
injected chaos faults (tests pass while the code path is broken), and
turns a crash the supervisor would recover from into undefined state.

This linter walks the AST (docstrings and comments never
false-positive) and flags, inside the ``distkeras_tpu`` package:

  * bare ``except:`` handlers
  * ``except BaseException`` handlers (alone or in a tuple)

UNLESS the handler body re-raises (a ``raise`` statement in the
handler itself — nested ``def``/``lambda`` bodies don't count: they
run later, not on this exception). Catching ``Exception`` stays legal —
that is the classification boundary the resilience layer is built on.

A justified swallow (e.g. a worker thread stashing the error for the
consumer thread to re-raise) carries the marker comment
``lint: allow-swallow`` on the ``except`` line — same pattern as
``lint_timing.py`` / ``lint_backend_forks.py``.

Scope is LIBRARY code only: ``bench.py``, ``examples/``, ``tools/`` and
tests are driver code. Exit status 1 when findings exist (wired into
tier-1 as ``tests/test_lint_exception_swallow.py``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

ALLOW_MARK = "lint: allow-swallow"

#: paths scanned, relative to the repo root (library code only)
SCAN = ("distkeras_tpu",)

Finding = Tuple[str, int, str]


def _mentions_base_exception(type_node) -> bool:
    """Does the handler's type expression name BaseException (directly
    or as a tuple element)?"""
    if type_node is None:
        return False
    nodes = (type_node.elts if isinstance(type_node, ast.Tuple)
             else [type_node])
    return any(isinstance(n, ast.Name) and n.id == "BaseException"
               for n in nodes)


def _reraises(handler: ast.ExceptHandler) -> bool:
    """A ``raise`` anywhere in the handler body counts as re-raising —
    EXCEPT inside nested function/class bodies, which execute later,
    not while this exception is in flight."""
    stack = list(handler.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return False


def check_source(src: str, rel: str) -> List[Finding]:
    """Findings for one file's source text."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:  # a broken file is its own finding
        return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    out: List[Finding] = []

    def allowed(node: ast.AST) -> bool:
        ln = getattr(node, "lineno", 0)
        return 0 < ln <= len(lines) and ALLOW_MARK in lines[ln - 1]

    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        bare = node.type is None
        base = _mentions_base_exception(node.type)
        if not (bare or base):
            continue
        if _reraises(node) or allowed(node):
            continue
        what = "bare 'except:'" if bare else "'except BaseException'"
        out.append((rel, node.lineno,
                    f"{what} without re-raise swallows "
                    "KeyboardInterrupt/SystemExit and injected faults — "
                    "catch Exception (the classification boundary), "
                    "re-raise, or mark the line with "
                    f"'# {ALLOW_MARK}'"))
    return out


def check_tree(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for entry in SCAN:
        p = root / entry
        files = sorted(p.rglob("*.py")) if p.is_dir() \
            else ([p] if p.exists() else [])
        for f in files:
            rel = str(f.relative_to(root))
            findings.extend(check_source(f.read_text(), rel))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} exception-swallow finding(s); see "
              f"tools/lint_exception_swallow.py", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
