#!/usr/bin/env python
"""Static check: raw clock reads belong to the telemetry layer only.

The repo-wide convention (telemetry PR, documented on
``utils.profiling.now``/``wall``): library code does not call
``time.time()`` / ``time.perf_counter()`` / ``time.monotonic()``
directly — every duration or timestamp routes through
``utils/profiling.py`` (the clock owner) or the ``obs`` subsystem built
on it. Ad-hoc clock reads are how the pre-telemetry fragments
(``ServingMetrics`` lists, bench prints) drifted apart: each invented
its own timing with no shared registry, units, or export path.

This linter walks the AST (docstrings and comments never
false-positive) and flags, inside the ``distkeras_tpu`` package but
outside ``obs/`` and ``utils/profiling.py``:

  * calls ``time.time(...)`` / ``time.perf_counter(...)`` /
    ``time.monotonic(...)``
  * ``from time import time/perf_counter/monotonic`` (the alias evasion)

Scope is LIBRARY code only: ``bench.py``, ``examples/``, ``tools/`` and
tests are measurement/driver code where raw clocks are the tool of the
trade. A justified library exception (e.g. a client-side deadline, not
telemetry) carries the marker comment ``lint: allow-raw-clock`` on the
offending line — same pattern as ``lint_backend_forks.py``.

Exit status 1 when findings exist (wired into tier-1 as
``tests/test_lint_timing.py``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

ALLOW_MARK = "lint: allow-raw-clock"

#: paths scanned, relative to the repo root (library code only)
SCAN = ("distkeras_tpu",)

#: modules allowed to read clocks raw: the clock owner and the
#: telemetry subsystem built on it
EXEMPT_FILES = ("profiling.py",)
EXEMPT_DIRS = ("obs",)

CLOCK_ATTRS = ("time", "perf_counter", "monotonic")

Finding = Tuple[str, int, str]


def _allowed(line: str) -> bool:
    return ALLOW_MARK in line


def check_source(src: str, rel: str) -> List[Finding]:
    """Findings for one file's source text."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:  # a broken file is its own finding
        return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    out: List[Finding] = []

    def line_of(node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return lines[ln - 1] if 0 < ln <= len(lines) else ""

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in CLOCK_ATTRS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "time":
            if not _allowed(line_of(node)):
                out.append((rel, node.lineno,
                            f"raw time.{node.func.attr}() call — use "
                            "utils.profiling.now()/wall() (or the obs "
                            "layer)"))
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            bad = [a.name for a in node.names if a.name in CLOCK_ATTRS]
            if bad and not _allowed(line_of(node)):
                out.append((rel, node.lineno,
                            f"from time import {', '.join(bad)} — "
                            "aliasing the raw clock; use "
                            "utils.profiling.now()/wall()"))
    return out


def check_tree(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for entry in SCAN:
        p = root / entry
        files = sorted(p.rglob("*.py")) if p.is_dir() \
            else ([p] if p.exists() else [])
        for f in files:
            if f.name in EXEMPT_FILES \
                    or any(d in f.parts for d in EXEMPT_DIRS):
                continue
            rel = str(f.relative_to(root))
            findings.extend(check_source(f.read_text(), rel))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} raw-clock finding(s); route through "
              f"utils.profiling.now()/wall() or mark the line with "
              f"'# {ALLOW_MARK}'", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
