"""Render a per-op time table from an XProf trace directory, or a span
table from a telemetry JSONL export.

Usage:
    python bench.py --profile /tmp/xprof            # capture
    python tools/xprof_op_table.py /tmp/xprof       # render markdown
    python tools/xprof_op_table.py --spans t.jsonl  # host-span table

Device mode parses the ``*.xplane.pb`` the JAX profiler writes,
aggregates the TPU device plane's "XLA Ops" line by op, and prints a
markdown table of the top ops plus a category rollup (convolution/matmul
vs batch-norm-statistics reductions vs other fusions vs data movement).
Runs with the pure-python protobuf implementation so it works even where
the tensorboard profile plugin's C++ bridge is version-mismatched (set
``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` if import fails).

Span mode (``--spans``) reads the JSONL the telemetry layer exports
(``obs.exporters.JsonlExporter`` — ``{"type": "span", "path": [...],
"total_s", "count"}`` lines) and renders the HOST-side span tree with
self-time accounting. Spans are bridged to
``jax.profiler.TraceAnnotation``, so the names in this table are the
same names on the xprof host timeline — the two views cross-reference.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from collections import defaultdict

os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def _category(op_name: str) -> str:
    n = op_name.lower()
    # on TPU the compiler fuses convolutions WITH their bf16->f32 convert +
    # BN-statistics reduction epilogues; op names alone cannot split conv
    # FLOPs from BN stats, so the buckets describe the fusion shapes
    if "convert_reduce" in n:
        return "fused conv + stats-reduce blocks"
    # Pallas kernels surface as custom-call ops named after the traced
    # function: the flash-attention fwd kernel lowers as "%jvp__.N" under
    # autodiff and the two backward kernels as "%transpose_jvp___.N"
    # (round 4 — they were previously mis-bucketed as data movement,
    # hiding 35% of the LM step behind "transposes"). Round 5 (advisor):
    # the jvp_ prefix alone also matches jvp-named FUSIONS from other
    # rematerialized/custom-vjp code (fused CE, ring attention backward),
    # so fusion ops are excluded here — they fall through to the
    # "elementwise fusions" bucket where their time belongs.
    if re.match(r"%?(transpose_)?jvp_", n):
        # route excluded jvp-named fusions to their true bucket HERE —
        # falling through would hit the "transpose" substring check first
        # and land transpose_jvp_* fusions back in "data movement"
        return ("elementwise fusions" if "fusion" in n
                else "pallas kernels (flash attention)")
    if "custom-call" in n or "pallas" in n:
        return "pallas kernels (other custom calls)"
    if "convolution" in n or re.match(r"%?(conv(?!ert)|dot)", n):
        return "unfused conv/matmul"
    if "reduce" in n and "window" not in n and "scatter" not in n:
        return "standalone reductions"
    if "select-and-scatter" in n or "reduce-window" in n:
        return "pooling"
    if "copy" in n or "transpose" in n or "bitcast" in n:
        return "data movement"
    if "all-reduce" in n or "all-gather" in n or "collective" in n \
            or "permute" in n:
        return "collectives"
    if "fusion" in n:
        return "elementwise fusions"
    return "other"


def load_op_times(trace_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    files = sorted(glob.glob(trace_dir + "/**/*.xplane.pb", recursive=True))
    if not files:
        raise SystemExit(f"no .xplane.pb under {trace_dir}")
    xs = xplane_pb2.XSpace()
    with open(files[-1], "rb") as f:
        xs.ParseFromString(f.read())
    dur = defaultdict(float)
    cnt = defaultdict(int)
    for p in xs.planes:
        if not p.name.startswith("/device:TPU"):
            continue
        ev_meta = {m.id: m.name for m in p.event_metadata.values()}
        for line in p.lines:
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                name = ev_meta.get(ev.metadata_id, "?").split(" = ")[0]
                dur[name] += ev.duration_ps / 1e12
                cnt[name] += 1
    return dur, cnt


def load_span_records(path: str):
    """``[(path_tuple, total_s, count)]`` from a telemetry JSONL export
    (latest ``seq`` in the file wins — the append-log convention of
    ``obs.exporters``). Standalone parser: the tool must work in an
    environment without the package importable."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    seq = max((r.get("seq", 0) for r in records), default=0)
    return [(tuple(r["path"]), float(r["total_s"]), int(r["count"]))
            for r in records
            if r.get("type") == "span" and r.get("seq", 0) == seq]


def render_span_table(records, top_n: int = 20) -> str:
    """Markdown: span path, count, total, self (total minus direct
    children — large self on a parent = untraced work inside it), and
    share of the root total."""
    if not records:
        return "no span records\n"
    totals = {path: (total, count) for path, total, count in records}
    self_s = {}
    for path, (total, _count) in totals.items():
        child_sum = sum(t for p, (t, _c) in totals.items()
                        if len(p) == len(path) + 1 and p[:-1] == path)
        self_s[path] = max(total - child_sum, 0.0)
    root_total = sum(t for p, (t, _c) in totals.items() if len(p) == 1)
    out = [f"Host span total (root spans): {root_total:.4f}s "
           f"({len(totals)} distinct paths)\n",
           "| span | count | total | self | share |",
           "|---|---|---|---|---|"]
    ranked = sorted(totals.items(), key=lambda kv: (-kv[1][0], kv[0]))
    for path, (total, count) in ranked[:top_n]:
        name = " / ".join(path)
        share = 100 * total / root_total if root_total else 0.0
        out.append(f"| `{name}` | {count} | {total * 1e3:.1f} ms | "
                   f"{self_s[path] * 1e3:.1f} ms | {share:.1f}% |")
    return "\n".join(out) + "\n"


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--spans":
        if len(sys.argv) < 3:
            raise SystemExit("usage: xprof_op_table.py --spans FILE.jsonl"
                             " [top_n]")
        top_n = int(sys.argv[3]) if len(sys.argv) > 3 else 20
        print(render_span_table(load_span_records(sys.argv[2]), top_n),
              end="")
        return
    trace_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/xprof"
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    dur, cnt = load_op_times(trace_dir)
    total = sum(dur.values())
    if not total:
        raise SystemExit("trace has no TPU XLA Ops events")

    cats = defaultdict(float)
    for name, d in dur.items():
        cats[_category(name)] += d

    print(f"Total device op time: {total:.4f}s "
          f"({len(dur)} distinct ops)\n")
    print("| category | time | share |")
    print("|---|---|---|")
    for cat, d in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"| {cat} | {d * 1e3:.1f} ms | {100 * d / total:.1f}% |")
    print(f"\n| top-{top_n} op | time | share | calls |")
    print("|---|---|---|---|")
    for name, d in sorted(dur.items(), key=lambda kv: -kv[1])[:top_n]:
        print(f"| `{name[:60]}` | {d * 1e3:.1f} ms | "
              f"{100 * d / total:.1f}% | {cnt[name]} |")


if __name__ == "__main__":
    main()
