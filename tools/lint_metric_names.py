#!/usr/bin/env python
"""Static check: registry metric names are literal ``component.snake_case``.

The metrics registry (``obs.registry``) keys series by NAME; labels
carry the variable dimensions. A name built at runtime — an f-string,
a concatenation, a variable — is the classic cardinality bomb: every
novel value mints a new top-level series, which no ``max_series`` cap
folds (the cap bounds LABEL sets per metric, not metric count), and
dashboards/alerts can't be written against names that don't exist in
the source. The telemetry layer's convention, stated in
docs/observability.md, is therefore:

  * every ``registry.counter(...)`` / ``.gauge(...)`` /
    ``.histogram(...)`` call in library code passes a STRING LITERAL
    first argument;
  * the literal matches ``component.snake_case`` — a lowercase
    dotted path like ``serving.ttft_s`` or ``slo.burn_rate`` (at
    least one dot: the first segment names the owning component).

This linter walks the AST (docstrings and comments never
false-positive) of the ``distkeras_tpu`` package and flags violations
of both rules. Justified exceptions — e.g. a tape whose metric prefix
is the trainer class name (a bounded, code-defined set), or an SLO
engine READING a configured series — carry the marker comment
``lint: allow-dynamic-metric-name`` on the offending line, same
pattern as the other four lints.

Exit status 1 when findings exist (wired into tier-1 as
``tests/test_lint_metric_names.py``).
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path
from typing import List, Tuple

ALLOW_MARK = "lint: allow-dynamic-metric-name"

#: paths scanned, relative to the repo root (library code only —
#: tests/bench/examples construct ad-hoc registries freely)
SCAN = ("distkeras_tpu",)

#: the registry instrument constructors
METRIC_METHODS = ("counter", "gauge", "histogram")

#: component.snake_case: lowercase dotted path, >= 2 segments
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z0-9_]+)+$")

Finding = Tuple[str, int, str]


def _allowed(line: str) -> bool:
    return ALLOW_MARK in line


def check_source(src: str, rel: str) -> List[Finding]:
    """Findings for one file's source text."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:  # a broken file is its own finding
        return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    out: List[Finding] = []

    def line_of(node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return lines[ln - 1] if 0 < ln <= len(lines) else ""

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_METHODS):
            continue
        if not node.args:
            continue                    # no positional name: not ours
        if _allowed(line_of(node)):
            continue
        arg = node.args[0]
        method = node.func.attr
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if not NAME_RE.match(arg.value):
                out.append((rel, node.lineno,
                            f".{method}({arg.value!r}): metric names "
                            "must be component.snake_case (lowercase "
                            "dotted path, e.g. 'serving.ttft_s')"))
        elif isinstance(arg, ast.JoinedStr):
            out.append((rel, node.lineno,
                        f".{method}(f\"...\"): f-string metric name — "
                        "a runtime-built name mints unbounded series; "
                        "use a literal name + labels"))
        else:
            out.append((rel, node.lineno,
                        f".{method}(<{type(arg).__name__}>): dynamic "
                        "metric name — use a string literal (labels "
                        "carry the variable dimensions)"))
    return out


def check_tree(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for entry in SCAN:
        p = root / entry
        files = sorted(p.rglob("*.py")) if p.is_dir() \
            else ([p] if p.exists() else [])
        for f in files:
            rel = str(f.relative_to(root))
            findings.extend(check_source(f.read_text(), rel))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} metric-name finding(s); use literal "
              f"component.snake_case names (labels for variable "
              f"dimensions) or mark the line with '# {ALLOW_MARK}'",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
