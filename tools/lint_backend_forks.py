#!/usr/bin/env python
"""Static check: backend/platform sniffs belong in ``compat.py`` only.

The repo-wide convention (PR 1, documented on ``compat.backend_is_tpu``
and ``models.decoding.generate``): every Pallas-vs-XLA fork keys off
``compat.backend_is_tpu()`` — ONE trace-time contract instead of ad-hoc
``jax.default_backend()`` / ``device.platform`` sniffs scattered per
call site, which fork compiled programs on attributes jit erases and
drift out of agreement with each other.

This linter walks the AST (so docstrings and comments never
false-positive) and flags, outside ``compat.py``:

  * any call to ``*.default_backend(...)``
  * any read of a ``.platform`` attribute (``jax.devices()[0].platform``
    and friends)

Scope: the ``distkeras_tpu`` package, ``bench.py``, ``examples/`` and
``tools/``. A justified exception carries the marker comment
``lint: allow-backend-sniff`` on the offending line.

Exit status 1 when findings exist (wired into tier-1 as
``tests/test_lint_backend_forks.py``).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

ALLOW_MARK = "lint: allow-backend-sniff"

#: paths scanned, relative to the repo root
SCAN = ("distkeras_tpu", "bench.py", "examples", "tools")

#: the one module allowed to sniff
EXEMPT = ("compat.py",)

Finding = Tuple[str, int, str]


def _allowed(line: str) -> bool:
    return ALLOW_MARK in line


def check_source(src: str, rel: str) -> List[Finding]:
    """Findings for one file's source text."""
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:  # a broken file is its own finding
        return [(rel, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    out: List[Finding] = []

    def line_of(node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return lines[ln - 1] if 0 < ln <= len(lines) else ""

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "default_backend":
            if not _allowed(line_of(node)):
                out.append((rel, node.lineno,
                            "direct jax.default_backend() call — use "
                            "compat.backend_is_tpu()"))
        elif isinstance(node, ast.Attribute) \
                and node.attr == "platform" \
                and isinstance(node.ctx, ast.Load):
            # stdlib look-alikes are not device sniffs: ``sys.platform``
            # and the ``platform`` module's own attributes
            if isinstance(node.value, ast.Name) \
                    and node.value.id in ("sys", "platform"):
                continue
            if not _allowed(line_of(node)):
                out.append((rel, node.lineno,
                            ".platform device sniff — use "
                            "compat.backend_is_tpu()"))
    return out


def check_tree(root: Path) -> List[Finding]:
    findings: List[Finding] = []
    for entry in SCAN:
        p = root / entry
        files = sorted(p.rglob("*.py")) if p.is_dir() \
            else ([p] if p.exists() else [])
        for f in files:
            if f.name in EXEMPT:
                continue
            rel = str(f.relative_to(root))
            findings.extend(check_source(f.read_text(), rel))
    return findings


def main(argv=None) -> int:
    root = Path(argv[0]) if argv else Path(__file__).resolve().parent.parent
    findings = check_tree(root)
    for rel, lineno, msg in findings:
        print(f"{rel}:{lineno}: {msg}")
    if findings:
        print(f"{len(findings)} backend-sniff finding(s); route through "
              f"compat.backend_is_tpu() or mark the line with "
              f"'# {ALLOW_MARK}'", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
