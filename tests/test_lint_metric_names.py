"""tools/lint_metric_names.py wired into tier-1: registry metric names
in library code must be literal ``component.snake_case`` strings — no
f-strings or runtime-built names (the series-cardinality bomb no
``max_series`` cap can fold) — and the checker itself must detect the
patterns it claims to."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint_metric_names import (ALLOW_MARK, NAME_RE,  # noqa: E402
                               check_source, check_tree)


def test_repo_library_code_uses_literal_metric_names():
    findings = check_tree(REPO)
    assert not findings, "\n".join(
        f"{f}:{ln}: {msg}" for f, ln, msg in findings)


def test_name_regex_accepts_the_repo_conventions():
    for good in ("serving.ttft_s", "slo.burn_rate", "faults.triggered",
                 "device.bytes_in_use", "a.b.c_d2"):
        assert NAME_RE.match(good), good
    for bad in ("serving", "Serving.ttft", "serving.TTFT", "serving.",
                ".ttft", "serving..x", "serving.ttft-s", "1x.y"):
        assert not NAME_RE.match(bad), bad


def test_checker_flags_fstring_and_dynamic_names():
    src = (
        "r.counter(f'serving.{kind}')\n"
        "r.gauge(name)\n"
        "r.histogram('prefix.' + kind)\n"
        "r.counter('serving.ok_name')\n"      # literal + well-formed
    )
    findings = check_source(src, "x.py")
    assert [ln for _, ln, _ in findings] == [1, 2, 3]
    assert "f-string" in findings[0][2]
    assert "dynamic" in findings[1][2]


def test_checker_flags_malformed_literals():
    src = ("r.counter('NoDots')\n"
           "r.gauge('Bad.Case')\n"
           "r.histogram('fine.name')\n")
    findings = check_source(src, "x.py")
    assert [ln for _, ln, _ in findings] == [1, 2]
    assert "component.snake_case" in findings[0][2]


def test_checker_skips_marked_lines_and_non_metric_calls():
    src = (
        f"r.histogram(f'{{name}}.phase_s')  # {ALLOW_MARK}\n"
        "collections.Counter(x)\n"            # not a metric method
        "r.counter()\n"                       # no positional name
        "r.describe(name)\n"                  # different method
        '"""r.counter(f"doc.{x}") in a docstring is prose."""\n'
    )
    assert check_source(src, "x.py") == []


def test_checker_reports_syntax_errors_as_findings():
    findings = check_source("def broken(:\n", "x.py")
    assert len(findings) == 1 and "syntax" in findings[0][2]
