"""int4 KV cache (quantized-decode PR): the 4-bit rung of the cache
dtype ladder. Unpacked request/slab caches share the int8 read paths
byte-for-byte (one int8 byte per entry, values in [-7, 7], the same
``q * scale`` dequant contract); the PAGED pool stores two positions
per byte (``pack_int4``'s half-split along the page position axis) and
the Pallas paged-attention kernel unpacks in-kernel. The oracle
discipline matches the int8 suite: kernel vs the ``_gather_pages``
reference in interpret mode across GQA/window/scrambled-page/W > 1
cases, pack/unpack bitwise roundtrips, RMW nibble isolation, and
end-to-end token/byte identity through the serving engine."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.models.decoding import (_cache_write_pages,
                                           _gather_pages, _quantize_kv,
                                           _use_paged_kernel, generate,
                                           init_cache, pack_int4,
                                           unpack_int4)
from distkeras_tpu.ops.attention import NEG_INF
from distkeras_tpu.ops.paged_attention import (page_aligned,
                                               page_alignment,
                                               paged_decode_attention)
from distkeras_tpu.serving import ServingEngine
from distkeras_tpu.serving.kv_pool import PagedKVPool


def _pool4(rs, n_pages, hkv, page_len, d):
    """A random PACKED int4 page pool (the PagedKVPool device layout)."""
    k = jnp.asarray(rs.randn(n_pages, hkv, page_len, d), jnp.float32)
    v = jnp.asarray(rs.randn(n_pages, hkv, page_len, d), jnp.float32)
    qk, ks = _quantize_kv(k, 4)
    qv, vs = _quantize_kv(v, 4)
    return {"k": pack_int4(qk), "v": pack_int4(qv),
            "k_scale": ks, "v_scale": vs,
            "q4": jnp.zeros((1, 1, 1, 1), jnp.int8)}


def _reference(q, kv, table, t, scale, window=None):
    """The gather-path readout (``test_paged_kernel._reference``, int4
    edition — ``_gather_pages`` unpacks, then the shared dequant)."""
    view = _gather_pages(kv, jnp.asarray(table))
    k = view["k"].astype(jnp.float32) * view["k_scale"][..., None]
    v = view["v"].astype(jnp.float32) * view["v_scale"][..., None]
    L = k.shape[2]
    w_len = q.shape[1]
    qg = q.astype(jnp.float32) * scale
    s = jnp.einsum("bqhgd,bhkd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32)
    pos = t[:, None] + jnp.arange(w_len)
    valid = jnp.arange(L)[None, None, :] <= pos[:, :, None]
    if window is not None:
        valid &= jnp.arange(L)[None, None, :] > (pos - window)[:, :, None]
    s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhgqk,bhkd->bqhgd", w, v,
                      preferred_element_type=jnp.float32)


#: scrambled physical placement with sentinel entries, page_len=64
#: edition of the int8 suite's TABLE/T
TABLE = np.array([[7, 2, 9, 10], [0, 5, 10, 10], [3, 1, 4, 6]],
                 np.int32)
T = np.array([100, 70, 130], np.int32)


# --- nibble packing ---------------------------------------------------------


def test_pack_unpack_roundtrip_bitwise():
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randint(-7, 8, size=(3, 2, 64, 16)), jnp.int8)
    np.testing.assert_array_equal(np.asarray(unpack_int4(pack_int4(q))),
                                  np.asarray(q))


def test_quantize_kv_int4_grid():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 3, 16), jnp.float32)
    q, s = _quantize_kv(x, 4)
    qn = np.asarray(q)
    assert qn.min() >= -7 and qn.max() <= 7
    # absmax entries hit the grid edge exactly
    err = np.abs(np.asarray(x) - qn * np.asarray(s)[..., None])
    assert err.max() <= np.asarray(s).max() / 2 + 1e-7
    # zero vectors stay exactly zero (zero-safe scale)
    q0, s0 = _quantize_kv(jnp.zeros((2, 16)), 4)
    assert not np.asarray(q0).any() and not np.asarray(s0).any()


# --- kernel vs gather oracle ------------------------------------------------


@pytest.mark.parametrize("g", [1, 4])
@pytest.mark.parametrize("w_len", [1, 3])
def test_int4_kernel_matches_gather_reference(g, w_len):
    rs = np.random.RandomState(2)
    kv = _pool4(rs, 10, 2, 64, 16)
    q = jnp.asarray(rs.randn(3, w_len, 2, g, 16), jnp.float32)
    scale = 16 ** -0.5
    out = paged_decode_attention(
        q, kv["k"], kv["v"], T, TABLE, scale=scale,
        k_scale=kv["k_scale"], v_scale=kv["v_scale"], interpret=True)
    ref = _reference(q, kv, TABLE, T, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)
    # bitwise at the comparison dtype: the two paths agree exactly
    # once both land in the serving compute dtype (bf16)
    np.testing.assert_array_equal(
        np.asarray(out.astype(jnp.bfloat16).astype(jnp.float32)),
        np.asarray(ref.astype(jnp.bfloat16).astype(jnp.float32)))


def test_int4_kernel_window_masking():
    rs = np.random.RandomState(3)
    kv = _pool4(rs, 10, 2, 64, 16)
    q = jnp.asarray(rs.randn(3, 2, 2, 2, 16), jnp.float32)
    scale = 16 ** -0.5
    out = paged_decode_attention(
        q, kv["k"], kv["v"], T, TABLE, scale=scale, window=40,
        k_scale=kv["k_scale"], v_scale=kv["v_scale"], interpret=True)
    ref = _reference(q, kv, TABLE, T, scale, window=40)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)


def test_int4_shape_mismatch_rejected():
    """A packed payload whose scale plane is not exactly 2x its rows is
    a layout bug, not a silently-different page_len."""
    rs = np.random.RandomState(4)
    kv = _pool4(rs, 4, 2, 64, 16)
    with pytest.raises(ValueError, match="int4 payload rows"):
        paged_decode_attention(
            jnp.asarray(rs.randn(1, 1, 2, 1, 16), jnp.float32),
            kv["k"], kv["v"], np.array([3]), np.array([[0]]),
            k_scale=kv["k_scale"][:, :, :48],
            v_scale=kv["v_scale"][:, :, :48], interpret=True)


# --- RMW page writes --------------------------------------------------------


def test_int4_rmw_write_and_nibble_isolation():
    """One-position writes into the packed plane: the written position
    dequantizes to its own 4-bit grid value, and the OTHER position
    sharing the byte row keeps its exact bits."""
    rs = np.random.RandomState(5)
    kv = _pool4(rs, 6, 2, 64, 16)
    table = np.array([[4, 1, 3]], np.int32)
    for t_pos in (0, 31, 32, 63, 64, 70, 129):
        kh = jnp.asarray(rs.randn(1, 1, 2, 16), jnp.float32)
        vh = jnp.asarray(rs.randn(1, 1, 2, 16), jnp.float32)
        buddy = t_pos + 32 if (t_pos % 64) < 32 else t_pos - 32
        view0 = _gather_pages(kv, jnp.asarray(table))
        before = np.asarray(view0["k"][0, :, buddy])
        kv = _cache_write_pages(kv, kh, vh, jnp.asarray([t_pos]),
                                jnp.asarray(table), 64)
        view = _gather_pages(kv, jnp.asarray(table))
        got = (view["k"].astype(jnp.float32)
               * view["k_scale"][..., None])[0, :, t_pos]
        qk, sk = _quantize_kv(kh.transpose(0, 2, 1, 3), 4)
        want = (qk.astype(jnp.float32) * sk[..., None])[0, :, 0]
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(view["k"][0, :, buddy]), before)


def test_int4_sentinel_write_drops():
    """An out-of-range position (the free/prefilling sentinel) must not
    corrupt any page — the RMW's merged garbage is scatter-dropped."""
    rs = np.random.RandomState(6)
    kv = _pool4(rs, 4, 2, 64, 16)
    table = np.array([[2, 4]], np.int32)     # 4 is the sentinel (>= N)
    before = jax.tree_util.tree_map(np.asarray, kv)
    kv2 = _cache_write_pages(
        kv, jnp.asarray(rs.randn(1, 1, 2, 16), jnp.float32),
        jnp.asarray(rs.randn(1, 1, 2, 16), jnp.float32),
        jnp.asarray([500]), jnp.asarray(table), 64)
    for key in ("k", "v", "k_scale", "v_scale"):
        np.testing.assert_array_equal(np.asarray(kv2[key]), before[key])


# --- pool staging transfers -------------------------------------------------


def _int4_lm(pattern_lm):
    from distkeras_tpu.models.decoding import _resolve_head_dims
    _resolve_head_dims(pattern_lm.module, pattern_lm.params)
    return pattern_lm


def test_pool_insert_then_load_prefix_roundtrip(pattern_lm):
    """Staging (unpacked) -> pool (packed) -> staging: the pack/unpack
    pair through ``insert_pages``/``load_prefix`` is bitwise."""
    m = _int4_lm(pattern_lm)
    pool = PagedKVPool(m.module, num_slots=1, max_len=32, page_len=8,
                       dtype="int4")
    rs = np.random.RandomState(7)
    staging = pool.make_request_cache()
    staging = [
        kv if kv is None else {
            key: (jnp.asarray(
                rs.randint(-7, 8, a.shape), jnp.int8)
                if key in ("k", "v") else
                (a if key == "q4" else
                 jnp.asarray(rs.rand(*a.shape), jnp.float32)))
            for key, a in kv.items()}
        for kv in staging]
    for lp in range(pool.pages_per_slot):
        pool.assign(0, lp, pool.alloc_page())
    pool.insert_pages(staging, 0, 0, 32)
    loaded = pool.load_prefix(pool.make_request_cache(),
                              pool.slot_pages(0), 32)
    for st, ld in zip(staging, loaded):
        if st is None:
            continue
        for key in ("k", "v", "k_scale", "v_scale"):
            np.testing.assert_array_equal(np.asarray(ld[key]),
                                          np.asarray(st[key]))


def test_int4_pool_requires_even_page_len(pattern_lm):
    m = _int4_lm(pattern_lm)
    with pytest.raises(ValueError, match="even"):
        PagedKVPool(m.module, num_slots=1, max_len=10, page_len=5,
                    dtype="int4")


def test_int4_offload_restore_bitwise(pattern_lm):
    """The host offload tier moves PACKED bytes — swap-out/swap-in of
    an int4 page is byte-identical, like every other dtype."""
    m = _int4_lm(pattern_lm)
    pool = PagedKVPool(m.module, num_slots=1, max_len=16, page_len=8,
                       dtype="int4", host_pages=2)
    pid = pool.alloc_page()
    rs = np.random.RandomState(8)
    pool.cache = [
        kv if kv is None else {
            key: (a if key == "q4" else
                  jnp.asarray(rs.randint(-100, 100, a.shape))
                  .astype(a.dtype))
            for key, a in kv.items()}
        for kv in pool.cache]
    before = [None if kv is None else
              {key: np.asarray(a[pid]) for key, a in kv.items()
               if key != "q4"}
              for kv in pool.cache]
    hids = pool.offload_pages([pid])
    assert hids is not None
    pool.cache = jax.tree_util.tree_map(jnp.zeros_like, pool.cache)
    pool.restore_pages(hids, [pid])
    for kv, want in zip(pool.cache, before):
        if kv is None:
            continue
        for key, arr in want.items():
            np.testing.assert_array_equal(np.asarray(kv[key][pid]), arr)


# --- init_cache / slab ladder -----------------------------------------------


def test_init_cache_int4_structure(pattern_lm):
    m = _int4_lm(pattern_lm)
    cache = init_cache(m.module, 2, 16, "int4")
    kvs = [kv for kv in cache if kv is not None]
    assert kvs
    for kv in kvs:
        assert kv["k"].dtype == jnp.int8          # unpacked staging/slab
        assert "q4" in kv
        assert kv["k"].shape[2] == 16
        assert kv["k_scale"].shape == kv["k"].shape[:3]


def test_generate_int4_cache_token_identical(pattern_lm):
    """The slab int4 cache through generate(): the memorized pattern's
    argmax margins dwarf 4-bit cache noise, so greedy continuation is
    token-identical to the float cache."""
    m = pattern_lm
    p = np.array([3, 1, 4, 1, 5, 9])
    np.testing.assert_array_equal(
        generate(m, p[None], 6, cache_dtype="int4")[0],
        generate(m, p[None], 6)[0])


# --- fallback decision / dtype matrix ---------------------------------------


def test_use_paged_kernel_dtype_matrix():
    """The gather-fallback decision across the dtype ladder: forced-on
    still refuses a page_len the kernel cannot tile for THAT dtype."""
    f32 = {"k": 0, "v": 0}
    i8 = {"k": 0, "v": 0, "k_scale": 0, "v_scale": 0}
    i4 = dict(i8, q4=0)
    assert _use_paged_kernel(f32, 8, True)
    assert not _use_paged_kernel(f32, 4, True)
    assert _use_paged_kernel(i8, 32, True)
    assert not _use_paged_kernel(i8, 16, True)
    assert _use_paged_kernel(i4, 64, True)
    assert not _use_paged_kernel(i4, 32, True)   # %32 is int8-only
    assert not _use_paged_kernel(i4, 64, False)  # forced off wins
    assert not _use_paged_kernel(f32, 8, False)


def test_page_alignment_full_matrix():
    assert page_alignment(False) == 8
    assert page_alignment("f32") == page_alignment("bfloat16") == 8
    assert page_alignment(True) == page_alignment("int8") == 32
    assert page_alignment(8) == 32
    assert page_alignment(4) == page_alignment("int4") == 64
    assert page_aligned(16, "bf16") and not page_aligned(12, "bf16")
    assert page_aligned(128, "int4") and not page_aligned(96, "int4")
    with pytest.raises(ValueError, match="unknown cache quantization"):
        page_alignment("int2")


# --- end-to-end through the serving engine ----------------------------------


PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


def test_engine_int4_greedy_token_identical(pattern_lm):
    """cache_dtype="int4" through the paged engine (gather path at
    page_len=8): greedy output token-identical to generate()."""
    eng = ServingEngine(pattern_lm, num_slots=2, max_len=32, page_len=8,
                        cache_dtype="int4")
    r0 = eng.submit(PATTERN[:4], 7)
    r1 = eng.submit(PATTERN[:6], 5)
    out = eng.run(max_steps=500)
    np.testing.assert_array_equal(
        out[r0], generate(pattern_lm, PATTERN[None, :4], 7)[0])
    np.testing.assert_array_equal(
        out[r1], generate(pattern_lm, PATTERN[None, :6], 5)[0])


def test_engine_int4_kernel_sampled_matches_gather(pattern_lm):
    """page_len=64 int4 pool: the Pallas kernel (interpret mode) and
    the gather fallback draw byte-identical sampled streams — the
    serving-level bitwise oracle for the packed in-kernel dequant."""
    def drive(kernel):
        eng = ServingEngine(pattern_lm, num_slots=2, max_len=128,
                            page_len=64, cache_dtype="int4",
                            decode_kernel=kernel)
        rid = eng.submit(PATTERN[:4], 8, temperature=0.9, top_p=0.95,
                         seed=7)
        return eng.run(max_steps=500)[rid]

    np.testing.assert_array_equal(drive("paged"), drive("off"))
