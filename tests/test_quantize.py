"""Weight-only int8 quantization: accuracy, size, save/load integration.

Capability ADD (the reference ships full-precision Keras weight lists;
``distkeras/utils.py :: serialize_keras_model``)."""

import os

import jax
import numpy as np
import pytest

from distkeras_tpu.models import (Dense, Model, Sequential, load_model,
                                  quantize_model, save_model, zoo)
from distkeras_tpu.models.quantize import (dequantize_model,
                                           dequantize_params,
                                           quantize_params)


def trained_mlp(seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(512, 16).astype(np.float32)
    y = np.argmax(X @ rs.randn(16, 4), axis=1)
    m = Model.build(Sequential([Dense(64, activation="relu"), Dense(4)]),
                    (16,), seed=seed)
    m.fit(X, y, optimizer="adam", learning_rate=1e-2, epochs=20,
          batch_size=64,
          loss="sparse_categorical_crossentropy_from_logits")
    return m, X, y


def test_quantize_roundtrip_error_small():
    m, X, _ = trained_mlp()
    qp, scales = quantize_params(m.params)
    back = jax.device_get(dequantize_params(qp, scales))
    for a, b in zip(jax.tree_util.tree_leaves(m.params),
                    jax.tree_util.tree_leaves(back)):
        a = np.asarray(a)
        if a.ndim >= 2:  # quantized leaves: error bounded by scale/2
            step = np.abs(a).max(axis=tuple(range(a.ndim - 1)),
                                 keepdims=True) / 127.0
            assert (np.abs(a - b) <= 0.5 * step + 1e-8).all()
        else:            # biases untouched
            np.testing.assert_array_equal(a, b)


def test_quantized_model_predictions_close():
    m, X, y = trained_mlp()
    qm = quantize_model(m)
    ref = m.predict(X)
    out = qm.predict(X)
    # same argmax decisions almost everywhere
    agree = (ref.argmax(-1) == out.argmax(-1)).mean()
    assert agree > 0.99, agree
    # int8 storage is ~4x smaller than the f32 kernels
    f32_bytes = sum(np.asarray(l).nbytes
                    for l in jax.tree_util.tree_leaves(m.params))
    assert qm.num_bytes() < 0.45 * f32_bytes  # tiny model: bias+scale overhead
    # and back to full precision
    m2 = dequantize_model(qm)
    np.testing.assert_allclose(m2.predict(X), out, atol=1e-5)


def test_save_load_quantized(tmp_path):
    m, X, _ = trained_mlp(seed=1)
    p_f32 = str(tmp_path / "full")
    p_q = str(tmp_path / "quant")
    save_model(m, p_f32)
    save_model(m, p_q, quantize=True)

    # the ~4x shrink shows at realistic kernel sizes (tiny models are
    # dominated by per-entry npz container overhead)
    big = Model.build(Sequential([Dense(512, activation="relu"),
                                  Dense(512), Dense(4)]), (256,), seed=0)
    save_model(big, str(tmp_path / "big"))
    save_model(big, str(tmp_path / "bigq"), quantize=True)
    assert os.path.getsize(str(tmp_path / "bigq.npz")) < \
        0.35 * os.path.getsize(str(tmp_path / "big.npz"))

    loaded = load_model(p_q)                      # transparent f32 restore
    assert (loaded.predict(X).argmax(-1) ==
            m.predict(X).argmax(-1)).mean() > 0.99

    qm = load_model(p_q, keep_quantized=True)     # int8 serving handle
    np.testing.assert_allclose(qm.predict(X), loaded.predict(X), atol=1e-5)

    with pytest.raises(ValueError, match="quantize=True"):
        load_model(p_f32, keep_quantized=True)


def test_quantize_policy_is_name_based():
    """Only the big matmul kernels/embeddings go int8 — MoE's stacked
    [E, ...] bias MATRICES, norm params, and the router gate stay f32."""
    from distkeras_tpu.models.moe import MoE
    m = Model.build(
        Sequential([MoE(num_experts=4, hidden_dim=8, top_k=2)]), (8,),
        seed=0)
    qp, scales = quantize_params(m.params)
    moe_p = qp[0]
    moe_s = scales[0]
    assert moe_p["w1"].dtype == np.int8 and moe_s["w1"] is not None
    assert moe_p["w2"].dtype == np.int8 and moe_s["w2"] is not None
    # 2-D but accuracy-critical: untouched
    for name in ("b1", "b2", "gate"):
        assert moe_p[name].dtype == np.float32, name
        assert moe_s[name] is None, name


def test_quantize_resnet_smoke():
    m = Model.build(zoo.resnet18_thin(num_classes=10, width=8),
                    (32, 32, 3), seed=0)
    qm = quantize_model(m)
    x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
    ref, out = m.predict(x), qm.predict(x)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=0.1)  # bn-dominated net

def test_save_load_quantized_root_level_params(tmp_path):
    """A module whose params live at the pytree ROOT (bare layer, no
    Sequential nesting): the quantize name check must strip the ``params:``
    store prefix, and a root param literally named ``scale`` (GroupNorm's)
    must survive the round-trip — scales live in their own ``scale:``
    namespace, so no name can collide with them."""
    from distkeras_tpu.models.layers import GroupNorm

    rs = np.random.RandomState(0)
    X = rs.randn(8, 64).astype(np.float32)

    dense = Model.build(Dense(32), (64,), seed=0)
    p = str(tmp_path / "bare_dense")
    save_model(dense, p, quantize=True)
    stored = np.load(p + ".npz")
    assert "scale:params:kernel" in stored.files, (
        "root-level kernel should be quantized (store-prefix stripped "
        "before the name check)")
    assert stored["params:kernel"].dtype == np.int8
    loaded = load_model(p)
    np.testing.assert_allclose(loaded.predict(X), dense.predict(X),
                               atol=0.05)

    norm = Model.build(GroupNorm(groups=4), (64,), seed=0)
    pn = str(tmp_path / "bare_norm")
    save_model(norm, pn, quantize=True)
    stored = np.load(pn + ".npz")
    # 'scale' is accuracy-critical: never quantized, and its key
    # ``params:scale`` must not be mistaken for a quantization scale
    assert "params:scale" in stored.files
    assert stored["params:scale"].dtype == np.float32
    loaded = load_model(pn)
    np.testing.assert_allclose(loaded.predict(X), norm.predict(X),
                               atol=1e-6)


def test_load_legacy_scale_suffix_quantized_file(tmp_path):
    """Round-1 quantized files stored scales as '<key>:scale' suffixes;
    they must still dequantize (not silently load int8 codes as floats)."""
    m, X, _ = trained_mlp(seed=2)
    p = str(tmp_path / "legacy")
    save_model(m, p, quantize=True)
    stored = dict(np.load(p + ".npz").items())
    legacy = {}
    for k, v in stored.items():
        if k.startswith("scale:"):
            legacy[k[len("scale:"):] + ":scale"] = v
        else:
            legacy[k] = v
    np.savez(p + ".npz", **legacy)

    loaded = load_model(p)
    assert (loaded.predict(X).argmax(-1) ==
            m.predict(X).argmax(-1)).mean() > 0.99
    # int8 serving handle reads legacy scales too
    qm = load_model(p, keep_quantized=True)
    np.testing.assert_allclose(qm.predict(X), loaded.predict(X), atol=1e-5)
