"""tools/lint_report_series.py wired into tier-1: every registry series
name the scenario report (``obs.report.REPORT_SERIES``) reads must
exist in a live instrument surface — a metric rename fails HERE instead
of silently flatlining a report panel."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint_report_series import check, live_series, main  # noqa: E402


def test_every_report_series_exists_live():
    findings = check()
    assert not findings, "\n".join(msg for _, msg in findings)
    assert main() == 0


def test_live_surface_covers_serving_and_slo():
    live = live_series()
    assert "serving.ttft_s" in live
    assert "slo.burn_rate" in live


def test_renamed_metric_is_flagged():
    findings = check(["serving.ttft_s", "serving.does_not_exist_s"])
    assert [name for name, _ in findings] == ["serving.does_not_exist_s"]
