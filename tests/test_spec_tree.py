"""Tree speculation (tree-speculation PR): the tree-masked verify
window, the in-program acceptance walk + accepted-path commit, the
tree draft sources (per-divergence branching n-gram, beam-style draft
model), the adaptive per-stream depth/width controller, and the
Pallas kernel's ancestor-mask path — pinned against the sequential
decode oracle and the landed linear speculation path.

The WIDTH-1 byte-identity contract (tree == linear, bit for bit) is
parametrized into the existing linear oracle suite
(``tests/test_spec_decode.py``); this file owns everything the chain
cannot express."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import (_resolve_head_dims,
                                           commit_tree_path,
                                           decode_step_slots, generate,
                                           init_cache, tree_walk,
                                           verify_step_slots)
from distkeras_tpu.serving import (DraftModel, DraftSource, NgramDraft,
                                   ServingEngine)
from distkeras_tpu.serving.speculation import (build_token_tree,
                                               tree_ancestors)

V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    """The shared session-scoped overfit-PATTERN LM (conftest pattern_lm): huge greedy argmax margins keep token-identity assertions robust; trained once per test session."""
    return pattern_lm


@pytest.fixture(scope="module")
def small_lm():
    """An untrained model for the numerical window units (no
    memorization needed — they compare against sequential decode)."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (16,), seed=4)
    _resolve_head_dims(m.module, m.params)
    return m


def _warm_cache(m, toks, hist, cap=16):
    cache = init_cache(m.module, len(hist), cap)
    for step in range(max(hist)):
        tk = np.array([toks[i, min(step, hist[i] - 1)]
                       for i in range(len(hist))], np.int32)
        tv = np.array([step if step < hist[i] else cap
                       for i in range(len(hist))], np.int32)
        _, cache = decode_step_slots(m.module, m.params, m.state, cache,
                                     jnp.asarray(tk), jnp.asarray(tv))
    return cache


# --- window units -----------------------------------------------------------


def test_tree_ancestors_units():
    parents = np.array([[-1, 0, 1, 0, -1],       # root -> 1 -> 2; root -> 3
                        [-1, 0, -1, -1, -1]], np.int32)
    depth, anc, n_nodes = tree_ancestors(parents)
    np.testing.assert_array_equal(depth[0], [0, 1, 2, 1, 0])
    np.testing.assert_array_equal(n_nodes, [4, 2])
    assert anc[0, 2, 0] and anc[0, 2, 1] and anc[0, 2, 2]
    assert not anc[0, 2, 3]                      # sibling branch invisible
    assert not anc[0, 1, 2]                      # child not ancestor
    assert not anc[0, 4].any()                   # unused node: no row
    assert not anc[0, :, 4].any()                # ...and no column
    assert anc[1, 1, 0] and anc[1, 1, 1]


def test_branched_tree_logits_match_sequential_root_paths(small_lm):
    """Every tree node's logits equal a sequential decode of its OWN
    root path — the tree mask's correctness statement."""
    m = small_lm
    rs = np.random.RandomState(0)
    toks = rs.randint(0, V, (2, 12)).astype(np.int32)
    hist = [3, 2]
    cache = _warm_cache(m, toks, hist)
    t = np.array(hist, np.int32)
    W = 4
    win = np.stack([toks[0, hist[0]:hist[0] + W],
                    toks[1, hist[1]:hist[1] + W]], 0)
    # root(0) -> 1 -> 2, root -> 3 (a depth-1 sibling with its own token)
    parents = np.tile(np.array([-1, 0, 1, 0], np.int32), (2, 1))
    win2 = win.copy()
    win2[:, 3] = (win[:, 1] + 7) % V
    depth, anc, _ = tree_ancestors(parents)
    lg, _, _ = verify_step_slots(
        m.module, m.params, m.state, cache, jnp.asarray(win2),
        jnp.asarray(t),
        tree={"depth": jnp.asarray(depth), "anc": jnp.asarray(anc)})
    lg = np.asarray(lg)

    def seq(path_cols):
        c, out = cache, None
        for j, col in enumerate(path_cols):
            out, c = decode_step_slots(
                m.module, m.params, m.state, c,
                jnp.asarray(win2[:, col]),
                jnp.asarray((t + j).astype(np.int32)))
        return np.asarray(out)

    np.testing.assert_allclose(lg[:, 2], seq([0, 1, 2]), atol=3e-5)
    np.testing.assert_allclose(lg[:, 3], seq([0, 3]), atol=3e-5)


def test_walk_and_commit_match_sequential_cache(small_lm):
    """Accepting a branch: the walk picks the child carrying the
    target's own argmax, and the committed cache equals a sequential
    decode of the accepted path on every committed position — decode
    then continues identically from either cache."""
    m = small_lm
    rs = np.random.RandomState(0)
    toks = rs.randint(0, V, (2, 12)).astype(np.int32)
    hist = [3, 2]
    cache = _warm_cache(m, toks, hist)
    t = np.array(hist, np.int32)
    lg0, _ = decode_step_slots(m.module, m.params, m.state, cache,
                               jnp.asarray(toks[:, 0]), jnp.asarray(t))
    arg0 = np.asarray(jnp.argmax(lg0, -1)).astype(np.int32)
    W = 4
    win = np.zeros((2, W), np.int32)
    win[:, 0] = toks[:, 0]
    win[:, 1] = (arg0 + 5) % V               # wrong depth-1 branch
    win[:, 2] = arg0                         # the branch the walk takes
    win[:, 3] = 1
    parents = np.tile(np.array([-1, 0, 0, 2], np.int32), (2, 1))
    depth, anc, _ = tree_ancestors(parents)
    lg, c_t, kvw = verify_step_slots(
        m.module, m.params, m.state, cache, jnp.asarray(win),
        jnp.asarray(t),
        tree={"depth": jnp.asarray(depth), "anc": jnp.asarray(anc)})
    em, ne, path, keys = tree_walk(lg, jnp.asarray(win),
                                   jnp.asarray(parents))
    assert keys is None
    em, ne, path = np.asarray(em), np.asarray(ne), np.asarray(path)
    assert (ne >= 2).all() and (path[:, 1] == 2).all()
    committed = commit_tree_path(c_t, kvw, jnp.asarray(path),
                                 jnp.asarray(t), jnp.asarray(ne))
    c_seq = cache
    _, c_seq = decode_step_slots(m.module, m.params, m.state, c_seq,
                                 jnp.asarray(win[:, 0]), jnp.asarray(t))
    _, c_seq = decode_step_slots(
        m.module, m.params, m.state, c_seq, jnp.asarray(arg0),
        jnp.asarray((t + 1).astype(np.int32)))
    for a, b in zip(c_seq, committed):
        if a is None:
            continue
        for kk in a:
            av, bv = np.asarray(a[kk]), np.asarray(b[kk])
            for s in range(2):
                hi = t[s] + 2
                np.testing.assert_allclose(av[s, :, :hi], bv[s, :, :hi],
                                           atol=3e-5)
    bonus = em[np.arange(2), ne - 1].astype(np.int32)
    nxt, _ = decode_step_slots(m.module, m.params, m.state, committed,
                               jnp.asarray(bonus),
                               jnp.asarray((t + ne).astype(np.int32)))
    ref, _ = decode_step_slots(m.module, m.params, m.state, c_seq,
                               jnp.asarray(bonus),
                               jnp.asarray((t + 2).astype(np.int32)))
    np.testing.assert_allclose(np.asarray(nxt), np.asarray(ref),
                               atol=3e-5)


def test_paged_kernel_tree_mask_matches_gather_reference():
    """The Pallas kernel's ancestor-mask operand (interpret mode)
    against the gather-path tree mask on scrambled page tables with a
    sentinel entry."""
    import distkeras_tpu.models.decoding as dec
    from distkeras_tpu.ops.attention import NEG_INF
    from distkeras_tpu.ops.paged_attention import paged_decode_attention
    rs = np.random.RandomState(1)
    Spg, Wq, Hkv, G, D, page_len, P, N = 2, 4, 2, 2, 8, 8, 3, 7
    q = rs.randn(Spg, Wq, Hkv, G, D).astype(np.float32)
    kp = rs.randn(N, Hkv, page_len, D).astype(np.float32)
    vp = rs.randn(N, Hkv, page_len, D).astype(np.float32)
    t = np.array([5, 9], np.int32)
    table = np.array([[2, 0, 7], [1, 4, 6]], np.int32)   # 7 = sentinel
    parents = np.tile(np.array([-1, 0, 0, 2], np.int32), (Spg, 1))
    depth, anc, _ = tree_ancestors(parents)
    o_kernel = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(t), jnp.asarray(table), anc=jnp.asarray(anc),
        interpret=True)
    kv_view = dec._gather_pages(
        {"k": jnp.asarray(kp), "v": jnp.asarray(vp)}, jnp.asarray(table))
    qg = (q.astype(np.float32) * (D ** -0.5)).reshape(
        Spg, Wq, Hkv, G, D)
    s = dec._decode_scores(jnp.asarray(qg), kv_view)
    valid = dec._window_valid_mask(
        jnp.asarray(t), Wq, P * page_len,
        {"depth": jnp.asarray(depth), "anc": jnp.asarray(anc)}, None)
    s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)
    o_ref = dec._decode_mix(jax.nn.softmax(s, axis=-1), kv_view)
    np.testing.assert_allclose(np.asarray(o_kernel), np.asarray(o_ref),
                               atol=2e-5, rtol=2e-5)


# --- tree draft sources -----------------------------------------------------


def test_ngram_continuations_surface_distinct_followers():
    d = NgramDraft(max_ngram=3, min_ngram=1)
    # suffix [1, 2] continued by 9 (older) and 7 (most recent)
    ctx = np.array([5, 1, 2, 9, 4, 1, 2, 7, 3, 1, 2], np.int32)
    assert d.continuations(ctx, 2) == [7, 9]
    assert d.continuations(ctx, 1) == [7]
    # nothing re-occurs
    assert d.continuations(np.array([1, 2, 3], np.int32), 2) == []


def test_ngram_grow_branches_at_divergence_points():
    """A context whose suffix has two historical continuations must
    produce a tree with BOTH branches — and the primary chain must be
    the linear draft's exact bet."""
    d = NgramDraft(max_ngram=3, min_ngram=1)
    head = [11, 7, 19]
    ctx = np.array(head + [2] + head + [8] + head, np.int32)
    W = 8
    toks = np.zeros(W, np.int32)
    parents = np.full(W, -1, np.int32)
    used = d._grow(ctx, toks, parents, depth=3, width=2, max_nodes=6)
    assert used >= 4
    # primary chain starts with lookup()'s choice
    chain = d.lookup(ctx, 3)
    assert toks[1] == chain[0]
    # both historical tails appear as children of SOME node
    roots = [toks[j] for j in range(1, used + 1) if parents[j] == 0]
    assert set(roots) == {2, 8}
    # topological parent order
    assert all(parents[j] < j for j in range(1, used + 1))


def test_build_token_tree_merges_prefixes_and_caps_budget():
    toks = np.zeros(8, np.int32)
    parents = np.full(8, -1, np.int32)
    chains = [np.array([5, 6, 7]), np.array([5, 9]), np.array([5, 6, 8])]
    used = build_token_tree(chains, toks, parents, max_nodes=7)
    # shared prefix [5] and [5, 6] hash-cons: 5,6,7,9,8 -> 5 nodes
    assert used == 5
    assert (parents[1:used + 1] < np.arange(1, used + 1)).all()
    # budget cap truncates later chains first
    toks2 = np.zeros(8, np.int32)
    parents2 = np.full(8, -1, np.int32)
    assert build_token_tree(chains, toks2, parents2, max_nodes=3) == 3
    np.testing.assert_array_equal(toks2[1:4], [5, 6, 7])


# --- engine oracles ---------------------------------------------------------


def test_tree_width2_ngram_matches_generate_paged(memorized_lm):
    """Branching n-gram trees on the paged engine: greedy outputs
    token-identical to generate(), speculation fired, and the tree
    metrics/tracer surfaces carry width/path data."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=3, max_len=48, page_len=4,
                        draft=NgramDraft(), spec_k=3, spec_tree=True,
                        spec_width=2)
    prompts = [np.tile(PATTERN, 2)[:10], np.tile(PATTERN, 2)[:14],
               PATTERN[:6]]
    budgets = [12, 9, 14]
    rids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    out = eng.run(max_steps=800)
    for i, rid in enumerate(rids):
        ref = generate(m, prompts[i][None], max_new_tokens=budgets[i],
                       temperature=0.0)
        np.testing.assert_array_equal(out[rid], ref[0])
    s = eng.metrics.summary()["speculation"]
    assert s["accepted"] > 0
    assert s["tree_width"] is not None and s["tree_width"]["p50"] >= 1
    assert s["accepted_path_len"] is not None
    tl = [t for t in eng.tracer.timelines() if t.rid == rids[0]][0]
    ev = [e for e in tl.events if e["name"] == "spec_verify"]
    assert ev and any("tree_width" in e for e in ev)
    assert any(e.get("accepted_path_len", 0) >= 1 for e in ev)


def test_tree_beam_draft_model_matches_generate(memorized_lm):
    """Beam-style DraftModel trees (greedy chain + top-width side
    branches): the perfect-drafter limit keeps token identity and
    near-1 acceptance."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=48, page_len=4,
                        draft=DraftModel(m, page_len=4), spec_k=3,
                        spec_tree=True, spec_width=2)
    r0 = eng.submit(np.tile(PATTERN, 2)[:10], 12)
    out = eng.run(max_steps=800)
    np.testing.assert_array_equal(
        out[r0], generate(m, np.tile(PATTERN, 2)[None, :10], 12,
                          temperature=0.0)[0])
    assert eng.metrics.summary()["acceptance_rate"] >= 0.4


def test_draft_model_heals_kv_after_side_branch_acceptance(memorized_lm):
    """A tree verify can accept a token the draft's greedy chain did
    NOT propose; the draft KV at that position then holds the wrong
    token's K/V. The heal pass must rewrite the divergent positions
    with the ACTUAL accepted tokens before the next draft round —
    byte-identical to feeding those tokens through the draft step
    directly (code-review regression, this PR)."""
    m = memorized_lm

    class Stub:
        num_slots, max_len = 1, 32

    class Req:
        pass

    def begun(ctx):
        d = DraftModel(m, page_len=4)
        d.bind(Stub())
        assert d.begin_slot(0, ctx)
        return d

    import jax.numpy as jnp
    prompt = PATTERN[:6]
    f = int(PATTERN[6])                  # pretend first sampled token
    draft = begun(prompt)
    req = Req()
    req.prompt = prompt
    req.generated = [f]
    toks = np.zeros((1, 7), np.int32)
    toks[0, 0] = f
    parents = np.full((1, 7), -1, np.int32)
    draft.propose_tree({0: req}, np.array([f], np.int32),
                       np.array([6], np.int32), toks, parents,
                       np.array([True]), np.array([3], np.int32),
                       np.array([2], np.int32), np.array([6], np.int32))
    g1 = draft._written[0][1][1]         # the chain token at position 7
    a = int((g1 + 3) % V)                # the "accepted side branch"
    b = int((g1 + 5) % V)
    req.generated = [f, a, b, 1]         # engine committed f,a,b; 1 pends
    draft.propose({0: req}, np.array([1], np.int32),
                  np.array([9], np.int32), np.zeros((1, 3), np.int32),
                  np.array([True]))
    # oracle: a fresh draft fed the SAME actual tokens step by step
    oracle = begun(prompt)
    fn = oracle._decode_fn(1)
    tables = oracle.pool.device_tables()
    for pos, tokv in ((6, f), (7, a), (8, b)):
        _, oracle.pool.cache = fn(
            oracle._params, oracle._state, oracle.pool.cache,
            jnp.asarray(np.array([tokv], np.int32)),
            jnp.asarray(np.array([pos], np.int32)), tables)
    for kv_d, kv_o in zip(draft.pool.cache, oracle.pool.cache):
        if kv_d is None:
            continue
        for key in kv_d:
            # both pools allocate slot 0's logical pages as physical
            # 0..7 in order, so position 7 = page 1 row 3 and position
            # 8 = page 2 row 0 — the healed rows must be byte-exact
            np.testing.assert_array_equal(np.asarray(kv_d[key])[1, :, 3],
                                          np.asarray(kv_o[key])[1, :, 3],
                                          err_msg=key)
            np.testing.assert_array_equal(np.asarray(kv_d[key])[2, :, 0],
                                          np.asarray(kv_o[key])[2, :, 0],
                                          err_msg=key)


def test_tree_sampled_stream_byte_identical_to_plain(memorized_lm):
    """The tree walk's rejection-sampling rule: a sampled stream under
    width-2 tree speculation draws the EXACT tokens plain decode
    draws (one split per emitted token, key selected by path length)."""
    m = memorized_lm

    def run(**kw):
        eng = ServingEngine(m, num_slots=2, max_len=48, **kw)
        g = eng.submit(np.tile(PATTERN, 2)[:10], 10)
        srid = eng.submit(PATTERN[:5], 9, temperature=0.9, top_p=0.95,
                          seed=7, speculate=bool(kw))
        out = eng.run(max_steps=800)
        return out[g], out[srid]

    g_plain, s_plain = run()
    g_tree, s_tree = run(draft=NgramDraft(), spec_k=3, spec_tree=True,
                         spec_width=2)
    np.testing.assert_array_equal(g_plain, g_tree)
    np.testing.assert_array_equal(s_plain, s_tree)


# --- adaptive controller / validation ---------------------------------------


class WrongDraft(DraftSource):
    """Always proposes token 0 — PATTERN never contains it."""

    def propose(self, requests, tok, t, out, active):
        out[:] = 0


def test_tree_paged_kernel_engine_matches_generate(memorized_lm):
    """decode_kernel='paged' (interpret off-TPU) drives the kernel's
    ancestor-mask path end to end — deliberately tiny (the
    interpreted kernel is ~5x slower per step on CPU)."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=8,
                        decode_kernel="paged", draft=NgramDraft(),
                        spec_k=3, spec_tree=True, spec_width=2)
    rid = eng.submit(np.tile(PATTERN, 2)[:8], 7)
    out = eng.run(max_steps=400)
    np.testing.assert_array_equal(
        out[rid], generate(m, np.tile(PATTERN, 2)[None, :8], 7,
                           temperature=0.0)[0])


def test_adaptive_controller_narrows_then_kill_switch(memorized_lm):
    """An adversarial draft: after warm-up the controller sheds width,
    and the sticky EMA floor demotes the stream to plain decode —
    output still correct."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=1, max_len=64, draft=WrongDraft(),
                        spec_k=3, spec_tree=True, spec_width=2,
                        spec_warmup=4)
    prompt = np.tile(PATTERN, 2)[:8]
    rid = eng.submit(prompt, 18)
    done = {}
    while eng.scheduler.pending:
        for r in eng.step():
            done[r.rid] = r
    req = done[rid]
    assert req.spec_disabled
    assert req.tree_width <= 2 and req.tree_depth <= 3
    np.testing.assert_array_equal(
        req.tokens, generate(m, prompt[None], 18, temperature=0.0)[0])


def test_adaptive_controller_keeps_hot_streams_wide(memorized_lm):
    """A well-predicted stream (memorized pattern, n-gram home turf)
    keeps its full tree shape through the run."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=1, max_len=64, draft=NgramDraft(),
                        spec_k=3, spec_tree=True, spec_width=2,
                        spec_warmup=2)
    rid = eng.submit(np.tile(PATTERN, 3)[:12], 16)
    done = {}
    while eng.scheduler.pending:
        for r in eng.step():
            done[r.rid] = r
    req = done[rid]
    assert not req.spec_disabled
    assert req.tree_depth == 3 and req.tree_width == 2


def test_spec_tree_knob_validation(memorized_lm):
    m = memorized_lm
    with pytest.raises(ValueError, match="spec_width"):
        ServingEngine(m, num_slots=1, max_len=32, draft=NgramDraft(),
                      spec_tree=True, spec_width=0)
    with pytest.raises(ValueError, match="spec_tree"):
        ServingEngine(m, num_slots=1, max_len=32, draft=NgramDraft(),
                      spec_width=2)
    with pytest.raises(ValueError, match="draft"):
        ServingEngine(m, num_slots=1, max_len=32, spec_tree=True)


def test_tree_preempt_resume_token_identity(memorized_lm):
    """Width-2 trees in a deliberately tiny page pool: the tree
    lookahead (worst-case node span) funds pages through preemption,
    and both streams stay token-identical through evict/resume."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        num_pages=8, prefix_cache=False,
                        draft=NgramDraft(), spec_k=3, spec_tree=True,
                        spec_width=2)
    r0 = eng.submit(np.tile(PATTERN, 2)[:5], 12)
    eng.step()
    eng.step()
    r1 = eng.submit(np.tile(PATTERN, 2)[:6], 11)
    out = eng.run(max_steps=2000)
    assert eng.metrics.requests_preempted >= 1
    np.testing.assert_array_equal(
        out[r0], generate(m, np.tile(PATTERN, 2)[None, :5], 12,
                          temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[r1], generate(m, np.tile(PATTERN, 2)[None, :6], 11,
                          temperature=0.0)[0])
