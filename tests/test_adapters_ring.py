"""Data adapters (torch/iterable ingest) + blocked ring attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distkeras_tpu.compat import shard_map
from distkeras_tpu.data import Dataset, from_iterable, from_torch
from distkeras_tpu.ops.attention import dot_product_attention
from distkeras_tpu.ops.ring_attention import ring_attention
from distkeras_tpu.parallel.mesh import make_mesh


# ---------------------------------------------------------------------------
# adapters
# ---------------------------------------------------------------------------

def test_from_iterable_pairs_and_dicts():
    rs = np.random.RandomState(0)
    rows = [(rs.randn(4), i % 3) for i in range(10)]
    ds = from_iterable(rows)
    assert ds["features"].shape == (10, 4)
    assert ds["label"].shape == (10,)

    ds2 = from_iterable([{"a": rs.randn(2), "b": 1} for _ in range(5)])
    assert ds2["a"].shape == (5, 2) and ds2["b"].shape == (5,)

    with pytest.raises(ValueError, match="empty"):
        from_iterable([])


def test_from_torch_dataset_and_loader():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader, TensorDataset

    X = torch.randn(32, 6)
    y = torch.randint(0, 3, (32,))
    tds = TensorDataset(X, y)

    ds = from_torch(tds)
    assert ds["features"].shape == (32, 6)
    np.testing.assert_allclose(ds["features"], X.numpy(), rtol=1e-6)

    loader = DataLoader(tds, batch_size=10)  # ragged final batch
    ds2 = from_torch(loader)
    assert ds2["features"].shape == (32, 6)
    np.testing.assert_allclose(ds2["label"], y.numpy())

    ds3 = from_torch(tds, limit=7)
    assert len(ds3["features"]) == 7

    # batch_size=None DataLoader yields SAMPLES, not batches
    ds4 = from_torch(DataLoader(tds, batch_size=None))
    assert ds4["features"].shape == (32, 6)
    np.testing.assert_allclose(ds4["label"], y.numpy())

    # adapters feed trainers directly
    from distkeras_tpu.models import Dense, Model, Sequential
    from distkeras_tpu.parallel import SingleTrainer
    model = Model.build(Sequential([Dense(3)]), (6,), seed=0)
    tr = SingleTrainer(model, batch_size=8, num_epoch=1,
                       loss="sparse_categorical_crossentropy_from_logits")
    tr.train(ds2)
    assert np.isfinite(tr.get_history().losses()).all()


# ---------------------------------------------------------------------------
# blocked ring attention
# ---------------------------------------------------------------------------

def ring_out(q, k, v, causal, block_size):
    mesh = make_mesh(4, axis_name="sp")
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=causal,
                                       block_size=block_size),
        mesh=mesh, in_specs=(P(None, "sp"),) * 3,
        out_specs=P(None, "sp"), check_vma=False)
    return np.asarray(jax.jit(fn)(q, k, v))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_size", [None, 4, 8])
def test_ring_attention_blocked_matches_dense(causal, block_size):
    rs = np.random.RandomState(0)
    B, S, H, D = 2, 32, 2, 8  # S=32 over 4 shards -> Sl=8
    q, k, v = (jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    ref = np.asarray(jax.jit(
        lambda q, k, v: dot_product_attention(q, k, v, causal=causal)
    )(q, k, v))
    out = ring_out(q, k, v, causal, block_size)
    np.testing.assert_allclose(ref, out, rtol=2e-5, atol=2e-5)


def test_ring_attention_bad_block_size():
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 32, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="divide"):
        ring_out(q, q, q, False, 3)  # 3 does not divide Sl=8


def test_mha_ring_block_size_roundtrip():
    from distkeras_tpu.models.attention import MultiHeadAttention
    from distkeras_tpu.models.core import layer_from_spec, layer_spec
    mha = MultiHeadAttention(num_heads=4, attn_impl="ring",
                             seq_axis_name="sp", ring_block_size=16)
    rebuilt = layer_from_spec(layer_spec(mha))
    assert rebuilt.ring_block_size == 16


def test_ring_attention_rejects_nonpositive_block_size():
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 32, 2, 8), jnp.float32)
    for bad in (0, -4):
        with pytest.raises(ValueError, match=">= 1"):
            ring_out(q, q, q, False, bad)


def test_from_torch_batch_sampler_loader():
    torch = pytest.importorskip("torch")
    from torch.utils.data import (BatchSampler, DataLoader,
                                  SequentialSampler, TensorDataset)

    X = torch.randn(32, 6)
    y = torch.randint(0, 3, (32,))
    tds = TensorDataset(X, y)
    loader = DataLoader(tds, batch_sampler=BatchSampler(
        SequentialSampler(tds), 4, False))
    ds = from_torch(loader)
    assert ds["features"].shape == (32, 6)
    np.testing.assert_allclose(ds["label"], y.numpy())
