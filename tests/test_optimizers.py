"""Unit tests for optimizers and losses (convergence on tiny problems)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops import apply_updates, get_loss, get_optimizer
from distkeras_tpu.ops.metrics import accuracy, top_k_accuracy


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("momentum", {"learning_rate": 0.05}),
    ("nesterov", {"learning_rate": 0.05}),
    ("adagrad", {"learning_rate": 0.5}),
    ("rmsprop", {"learning_rate": 0.05}),
    ("adam", {"learning_rate": 0.1}),
    ("adamw", {"learning_rate": 0.1, "weight_decay": 1e-4}),
    ("adadelta", {"learning_rate": 2.0}),
    ("lamb", {"learning_rate": 0.05}),
])
def test_optimizer_minimizes_quadratic(name, kwargs):
    opt = get_optimizer(name, **kwargs)
    params = {"w": jnp.array([3.0, -2.0]), "b": jnp.array(1.5)}
    target = {"w": jnp.array([1.0, 1.0]), "b": jnp.array(0.0)}

    def loss_fn(p):
        return (jnp.sum(jnp.square(p["w"] - target["w"])) +
                jnp.square(p["b"] - target["b"]))

    state = opt.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params)
        return apply_updates(params, updates), state

    for _ in range(300):
        params, state = step(params, state)
    assert float(loss_fn(params)) < 1e-2, f"{name} failed to converge"


def test_lars_reduces_loss_with_schedule():
    """LARS holds a CONSTANT relative step (lr·tc·‖w‖), so it orbits a
    toy optimum rather than entering it — assert strong loss reduction
    under a decaying schedule instead (its real use is large-batch
    ResNet with cosine decay)."""
    from distkeras_tpu.ops.schedules import get_schedule
    sched = get_schedule("cosine_decay", init_value=0.5, decay_steps=400)
    opt = get_optimizer("lars", learning_rate=sched,
                        trust_coefficient=0.1, momentum=0.9)
    params = {"w": jnp.array([3.0, -2.0])}
    target = jnp.array([1.0, 1.0])
    loss_fn = lambda p: jnp.sum(jnp.square(p["w"] - target))
    state = opt.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params)
        return apply_updates(params, updates), state

    first = float(loss_fn(params))
    for _ in range(400):
        params, state = step(params, state)
    assert float(loss_fn(params)) < 0.02 * first


def test_adamw_decoupled_decay_shrinks_weights():
    """With zero gradients AdamW still decays weights toward 0 (decoupled
    L2, unlike plain Adam)."""
    opt = get_optimizer("adamw", learning_rate=0.1, weight_decay=0.5)
    params = {"w": jnp.array([2.0])}
    state = opt.init(params)
    grads = {"w": jnp.zeros(1)}
    for _ in range(10):
        upd, state = opt.update(grads, state, params)
        params = apply_updates(params, upd)
    assert float(params["w"][0]) < 2.0 * (1 - 0.04) ** 9
    # plain adam with zero grads moves nothing
    opt2 = get_optimizer("adam", learning_rate=0.1)
    s2 = opt2.init({"w": jnp.array([2.0])})
    upd2, _ = opt2.update(grads, s2, {"w": jnp.array([2.0])})
    np.testing.assert_allclose(np.asarray(upd2["w"]), 0.0)


def test_lars_lamb_trust_ratio_scales_per_tensor():
    """A tensor with tiny weights must get a proportionally tiny step,
    regardless of its gradient magnitude."""
    for name in ("lars", "lamb"):
        opt = get_optimizer(name, learning_rate=0.1)
        params = {"big": jnp.full((4,), 10.0), "small": jnp.full((4,), 0.01)}
        state = opt.init(params)
        grads = {"big": jnp.ones(4), "small": jnp.ones(4)}
        upd, _ = opt.update(grads, state, params)
        big_step = float(jnp.abs(upd["big"]).max())
        small_step = float(jnp.abs(upd["small"]).max())
        assert small_step < big_step / 100, (name, big_step, small_step)


def test_clip_by_global_norm():
    from distkeras_tpu.ops.optimizers import clip_by_global_norm
    opt = clip_by_global_norm(get_optimizer("sgd", learning_rate=1.0), 1.0)
    params = {"w": jnp.zeros(4)}
    state = opt.init(params)
    upd, _ = opt.update({"w": jnp.full((4,), 100.0)}, state, params)
    # clipped to global norm 1 then scaled by lr=1: |upd| == 1
    np.testing.assert_allclose(
        float(jnp.linalg.norm(np.asarray(upd["w"]))), 1.0, rtol=1e-5)
    # under the clip threshold: untouched
    upd2, _ = opt.update({"w": jnp.full((4,), 0.1)}, state, params)
    np.testing.assert_allclose(np.asarray(upd2["w"]), -0.1, rtol=1e-6)
    with pytest.raises(ValueError, match="> 0"):
        clip_by_global_norm(get_optimizer("sgd"), 0.0)


def test_trainer_clip_grad_norm_kwarg():
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import Dense, Model, Sequential
    from distkeras_tpu.parallel import SingleTrainer
    rs = np.random.RandomState(0)
    X = rs.randn(128, 8).astype(np.float32)
    y = (X @ rs.randn(8) > 0).astype(np.int32)
    m = Model.build(Sequential([Dense(16, activation="relu"), Dense(2)]),
                    (8,), seed=0)
    tr = SingleTrainer(m, worker_optimizer="sgd", learning_rate=1e5,
                       loss="sparse_categorical_crossentropy_from_logits",
                       batch_size=32, num_epoch=3, clip_grad_norm=1e-6)
    tr.train(Dataset({"features": X, "label": y}))
    # an unclipped lr=1e5 run diverges instantly; clipped stays finite
    assert np.isfinite(tr.get_history().losses()).all()


def test_sgd_step_math():
    opt = get_optimizer("sgd", learning_rate=0.5)
    params = {"w": jnp.array([2.0])}
    grads = {"w": jnp.array([1.0])}
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    new = apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(new["w"]), [1.5])


def test_optimizer_state_is_pytree():
    opt = get_optimizer("adam")
    params = {"a": jnp.ones((3,)), "b": {"c": jnp.ones((2, 2))}}
    state = opt.init(params)
    leaves = jax.tree_util.tree_leaves(state)
    assert all(hasattr(l, "shape") for l in leaves)


@pytest.mark.parametrize("loss_name", [
    "mse", "mae", "categorical_crossentropy",
    "categorical_crossentropy_from_logits", "binary_crossentropy",
    "binary_crossentropy_from_logits", "hinge",
])
def test_losses_scalar_and_nonnegative(loss_name):
    loss = get_loss(loss_name)
    if "binary" in loss_name or loss_name == "hinge":
        y_true = jnp.array([[1.0], [0.0], [1.0]])
        if loss_name == "hinge":
            y_true = 2 * y_true - 1
        y_pred = jnp.array([[0.8], [0.3], [0.6]])
    else:
        y_true = jnp.eye(4)[:3]
        y_pred = jax.nn.softmax(jnp.ones((3, 4)))
    val = loss(y_true, y_pred)
    assert val.shape == ()
    assert float(val) >= -1e-6


def test_auc_rank_statistic():
    from distkeras_tpu.ops.metrics import auc
    # perfect separation -> 1.0; inverted -> 0.0; random-ish hand case
    y = jnp.array([0, 0, 1, 1])
    assert float(auc(y, jnp.array([0.1, 0.2, 0.8, 0.9]))) == 1.0
    assert float(auc(y, jnp.array([0.9, 0.8, 0.2, 0.1]))) == 0.0
    # hand-computed: pairs (neg, pos): (.4,.3)=0, (.4,.8)=1, (.6,.3)=0,
    # (.6,.8)=1 -> AUC = 2/4
    assert float(auc(y, jnp.array([0.4, 0.6, 0.3, 0.8]))) == \
        pytest.approx(0.5)
    # ties count half: all-equal scores -> 0.5
    assert float(auc(y, jnp.ones(4))) == pytest.approx(0.5)
    # monotone-transform invariant (logits vs probs)
    p = jnp.array([0.2, 0.7, 0.4, 0.9])
    logit = jnp.log(p) - jnp.log1p(-p)
    assert float(auc(y, p)) == pytest.approx(float(auc(y, logit)))
    # [N, 2] softmax input ranks by the class-1 margin
    two = jnp.stack([1 - p, p], axis=-1)
    assert float(auc(y, two)) == pytest.approx(float(auc(y, p)))
    # [N, 2] LOGIT input: ranking must follow softmax p1 (= s1 - s0), not
    # the raw class-1 column (regression: [[0,1],[10,2]] ranks wrong by
    # column alone)
    ylg = jnp.array([1, 0])
    lg = jnp.array([[0.0, 1.0], [10.0, 2.0]])
    assert float(auc(ylg, lg)) == 1.0
    # degenerate single-class labels -> 0.5, not NaN
    assert float(auc(jnp.zeros(4), p)) == 0.5
    # works under jit
    assert float(jax.jit(auc)(y, p)) == pytest.approx(float(auc(y, p)))


def test_class_weight_math_and_identity():
    from distkeras_tpu.ops import with_class_weight
    logits = jax.random.normal(jax.random.PRNGKey(2), (6, 3))
    y = jnp.array([0, 1, 2, 0, 1, 2])
    base = get_loss("sparse_categorical_crossentropy_from_logits")
    # all-ones weights == unweighted
    w1 = with_class_weight("sparse_categorical_crossentropy_from_logits",
                           {0: 1.0, 1: 1.0, 2: 1.0})
    np.testing.assert_allclose(float(w1(y, logits)),
                               float(base(y, logits)), rtol=1e-6)
    # manual check: per-sample ce scaled by the true class's weight
    wfn = with_class_weight("sparse_categorical_crossentropy_from_logits",
                            {0: 1.0, 1: 5.0, 2: 0.5})
    logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    per = -logp[np.arange(6), np.asarray(y)]
    expect = (per * np.array([1.0, 5.0, 0.5, 1.0, 5.0, 0.5])).mean()
    np.testing.assert_allclose(float(wfn(y, logits)), expect, rtol=1e-5)
    # binary + dense-array form
    wb = with_class_weight("binary_crossentropy_from_logits",
                           np.array([1.0, 3.0]))
    x = jnp.array([0.5, -0.5])
    t = jnp.array([1, 0])
    per_b = np.log1p(np.exp(-np.abs(np.asarray(x)))) + \
        np.maximum(np.asarray(x), 0) - np.asarray(x) * np.asarray(t)
    np.testing.assert_allclose(float(wb(t, x)),
                               (per_b * np.array([3.0, 1.0])).mean(),
                               rtol=1e-5)
    with pytest.raises(ValueError, match="classification"):
        with_class_weight("mse", {0: 1.0})
    # classes missing from the dict default to weight 1.0 (Keras-style),
    # never clamp onto a neighbor's weight
    w_partial = with_class_weight(
        "sparse_categorical_crossentropy_from_logits", {1: 5.0})
    per3 = -logp[np.arange(6), np.asarray(y)]
    exp3 = (per3 * np.array([1.0, 5.0, 1.0, 1.0, 5.0, 1.0])).mean()
    np.testing.assert_allclose(float(w_partial(y, logits)), exp3,
                               rtol=1e-5)
    # a weight for a class the loss can't see fails loudly at trace time
    w_over = with_class_weight(
        "sparse_categorical_crossentropy_from_logits", {7: 2.0})
    with pytest.raises(ValueError, match="only 3 classes"):
        w_over(y, logits)
    with pytest.raises(ValueError, match="3 entries"):
        with_class_weight("sparse_categorical_crossentropy_from_logits",
                          np.ones(3))(y, logits[:, :2])


def test_class_weight_leaves_val_loss_unweighted():
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import Dense, Model, Sequential
    from distkeras_tpu.parallel import SingleTrainer
    rs = np.random.RandomState(3)
    X = rs.randn(128, 4).astype(np.float32)
    y = rs.randint(0, 2, 128)
    ds = Dataset({"features": X, "label": y})
    kw = dict(worker_optimizer="sgd", learning_rate=0.0, batch_size=64,
              num_epoch=1, shuffle_each_epoch=False,
              loss="sparse_categorical_crossentropy_from_logits",
              validation_data=(X, y))
    m = Model.build(Sequential([Dense(2)]), (4,), seed=5)
    t0 = SingleTrainer(m, **kw)
    t0.train(ds)
    m2 = Model.build(Sequential([Dense(2)]), (4,), seed=5)
    t1 = SingleTrainer(m2, class_weight={0: 1.0, 1: 10.0}, **kw)
    t1.train(ds)
    # lr=0: same params throughout; TRAIN loss differs, VAL loss must not
    assert t1.get_history().losses()[0] > 2 * t0.get_history().losses()[0]
    np.testing.assert_allclose(t1.get_history().metric("val_loss"),
                               t0.get_history().metric("val_loss"),
                               rtol=1e-6)


def test_trainer_class_weight_shifts_decisions():
    """10x weight on the rare class must raise its recall vs unweighted
    on an imbalanced problem."""
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import Dense, Model, Sequential
    from distkeras_tpu.parallel import SingleTrainer
    rs = np.random.RandomState(0)
    n = 2048
    y = (rs.rand(n) < 0.15).astype(np.int32)    # 15% positives
    # heavy class overlap: the OPTIMAL boundary depends on the weighting
    X = (rs.randn(n, 2) + y[:, None] * 0.8).astype(np.float32)
    ds = Dataset({"features": X, "label": y})

    def recall(model):
        pred = model.predict(X).argmax(-1)
        return (pred[y == 1] == 1).mean()

    kw = dict(worker_optimizer="sgd", learning_rate=0.1, batch_size=128,
              num_epoch=20,
              loss="sparse_categorical_crossentropy_from_logits")
    m0 = Model.build(Sequential([Dense(2)]), (2,), seed=1)
    t0 = SingleTrainer(m0, **kw).train(ds)
    m1 = Model.build(Sequential([Dense(2)]), (2,), seed=1)
    t1 = SingleTrainer(m1, class_weight={0: 1.0, 1: 10.0},
                       **kw).train(ds)
    assert recall(t1) > recall(t0) + 0.1, (recall(t0), recall(t1))


def test_crossentropy_from_logits_matches_probs():
    logits = jax.random.normal(jax.random.PRNGKey(0), (5, 7))
    y = jax.nn.one_hot(jnp.arange(5) % 7, 7)
    a = get_loss("categorical_crossentropy")(y, jax.nn.softmax(logits))
    b = get_loss("categorical_crossentropy_from_logits")(y, logits)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-5)


def test_sparse_crossentropy_matches_dense():
    logits = jax.random.normal(jax.random.PRNGKey(1), (5, 7))
    labels = jnp.arange(5) % 7
    dense = get_loss("categorical_crossentropy_from_logits")(
        jax.nn.one_hot(labels, 7), logits)
    sparse = get_loss("sparse_categorical_crossentropy_from_logits")(
        labels, logits)
    np.testing.assert_allclose(float(dense), float(sparse), rtol=1e-5)


def test_accuracy_metric():
    y_true = jnp.array([0, 1, 2, 1])
    y_pred = jax.nn.one_hot(jnp.array([0, 1, 0, 1]), 3)
    assert float(accuracy(y_true, y_pred)) == pytest.approx(0.75)
    y_true_oh = jax.nn.one_hot(y_true, 3)
    assert float(accuracy(y_true_oh, y_pred)) == pytest.approx(0.75)


def test_binary_accuracy_thresholds_sigmoid_scores():
    y_true = jnp.array([1, 1, 0, 0])
    y_pred = jnp.array([[0.9], [0.8], [0.2], [0.1]])  # perfect predictions
    assert float(accuracy(y_true, y_pred)) == pytest.approx(1.0)
    assert float(accuracy(y_true, jnp.array([0.9, 0.3, 0.2, 0.6]))) == \
        pytest.approx(0.5)


def test_binary_accuracy_with_logits():
    y_true = jnp.array([1, 1, 0, 0])
    logits = jnp.array([0.3, 2.0, -0.2, -1.0])  # all correct at 0 threshold
    assert float(accuracy(y_true, logits)) == pytest.approx(1.0)


def test_hinge_converts_binary_labels():
    loss = get_loss("hinge")
    y01 = jnp.array([[1.0], [0.0]])
    ypm = jnp.array([[1.0], [-1.0]])
    y_pred = jnp.array([[2.0], [-2.0]])
    # 0/1 labels behave like +-1 labels (Keras conversion semantics)
    np.testing.assert_allclose(float(loss(y01, y_pred)),
                               float(loss(ypm, y_pred)))
    assert float(loss(ypm, y_pred)) == pytest.approx(0.0)


def test_top_k_accuracy():
    y_true = jnp.array([2, 0])
    y_pred = jnp.array([[0.1, 0.3, 0.2, 0.4], [0.9, 0.05, 0.03, 0.02]])
    assert float(top_k_accuracy(y_true, y_pred, k=2)) == pytest.approx(0.5)
    assert float(top_k_accuracy(y_true, y_pred, k=3)) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# precision / recall / f1 (macro)
# ---------------------------------------------------------------------------

def test_precision_recall_f1_macro_hand_computed():
    import numpy as np

    from distkeras_tpu.ops.metrics import f1, precision, recall
    y_true = np.array([0, 0, 1, 1, 2, 2])
    y_pred = np.array([0, 1, 1, 1, 2, 0])
    # class 0: tp=1, pred=2, true=2 -> p=0.5, r=0.5
    # class 1: tp=2, pred=3, true=2 -> p=2/3, r=1.0
    # class 2: tp=1, pred=1, true=2 -> p=1.0, r=0.5
    p_macro = (0.5 + 2 / 3 + 1.0) / 3
    r_macro = (0.5 + 1.0 + 0.5) / 3
    assert abs(float(precision(y_true, y_pred)) - p_macro) < 1e-6
    assert abs(float(recall(y_true, y_pred)) - r_macro) < 1e-6
    f = 2 * p_macro * r_macro / (p_macro + r_macro)
    assert abs(float(f1(y_true, y_pred)) - f) < 1e-6


def test_precision_handles_logits_and_absent_classes():
    import numpy as np

    from distkeras_tpu.ops.metrics import precision, recall
    # logits [n, k]; class 2 never appears in y_true -> excluded from macro
    y_true = np.array([0, 1, 0, 1])
    logits = np.array([[2.0, 0.0, -1], [0.0, 2.0, -1],
                       [2.0, 0.0, -1], [2.0, 0.0, -1]])
    # preds: 0, 1, 0, 0; class 0: tp=2, pred=3, true=2; class 1: tp=1,
    # pred=1, true=2
    assert abs(float(precision(y_true, logits)) - (2 / 3 + 1.0) / 2) < 1e-6
    assert abs(float(recall(y_true, logits)) - (1.0 + 0.5) / 2) < 1e-6


def test_metrics_work_under_jit():
    import jax
    import numpy as np

    from distkeras_tpu.ops.metrics import f1
    y = np.array([0, 1, 1, 0])
    p = np.array([[1.0, 0], [0, 1.0], [1.0, 0], [1.0, 0]])
    assert np.isfinite(float(jax.jit(f1)(y, p)))


def test_precision_rejects_out_of_range_labels_for_binary_scores():
    import numpy as np

    from distkeras_tpu.ops.metrics import precision
    with pytest.raises(ValueError, match="only\\s+cover"):
        precision(np.array([0, 1, 2, 2]), np.array([0.9, 0.2, 0.8, 0.1]))


def test_from_iterable_list_rows_are_features_not_pairs():
    import numpy as np

    from distkeras_tpu.data import from_iterable
    ds = from_iterable([[1.0, 2.0], [3.0, 4.0]])
    assert ds["features"].shape == (2, 2)
    assert "label" not in ds.columns

    with pytest.raises(ValueError, match="mixed dict"):
        from_iterable([{"a": 1}, (np.zeros(2), 0)])
    with pytest.raises(ValueError, match="3-tuple"):
        from_iterable([(1, 2, 3)])


def test_precision_rejects_out_of_range_predictions():
    import numpy as np

    from distkeras_tpu.ops.metrics import precision
    with pytest.raises(ValueError, match="predictions contain class 7"):
        precision(np.eye(2)[[0, 0, 1]], np.array([0, 7, 1]))


def test_label_smoothing():
    from distkeras_tpu.ops import with_label_smoothing
    logits = jax.random.normal(jax.random.PRNGKey(5), (4, 3))
    y = jnp.array([0, 1, 2, 0])
    # s=0 identical to the plain loss
    f0 = with_label_smoothing(
        "sparse_categorical_crossentropy_from_logits", 0.0)
    base = get_loss("sparse_categorical_crossentropy_from_logits")
    np.testing.assert_allclose(float(f0(y, logits)), float(base(y, logits)),
                               rtol=1e-6)
    # manual check at s=0.3
    fs = with_label_smoothing(
        "sparse_categorical_crossentropy_from_logits", 0.3)
    logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    tgt = np.eye(3)[np.asarray(y)] * 0.7 + 0.1
    expect = -(tgt * logp).sum(-1).mean()
    np.testing.assert_allclose(float(fs(y, logits)), expect, rtol=1e-5)
    # dense one-hot targets path
    fd = with_label_smoothing("categorical_crossentropy_from_logits", 0.3)
    np.testing.assert_allclose(float(fd(jnp.eye(3)[y], logits)), expect,
                               rtol=1e-5)
    with pytest.raises(ValueError, match="categorical"):
        with_label_smoothing("mse", 0.1)
    with pytest.raises(ValueError, match="\\[0, 1\\)"):
        with_label_smoothing("categorical_crossentropy", 1.0)
