"""Unit tests for optimizers and losses (convergence on tiny problems)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.ops import apply_updates, get_loss, get_optimizer
from distkeras_tpu.ops.metrics import accuracy, top_k_accuracy


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("momentum", {"learning_rate": 0.05}),
    ("nesterov", {"learning_rate": 0.05}),
    ("adagrad", {"learning_rate": 0.5}),
    ("rmsprop", {"learning_rate": 0.05}),
    ("adam", {"learning_rate": 0.1}),
    ("adadelta", {"learning_rate": 2.0}),
])
def test_optimizer_minimizes_quadratic(name, kwargs):
    opt = get_optimizer(name, **kwargs)
    params = {"w": jnp.array([3.0, -2.0]), "b": jnp.array(1.5)}
    target = {"w": jnp.array([1.0, 1.0]), "b": jnp.array(0.0)}

    def loss_fn(p):
        return (jnp.sum(jnp.square(p["w"] - target["w"])) +
                jnp.square(p["b"] - target["b"]))

    state = opt.init(params)

    @jax.jit
    def step(params, state):
        grads = jax.grad(loss_fn)(params)
        updates, state = opt.update(grads, state, params)
        return apply_updates(params, updates), state

    for _ in range(300):
        params, state = step(params, state)
    assert float(loss_fn(params)) < 1e-2, f"{name} failed to converge"


def test_sgd_step_math():
    opt = get_optimizer("sgd", learning_rate=0.5)
    params = {"w": jnp.array([2.0])}
    grads = {"w": jnp.array([1.0])}
    state = opt.init(params)
    updates, _ = opt.update(grads, state, params)
    new = apply_updates(params, updates)
    np.testing.assert_allclose(np.asarray(new["w"]), [1.5])


def test_optimizer_state_is_pytree():
    opt = get_optimizer("adam")
    params = {"a": jnp.ones((3,)), "b": {"c": jnp.ones((2, 2))}}
    state = opt.init(params)
    leaves = jax.tree_util.tree_leaves(state)
    assert all(hasattr(l, "shape") for l in leaves)


@pytest.mark.parametrize("loss_name", [
    "mse", "mae", "categorical_crossentropy",
    "categorical_crossentropy_from_logits", "binary_crossentropy",
    "binary_crossentropy_from_logits", "hinge",
])
def test_losses_scalar_and_nonnegative(loss_name):
    loss = get_loss(loss_name)
    if "binary" in loss_name or loss_name == "hinge":
        y_true = jnp.array([[1.0], [0.0], [1.0]])
        if loss_name == "hinge":
            y_true = 2 * y_true - 1
        y_pred = jnp.array([[0.8], [0.3], [0.6]])
    else:
        y_true = jnp.eye(4)[:3]
        y_pred = jax.nn.softmax(jnp.ones((3, 4)))
    val = loss(y_true, y_pred)
    assert val.shape == ()
    assert float(val) >= -1e-6


def test_crossentropy_from_logits_matches_probs():
    logits = jax.random.normal(jax.random.PRNGKey(0), (5, 7))
    y = jax.nn.one_hot(jnp.arange(5) % 7, 7)
    a = get_loss("categorical_crossentropy")(y, jax.nn.softmax(logits))
    b = get_loss("categorical_crossentropy_from_logits")(y, logits)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-5)


def test_sparse_crossentropy_matches_dense():
    logits = jax.random.normal(jax.random.PRNGKey(1), (5, 7))
    labels = jnp.arange(5) % 7
    dense = get_loss("categorical_crossentropy_from_logits")(
        jax.nn.one_hot(labels, 7), logits)
    sparse = get_loss("sparse_categorical_crossentropy_from_logits")(
        labels, logits)
    np.testing.assert_allclose(float(dense), float(sparse), rtol=1e-5)


def test_accuracy_metric():
    y_true = jnp.array([0, 1, 2, 1])
    y_pred = jax.nn.one_hot(jnp.array([0, 1, 0, 1]), 3)
    assert float(accuracy(y_true, y_pred)) == pytest.approx(0.75)
    y_true_oh = jax.nn.one_hot(y_true, 3)
    assert float(accuracy(y_true_oh, y_pred)) == pytest.approx(0.75)


def test_binary_accuracy_thresholds_sigmoid_scores():
    y_true = jnp.array([1, 1, 0, 0])
    y_pred = jnp.array([[0.9], [0.8], [0.2], [0.1]])  # perfect predictions
    assert float(accuracy(y_true, y_pred)) == pytest.approx(1.0)
    assert float(accuracy(y_true, jnp.array([0.9, 0.3, 0.2, 0.6]))) == \
        pytest.approx(0.5)


def test_binary_accuracy_with_logits():
    y_true = jnp.array([1, 1, 0, 0])
    logits = jnp.array([0.3, 2.0, -0.2, -1.0])  # all correct at 0 threshold
    assert float(accuracy(y_true, logits)) == pytest.approx(1.0)


def test_hinge_converts_binary_labels():
    loss = get_loss("hinge")
    y01 = jnp.array([[1.0], [0.0]])
    ypm = jnp.array([[1.0], [-1.0]])
    y_pred = jnp.array([[2.0], [-2.0]])
    # 0/1 labels behave like +-1 labels (Keras conversion semantics)
    np.testing.assert_allclose(float(loss(y01, y_pred)),
                               float(loss(ypm, y_pred)))
    assert float(loss(ypm, y_pred)) == pytest.approx(0.0)


def test_top_k_accuracy():
    y_true = jnp.array([2, 0])
    y_pred = jnp.array([[0.1, 0.3, 0.2, 0.4], [0.9, 0.05, 0.03, 0.02]])
    assert float(top_k_accuracy(y_true, y_pred, k=2)) == pytest.approx(0.5)
    assert float(top_k_accuracy(y_true, y_pred, k=3)) == pytest.approx(1.0)
