"""HBM byte accounting for the paged KV pool (quantized-decode PR).

Satellite fix: ``PagedKVPool.page_bytes`` must count EVERYTHING a
physical page allocates — quantized payload (int4: two nibbles per
byte) AND the per-token f32 scale planes — and the byte-budget pool
sizing (``hbm_budget``) plus the engine's cost-aware admission must
run on that number. The accounting tests pin ``page_bytes`` against
the actually-allocated buffers; the admission test demonstrates the
tentpole's capacity claim: int4 KV admits MORE concurrent streams
than bf16 under the SAME byte budget.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.serving.engine import ServingEngine
from distkeras_tpu.serving.kv_pool import PagedKVPool

PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def lm():
    from distkeras_tpu.models.decoding import _resolve_head_dims
    m = Model.build(
        zoo.transformer_lm(29, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (12,), seed=0)
    _resolve_head_dims(m.module, m.params)   # bare-module pool probes
    return m


def _allocated_bytes(pool):
    """Actually-allocated device bytes of the page planes, split into
    (page-proportional planes, structural markers)."""
    pages, markers = 0, 0
    for kv in pool.cache:
        if kv is None:
            continue
        for key, a in kv.items():
            n = np.asarray(a).nbytes
            if key == "q4":
                markers += n
            else:
                pages += n
    return pages, markers


@pytest.mark.parametrize("dtype,name", [
    (jnp.float32, "f32"), (jnp.bfloat16, "bf16"),
    ("int8", "int8"), ("int4", "int4")])
def test_page_bytes_matches_allocated_buffers(lm, dtype, name):
    """``num_pages * page_bytes`` equals the bytes the pool actually
    allocated, for every rung of the KV ladder (the structural int4
    marker is per-layer, not per-page, and stays excluded)."""
    pool = PagedKVPool(lm.module, 3, 64, page_len=16, dtype=dtype)
    pages, markers = _allocated_bytes(pool)
    assert pages == pool.num_pages * pool.page_bytes, name
    if name == "int4":
        assert markers > 0            # one (1,1,1,1) int8 leaf per layer
        assert markers <= len(pool.cache)


def test_page_bytes_includes_scale_planes(lm):
    """The satellite bug: budget math counting payload only. At D=8
    with f32 per-token scales the scale planes are a third of the
    int8 page and two thirds of the int4 payload — page_bytes must
    carry them."""
    f32 = PagedKVPool(lm.module, 1, 32, page_len=16, dtype=jnp.float32)
    i8 = PagedKVPool(lm.module, 1, 32, page_len=16, dtype="int8")
    i4 = PagedKVPool(lm.module, 1, 32, page_len=16, dtype="int4")
    layers = sum(1 for kv in f32.cache if kv is not None)
    hkv, d = 4, 8                                   # pattern-LM geometry
    payload_i8 = layers * 2 * hkv * 16 * d          # int8 k+v bytes/page
    scales = layers * 2 * hkv * 16 * 4              # f32 k+v scale rows
    assert i8.page_bytes == payload_i8 + scales
    assert i4.page_bytes == payload_i8 // 2 + scales
    assert f32.page_bytes == payload_i8 * 4         # no scale planes


def test_hbm_budget_sizes_pool(lm):
    pb = PagedKVPool(lm.module, 2, 64, page_len=16,
                     dtype="int4").page_bytes
    pool = PagedKVPool(lm.module, 2, 64, page_len=16, dtype="int4",
                       hbm_budget=10 * pb + pb // 2, reserve_bytes=pb)
    assert pool.num_pages == 9        # (10.5 - 1) pages round down
    with pytest.raises(ValueError, match="not both"):
        PagedKVPool(lm.module, 2, 64, page_len=16, num_pages=4,
                    hbm_budget=1 << 20)
    with pytest.raises(ValueError, match="does not fit"):
        PagedKVPool(lm.module, 2, 64, page_len=16, hbm_budget=pb,
                    reserve_bytes=pb)
    with pytest.raises(ValueError, match="even"):
        PagedKVPool(lm.module, 2, 64, page_len=15, dtype="int4")


def test_int4_kv_admits_more_streams_under_same_budget(lm):
    """The capacity claim, end to end through the engine's cost-aware
    admission: same hbm_budget, same weights — the int4-KV engine
    holds MORE concurrent decoding streams than the bf16 engine
    (whose worst-case page demand exhausts the budget after one)."""
    probe = ServingEngine(lm, num_slots=4, max_len=32, page_len=8)
    weight_bytes = sum(np.asarray(l).nbytes for l in
                      jax.tree_util.tree_leaves(probe._params))
    bf16_pb = PagedKVPool(lm.module, 1, 32, page_len=8,
                          dtype=jnp.bfloat16).page_bytes
    # envelope: four bf16 pages of KV. An 8-token prompt costs
    # pages_for(9) = 2 pages at admission, so bf16 seats two streams.
    budget = weight_bytes + 4 * bf16_pb

    def occupied(cache_dtype):
        eng = ServingEngine(lm, num_slots=4, max_len=32, page_len=8,
                            cache_dtype=cache_dtype, hbm_budget=budget)
        for _ in range(6):
            eng.submit(PATTERN[:8], 4)
        eng.step()
        return eng.pool.num_pages, eng.scheduler.occupied

    bf16_pages, bf16_occ = occupied(jnp.bfloat16)
    int4_pages, int4_occ = occupied("int4")
    assert bf16_pages == 4 and bf16_occ == 2
    assert int4_pages > bf16_pages
    assert int4_occ > bf16_occ


def test_quantized_weights_free_budget_for_pages(lm):
    """weight_quant shrinks the reserve side of the same envelope:
    f32 weights + the rest as pages vs int4 weights + the rest as
    pages — the quantized engine ends up with strictly more pages."""
    f32_w = sum(np.asarray(l).nbytes for l in
                jax.tree_util.tree_leaves(lm.params))
    budget = f32_w + 6 * PagedKVPool(
        lm.module, 1, 32, page_len=8, dtype="int4").page_bytes
    base = ServingEngine(lm, num_slots=2, max_len=32, page_len=8,
                         cache_dtype="int4", hbm_budget=budget)
    quant = ServingEngine(lm, num_slots=2, max_len=32, page_len=8,
                          cache_dtype="int4", weight_quant="int4",
                          hbm_budget=budget)
    assert quant.pool.num_pages > base.pool.num_pages


def test_staging_cache_accounting(lm):
    """make_request_cache covers pages_per_slot * page_len positions;
    its int4 planes pack the same way the pool's do (bitwise-roundtrip
    covered in test_int4_kv; here: the byte shape contract)."""
    pool = PagedKVPool(lm.module, 2, 64, page_len=16, dtype="int4")
    st = pool.make_request_cache()
    for kv in st:
        if kv is None:
            continue
        assert kv["k"].shape[2] == pool.pages_per_slot * pool.page_len
        assert "q4" in kv
