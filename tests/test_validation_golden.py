"""validation_data support + the golden-metric convergence test (SURVEY §4
calls for an MNIST-MLP golden metric as BASELINE config 1's stand-in)."""

import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.models import Dense, Model, Sequential
from distkeras_tpu.parallel import (AEASGD, SingleTrainer, SPMDTrainer,
                                    make_mesh_2d)


def split_problem(seed=0, N=2048, D=16, C=4):
    rs = np.random.RandomState(seed)
    X = rs.randn(N, D).astype(np.float32)
    y = (X @ rs.randn(D, C)).argmax(-1)
    n_tr = int(N * 0.8)
    return (Dataset({"features": X[:n_tr], "label": y[:n_tr]}),
            Dataset({"features": X[n_tr:], "label": y[n_tr:]}), D, C)


KW = dict(worker_optimizer="momentum",
          optimizer_kwargs={"learning_rate": 0.05},
          loss="sparse_categorical_crossentropy_from_logits",
          metrics=["accuracy"], batch_size=64, num_epoch=5)


def check_val(trainer, expect_epochs):
    h = trainer.get_history()
    vl = h.metric("val_loss")
    va = h.metric("val_accuracy")
    assert vl.shape == (expect_epochs,) and va.shape == (expect_epochs,)
    assert np.isfinite(vl).all()
    assert vl[-1] < vl[0]          # held-out loss improves
    assert va[-1] > 0.8, va        # and generalizes


def test_single_trainer_validation():
    tr_ds, va_ds, D, C = split_problem()
    model = Model.build(Sequential([Dense(64, activation="relu"),
                                    Dense(C)]), (D,), seed=0)
    tr = SingleTrainer(model, validation_data=va_ds, **KW)
    tr.train(tr_ds)
    check_val(tr, KW["num_epoch"])


def test_spmd_trainer_validation_xy_pair():
    tr_ds, va_ds, D, C = split_problem(1)
    model = Model.build(Sequential([Dense(64, activation="relu"),
                                    Dense(C)]), (D,), seed=0)
    tr = SPMDTrainer(model, mesh=make_mesh_2d({"workers": 2, "tp": 4}),
                     tp_axis="tp",
                     validation_data=(va_ds["features"], va_ds["label"]),
                     **KW)
    tr.train(tr_ds)
    check_val(tr, KW["num_epoch"])


def test_distributed_trainer_validation_on_center():
    tr_ds, va_ds, D, C = split_problem(2)
    model = Model.build(Sequential([Dense(64, activation="relu"),
                                    Dense(C)]), (D,), seed=0)
    kw = {**KW, "num_epoch": 10}
    tr = AEASGD(model, num_workers=8, communication_window=4, rho=5.0,
                learning_rate=0.02, validation_data=va_ds, **kw)
    tr.train(tr_ds)
    check_val(tr, kw["num_epoch"])


def test_golden_mnist_mlp_convergence():
    """Golden metric (BASELINE config 1 stand-in): the synthetic-MNIST MLP
    pipeline must reach >= 0.97 train accuracy in 3 epochs with the default
    example settings. A regression in layers/optimizers/trainers shows up
    here as a hard number, not a vague slowdown."""
    from examples.mnist_workflow import build_model, make_synthetic_mnist
    from distkeras_tpu.data import MinMaxTransformer
    from distkeras_tpu.ops.metrics import accuracy

    X, y = make_synthetic_mnist(4096)
    ds = Dataset({"features": X, "label": y})
    ds = MinMaxTransformer(0.0, 1.0, 0.0, 255.0, "features",
                           "features_norm")(ds)
    model = build_model((784,), conv=False)
    tr = SingleTrainer(model, worker_optimizer="momentum",
                       optimizer_kwargs={"learning_rate": 0.05},
                       loss="sparse_categorical_crossentropy_from_logits",
                       features_col="features_norm",
                       batch_size=64, num_epoch=3, seed=0)
    trained = tr.train(ds)
    acc = float(accuracy(y, trained.predict(ds["features_norm"],
                                            batch_size=1024)))
    assert acc >= 0.97, f"golden MNIST-MLP accuracy regressed: {acc:.4f}"


def test_host_async_trainer_validation():
    from distkeras_tpu.parallel import HostAsyncTrainer
    tr_ds, va_ds, D, C = split_problem(3, N=1024)
    model = Model.build(Sequential([Dense(32, activation="relu"),
                                    Dense(C)]), (D,), seed=0)
    # plain SGD: momentum-inflated DOWNPOUR commits summed at the center
    # can oscillate depending on thread interleaving, making the val curve
    # flaky on this tiny problem
    kw = {**KW, "num_epoch": 6, "batch_size": 16,
          "worker_optimizer": "sgd",
          "optimizer_kwargs": {"learning_rate": 0.05}}
    tr = HostAsyncTrainer(model, num_workers=4, communication_window=4,
                          validation_data=va_ds, **kw)
    tr.train(tr_ds)
    vl = tr.get_history().metric("val_loss")
    assert vl.shape == (6,) and vl[-1] < vl[0]


def test_ensemble_trainer_rejects_validation_data():
    from distkeras_tpu.parallel import EnsembleTrainer
    tr_ds, va_ds, D, C = split_problem()
    model = Model.build(Sequential([Dense(C)]), (D,), seed=0)
    tr = EnsembleTrainer(model, num_models=2, validation_data=va_ds, **KW)
    with pytest.raises(ValueError, match="does not support validation"):
        tr.train(tr_ds)


def test_distributed_validation_uses_trained_bn_state():
    """Regression: center model STATE never advances in the engine, so
    validation must use the worker-averaged BatchNorm stats."""
    from distkeras_tpu.models.layers import BatchNorm
    from distkeras_tpu.parallel import DOWNPOUR

    tr_ds, va_ds, D, C = split_problem(5, N=4096)
    # scale features so init BN stats (mean 0 / var 1) are WRONG
    big_tr = Dataset({"features": tr_ds["features"] * 10.0 + 3.0,
                      "label": tr_ds["label"]})
    big_va = Dataset({"features": va_ds["features"] * 10.0 + 3.0,
                      "label": va_ds["label"]})
    model = Model.build(Sequential([BatchNorm(),
                                    Dense(32, activation="relu"),
                                    Dense(C)]), (D,), seed=0)
    kw = {**KW, "num_epoch": 8, "batch_size": 32}
    tr = DOWNPOUR(model, num_workers=8, communication_window=4,
                  commit_scale=1 / 8, validation_data=big_va, **kw)
    tr.train(big_tr)
    va = tr.get_history().metric("val_accuracy")
    assert va[-1] > 0.75, va
