"""KV-cache autoregressive decoding: step-decode must equal the full
forward, and generate() must continue a memorized sequence (capability
ADD — the reference has no generative path, SURVEY §3.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import (decode_step, generate,
                                           init_cache)

V, S = 29, 12


def lm(use_rope=True, moe=False, seed=0):
    return Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=use_rope,
                           max_len=None if use_rope else 64,
                           moe_every=2 if moe else 0,
                           num_experts=4 if moe else 0),
        (S,), seed=seed)


@pytest.mark.parametrize("use_rope,moe", [(True, False), (False, False),
                                          (True, True)])
def test_decode_step_matches_full_forward(use_rope, moe):
    m = lm(use_rope=use_rope, moe=moe)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, V)
    full, _ = m.module.apply(m.params, m.state, tokens, training=False)

    from distkeras_tpu.models.decoding import _resolve_head_dims
    _resolve_head_dims(m.module, m.params)
    cache = init_cache(m.module, 2, S)
    outs = []
    for t in range(S):
        logits, cache = decode_step(m.module, m.params, m.state, cache,
                                    tokens[:, t], t)
        outs.append(logits)
    stepwise = jnp.stack(outs, axis=1)                   # [B, S, V]
    np.testing.assert_allclose(np.asarray(stepwise), np.asarray(full),
                               atol=2e-4)


PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    """The shared session-scoped overfit-PATTERN LM (conftest pattern_lm): huge greedy argmax margins keep token-identity assertions robust; trained once per test session."""
    return pattern_lm


def test_generate_continues_memorized_sequence(memorized_lm):
    prompts = np.tile(PATTERN[:4], (2, 1))
    out = generate(memorized_lm, prompts, max_new_tokens=7,
                   temperature=0.0)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[0], PATTERN[:11])
    np.testing.assert_array_equal(out[1], PATTERN[:11])


def test_generate_sampling_and_validation():
    m = lm()
    prompts = np.array([[1, 2, 3]])
    out = generate(m, prompts, max_new_tokens=4, temperature=1.0, top_k=5,
                   seed=7)
    assert out.shape == (1, 7)
    np.testing.assert_array_equal(out[:, :3], prompts)  # prompt preserved
    assert (out < V).all() and (out >= 0).all()
    # same seed -> same sample; different seed -> (almost surely) different
    out2 = generate(m, prompts, max_new_tokens=4, temperature=1.0, top_k=5,
                    seed=7)
    np.testing.assert_array_equal(out, out2)

    with pytest.raises(ValueError, match="B, P"):
        generate(m, np.array([1, 2, 3]), max_new_tokens=2)


def test_generate_stop_token_pads_tail(memorized_lm):
    """After a sequence emits stop_token, every later slot is stop_token;
    the overfit LM emits the pattern, so making one of its tokens the stop
    token truncates deterministically."""
    out = memorized_lm.generate(PATTERN[None, :4], max_new_tokens=7,
                                temperature=0.0, stop_token=9)
    np.testing.assert_array_equal(out[0, :6], PATTERN[:6])  # ...,5,9
    np.testing.assert_array_equal(out[0, 6:], np.full(5, 9))  # padded


def test_generate_rejects_positions_beyond_table():
    m = lm(use_rope=False)  # PositionalEmbedding(max_len=64)
    with pytest.raises(ValueError, match="too\\s+small"):
        generate(m, np.zeros((1, 60), np.int32), max_new_tokens=10)


def test_generate_with_tp_sharded_params(memorized_lm):
    """Generation under tensor parallelism: shard the params with Megatron
    specs and let GSPMD partition the decode scan.

    Two layers of coverage: (a) per-step logits match the replicated run
    within reduction-reorder tolerance on an untrained model; (b) the
    FULL compiled generate scan reproduces the memorized pattern
    token-for-token when sharded (the overfit model's argmax margins are
    huge, so exact token equality is robust)."""
    from distkeras_tpu.models.decoding import (_resolve_head_dims,
                                               decode_step, init_cache)
    from distkeras_tpu.parallel.mesh import make_mesh_2d
    from distkeras_tpu.parallel.sharding import param_specs, shard_params

    mesh = make_mesh_2d({"workers": 2, "tp": 4})

    # (a) stepwise logits, untrained model
    m = lm(seed=4)
    prompts = np.array([[1, 2, 3, 4], [5, 6, 7, 8]])
    _resolve_head_dims(m.module, m.params)
    specs = param_specs(m.module, m.params, mesh, tp_axis="tp")
    sharded = shard_params(m.params, specs, mesh)
    cache_r = init_cache(m.module, 2, 4)
    cache_s = init_cache(m.module, 2, 4)
    for t in range(4):
        ref, cache_r = decode_step(m.module, m.params, m.state, cache_r,
                                   prompts[:, t], t)
        out, cache_s = decode_step(m.module, sharded, m.state, cache_s,
                                   prompts[:, t], t)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    # (b) full compiled scan, sharded, end-to-end token equality
    mm = memorized_lm
    specs = param_specs(mm.module, mm.params, mesh, tp_axis="tp")
    m2 = Model(mm.module, shard_params(mm.params, specs, mesh), mm.state,
               mm.input_shape, mm.output_shape)
    toks = generate(m2, PATTERN[None, :4], max_new_tokens=7,
                    temperature=0.0)
    np.testing.assert_array_equal(toks[0], PATTERN[:11])


def test_generate_jit_cached_across_calls():
    m = lm()
    prompts = np.array([[1, 2, 3]])
    generate(m, prompts, max_new_tokens=3)
    assert len(m._jit_generate) == 1
    generate(m, prompts, max_new_tokens=3)       # same config: cache hit
    assert len(m._jit_generate) == 1
    generate(m, prompts, max_new_tokens=3, temperature=0.5)
    assert len(m._jit_generate) == 2             # new sampling config

def test_top_k_ties_admit_exactly_k():
    """Ties at the k-th logit must not widen the candidate set: the mask is
    built from top_k's indices, not a value threshold."""
    from distkeras_tpu.models.decoding import _sample

    logits = jnp.asarray([[0.0, 5.0, 5.0, 5.0, -1.0]])  # 3-way tie, k=2
    idx = set(jax.device_get(jax.lax.top_k(logits, 2)[1][0]).tolist())
    draws = {
        int(_sample(logits, 1.0, 2, jax.random.PRNGKey(s))[0])
        for s in range(200)
    }
    assert draws == idx, f"sampled outside the top-2 set: {draws - idx}"


def test_init_cache_rejects_capacity_beyond_position_table():
    """Custom serving loops build caches directly — the max_len guard must
    fire here too, not only inside generate()."""
    from distkeras_tpu.models.decoding import _resolve_head_dims

    m = lm(use_rope=False)  # PositionalEmbedding(max_len=64)
    _resolve_head_dims(m.module, m.params)
    with pytest.raises(ValueError, match="too small"):
        init_cache(m.module, 1, 65)
    init_cache(m.module, 1, 64)  # at capacity: fine


def test_gqa_decode_matches_full_forward_and_shrinks_cache():
    """Grouped-query attention: the KV cache stores only kv_heads heads,
    and incremental decode matches the full forward exactly."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_kv_heads=2,
                           num_layers=2, mlp_ratio=2, use_rope=True),
        (S,), seed=0)
    from distkeras_tpu.models.decoding import _resolve_head_dims
    _resolve_head_dims(m.module, m.params)

    # kv projections and cache sized by kv heads
    blk = next(l for l in m.module.layers
               if type(l).__name__ == "TransformerBlock")
    assert blk.attn.kv_heads == 2
    i = m.module.layers.index(blk)
    assert m.params[i]["attn"]["wk"].shape == (32, 2, 8)
    cache = init_cache(m.module, 2, S)
    kv = next(c for c in cache if c is not None)
    assert kv["k"].shape == (2, 2, S, 8)

    rs = np.random.RandomState(0)
    toks = rs.randint(0, V, (2, S))
    full = m.predict(toks)                       # [B, S, V]
    logits_steps = []
    for t in range(S):
        step_logits, cache = decode_step(m.module, m.params, m.state,
                                         cache, jnp.asarray(toks[:, t]), t)
        logits_steps.append(np.asarray(step_logits))
    np.testing.assert_allclose(np.stack(logits_steps, axis=1), full,
                               atol=2e-4)

    out = generate(m, toks[:, :3], max_new_tokens=4)
    assert out.shape == (2, 7)


def test_gqa_validates_head_divisibility():
    from distkeras_tpu.models.attention import MultiHeadAttention

    with pytest.raises(ValueError, match="positive divisor"):
        MultiHeadAttention(num_heads=4, num_kv_heads=3)


def test_gqa_swa_rope_scale_compose():
    """The three LM knobs compose: a GQA + sliding-window + scaled-rope
    model trains a step, decodes incrementally equal to its full forward,
    and survives a save/load roundtrip."""
    import tempfile

    from distkeras_tpu.models import load_model, save_model
    from distkeras_tpu.models.decoding import _resolve_head_dims

    S = 12
    m = Model.build(
        zoo.transformer_lm(16, d_model=16, num_heads=4, num_kv_heads=2,
                           num_layers=2, mlp_ratio=2, attn_window=5,
                           rope_scale=2.0), (S,), seed=0)
    _resolve_head_dims(m.module, m.params)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 16, (2, S))

    full = m.predict(toks)
    cache = init_cache(m.module, 2, S)
    steps = []
    for t in range(S):
        lg, cache = decode_step(m.module, m.params, m.state, cache,
                                jnp.asarray(toks[:, t]), t)
        steps.append(np.asarray(lg))
    np.testing.assert_allclose(np.stack(steps, axis=1), full, atol=2e-4)

    import os
    p = os.path.join(tempfile.mkdtemp(), "m")
    save_model(m, p)
    loaded = load_model(p)
    blk = next(l for l in loaded.module.layers
               if type(l).__name__ == "TransformerBlock")
    assert blk.attn.attn_window == 5
    assert blk.attn.rope_scale == 2.0
    assert blk.attn.kv_heads == 2
    np.testing.assert_allclose(loaded.predict(toks), full, atol=1e-5)


def test_generate_int8_weights_matches_bf16_mostly():
    """weights_dtype='int8' (weight-only per-channel quantized serving):
    the machinery runs end to end and greedy decoding agrees with the
    full-precision path on a trained-ish model's confident logits."""
    from distkeras_tpu.models import Model, zoo
    from distkeras_tpu.models.decoding import generate

    V, S = 32, 16
    m = Model.build(zoo.transformer_lm(V, d_model=32, num_heads=4,
                                       num_layers=2, mlp_ratio=2),
                    (S,), seed=0)
    p = np.random.RandomState(0).randint(0, V, (2, 4)).astype(np.int32)
    o_ref = generate(m, p, max_new_tokens=8, weights_dtype=None)
    o_i8 = generate(m, p, max_new_tokens=8, weights_dtype="int8")
    assert o_i8.shape == o_ref.shape
    np.testing.assert_array_equal(o_i8[:, :4], p)  # prompt preserved
    # untrained logits are near-ties; require majority agreement, not
    # bitwise (int8 weight rounding legitimately flips knife-edge argmax)
    assert (o_ref == o_i8).mean() > 0.5
    # the quantized tree is cached on the model (one quantization per
    # params identity, per dtype slot)
    assert "int8" in m._serving_params_cache
    c0 = m._serving_params_cache["int8"]
    generate(m, p, max_new_tokens=8, weights_dtype="int8")
    assert m._serving_params_cache["int8"] is c0
    # np.int8 normalizes to the quantized path (an astype would zero
    # sub-1.0 float weights); other int dtypes are rejected
    o_np = generate(m, p, max_new_tokens=8, weights_dtype=np.int8)
    np.testing.assert_array_equal(o_np, o_i8)
    with pytest.raises(ValueError, match="weights_dtype"):
        generate(m, p, max_new_tokens=8, weights_dtype=np.int32)


@pytest.fixture(scope="module")
def long_memorized_lm():
    """A tiny LM overfit on LONG repetitions of the pattern (trained
    positions reach 160), so greedy rollouts keep large argmax margins
    for >= 140 steps — the horizon the int8-cache criterion needs.
    (The short ``memorized_lm`` only ever saw positions 0..10; its
    rollouts past there are near-ties where any rounding flips tokens.)"""
    S_train = 160
    X = np.tile(PATTERN, (192, S_train // len(PATTERN) + 2))[:, :S_train + 1]
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True),
        (S_train,), seed=2)
    m.fit(X[:, :-1], X[:, 1:], optimizer="adam", learning_rate=5e-3,
          batch_size=64, epochs=12,
          loss="sparse_categorical_crossentropy_from_logits")
    return m


@pytest.mark.slow
def test_int8_kv_cache_greedy_matches_bf16_cache(long_memorized_lm):
    """cache_dtype='int8' (per-token-per-head scales, round 4): greedy
    decoding from a trained model must match the full-precision cache
    token-for-token over >= 128 steps (VERDICT r3 'done' criterion). The
    long-memorized model keeps large argmax margins across the whole
    rollout, so any systematic quantization bias would surface as
    divergence."""
    prompts = np.tile(PATTERN[:4], (2, 1))
    n = 140
    o_ref = generate(long_memorized_lm, prompts, max_new_tokens=n,
                     temperature=0.0)
    # sanity: the reference rollout actually tracks the pattern (margins
    # are real, not noise) — else the equality below would be vacuous
    want = np.tile(PATTERN, n // len(PATTERN) + 2)[:4 + n]
    assert (np.asarray(o_ref[0]) == want).mean() > 0.9
    o_i8 = generate(long_memorized_lm, prompts, max_new_tokens=n,
                    temperature=0.0, cache_dtype="int8")
    assert o_i8.shape == (2, 4 + n)
    np.testing.assert_array_equal(o_i8, o_ref)


def test_int8_kv_cache_composes_with_gqa():
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_kv_heads=2,
                           num_layers=2, mlp_ratio=2),
        (S,), seed=3)
    p = np.random.RandomState(1).randint(0, V, (2, 6)).astype(np.int32)
    o_ref = generate(m, p, max_new_tokens=12)
    o_i8 = generate(m, p, max_new_tokens=12, cache_dtype="int8")
    assert o_i8.shape == o_ref.shape
    np.testing.assert_array_equal(o_i8[:, :6], p)
    # untrained ties can flip; but the cache machinery must agree mostly
    assert (o_ref == o_i8).mean() > 0.5


def test_int8_cache_quantization_roundtrip_error_bounded():
    from distkeras_tpu.models.decoding import _quantize_kv
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(2, 7, 3, 16), jnp.float32)
    q, s = _quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 7, 3)
    err = np.abs(np.asarray(q, np.float32) * np.asarray(s)[..., None]
                 - np.asarray(x))
    # max-abs scaling bounds the per-entry error at scale/2
    assert (err <= np.asarray(s)[..., None] * 0.5 + 1e-7).all()
    # zero vectors quantize to exactly zero (no 0/0)
    q0, s0 = _quantize_kv(jnp.zeros((1, 2, 1, 8)))
    assert float(jnp.max(jnp.abs(q0))) == 0.0 and \
        float(jnp.max(jnp.abs(s0))) == 0.0


def test_prefill_matches_stepwise_decode():
    """The batched prefill (one causal pass over the prompt) must hand the
    decode scan EXACTLY the state the token-by-token replay produces: a
    greedy generate() must equal a manual oracle that builds the cache
    with sequential decode_step calls over the prompt and then rolls out
    argmax tokens — including beyond any trained horizon (mechanics, not
    memorization). A 1-token prompt is the degenerate prefill."""
    from distkeras_tpu.models.decoding import _resolve_head_dims
    m = lm(use_rope=True)
    _resolve_head_dims(m.module, m.params)
    rs = np.random.RandomState(7)
    b, p_len, n = 2, 9, 6
    prompts = rs.randint(0, V, (b, p_len)).astype(np.int32)
    out = generate(m, prompts, max_new_tokens=n, temperature=0.0)

    cache = init_cache(m.module, b, p_len + n)
    logits = None
    for t in range(p_len):
        logits, cache = decode_step(m.module, m.params, m.state, cache,
                                    jnp.asarray(prompts[:, t]), t)
    toks = [np.asarray(jnp.argmax(logits, -1))]
    for j in range(1, n):
        logits, cache = decode_step(m.module, m.params, m.state, cache,
                                    jnp.asarray(toks[-1]), p_len + j - 1)
        toks.append(np.asarray(jnp.argmax(logits, -1)))
    oracle = np.concatenate([prompts, np.stack(toks, 1)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), oracle)

    out1 = generate(m, prompts[:, :1], max_new_tokens=3, temperature=0.0)
    assert out1.shape == (b, 4)


def test_prefill_writes_cache_identical_to_decode_steps():
    """Direct cache equivalence: prefill's batched K/V writes equal the
    sequential decode_step writes, bitwise in f32."""
    from distkeras_tpu.models.decoding import (_resolve_head_dims,
                                               prefill)
    m = lm(use_rope=True)
    _resolve_head_dims(m.module, m.params)
    toks = jax.random.randint(jax.random.PRNGKey(3), (2, S), 0, V)
    cache_a = init_cache(m.module, 2, S)
    _, cache_a = prefill(m.module, m.params, m.state, cache_a, toks)
    cache_b = init_cache(m.module, 2, S)
    for t in range(S):
        _, cache_b = decode_step(m.module, m.params, m.state, cache_b,
                                 toks[:, t], t)
    for ca, cb in zip(cache_a, cache_b):
        if ca is None:
            continue
        for key in ("k", "v"):
            np.testing.assert_allclose(np.asarray(ca[key], np.float32),
                                       np.asarray(cb[key], np.float32),
                                       atol=2e-5)


def test_generate_zero_new_tokens_returns_prompts_unchanged():
    """max_new_tokens=0 must be an identity (review r4: the clamped
    first-token write used to overwrite the final prompt position)."""
    m = lm()
    prompts = np.array([[3, 1, 4, 1, 5, 9]], np.int32)
    out = generate(m, prompts, max_new_tokens=0, temperature=0.0)
    np.testing.assert_array_equal(out, prompts)
    with pytest.raises(ValueError, match=">= 0"):
        generate(m, prompts, max_new_tokens=-1)


def test_top_p_confines_samples_to_the_nucleus():
    """Nucleus sampling (round 4): every draw lies in the smallest
    probability-sorted prefix reaching mass p; boundary construction
    matches the standard 'include the crossing token' rule."""
    from distkeras_tpu.models.decoding import _sample

    logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0, -8.0]])
    probs = np.asarray(jax.nn.softmax(logits, -1))[0]
    order = np.argsort(-probs)
    cum = np.cumsum(probs[order])
    p = 0.8
    nucleus = set(order[:int(np.searchsorted(cum, p) + 1)].tolist())
    draws = {int(_sample(logits, 1.0, None, jax.random.PRNGKey(s),
                         top_p=p)[0]) for s in range(300)}
    assert draws <= nucleus and len(draws) > 1, (draws, nucleus)
    # p=1.0 keeps everything reachable; composes with top_k
    draws_all = {int(_sample(logits, 1.0, None, jax.random.PRNGKey(s),
                             top_p=1.0)[0]) for s in range(400)}
    assert len(draws_all) >= 4
    draws_k = {int(_sample(logits, 1.0, 2, jax.random.PRNGKey(s),
                           top_p=0.99)[0]) for s in range(200)}
    assert draws_k <= {0, 1}


def test_generate_top_p_end_to_end():
    m = lm()
    prompts = np.array([[1, 2, 3]])
    out = generate(m, prompts, max_new_tokens=4, temperature=1.0,
                   top_p=0.9, seed=3)
    assert out.shape == (1, 7)
    out2 = generate(m, prompts, max_new_tokens=4, temperature=1.0,
                    top_p=0.9, seed=3)
    np.testing.assert_array_equal(out, out2)     # same seed, same draw
    with pytest.raises(ValueError, match="top_p"):
        generate(m, prompts, max_new_tokens=2, temperature=1.0, top_p=1.5)


# --- chunked prefill (round 5) ---------------------------------------------

def test_merge_attention_is_exact():
    """The lse merge of two disjoint-key partials must equal one softmax
    attention over the union (algebraic identity, checked to fp)."""
    from distkeras_tpu.models.decoding import _merge_attention
    rs = np.random.RandomState(0)
    q = rs.randn(2, 3, 5, 8).astype(np.float32)   # [B, H, S, D]
    k = rs.randn(2, 3, 16, 8).astype(np.float32)
    v = rs.randn(2, 3, 16, 8).astype(np.float32)

    def attn(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", jnp.exp(s - lse[..., None]), v)
        return o, lse

    o_full, _ = attn(q, k, v)
    o_a, l_a = attn(q, k[:, :, :7], v[:, :, :7])
    o_b, l_b = attn(q, k[:, :, 7:], v[:, :, 7:])
    merged = _merge_attention(o_a, l_a, o_b, l_b)
    np.testing.assert_allclose(np.asarray(merged), np.asarray(o_full),
                               atol=1e-5)


@pytest.mark.parametrize("kv_heads,cache_dtype", [
    (None, None), (2, None), (None, "int8")])
def test_chunked_prefill_matches_one_pass(kv_heads, cache_dtype):
    """generate(prefill_chunk=...) must reproduce the one-pass prefill's
    greedy tokens (the merge is exact; bf16 cache stores the same values
    either way). Covers MHA, GQA, and the int8 cache — for int8 the
    chunked prefix attends to QUANTIZED earlier entries (the standard
    serving contract), so logits differ slightly and the assertion is on
    continuation tokens of a memorized pattern, not bitwise logits."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4,
                           num_kv_heads=kv_heads, num_layers=2,
                           mlp_ratio=2, use_rope=True),
        (S,), seed=3)
    X = np.tile(PATTERN, (256, 1))
    m.fit(X[:, :-1], X[:, 1:], optimizer="adam", learning_rate=5e-3,
          batch_size=64, epochs=20,
          loss="sparse_categorical_crossentropy_from_logits")
    p_len = 28                     # not a multiple of chunk: ragged tail
    prompts = np.tile(PATTERN, (2, 3))[:, :p_len]
    kw = {} if cache_dtype is None else {"cache_dtype": cache_dtype}
    one = generate(m, prompts, max_new_tokens=9, temperature=0.0, **kw)
    chunked = generate(m, prompts, max_new_tokens=9, temperature=0.0,
                       prefill_chunk=8, **kw)
    match = float((np.asarray(one) == np.asarray(chunked)).mean())
    assert match >= (1.0 if cache_dtype is None else 0.95), \
        (one, chunked)


@pytest.mark.parametrize("kv_heads", [None, 2])
def test_chunked_prefill_cache_identical_to_one_pass(kv_heads):
    """The cache AND last-position logits the chunked prefill leaves
    behind must match the one-pass prefill's (same projections, same
    write positions — up to dot-tiling fp reassociation: the chunked
    projections contract over differently shaped operands). The GQA
    variant pins the prefix lse head-order flatten at tight tolerance —
    a memorized-pattern greedy match survives large attention errors
    and missed exactly this (review r5)."""
    from distkeras_tpu.models.decoding import (_resolve_head_dims,
                                               prefill, prefill_chunked)
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4,
                           num_kv_heads=kv_heads, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=5)
    _resolve_head_dims(m.module, m.params)
    prompts = jnp.asarray(
        np.random.RandomState(1).randint(0, V, (2, 20)), jnp.int32)
    c0 = init_cache(m.module, 2, 24)
    logits_a, cache_a = prefill(m.module, m.params, m.state, c0, prompts)
    logits_b, cache_b = prefill_chunked(m.module, m.params, m.state, c0,
                                        prompts, 8)
    np.testing.assert_allclose(np.asarray(logits_a),
                               np.asarray(logits_b), atol=2e-5)
    for a, b in zip(cache_a, cache_b):
        if a is None:
            assert b is None
            continue
        for key in a:
            np.testing.assert_allclose(np.asarray(a[key]),
                                       np.asarray(b[key]), atol=1e-5)


@pytest.mark.parametrize("attn_window,chunk", [
    (8, 8),    # window == chunk: band spans into the previous chunk
    (8, 4),    # window > chunk: band reaches two chunks back
    (3, 8),    # window < chunk: most queries never touch the band
])
def test_chunked_prefill_sliding_window_matches_one_pass(attn_window,
                                                         chunk):
    """SWA chunked prefill (round 5): windowed diagonal + masked prefix
    band must reproduce the one-pass windowed prefill's cache and
    logits (the band mask and the fully-masked-row merge are the parts
    a refactor would break)."""
    from distkeras_tpu.models.decoding import (_resolve_head_dims,
                                               prefill, prefill_chunked)
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_kv_heads=2,
                           num_layers=2, mlp_ratio=2, use_rope=True,
                           attn_window=attn_window),
        (S,), seed=6)
    _resolve_head_dims(m.module, m.params)
    prompts = jnp.asarray(
        np.random.RandomState(3).randint(0, V, (2, 27)), jnp.int32)
    c0 = init_cache(m.module, 2, 30)
    logits_a, cache_a = prefill(m.module, m.params, m.state, c0, prompts)
    logits_b, cache_b = prefill_chunked(m.module, m.params, m.state, c0,
                                        prompts, chunk)
    np.testing.assert_allclose(np.asarray(logits_a),
                               np.asarray(logits_b), atol=2e-5)
    for a, b in zip(cache_a, cache_b):
        if a is None:
            continue
        for key in a:
            np.testing.assert_allclose(np.asarray(a[key]),
                                       np.asarray(b[key]), atol=1e-5)


def test_generate_validates_prefill_chunk():
    m = lm()
    with pytest.raises(ValueError, match="prefill_chunk"):
        generate(m, np.zeros((1, 8), np.int32), max_new_tokens=2,
                 prefill_chunk=0)


def test_chunked_prefill_stop_on_first_token_pads_identically(
        memorized_lm):
    """prefill_chunked x stop_token interplay (this PR): when the very
    FIRST generated token — the one sampled from the prefill's last
    logits, before the decode scan runs — is the stop token, the
    chunked and one-pass prefills must produce identical padding (the
    done flag must be seeded from the first token on both paths)."""
    m = memorized_lm
    p_len = 9                              # not a chunk multiple
    prompts = np.tile(PATTERN[:p_len], (2, 1))
    # the memorized continuation's first token (inside the trained
    # horizon, so both prefill paths agree on it with a huge margin) —
    # make it the stop token: generation stops on token 1 and every
    # generated position must be the pad
    first = int(generate(m, prompts, max_new_tokens=1,
                         temperature=0.0)[0, p_len])
    assert first == PATTERN[p_len]         # margins are real
    one = generate(m, prompts, max_new_tokens=6, temperature=0.0,
                   stop_token=first)
    chunked = generate(m, prompts, max_new_tokens=6, temperature=0.0,
                       stop_token=first, prefill_chunk=4)
    np.testing.assert_array_equal(one, chunked)
    assert (np.asarray(one)[:, p_len:] == first).all()


# --- per-sequence sampling arrays (this PR) --------------------------------


def test_generate_per_seq_greedy_matches_scalar(memorized_lm):
    """A temperature VECTOR of zeros must reproduce the scalar greedy
    path token-for-token (same program semantics, traced knobs)."""
    prompts = np.tile(PATTERN[:4], (2, 1))
    ref = generate(memorized_lm, prompts, max_new_tokens=7,
                   temperature=0.0)
    vec = generate(memorized_lm, prompts, max_new_tokens=7,
                   temperature=np.zeros(2))
    np.testing.assert_array_equal(ref, vec)


def test_generate_per_seq_stop_token_pads_per_row(memorized_lm):
    """Row 0 stops on 9 (padding from there), row 1 never stops (-1
    sentinel) — the same call."""
    prompts = np.tile(PATTERN[:4], (2, 1))
    out = memorized_lm.generate(prompts, max_new_tokens=7,
                                temperature=0.0,
                                stop_token=np.array([9, -1]))
    np.testing.assert_array_equal(out[0, :6], PATTERN[:6])   # ...,5,9
    np.testing.assert_array_equal(out[0, 6:], np.full(5, 9))  # padded
    np.testing.assert_array_equal(out[1], PATTERN[:11])       # unstopped


def test_generate_per_seq_sampling_one_program_many_configs():
    """Per-sequence knobs are TRACED: different vector values reuse one
    compiled program; heterogeneous rows sample within their own
    truncation sets; scalar stop broadcasts alongside."""
    m = lm()
    prompts = np.array([[1, 2, 3], [4, 5, 6], [7, 8, 9]])
    out = generate(m, prompts, max_new_tokens=4,
                   temperature=np.array([0.0, 1.0, 1.0]),
                   top_k=np.array([0, 5, 2]), seed=7)
    assert out.shape == (3, 7)
    n_keys = len(m._jit_generate)
    out2 = generate(m, prompts, max_new_tokens=4,
                    temperature=np.array([0.0, 0.5, 2.0]),
                    top_k=np.array([0, 3, 1]), seed=7)
    assert len(m._jit_generate) == n_keys            # same program
    # greedy row is deterministic across configs
    np.testing.assert_array_equal(out[0], out2[0])
    # same call twice: same draws
    out3 = generate(m, prompts, max_new_tokens=4,
                    temperature=np.array([0.0, 0.5, 2.0]),
                    top_k=np.array([0, 3, 1]), seed=7)
    np.testing.assert_array_equal(out2, out3)


def test_generate_per_seq_validation():
    m = lm()
    prompts = np.array([[1, 2, 3], [4, 5, 6]])
    with pytest.raises(ValueError, match="temperature"):
        generate(m, prompts, max_new_tokens=2,
                 temperature=np.zeros(3))            # batch mismatch
    with pytest.raises(ValueError, match="top_p"):
        generate(m, prompts, max_new_tokens=2, temperature=1.0,
                 top_p=np.array([0.5, 1.5]))


def test_sample_vec_top_k_rank_mask_matches_top_k_ties():
    """The vector sampler's rank-based top_k admits exactly the scalar
    path's index-exact candidate set, ties included."""
    from distkeras_tpu.models.decoding import _sample_vec

    logits = jnp.asarray([[0.0, 5.0, 5.0, 5.0, -1.0]])  # 3-way tie, k=2
    idx = set(jax.device_get(jax.lax.top_k(logits, 2)[1][0]).tolist())
    draws = {
        int(_sample_vec(logits, jnp.ones(1), jnp.full((1,), 2),
                        jnp.ones(1), jax.random.PRNGKey(s))[0])
        for s in range(200)
    }
    assert draws == idx, f"sampled outside the top-2 set: {draws - idx}"
    # sentinel rows: top_k 0 keeps everything reachable, temperature 0
    # is greedy regardless of rng
    all_draws = {
        int(_sample_vec(logits, jnp.ones(1), jnp.zeros(1, jnp.int32),
                        jnp.ones(1), jax.random.PRNGKey(s))[0])
        for s in range(300)
    }
    assert len(all_draws) >= 4
    g = _sample_vec(logits, jnp.zeros(1), jnp.zeros(1, jnp.int32),
                    jnp.ones(1), jax.random.PRNGKey(0))
    assert int(g[0]) == 1                            # lowest tied index


# --- fused wqkv serving projection (round 5) -------------------------------

def test_fused_qkv_projection_matches_separate_gqa():
    """_project_qkv on a fused tree must reproduce the three separate
    projections exactly — the GQA slice offsets (q: [:H], k: [H:H+Hkv],
    v: [H+Hkv:]) are the part a refactor would silently break."""
    from distkeras_tpu.models.attention import TransformerBlock
    from distkeras_tpu.models.decoding import (_fuse_qkv_params,
                                               _project_qkv)
    from distkeras_tpu.models import Sequential

    block = TransformerBlock(num_heads=4, num_kv_heads=2, mlp_ratio=2,
                             causal=True, use_rope=True)
    module = Sequential([block])
    params, _, _ = module.init(jax.random.PRNGKey(0), (8, 32))
    block.attn.head_dim = int(params[0]["attn"]["wq"].shape[-1])
    fused = _fuse_qkv_params(module, params)
    assert "wqkv" in fused[0]["attn"] and "wq" not in fused[0]["attn"]
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 32))
    q0, k0, v0 = _project_qkv(block.attn, params[0]["attn"], x)
    q1, k1, v1 = _project_qkv(block.attn, fused[0]["attn"], x)
    np.testing.assert_allclose(np.asarray(q0), np.asarray(q1), atol=1e-6)
    np.testing.assert_allclose(np.asarray(k0), np.asarray(k1), atol=1e-6)
    np.testing.assert_allclose(np.asarray(v0), np.asarray(v1), atol=1e-6)


def test_generate_deep_cache_takes_fused_tree_and_matches_unfused():
    """Suite-level coverage of the fused serving path (review r5: the
    depth gate means no other test reaches it): total >= 1024 positions
    with weights_dtype='float32' (an identity cast, so fused-vs-master
    greedy tokens must agree) on a GQA model."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=16, num_heads=4, num_kv_heads=2,
                           num_layers=1, mlp_ratio=2, use_rope=True),
        (S,), seed=7)
    p = np.tile(PATTERN, (1, 90))[:, :1040].astype(np.int32)
    out_master = generate(m, p, max_new_tokens=4, temperature=0.0,
                          weights_dtype=None)
    out_fused = generate(m, p, max_new_tokens=4, temperature=0.0,
                         weights_dtype="float32")
    # the fused tree must actually be in play at this depth
    assert any("+wqkv" in k for k in m._serving_params_cache)
    # compare the FIRST new token only: greedy comparisons cascade on a
    # near-tie flip, so later positions are not independent evidence
    # (the fused projection's exact numerics are pinned by
    # test_fused_qkv_projection_matches_separate_gqa above)
    np.testing.assert_array_equal(np.asarray(out_master)[:, 1040],
                                  np.asarray(out_fused)[:, 1040])
    # short prompts at the same dtype stay on the UNFUSED base tree
    generate(m, p[:, :64], max_new_tokens=2, weights_dtype="float32")
    assert "float32" in m._serving_params_cache
