"""Host-KV offload hardening (tree-speculation PR satellites): the
ASYNC swap-out (D2H copies enqueue at preempt time and fence lazily at
the first restore/free touch — the preempt path no longer blocks the
serving iteration on a D2H round trip) and the PREFIX-AWARE swap
snapshot (pages still resident in the prefix cache are pinned by
refcount instead of duplicated to host, re-linked in place on resume —
closing the PR-17 private-duplicate trade-off)."""

import numpy as np
import pytest

import jax.numpy as jnp

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import generate
from distkeras_tpu.serving import NgramDraft, PagedKVPool, ServingEngine

V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    """The shared session-scoped overfit-PATTERN LM (conftest pattern_lm): huge greedy argmax margins keep token-identity assertions robust; trained once per test session."""
    return pattern_lm


@pytest.fixture()
def pool(memorized_lm):
    from distkeras_tpu.models.decoding import _resolve_head_dims
    _resolve_head_dims(memorized_lm.module, memorized_lm.params)
    return PagedKVPool(memorized_lm.module, num_slots=2, max_len=32,
                       page_len=4, host_pages=6)


def _fill_page(pool, pid, seed):
    """Deterministic nonzero content in one physical page."""
    rs = np.random.RandomState(seed)
    new = []
    for kv in pool.cache:
        if kv is None:
            new.append(kv)
            continue
        out = {}
        for key, arr in kv.items():
            row = rs.randn(*arr.shape[1:]).astype(np.float32)
            out[key] = arr.at[pid].set(jnp.asarray(row, arr.dtype))
        new.append(out)
    pool.cache = new


def _page_bytes(pool, pid):
    return [{k: np.asarray(v[pid]) for k, v in kv.items()}
            for kv in pool.cache if kv is not None]


# --- async swap-out (pool level) --------------------------------------------


def test_offload_is_lazy_and_restore_fences_byte_identically(pool):
    p0 = pool.alloc_page()
    p1 = pool.alloc_page()
    _fill_page(pool, p0, 0)
    _fill_page(pool, p1, 1)
    want0, want1 = _page_bytes(pool, p0), _page_bytes(pool, p1)
    hids = pool.offload_pages([p0, p1])
    # nothing fenced yet: the D2H is enqueued, not consumed
    assert pool.host_swap_pending == 2
    assert pool.host_fences == 0
    assert pool.pages_offloaded == 2
    # ... even if the source pages are overwritten afterwards (the
    # gather snapshotted them)
    _fill_page(pool, p0, 7)
    d0, d1 = pool.alloc_page(), pool.alloc_page()
    pool.restore_pages(hids, [d0, d1])
    assert pool.host_fences == 1
    assert pool.host_swap_pending == 0
    for got, want in zip(_page_bytes(pool, d0), want0):
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
    for got, want in zip(_page_bytes(pool, d1), want1):
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
    pool.free_host(hids)


def test_free_host_drops_unread_batch_without_fencing(pool):
    p0 = pool.alloc_page()
    _fill_page(pool, p0, 3)
    hids = pool.offload_pages([p0])
    assert pool.host_swap_pending == 1
    pool.free_host(hids)                 # never restored: just drop
    assert pool.host_fences == 0
    assert pool.host_swap_pending == 0
    assert pool.host_free_pages == pool.host_pages
    # double-free still loud
    with pytest.raises(RuntimeError, match="double-freed"):
        pool.free_host(hids)


def test_partially_freed_batch_fences_surviving_pages(pool):
    p0, p1 = pool.alloc_page(), pool.alloc_page()
    _fill_page(pool, p0, 4)
    _fill_page(pool, p1, 5)
    want1 = _page_bytes(pool, p1)
    hids = pool.offload_pages([p0, p1])
    pool.free_host(hids[:1])             # partial free: must fence
    assert pool.host_fences == 1
    assert pool.host_swap_pending == 0
    d1 = pool.alloc_page()
    pool.restore_pages(hids[1:], [d1])
    for got, want in zip(_page_bytes(pool, d1), want1):
        for k in want:
            np.testing.assert_array_equal(got[k], want[k])
    pool.free_host(hids[1:])


# --- async swap-out (engine level) ------------------------------------------


def test_preempt_heavy_loop_defers_the_fence(memorized_lm):
    """A preempt-heavy drive with the host tier: swap-outs enqueue
    without fencing inside the iteration (pending backlog observed
    while victims sit queued), every fence is paid by a resume, and
    outputs stay token-identical to generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        num_pages=8, prefix_cache=False,
                        host_kv_pages=16)
    r0 = eng.submit(np.tile(PATTERN, 2)[:5], 16)
    eng.step()
    eng.step()
    r1 = eng.submit(np.tile(PATTERN, 2)[:6], 15)
    max_pending = 0
    done = {}
    while eng.scheduler.pending:
        for r in eng.step():
            done[r.rid] = r
        max_pending = max(max_pending, eng.pool.host_swap_pending)
    assert eng.metrics.requests_preempted >= 1
    assert eng.pool.pages_offloaded > 0
    # the lazy contract: some iteration ran with an unfenced backlog,
    # and fences never exceed one per offload batch consumed
    assert max_pending > 0
    assert eng.pool.host_fences <= eng.metrics.requests_preempted
    np.testing.assert_array_equal(
        done[r0].tokens, generate(m, np.tile(PATTERN, 2)[None, :5], 16,
                                  temperature=0.0)[0])
    np.testing.assert_array_equal(
        done[r1].tokens, generate(m, np.tile(PATTERN, 2)[None, :6], 15,
                                  temperature=0.0)[0])


# --- prefix-aware swap snapshot ---------------------------------------------


def test_prefix_resident_pages_relink_instead_of_swapping(memorized_lm):
    """A victim whose context shares prefix-cache pages swaps only the
    PRIVATE remainder D2H; the shared pages take a refcount hold and
    re-link on resume, with refcounts returning to cache-only after
    the request drains."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        host_kv_pages=16)
    prompt = np.tile(PATTERN, 2)[:12]            # 3 full shared pages
    rA = eng.submit(prompt, 4)
    outA = eng.run(max_steps=400)                # registers the prefix
    np.testing.assert_array_equal(
        outA[rA], generate(m, prompt[None], 4, temperature=0.0)[0])
    assert len(eng.prefix) >= 3
    rB = eng.submit(prompt, 12)
    while eng[rB].state.value != "decoding":
        eng.step()
    eng.step()
    before_off = eng.pool.pages_offloaded
    req = eng[rB]
    eng._preempt(req)
    swap = req._swap
    assert swap is not None
    # prefix matches cap at len(prompt) - 1, so the final prompt page
    # is always private: 2 of the 3 full pages share
    assert len(swap["shared"]) >= 2
    shared_pids = [pid for _lp, pid in swap["shared"]]
    # shared pages pinned (cache ref + snapshot hold), not offloaded
    for pid in shared_pids:
        assert eng.pool.ref[pid] >= 2
        assert eng.prefix.resident(pid)
    assert eng.pool.pages_offloaded - before_off == len(swap["host"])
    assert len(swap["host"]) < len(shared_pids) + len(swap["host"]) \
        or not swap["host"]
    out = eng.run(max_steps=800)
    np.testing.assert_array_equal(
        out[rB], generate(m, prompt[None], 12, temperature=0.0)[0])
    # refcount regression: after the drain the shared pages are held
    # by the cache alone again
    for pid in shared_pids:
        assert eng.pool.ref[pid] == 1
    assert eng.pool.host_free_pages == eng.pool.host_pages


def test_host_full_fallback_rolls_back_shared_holds(memorized_lm):
    """When the host tier cannot take the PRIVATE remainder, the swap
    falls through to the re-prefill path — and the shared pages'
    snapshot holds are rolled back (no refcount leak), still
    token-identical."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        host_kv_pages=1)         # < private page count
    prompt = np.tile(PATTERN, 2)[:12]
    rA = eng.submit(prompt, 4)
    eng.run(max_steps=400)
    shared_before = {int(p): int(eng.pool.ref[p])
                     for p in list(eng.prefix._by_page)}
    rB = eng.submit(prompt, 12)
    while eng[rB].state.value != "decoding":
        eng.step()
    eng.step()
    req = eng[rB]
    eng._preempt(req)
    assert getattr(req, "_swap", None) is None   # host tier too small
    # no leaked snapshot holds: resident pages carry the cache ref
    # plus (at most) live slot refs — after the drain, cache-only
    out = eng.run(max_steps=800)
    np.testing.assert_array_equal(
        out[rB], generate(m, prompt[None], 12, temperature=0.0)[0])
    for pid in shared_before:
        if eng.prefix.resident(pid):
            assert eng.pool.ref[pid] == 1


def test_terminated_swap_releases_shared_holds(memorized_lm):
    """Cancelling a swapped-out victim drops the snapshot's refcount
    holds (shared pages fall back to cache-only) and frees its host
    pages without fencing them."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        host_kv_pages=16)
    prompt = np.tile(PATTERN, 2)[:12]
    rA = eng.submit(prompt, 4)
    eng.run(max_steps=400)
    rB = eng.submit(prompt, 12)
    while eng[rB].state.value != "decoding":
        eng.step()
    eng.step()
    req = eng[rB]
    eng._preempt(req)
    swap = req._swap
    assert swap is not None
    shared_pids = [pid for _lp, pid in swap["shared"]]
    fences = eng.pool.host_fences
    eng.cancel(rB)
    for pid in shared_pids:
        assert eng.pool.ref[pid] == 1            # cache-only again
    assert eng.pool.host_free_pages == eng.pool.host_pages
    assert eng.pool.host_fences == fences        # dropped, not fenced
