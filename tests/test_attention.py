"""Attention stack: SDPA reference, Pallas flash kernel (interpreter mode),
ring attention on the 8-device CPU mesh, RoPE, MoE, transformer LM."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from distkeras_tpu.compat import shard_map

from distkeras_tpu.models import Model, Sequential, TransformerBlock, zoo
from distkeras_tpu.models.attention import MultiHeadAttention
from distkeras_tpu.models.moe import MoE
from distkeras_tpu.ops.attention import (apply_rope, causal_mask,
                                         dot_product_attention)
from distkeras_tpu.ops.flash_attention import flash_attention
from distkeras_tpu.ops.ring_attention import ring_attention


def _rand_qkv(rng, b=2, s=16, h=2, d=8):
    ks = jax.random.split(rng, 3)
    return tuple(jax.random.normal(k, (b, s, h, d)) for k in ks)


def _naive_attention(q, k, v, causal=False):
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q, np.float64),
                  np.asarray(k, np.float64)) * scale
    if causal:
        mask = np.tril(np.ones((q.shape[1], k.shape[1]), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, np.asarray(v, np.float64))


@pytest.mark.parametrize("causal", [False, True])
def test_sdpa_matches_naive(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0))
    out = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out),
                               _naive_attention(q, k, v, causal), atol=1e-5)


def test_causal_mask_offsets():
    m = causal_mask(4, 4, q_offset=4, k_offset=0)
    assert bool(m.all())  # queries strictly after all keys
    m2 = causal_mask(4, 4, q_offset=0, k_offset=4)
    assert not bool(m2.any())


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_sdpa(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), b=1, s=32, h=2, d=8)
    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_nondivisible_seq_padded(causal):
    # seq length 20 does not divide block 8 — exercised via the pad path
    q, k, v = _rand_qkv(jax.random.PRNGKey(12), b=1, s=20, h=1, d=8)
    out = flash_attention(q, k, v, causal=causal, block_q=8, block_k=8,
                          interpret=True)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    g1 = jax.grad(lambda a, b, c: jnp.sum(jnp.square(flash_attention(
        a, b, c, causal=causal, block_q=8, block_k=8,
        interpret=True))), argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda a, b, c: jnp.sum(jnp.square(
        dot_product_attention(a, b, c, causal=causal))),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_flash_gradients_match():
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), b=1, s=16, h=1, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(jnp.square(flash_attention(
            q, k, v, causal=True, block_q=8, block_k=8, interpret=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(
            dot_product_attention(q, k, v, causal=True)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal, devices):
    n = len(devices)
    mesh = Mesh(np.array(devices), ("seq",))
    b, s, h, d = 2, 8 * n, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b=b, s=s, h=h, d=d)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="seq", causal=causal),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"))
    out = jax.jit(ring)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_size", [None, 8])
def test_ring_gradients_match_full(causal, block_size, devices):
    """Custom-VJP ring backward vs dense-attention autodiff oracle."""
    n = len(devices)
    mesh = Mesh(np.array(devices), ("seq",))
    b, s, h, d = 2, 16 * n, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), b=b, s=s, h=h, d=d)

    ring = shard_map(
        functools.partial(ring_attention, axis_name="seq", causal=causal,
                          block_size=block_size),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring(q, k, v)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(
            dot_product_attention(q, k, v, causal=causal)))

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-4)


def test_ring_gradients_match_loop_autodiff(devices):
    """Custom backward vs plain autodiff through the same ring loop."""
    n = len(devices)
    mesh = Mesh(np.array(devices), ("seq",))
    q, k, v = _rand_qkv(jax.random.PRNGKey(8), b=1, s=8 * n, h=2, d=8)

    def make(use_custom):
        ring = shard_map(
            functools.partial(ring_attention, axis_name="seq", causal=True,
                              use_custom_vjp=use_custom),
            mesh=mesh, in_specs=(P(None, "seq"),) * 3,
            out_specs=P(None, "seq"))
        return jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(jnp.square(ring(q, k, v))),
            argnums=(0, 1, 2)))

    for a, b_ in zip(make(True)(q, k, v), make(False)(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=2e-5)


def test_ring_backward_residuals_ring_independent(devices):
    """The saved-for-backward bytes per device must not scale with the
    ring size (the point of the custom VJP: autodiff through the ppermute
    loop would stash one rotated K/V copy per hop)."""
    from distkeras_tpu.ops.ring_attention import _ring_fwd_rule

    per_device = {}
    for n in (2, 4, 8):
        mesh = Mesh(np.array(devices[:n]), ("seq",))
        b, s_local, h, d = 2, 16, 2, 8  # fixed LOCAL shard size

        def fwd(q, k, v):
            out, res = _ring_fwd_rule(q, k, v, None, d ** -0.5, True,
                                      None, "seq")
            return res[:5]   # segment_ids residual is None here

        specs = (P(None, "seq"),) * 3
        shp = jax.ShapeDtypeStruct((b, s_local * n, h, d), jnp.float32)
        res = jax.eval_shape(
            shard_map(fwd, mesh=mesh, in_specs=specs,
                      out_specs=(P(None, "seq"),) * 4
                      + (P(None, None, "seq"),)),
            shp, shp, shp)
        total = sum(int(np.prod(r.shape)) * r.dtype.itemsize
                    for r in jax.tree_util.tree_leaves(res))
        per_device[n] = total // n
    assert len(set(per_device.values())) == 1, per_device


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal, devices):
    from distkeras_tpu.ops.ulysses import ulysses_attention
    n = len(devices)
    mesh = Mesh(np.array(devices), ("seq",))
    b, s, h, d = 2, 4 * n, n, 8  # h must divide over the axis
    q, k, v = _rand_qkv(jax.random.PRNGKey(13), b=b, s=s, h=h, d=d)

    uly = shard_map(
        functools.partial(ulysses_attention, axis_name="seq", causal=causal),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"))
    out = jax.jit(uly)(q, k, v)
    ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_grad_matches_full(devices):
    from distkeras_tpu.ops.ulysses import ulysses_attention
    n = len(devices)
    mesh = Mesh(np.array(devices), ("seq",))
    q, k, v = _rand_qkv(jax.random.PRNGKey(14), b=1, s=2 * n, h=n, d=4)

    uly = shard_map(
        functools.partial(ulysses_attention, axis_name="seq", causal=True),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"))
    g1 = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(jnp.square(uly(q, k, v))),
        argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(
        lambda q, k, v: jnp.sum(jnp.square(
            dot_product_attention(q, k, v, causal=True))),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ulysses_rejects_indivisible_heads(devices):
    from distkeras_tpu.ops.ulysses import ulysses_attention
    n = len(devices)
    mesh = Mesh(np.array(devices), ("seq",))
    q, k, v = _rand_qkv(jax.random.PRNGKey(15), b=1, s=2 * n, h=n + 1, d=4)
    uly = shard_map(
        functools.partial(ulysses_attention, axis_name="seq"),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"))
    with pytest.raises(ValueError, match="divisible"):
        jax.jit(uly)(q, k, v)


def test_mha_ulysses_layer_matches_xla(devices):
    """MultiHeadAttention(attn_impl='ulysses') under shard_map matches the
    single-device xla path, including global RoPE positions."""
    n = len(devices)
    mesh = Mesh(np.array(devices), ("sp",))
    d_model, h, s, b = 16, n, 2 * n, 2
    x = jax.random.normal(jax.random.PRNGKey(16), (b, s, d_model))

    ref_layer = MultiHeadAttention(num_heads=h, causal=True, use_rope=True)
    params, state, _ = ref_layer.init(jax.random.PRNGKey(17),
                                      (b, s, d_model))
    ref, _ = ref_layer.apply(params, state, x)

    sp_layer = MultiHeadAttention(num_heads=h, causal=True, use_rope=True,
                                  attn_impl="ulysses", seq_axis_name="sp")
    fn = shard_map(
        lambda p, xx: sp_layer.apply(p, {}, xx)[0],
        mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp"))
    out = jax.jit(fn)(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_rope_preserves_norm_and_relative_phase():
    x = jax.random.normal(jax.random.PRNGKey(4), (1, 8, 2, 16))
    y = apply_rope(x)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(x), axis=-1),
        np.linalg.norm(np.asarray(y), axis=-1), atol=1e-4)
    # relative property: <rope(q)_i, rope(k)_j> depends only on i - j
    q = jnp.tile(x[:, :1], (1, 8, 1, 1))  # same content at all positions
    k = q
    qr, kr = apply_rope(q), apply_rope(k)
    dots = np.einsum("bqhd,bkhd->bqk", np.asarray(qr), np.asarray(kr))
    np.testing.assert_allclose(np.diag(dots[0], k=1),
                               np.full(7, dots[0, 0, 1]), rtol=1e-4)


def test_rope_explicit_positions_match_offset_slice():
    x = jax.random.normal(jax.random.PRNGKey(5), (1, 16, 1, 8))
    full = apply_rope(x)
    shard = apply_rope(x[:, 8:], positions=jnp.arange(8, 16))
    np.testing.assert_allclose(np.asarray(full[:, 8:]), np.asarray(shard),
                               atol=1e-5)


def test_moe_dense_vs_expert_parallel(devices):
    n = len(devices)
    mesh = Mesh(np.array(devices), ("expert",))
    d_model, e = 8, 2 * n
    moe_dense = MoE(e, 16, top_k=2)
    moe_ep = MoE(e, 16, top_k=2, expert_axis_name="expert")
    params, state, _ = moe_dense.init(jax.random.PRNGKey(6), (4, d_model))
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 4, d_model))

    ref, _ = moe_dense.apply(params, state, x)

    ep_fn = shard_map(
        lambda p, xx: moe_ep.apply(p, {}, xx)[0],
        mesh=mesh,
        in_specs=({"gate": P(), "w1": P("expert"), "b1": P("expert"),
                   "w2": P("expert"), "b2": P("expert")}, P()),
        out_specs=P())
    out = jax.jit(ep_fn)(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_topk_masks_routing():
    moe = MoE(8, 4, top_k=2)
    params, _, _ = moe.init(jax.random.PRNGKey(8), (4,))
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 4, 4))
    probs, _, _ = moe._gate_probs(x, params["gate"])
    nonzero = (np.asarray(probs) > 0).sum(-1)
    assert (nonzero == 2).all()
    np.testing.assert_allclose(np.asarray(probs).sum(-1), 1.0, atol=1e-6)


def test_moe_balance_loss_math():
    moe = MoE(8, 4, top_k=2, aux_loss_weight=0.01)
    params, state, _ = moe.init(jax.random.PRNGKey(8), (4,))
    assert "__aux_loss__" in state
    # uniform router (zero gate) -> balance loss exactly 1
    params["gate"] = jnp.zeros_like(params["gate"])
    x = jax.random.normal(jax.random.PRNGKey(9), (2, 16, 4))
    _, full, mask = moe._gate_probs(x, params["gate"])
    np.testing.assert_allclose(float(moe._balance_loss(full, mask)), 1.0,
                               atol=1e-5)
    # a collapsed router (expert 0 gets all prob, slots split 0/1)
    # scores E * (0.5*1.0) = 4 — far above the uniform optimum of 1
    full_c = jnp.zeros((2, 16, 8)).at[..., 0].set(1.0)
    mask_c = (jnp.zeros((2, 16, 8), bool).at[..., 0].set(True)
              .at[..., 1].set(True))
    np.testing.assert_allclose(float(moe._balance_loss(full_c, mask_c)),
                               4.0, atol=1e-5)
    # aux only published in TRAINING mode
    _, st_eval = moe.apply(params, state, x, training=False)
    assert float(st_eval["__aux_loss__"]) == 0.0
    _, st_train = moe.apply(params, state, x, training=True)
    assert float(st_train["__aux_loss__"]) > 0.005  # ~0.01 * >=1


def test_moe_aux_loss_joins_training_loss():
    from distkeras_tpu.models.core import collect_aux_losses
    from distkeras_tpu.ops import get_loss, get_optimizer
    from distkeras_tpu.parallel.worker import TrainCarry, make_train_step

    tokens = jax.random.randint(jax.random.PRNGKey(11), (2, 8), 0, 17)

    losses = {}
    states = {}
    for w in (0.0, 0.1):
        spec = zoo.transformer_lm(17, d_model=16, num_heads=2, num_layers=2,
                                  mlp_ratio=2, moe_every=1, num_experts=4,
                                  moe_aux_loss_weight=w)
        model = Model.build(spec, (8,), seed=3)
        opt = get_optimizer("sgd", learning_rate=0.0)
        step = make_train_step(
            spec, get_loss("sparse_categorical_crossentropy_from_logits"),
            opt)
        carry = TrainCarry(model.params, model.state,
                           opt.init(model.params), jax.random.PRNGKey(0))
        new_carry, loss = step(carry, (tokens, tokens))
        losses[w] = float(loss)
        states[w] = new_carry.state
    aux = float(collect_aux_losses(states[0.1]))
    assert aux > 0.05  # two MoE blocks, each >= 0.1 * ~1.0... scaled
    np.testing.assert_allclose(losses[0.1] - losses[0.0], aux, rtol=1e-4)


def test_transformer_lm_forward_and_train_step():
    vocab, s = 31, 16
    spec = zoo.transformer_lm(vocab, d_model=32, num_heads=4, num_layers=2,
                              mlp_ratio=2)
    model = Model.build(spec, (s,), seed=0)
    tokens = jax.random.randint(jax.random.PRNGKey(10), (2, s), 0, vocab)
    logits, _ = spec.apply(model.params, model.state,
                           tokens, training=False)
    assert logits.shape == (2, s, vocab)

    # a couple of SGD steps reduce next-token loss
    from distkeras_tpu.ops import get_loss, get_optimizer
    loss_fn = get_loss("sparse_categorical_crossentropy_from_logits")
    opt = get_optimizer("adam", learning_rate=1e-2)

    def loss(params, x, y):
        out, _ = spec.apply(params, model.state, x, training=False)
        return loss_fn(y, out)

    x, y = tokens[:, :-1], tokens[:, 1:]
    params, opt_state = model.params, opt.init(model.params)
    l0 = float(loss(params, x, y))
    step = jax.jit(lambda p, o: _sgd_step(p, o, x, y, loss, opt))
    for _ in range(10):
        params, opt_state, _ = step(params, opt_state)
    assert float(loss(params, x, y)) < l0


def _sgd_step(params, opt_state, x, y, loss, opt):
    l, g = jax.value_and_grad(loss)(params, x, y)
    updates, opt_state = opt.update(g, opt_state, params)
    from distkeras_tpu.ops import apply_updates
    return apply_updates(params, updates), opt_state, l


def test_transformer_moe_lm_builds():
    spec = zoo.transformer_lm(17, d_model=16, num_heads=2, num_layers=2,
                              mlp_ratio=2, moe_every=2, num_experts=4)
    model = Model.build(spec, (8,), seed=0)
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits, _ = spec.apply(model.params, model.state, tokens)
    assert logits.shape == (1, 8, 17)


def test_transformer_block_serialization_roundtrip():
    from distkeras_tpu.models.serialization import (deserialize_model,
                                                    serialize_model)
    spec = Sequential([TransformerBlock(num_heads=2, mlp_ratio=2)])
    model = Model.build(spec, (8, 16), seed=1)
    blob = serialize_model(model)
    model2 = deserialize_model(blob)
    x = jax.random.normal(jax.random.PRNGKey(11), (2, 8, 16))
    y1, _ = model.module.apply(model.params, model.state, x)
    y2, _ = model2.module.apply(model2.params, model2.state, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-6)


def test_moe_topk_exact_on_tied_logits():
    # tied router logits (zero input -> all logits equal) must still
    # activate exactly top_k experts, not every tied one
    moe = MoE(8, 4, top_k=2)
    params, _, _ = moe.init(jax.random.PRNGKey(8), (4,))
    x = jnp.zeros((1, 4, 4))
    probs, _, _ = moe._gate_probs(x, params["gate"])
    nonzero = (np.asarray(probs) > 0).sum(-1)
    assert (nonzero == 2).all(), nonzero


def test_transformer_block_reinit_tracks_d_model():
    # re-initializing the same block instance at a different width must
    # resize the auto-resolved MLP (regression: stale cached hidden_dim)
    blk = TransformerBlock(num_heads=2, mlp_ratio=4)
    Model.build(Sequential([blk]), (8, 16), seed=0)
    assert blk.mlp.hidden_dim == 64
    m2 = Model.build(Sequential([blk]), (8, 32), seed=0)
    assert blk.mlp.hidden_dim == 128
    assert m2.params[0]["mlp"]["w1"].shape == (32, 128)


def test_positional_embedding_global_under_seq_sharding(devices):
    from distkeras_tpu.models.attention import PositionalEmbedding
    from jax.sharding import Mesh, PartitionSpec as P

    d, s, n = 4, 16, 8
    pe_global = PositionalEmbedding(s)
    pe_sharded = PositionalEmbedding(s, seq_axis_name="sp")
    params, _, _ = pe_global.init(jax.random.PRNGKey(0), (s, d))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, s, d))
    ref, _ = pe_global.apply(params, {}, x)

    mesh = Mesh(np.array(devices[:n]), ("sp",))
    fn = shard_map(
        lambda p, xx: pe_sharded.apply(p, {}, xx)[0],
        mesh=mesh, in_specs=(P(), P(None, "sp")), out_specs=P(None, "sp"))
    out = jax.jit(fn)(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_attention_init_uses_logical_2d_fans():
    # glorot limit must come from the logical (d_model, H*Dh) matrix, not
    # conv-kernel fan rules over the 3D shape (regression: ~6x-too-small init)
    mha = MultiHeadAttention(num_heads=8, head_dim=64)
    params, _, _ = mha.init(jax.random.PRNGKey(0), (16, 512))
    limit = np.sqrt(6.0 / (512 + 512))
    wq = np.asarray(params["wq"])
    assert wq.max() > 0.9 * limit, (wq.max(), limit)
    assert abs(wq).max() <= limit * 1.0001


def test_positional_embedding_undersized_table_raises(devices):
    from distkeras_tpu.models.attention import PositionalEmbedding
    from jax.sharding import Mesh, PartitionSpec as P

    pe = PositionalEmbedding(16, seq_axis_name="sp")  # global seq is 32
    params, _, _ = pe.init(jax.random.PRNGKey(0), (32, 4))
    x = jnp.zeros((1, 32, 4))
    mesh = Mesh(np.array(devices[:8]), ("sp",))
    fn = shard_map(
        lambda p, xx: pe.apply(p, {}, xx)[0],
        mesh=mesh, in_specs=(P(), P(None, "sp")), out_specs=P(None, "sp"))
    with pytest.raises(ValueError, match="too small"):
        jax.jit(fn)(params, x)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_pallas_backward_matches_oracles(causal):
    """The in-kernel backward (TPU default) must match both the XLA-scan
    backward and the reference SDPA gradients — including a sequence that
    doesn't divide the block sizes (pad-row handling in both passes)."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), b=2, s=44, h=2, d=8)
    co = jax.random.normal(jax.random.PRNGKey(9), q.shape)

    def grads(fn):
        return jax.grad(lambda a, b, c: jnp.sum(fn(a, b, c) * co),
                        argnums=(0, 1, 2))(q, k, v)

    ref = grads(lambda a, b, c: dot_product_attention(a, b, c,
                                                      causal=causal))
    pal = grads(lambda a, b, c: flash_attention(
        a, b, c, causal=causal, interpret=True, bwd="pallas",
        block_q=16, block_k=16))
    xla = grads(lambda a, b, c: flash_attention(
        a, b, c, causal=causal, interpret=True, bwd="xla",
        block_q=16, block_k=16))
    for p, x, r in zip(pal, xla, ref):
        np.testing.assert_allclose(p, r, atol=2e-5)
        np.testing.assert_allclose(p, x, atol=2e-5)

    with pytest.raises(ValueError, match="bwd must be"):
        flash_attention(q, k, v, interpret=True, bwd="fused")


@pytest.mark.parametrize("causal", [True, False])
def test_flash_bhsd_layout_matches_bshd(causal):
    """layout="bhsd" (the layer's transpose-free path) must match the
    default layout in both passes."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), b=2, s=40, h=2, d=8)
    co = jax.random.normal(jax.random.PRNGKey(4), q.shape)
    t = lambda x: x.transpose(0, 2, 1, 3)

    out_s = flash_attention(q, k, v, causal=causal, interpret=True,
                            block_q=16, block_k=16)
    out_h = flash_attention(t(q), t(k), t(v), causal=causal,
                            layout="bhsd", interpret=True,
                            block_q=16, block_k=16)
    np.testing.assert_allclose(t(out_h), out_s, atol=1e-6)

    gs = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal=causal, interpret=True, bwd="pallas",
        block_q=16, block_k=16) * co), argnums=(0, 1, 2))(q, k, v)
    gh = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal=causal, layout="bhsd", interpret=True,
        bwd="pallas", block_q=16, block_k=16) * t(co)),
        argnums=(0, 1, 2))(t(q), t(k), t(v))
    for a, b in zip(gh, gs):
        np.testing.assert_allclose(t(a), b, atol=2e-5)

    with pytest.raises(ValueError, match="layout must be"):
        flash_attention(q, k, v, layout="hbsd")


def test_gqa_trains_and_roundtrips(tmp_path):
    """GQA model family: k/v project to fewer heads, training works on
    every attention path, and the config serializes."""
    from distkeras_tpu.data import Dataset
    from distkeras_tpu.models import (Model, load_model, save_model, zoo)
    from distkeras_tpu.parallel import SingleTrainer

    rs = np.random.RandomState(0)
    toks = rs.randint(0, 16, (128, 8))
    m = Model.build(
        zoo.transformer_lm(16, d_model=16, num_heads=4, num_kv_heads=1,
                           num_layers=1, mlp_ratio=2), (8,), seed=0)
    tr = SingleTrainer(m, batch_size=16, num_epoch=2,
                       worker_optimizer="adam",
                       optimizer_kwargs={"learning_rate": 1e-2},
                       loss="sparse_categorical_crossentropy_from_logits")
    trained = tr.train(Dataset({"features": toks, "label": toks}))
    assert np.isfinite(tr.get_history().losses()).all()

    p = str(tmp_path / "gqa")
    save_model(trained, p)
    loaded = load_model(p)
    np.testing.assert_allclose(loaded.predict(toks[:4]),
                               trained.predict(toks[:4]), atol=1e-5)


def test_gqa_rejects_nonpositive_kv_heads():
    from distkeras_tpu.models.attention import MultiHeadAttention

    with pytest.raises(ValueError, match="positive divisor"):
        MultiHeadAttention(num_heads=8, num_kv_heads=0)
    with pytest.raises(ValueError, match="positive divisor"):
        MultiHeadAttention(num_heads=8, num_kv_heads=-4)


def test_rope_scale_interpolates_positions():
    """Linear position interpolation: scale=2 at position 2t equals
    scale=1 at position t, and a scaled model decodes consistently."""
    from distkeras_tpu.ops.attention import apply_rope

    x = jax.random.normal(jax.random.PRNGKey(0), (1, 4, 2, 8))
    a = apply_rope(x, positions=jnp.asarray([0, 2, 4, 6]), scale=2.0)
    b = apply_rope(x, positions=jnp.asarray([0, 1, 2, 3]), scale=1.0)
    np.testing.assert_allclose(a, b, atol=1e-6)

    from distkeras_tpu.models import Model, zoo
    from distkeras_tpu.models.decoding import generate

    m = Model.build(zoo.transformer_lm(16, d_model=16, num_heads=2,
                                       num_layers=1, mlp_ratio=2,
                                       rope_scale=4.0), (8,), seed=0)
    out = generate(m, np.zeros((1, 4), np.int32), max_new_tokens=4)
    assert out.shape == (1, 8)
    # config roundtrip carries the scale
    blk = next(l for l in m.module.layers
               if type(l).__name__ == "TransformerBlock")
    assert blk.get_config()["rope_scale"] == 4.0


@pytest.mark.parametrize("window", [1, 7, 16, 100])
def test_sliding_window_matches_banded_reference(window):
    """Causal sliding-window attention (fwd + both backwards) must equal
    an explicitly band-masked softmax reference, including windows larger
    than the sequence (== full causal) and non-divisible lengths."""
    from distkeras_tpu.ops.attention import NEG_INF

    B, S, H, D = 2, 44, 2, 8
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), b=B, s=S, h=H, d=D)
    co = jax.random.normal(jax.random.PRNGKey(8), q.shape)

    def banded(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        qp, kp = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
        allowed = (qp >= kp) & (kp > qp - window)
        w = jax.nn.softmax(jnp.where(allowed[None, None], s, NEG_INF), -1)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)

    out = flash_attention(q, k, v, causal=True, window=window,
                          interpret=True, block_q=16, block_k=16)
    np.testing.assert_allclose(out, banded(q, k, v), atol=1e-5)

    gr = jax.grad(lambda a, b, c: jnp.sum(banded(a, b, c) * co),
                  argnums=(0, 1, 2))(q, k, v)
    for bwd in ("pallas", "xla"):
        gw = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
            a, b, c, causal=True, window=window, interpret=True, bwd=bwd,
            block_q=16, block_k=16) * co), argnums=(0, 1, 2))(q, k, v)
        for x, y in zip(gw, gr):
            np.testing.assert_allclose(x, y, atol=2e-5)

    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, causal=False, window=window,
                        interpret=True)


@pytest.mark.parametrize("window,block_q,block_k",
                         [(8, 16, 8), (24, 8, 16), (3, 8, 8)])
def test_sliding_window_grid_remap_exact(window, block_q, block_k):
    """W << S exercises the shrunken, REMAPPED k/q grids (round 3): the
    k-axis grid covers only each q block's window reach, so correctness
    here proves the index-map clamping never drops or double-counts a
    block (fwd, dq, and the mirrored dk/dv sweeps)."""
    from distkeras_tpu.ops.attention import NEG_INF
    from distkeras_tpu.ops.flash_attention import (_window_kblocks,
                                                   _window_qblocks)

    B, S, H, D = 1, 128, 2, 8
    nk = S // block_k
    assert _window_kblocks(block_q, block_k, nk, window,
                           S // block_q) < nk  # remap on
    q, k, v = _rand_qkv(jax.random.PRNGKey(17), b=B, s=S, h=H, d=D)
    co = jax.random.normal(jax.random.PRNGKey(18), q.shape)

    def banded(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        qp, kp = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
        allowed = (qp >= kp) & (kp > qp - window)
        w = jax.nn.softmax(jnp.where(allowed[None, None], s, NEG_INF), -1)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)

    out = flash_attention(q, k, v, causal=True, window=window,
                          interpret=True, block_q=block_q,
                          block_k=block_k)
    np.testing.assert_allclose(out, banded(q, k, v), atol=1e-5)
    gr = jax.grad(lambda a, b, c: jnp.sum(banded(a, b, c) * co),
                  argnums=(0, 1, 2))(q, k, v)
    gw = jax.grad(lambda a, b, c: jnp.sum(flash_attention(
        a, b, c, causal=True, window=window, interpret=True, bwd="pallas",
        block_q=block_q, block_k=block_k) * co), argnums=(0, 1, 2))(q, k, v)
    for x, y in zip(gw, gr):
        np.testing.assert_allclose(x, y, atol=2e-5)


def test_sliding_window_model_trains_and_decodes():
    """attn_window on the LM family: training runs, decode_step masks the
    cache to the window and matches the full forward."""
    from distkeras_tpu.models import Model, zoo
    from distkeras_tpu.models.decoding import (decode_step, init_cache,
                                               _resolve_head_dims)

    S = 10
    m = Model.build(zoo.transformer_lm(16, d_model=16, num_heads=2,
                                       num_layers=1, mlp_ratio=2,
                                       attn_window=4), (S,), seed=0)
    _resolve_head_dims(m.module, m.params)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 16, (2, S))
    full = m.predict(toks)
    cache = init_cache(m.module, 2, S)
    steps = []
    for t in range(S):
        lg, cache = decode_step(m.module, m.params, m.state, cache,
                                jnp.asarray(toks[:, t]), t)
        steps.append(np.asarray(lg))
    np.testing.assert_allclose(np.stack(steps, axis=1), full, atol=2e-4)

    with pytest.raises(ValueError, match="causal"):
        from distkeras_tpu.models.attention import MultiHeadAttention
        MultiHeadAttention(num_heads=2, causal=False, attn_window=4)
