"""Paged-attention decode kernel vs the ``_gather_pages`` reference
(decode-kernel PR), in interpreter mode on the CPU mesh — the same
oracle pattern as ``test_decode_kernel.py``/``test_moe_fused.py``: the
kernel must reproduce the gather + masked-softmax readout the off-TPU
serving path runs, across GQA, int8, scrambled physical page order,
sentinel table entries and W > 1 verify windows, and end-to-end
through the serving engine (greedy token-identical, sampled
byte-identical to the gather engine)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import (_gather_pages, _quantize_kv,
                                           generate,
                                           verify_step_slots_paged)
from distkeras_tpu.ops.attention import NEG_INF
from distkeras_tpu.ops.paged_attention import (page_aligned,
                                               paged_decode_attention)
from distkeras_tpu.serving import ServingEngine


def _pool(rs, n_pages, hkv, page_len, d, int8=False):
    k = jnp.asarray(rs.randn(n_pages, hkv, page_len, d), jnp.float32)
    v = jnp.asarray(rs.randn(n_pages, hkv, page_len, d), jnp.float32)
    if not int8:
        return {"k": k, "v": v}
    qk, ks = _quantize_kv(k)
    qv, vs = _quantize_kv(v)
    return {"k": qk, "v": qv, "k_scale": ks, "v_scale": vs}


def _reference(q, kv, table, t, scale, window=None):
    """The gather-path readout: ``_gather_pages`` + the exact masked
    softmax of ``_slot_attn_readout`` (dequantized for int8), without
    the output projection."""
    view = _gather_pages(kv, jnp.asarray(table))
    k, v = view["k"], view["v"]
    if "k_scale" in view:
        k = k.astype(jnp.float32) * view["k_scale"][..., None]
        v = v.astype(jnp.float32) * view["v_scale"][..., None]
    L = k.shape[2]
    w_len = q.shape[1]
    qg = q.astype(jnp.float32) * scale               # [S, W, H, G, D]
    s = jnp.einsum("bqhgd,bhkd->bhgqk", qg, k,
                   preferred_element_type=jnp.float32)
    pos = t[:, None] + jnp.arange(w_len)
    valid = jnp.arange(L)[None, None, :] <= pos[:, :, None]
    if window is not None:
        valid &= jnp.arange(L)[None, None, :] > (pos - window)[:, :, None]
    s = jnp.where(valid[:, None, None, :, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhgqk,bhkd->bqhgd", w, v,
                      preferred_element_type=jnp.float32)


#: scrambled physical placement with sentinel (unallocated) entries —
#: logical page order must come from the TABLE, never from page ids
TABLE = np.array([[7, 2, 9, 10], [0, 5, 10, 10], [3, 1, 4, 6]],
                 np.int32)
T = np.array([20, 11, 30], np.int32)


@pytest.mark.parametrize("g", [1, 4])
@pytest.mark.parametrize("w_len", [1, 3])
def test_kernel_matches_gather_reference(g, w_len):
    rs = np.random.RandomState(0)
    kv = _pool(rs, 10, 2, 8, 16)
    q = jnp.asarray(rs.randn(3, w_len, 2, g, 16), jnp.float32)
    scale = 16 ** -0.5
    out = paged_decode_attention(q, kv["k"], kv["v"], T, TABLE,
                                 scale=scale, interpret=True)
    ref = _reference(q, kv, TABLE, T, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_kernel_window_masking():
    rs = np.random.RandomState(1)
    kv = _pool(rs, 10, 2, 8, 16)
    q = jnp.asarray(rs.randn(3, 2, 2, 2, 16), jnp.float32)
    scale = 16 ** -0.5
    out = paged_decode_attention(q, kv["k"], kv["v"], T, TABLE,
                                 scale=scale, window=6, interpret=True)
    ref = _reference(q, kv, TABLE, T, scale, window=6)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_kernel_int8_dequant_matches_dequantized_reference():
    # int8 page blocks need page_len % 32 (Mosaic sublane rule)
    rs = np.random.RandomState(2)
    kv = _pool(rs, 6, 2, 32, 16, int8=True)
    table = np.array([[4, 1, 6], [2, 0, 5]], np.int32)
    t = np.array([40, 70], np.int32)
    q = jnp.asarray(rs.randn(2, 3, 2, 2, 16), jnp.float32)
    scale = 16 ** -0.5
    out = paged_decode_attention(
        q, kv["k"], kv["v"], t, table, scale=scale,
        k_scale=kv["k_scale"], v_scale=kv["v_scale"], interpret=True)
    ref = _reference(q, kv, table, t, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)


def test_kernel_under_jit_with_traced_inputs():
    """t and table are traced arguments inside the engine's compiled
    step — the scalar-prefetch operands must accept them."""
    rs = np.random.RandomState(3)
    kv = _pool(rs, 10, 2, 8, 16)
    q = jnp.asarray(rs.randn(3, 1, 2, 2, 16), jnp.float32)
    scale = 16 ** -0.5

    @jax.jit
    def run(t, table):
        return paged_decode_attention(q, kv["k"], kv["v"], t, table,
                                      scale=scale, interpret=True)

    out = run(jnp.asarray(T), jnp.asarray(TABLE))
    ref = _reference(q, kv, TABLE, T, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_alignment_gate():
    """The tiling gate: unaligned page_len raises on the direct call
    (callers pre-check ``page_aligned`` and keep the gather path)."""
    assert page_aligned(16, quantized=False)
    assert not page_aligned(4, quantized=False)
    assert page_aligned(32, quantized=True)
    assert not page_aligned(16, quantized=True)
    rs = np.random.RandomState(4)
    kv = _pool(rs, 4, 2, 4, 16)
    q = jnp.asarray(rs.randn(1, 1, 2, 2, 16), jnp.float32)
    with pytest.raises(ValueError, match="kernel-tileable"):
        paged_decode_attention(q, kv["k"], kv["v"], np.array([3]),
                               np.array([[0]]), interpret=True)


# --- end-to-end: the serving engine with the kernel forced ----------------


V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    """The shared session-scoped overfit-PATTERN LM (conftest pattern_lm): huge greedy argmax margins keep token-identity assertions robust; trained once per test session."""
    return pattern_lm


def test_engine_kernel_greedy_matches_generate(memorized_lm):
    """decode_kernel="paged" (interpreter mode on CPU): greedy engine
    output through the kernel readout is token-identical to
    standalone generate() — the serving oracle, kernel edition."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=8,
                        decode_kernel="paged")
    r0 = eng.submit(PATTERN[:4], 7)
    r1 = eng.submit(PATTERN[:6], 5)
    out = eng.run(max_steps=500)
    np.testing.assert_array_equal(
        out[r0], generate(m, PATTERN[None, :4], 7, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[r1], generate(m, PATTERN[None, :6], 5, temperature=0.0)[0])


def test_engine_kernel_sampled_matches_gather_engine(memorized_lm):
    """A sampled stream decoded through the kernel draws the same
    bytes as through the gather path (the logits agree far inside
    the categorical draw's decision margins on this fixture)."""
    m = memorized_lm

    def drive(kernel):
        eng = ServingEngine(m, num_slots=2, max_len=32, page_len=8,
                            decode_kernel=kernel)
        rid = eng.submit(PATTERN[:4], 8, temperature=0.9, top_p=0.95,
                         seed=7)
        return eng.run(max_steps=500)[rid]

    np.testing.assert_array_equal(drive("paged"), drive("off"))


def test_verify_window_kernel_matches_gather(memorized_lm):
    """The speculative verify step ([S, W] window-causal) through the
    kernel equals the gather path on the same paged cache — the W > 1
    generalization the spec engine rides."""
    m = memorized_lm
    from distkeras_tpu.models.decoding import _resolve_head_dims
    from distkeras_tpu.serving.kv_pool import PagedKVPool
    _resolve_head_dims(m.module, m.params)
    pool = PagedKVPool(m.module, num_slots=2, max_len=32, page_len=8)
    # allocate every slot's pages so window writes land
    for slot in range(2):
        for lp in range(pool.pages_per_slot):
            pool.assign(slot, lp, pool.alloc_page())
    toks = jnp.asarray(np.array([[3, 1, 4], [5, 9, 2]], np.int32))
    t = jnp.asarray(np.array([5, 9], np.int32))
    outs = {}
    for kernel in (True, False):
        logits, _ = verify_step_slots_paged(
            m.module, m.params, m.state, pool.cache, toks, t,
            pool.device_tables(), pool.page_len, paged_kernel=kernel)
        outs[kernel] = np.asarray(logits)
    np.testing.assert_allclose(outs[True], outs[False], atol=1e-4)
    np.testing.assert_array_equal(outs[True].argmax(-1),
                                  outs[False].argmax(-1))
