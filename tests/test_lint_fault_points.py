"""tools/lint_fault_points.py wired into tier-1: every
``faults.point``/``faults.corrupt`` name in library code must appear
in the docs/resilience.md catalog table and vice versa — a renamed
injection site fails HERE instead of letting chaos schedules silently
no-op against the old name."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint_fault_points import (check, code_points,  # noqa: E402
                               doc_points, main)


def test_code_and_catalog_agree():
    findings = check()
    assert not findings, "\n".join(msg for _, msg in findings)
    assert main() == 0


def test_walk_finds_known_sites():
    pts = code_points(REPO / "distkeras_tpu")
    # the serving-chaos surface this PR scripts against
    for name in ("replica.die", "serving.prefill", "serving.decode",
                 "router.dispatch", "ckpt.write", "train.loss"):
        assert name in pts, name
    # every site is a file:line anchor
    assert all(":" in site for sites in pts.values() for site in sites)


def test_catalog_parser_reads_table_rows():
    doc = (REPO / "docs" / "resilience.md").read_text()
    names = doc_points(doc)
    assert "replica.die" in names
    assert "ckpt.d2h" in names
    # prose backticks and non-dotted cells are not catalog rows
    assert "faults" not in names


def test_undocumented_point_is_flagged(tmp_path):
    # negative injection: a point declared in code but missing from
    # the catalog must produce a finding naming its site
    src = tmp_path / "pkg"
    src.mkdir()
    (src / "mod.py").write_text(
        "from distkeras_tpu.resilience import faults\n"
        "def f():\n"
        "    faults.point('serving.prefill')\n"
        "    faults.point('totally.undocumented')\n")
    doc = ("| `serving.prefill`  | site | models |\n"
           "| `serving.vanished` | site | models |\n")
    findings = check(root=src, doc_text=doc)
    names = [n for n, _ in findings]
    assert "totally.undocumented" in names       # code, not catalog
    assert "serving.vanished" in names           # catalog, not code
    assert "serving.prefill" not in names
    undoc = next(m for n, m in findings if n == "totally.undocumented")
    assert "mod.py:4" in undoc
