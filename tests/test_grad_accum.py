"""Gradient accumulation: identical math to the full-batch step."""

import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.models import Dense, Model, Sequential
from distkeras_tpu.parallel import SingleTrainer, SPMDTrainer, make_mesh_2d


def problem(seed=0, N=512, D=8, C=3):
    rs = np.random.RandomState(seed)
    X = rs.randn(N, D).astype(np.float32)
    y = (X @ rs.randn(D, C)).argmax(-1)
    return Dataset({"features": X, "label": y}), D, C


KW = dict(batch_size=64, num_epoch=2, worker_optimizer="sgd",
          optimizer_kwargs={"learning_rate": 0.05},
          loss="sparse_categorical_crossentropy_from_logits",
          shuffle_each_epoch=False, metrics=["accuracy"])


def losses_for(accum):
    ds, D, C = problem()
    model = Model.build(Sequential([Dense(32, activation="tanh"),
                                    Dense(C)]), (D,), seed=7)
    tr = SingleTrainer(model, grad_accum_steps=accum, **KW)
    tr.train(ds)
    return tr.get_history().losses(), tr.get_history().metric("accuracy")


def test_accum_matches_full_batch_exactly():
    l1, a1 = losses_for(1)
    l4, a4 = losses_for(4)
    np.testing.assert_allclose(l1, l4, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(a1, a4, rtol=1e-5, atol=1e-6)


def test_accum_in_spmd_trainer():
    ds, D, C = problem(1, N=1024)
    model = Model.build(Sequential([Dense(32, activation="relu"),
                                    Dense(C)]), (D,), seed=0)
    tr = SPMDTrainer(model, mesh=make_mesh_2d({"workers": 2, "tp": 4}),
                     tp_axis="tp", grad_accum_steps=2,
                     **{**KW, "num_epoch": 6, "shuffle_each_epoch": True})
    trained = tr.train(ds)
    from distkeras_tpu.ops.metrics import accuracy
    assert float(accuracy(ds["label"],
                          trained.predict(ds["features"]))) > 0.8


def test_accum_validation():
    ds, D, C = problem()
    model = Model.build(Sequential([Dense(C)]), (D,), seed=0)
    with pytest.raises(ValueError, match=">= 1"):
        SingleTrainer(model, grad_accum_steps=0, **KW).train(ds)
    with pytest.raises(ValueError, match="divide into"):
        SingleTrainer(model, grad_accum_steps=7, **KW).train(ds)


def test_unsupported_trainers_reject_grad_accum():
    from distkeras_tpu.parallel import (AEASGD, EnsembleTrainer,
                                        HostAsyncTrainer)
    ds, D, C = problem()
    model = Model.build(Sequential([Dense(C)]), (D,), seed=0)
    for cls, kw in ((AEASGD, {"num_workers": 4}),
                    (EnsembleTrainer, {"num_models": 2}),
                    (HostAsyncTrainer, {"num_workers": 2})):
        tr = cls(model, grad_accum_steps=2, **{**KW, **kw})
        with pytest.raises(ValueError, match="does not support"):
            tr.train(ds)
