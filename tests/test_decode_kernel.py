"""Fused decode-attention Pallas kernel vs the einsum oracle (round 4).

The kernel runs in interpreter mode on the CPU mesh; the oracle is the
einsum decode path (``_decode_scores``/``_decode_mix`` + masked softmax)
that off-TPU serving uses. f32 everywhere (CPU XLA has no bf16 dot).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models.decoding import (_decode_mix, _decode_scores,
                                           _quantize_kv)
from distkeras_tpu.ops.attention import NEG_INF
from distkeras_tpu.ops.decode_attention import decode_attention


def _mk(bh=3, g=4, d=16, L=32, seed=0):
    rs = np.random.RandomState(seed)
    q = jnp.asarray(rs.randn(bh, g, d), jnp.float32)
    k = jnp.asarray(rs.randn(bh, L, d), jnp.float32)
    v = jnp.asarray(rs.randn(bh, L, d), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("t", [0, 7, 31])
@pytest.mark.parametrize("g", [1, 4])
def test_kernel_matches_oracle(t, g):
    q, k, v = _mk(g=g)
    scale = q.shape[-1] ** -0.5
    out = decode_attention(q, k, v, t, scale=scale, block_l=8,
                           interpret=True)
    # oracle directly: masked softmax attention over positions <= t
    s = jnp.einsum("bgd,bld->bgl", q * scale, k)
    s = jnp.where((jnp.arange(k.shape[1]) <= t)[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, -1)
    ref = jnp.einsum("bgl,bld->bgd", w, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_kernel_window_masking():
    q, k, v = _mk(seed=1)
    scale = q.shape[-1] ** -0.5
    t, win = 20, 6
    out = decode_attention(q, k, v, t, scale=scale, window=win,
                           block_l=8, interpret=True)
    s = jnp.einsum("bgd,bld->bgl", q * scale, k)
    pos = jnp.arange(k.shape[1])
    ok = (pos <= t) & (pos > t - win)
    s = jnp.where(ok[None, None], s, NEG_INF)
    ref = jnp.einsum("bgl,bld->bgd", jax.nn.softmax(s, -1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5)


def test_kernel_int8_dequant_matches_dequantized_oracle():
    q, k, v = _mk(seed=2)
    scale = q.shape[-1] ** -0.5
    t = 17
    qk, ks = _quantize_kv(k)
    qv, vs = _quantize_kv(v)
    out = decode_attention(q, qk, qv, t, scale=scale, block_l=8,
                           k_scale=ks, v_scale=vs, interpret=True)
    kd = qk.astype(jnp.float32) * ks[..., None]
    vd = qv.astype(jnp.float32) * vs[..., None]
    s = jnp.einsum("bgd,bld->bgl", q * scale, kd)
    s = jnp.where((jnp.arange(k.shape[1]) <= t)[None, None], s, NEG_INF)
    ref = jnp.einsum("bgl,bld->bgd", jax.nn.softmax(s, -1), vd)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4)


def test_kernel_rejects_unaligned_cache():
    q, k, v = _mk(L=30)
    with pytest.raises(ValueError, match="multiple of block_l"):
        decode_attention(q, k, v, 3, block_l=8, interpret=True)
    with pytest.raises(ValueError, match="no supported tile"):
        decode_attention(q, k, v, 3, interpret=True)


def test_kernel_under_scan_with_traced_t():
    """t is a traced scalar inside the decode scan — the scalar-prefetch
    operand must accept it."""
    q, k, v = _mk(seed=3)
    scale = q.shape[-1] ** -0.5

    def body(_, t):
        return None, decode_attention(q, k, v, t, scale=scale, block_l=8,
                                      interpret=True)

    _, outs = jax.lax.scan(body, None, jnp.arange(4, 8))
    for i, t in enumerate(range(4, 8)):
        s = jnp.einsum("bgd,bld->bgl", q * scale, k)
        s = jnp.where((jnp.arange(k.shape[1]) <= t)[None, None], s,
                      NEG_INF)
        ref = jnp.einsum("bgl,bld->bgd", jax.nn.softmax(s, -1), v)
        np.testing.assert_allclose(np.asarray(outs[i]), np.asarray(ref),
                                   atol=1e-5)
