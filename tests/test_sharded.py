"""Out-of-core training: ShardedDataset on Single and SPMD trainers.

Reference parity: Spark streams partitions from HDFS so dist-keras trains
on data that never fits one machine; here shards (npz/csv/loader thunks)
flow through the compiled epoch scan one at a time with background
prefetch (data/sharded.py)."""

import os

import numpy as np
import pytest

from distkeras_tpu.data import Dataset, ShardedDataset
from distkeras_tpu.models import Dense, Model, Sequential
from distkeras_tpu.ops.metrics import accuracy
from distkeras_tpu.parallel import DOWNPOUR, SingleTrainer
from distkeras_tpu.parallel.mesh import make_mesh_2d
from distkeras_tpu.parallel.spmd import SPMDTrainer

D, C = 8, 3


def make_arrays(n, seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(n, D).astype(np.float32)
    y = np.argmax(X @ rs.randn(D, C), axis=1)
    return X, y


def mlp(seed=0):
    return Model.build(Sequential([Dense(32, activation="relu"), Dense(C)]),
                       (D,), seed=seed)


def as_shards(X, y, k):
    n = len(X) // k
    return ShardedDataset.from_datasets([
        Dataset({"features": X[i * n:(i + 1) * n],
                 "label": y[i * n:(i + 1) * n]}) for i in range(k)])


def test_sharded_single_trainer_learns():
    X, y = make_arrays(512)
    sds = as_shards(X, y, 4)
    tr = SingleTrainer(mlp(), worker_optimizer="sgd", learning_rate=0.05,
                       loss="sparse_categorical_crossentropy_from_logits",
                       batch_size=32, num_epoch=8)
    trained = tr.train(sds)
    acc = float(accuracy(y, trained.predict(X)))
    assert acc > 0.85, acc
    # per-epoch history covers ALL shards: 512/32 = 16 steps per epoch
    assert len(tr.get_history().epochs) == 8
    assert len(tr.get_history().losses()) == 8 * 16


def test_sharded_matches_inmemory_when_unshuffled():
    """shards visited in order without shuffling ⇒ identical batch
    sequence ⇒ loss-for-loss identical to the in-memory run."""
    X, y = make_arrays(256, seed=1)
    kw = dict(worker_optimizer="sgd", learning_rate=0.05,
              loss="sparse_categorical_crossentropy_from_logits",
              batch_size=32, num_epoch=3, shuffle_each_epoch=False)
    t1 = SingleTrainer(mlp(seed=5), **kw)
    t1.train(Dataset({"features": X, "label": y}))
    t2 = SingleTrainer(mlp(seed=5), **kw)
    t2.train(as_shards(X, y, 4))
    np.testing.assert_allclose(t1.get_history().losses(),
                               t2.get_history().losses(), rtol=1e-5)


def test_sharded_from_npz_and_csv_files(tmp_path):
    X, y = make_arrays(128, seed=2)
    paths = []
    for i in range(2):
        p = str(tmp_path / f"shard-{i}.npz")
        sl = slice(i * 64, (i + 1) * 64)
        np.savez(p, features=X[sl], label=y[sl])
        paths.append(p)
    sds = ShardedDataset.from_files(paths)
    assert sds.num_shards == 2
    shard = sds.load_shard(1)
    np.testing.assert_array_equal(shard["features"], X[64:])

    with pytest.raises(FileNotFoundError):
        ShardedDataset.from_files([str(tmp_path / "missing.npz")])


def test_sharded_loader_thunks_and_shard_order():
    X, y = make_arrays(128, seed=3)
    calls = []

    def loader(i):
        def f():
            calls.append(i)
            sl = slice(i * 64, (i + 1) * 64)
            return Dataset({"features": X[sl], "label": y[sl]})
        return f

    sds = ShardedDataset([loader(0), loader(1)])
    order_a = sds.shard_order(0, seed=0, shuffle=True)
    order_b = sds.shard_order(0, seed=0, shuffle=True)
    assert order_a == order_b  # deterministic per (epoch, seed)
    assert sorted(order_a) == [0, 1]
    assert sds.shard_order(0, seed=0, shuffle=False) == [0, 1]
    sds.load_shard(0)
    assert calls == [0]  # lazy: only the requested shard loads


def test_sharded_spmd_trainer_learns():
    X, y = make_arrays(1024, seed=4)
    sds = as_shards(X, y, 4)
    mesh = make_mesh_2d({"workers": 4, "tp": 2})
    tr = SPMDTrainer(mlp(), mesh=mesh, tp_axis="tp", batch_size=64,
                     num_epoch=8, worker_optimizer="momentum",
                     optimizer_kwargs={"learning_rate": 0.1},
                     loss="sparse_categorical_crossentropy_from_logits")
    trained = tr.train(sds)
    acc = float(accuracy(y, trained.predict(X)))
    assert acc > 0.85, acc
    assert len(tr.get_history().epochs) == 8


def test_sharded_rejected_by_engine_trainers():
    X, y = make_arrays(128)
    tr = DOWNPOUR(mlp(), num_workers=8, batch_size=16,
                  communication_window=2, num_epoch=1,
                  loss="sparse_categorical_crossentropy_from_logits")
    with pytest.raises(ValueError, match="ShardedDataset"):
        tr.train(as_shards(X, y, 2))


def test_sharded_rejected_by_pipeline_trainer():
    from distkeras_tpu.models.attention import TransformerBlock
    from distkeras_tpu.models.layers import Dense as D_, Embedding
    from distkeras_tpu.parallel.pipeline import (PipelinedLM,
                                                 PipelineTrainer)
    X, y = make_arrays(128)
    mesh = make_mesh_2d({"workers": 2, "pp": 4})
    lm = PipelinedLM(embed=Embedding(8, 16),
                     block=TransformerBlock(num_heads=2, mlp_ratio=2),
                     head=D_(8, use_bias=False), num_layers=4,
                     num_microbatches=2)
    tr = PipelineTrainer(lm, mesh, batch_size=16, num_epoch=1)
    with pytest.raises(ValueError, match="ShardedDataset"):
        tr.train(as_shards(X, y, 2))


def test_sharded_resume_is_exact(tmp_path):
    """Out-of-core + full-carry checkpoints: crash+resume on a
    ShardedDataset is bitwise-identical to the uninterrupted run (the
    flat prefetch stream replays the same shard order and permutations)."""
    X, y = make_arrays(256, seed=9)
    sds = as_shards(X, y, 4)

    def make(num_epoch, ckpt=None, resume=False):
        return SingleTrainer(
            mlp(seed=9), batch_size=32, num_epoch=num_epoch,
            worker_optimizer="adam", learning_rate=0.01,
            loss="sparse_categorical_crossentropy_from_logits",
            checkpoint_dir=ckpt, resume=resume)

    uninterrupted = make(4).train(sds)
    ckpt = str(tmp_path / "ck")
    make(2, ckpt=ckpt).train(sds)            # "crash" after epoch 2
    resumed = make(4, ckpt=ckpt, resume=True).train(sds)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(uninterrupted.params),
                    jax.tree_util.tree_leaves(resumed.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_is_truthy_and_len_raises():
    X, y = make_arrays(64)
    sds = as_shards(X, y, 2)
    assert bool(sds)  # `if sds:` must work
    with pytest.raises(TypeError):
        len(sds)


def test_sharded_evaluate_matches_inmemory():
    X, y = make_arrays(128)
    m = mlp()
    full = m.evaluate(Dataset({"features": X, "label": y}),
                      loss="sparse_categorical_crossentropy_from_logits",
                      metrics=("accuracy",))
    sharded = m.evaluate(as_shards(X, y, 4),
                         loss="sparse_categorical_crossentropy_from_logits",
                         metrics=("accuracy",))
    for k in full:
        np.testing.assert_allclose(sharded[k], full[k], rtol=1e-5,
                                   err_msg=k)
    with pytest.raises(ValueError, match="decomposable"):
        m.evaluate(as_shards(X, y, 2), metrics=("precision",))


def test_sharded_write_roundtrip(tmp_path):
    X, y = make_arrays(100, seed=7)
    ds = Dataset({"features": X, "label": y})
    sds = ShardedDataset.write(ds, str(tmp_path / "out"), num_shards=3)
    assert sds.num_shards == 3
    back = sds.load_shard(0)
    for i in range(1, 3):
        back = back.concat(sds.load_shard(i))
    np.testing.assert_array_equal(back["features"], X)  # uneven split OK
    np.testing.assert_array_equal(back["label"], y)
    with pytest.raises(ValueError, match="shards"):
        ShardedDataset.write(ds, str(tmp_path / "o2"), num_shards=0)


def test_sharded_fit_and_callbacks():
    from distkeras_tpu.utils import EarlyStopping
    X, y = make_arrays(256, seed=6)
    m = mlp()
    hist = m.fit(as_shards(X, y, 2), optimizer="sgd",
                 loss="sparse_categorical_crossentropy_from_logits",
                 batch_size=32, epochs=20,
                 callbacks=[EarlyStopping(monitor="loss", min_delta=1e9,
                                          patience=1)])
    assert len(hist.epochs) == 2  # epoch 0 best, stop after 1 bad epoch