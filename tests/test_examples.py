"""Examples as integration tests — the reference's de-facto test strategy
(SURVEY §4: example notebooks exercised the full pipeline). Each example is
run in-process on tiny configurations so the suite keeps them green."""

import sys

import numpy as np
import pytest


def run_example(module, argv=("x",)):
    old = sys.argv
    sys.argv = list(argv)
    try:
        import importlib
        return importlib.import_module(module).main()
    finally:
        sys.argv = old


@pytest.mark.parametrize("trainer", ["single", "ensemble", "averaging",
                                     "downpour", "easgd", "aeasgd", "adag",
                                     "dynsgd"])
def test_mnist_workflow(trainer):
    acc = run_example("examples.mnist_workflow",
                      ("x", "--trainer", trainer, "--epochs", "2",
                       "--n", "2048"))
    assert acc > 0.75, (trainer, acc)


def test_lm_generate_example(capsys):
    acc = run_example("examples.lm_generate")
    out = capsys.readouterr().out
    assert "int8 vs f32" in out
    assert acc > 0.9, acc


def test_continuous_batching_example(capsys):
    matches = run_example("examples.continuous_batching")
    out = capsys.readouterr().out
    assert "token-identical to generate()" in out
    assert matches >= 3       # every greedy request passed its oracle


def test_speculative_serving_example(capsys):
    matches = run_example("examples.speculative_serving")
    out = capsys.readouterr().out
    assert "token-identical to generate()" in out
    assert "kicked back to plain decode" in out
    assert matches == 5       # every speculative request passed its oracle


def test_router_serving_example(capsys):
    matches = run_example("examples.router_serving")
    out = capsys.readouterr().out
    assert "token-identical to generate()" in out
    assert "prefix-affinity hit rates" in out
    assert "handed off, outputs token-identical" in out
    assert "failed over and completed token-identically" in out
    assert "'slow': 'drain'" in out and "'slow': 'resume'" in out
    assert "OK" in out
    assert matches == 11    # every oracle-checked request matched


def test_vit_finetune_callbacks_example(capsys):
    acc = run_example("examples.vit_finetune_callbacks")
    out = capsys.readouterr().out
    assert "epochs logged" in out
    assert acc > 0.85, acc


def test_streaming_inference_example(capsys):
    run_example("examples.streaming_inference")
    out = capsys.readouterr().out
    assert "streamed 10624 rows" in out


def test_large_model_spmd_example(capsys):
    run_example("examples.large_model_spmd")
    out = capsys.readouterr().out
    assert "next-token accuracy: 1.000" in out


def test_long_context_pipeline_example(capsys):
    run_example("examples.long_context_pipeline",
                ("x", "--seq", "64", "--epochs", "2"))
    assert "loss" in capsys.readouterr().out


def test_criteo_wide_deep_example():
    acc = run_example("examples.criteo_wide_deep")
    assert acc > 0.85, acc


def test_imagenet_resnet_spmd_example():
    acc = run_example("examples.imagenet_resnet_spmd",
                      ("x", "--n", "2048", "--epochs", "4", "--batch",
                       "32", "--fsdp"))
    assert acc > 0.9, acc


def test_higgs_physics_example(capsys):
    acc = run_example("examples.higgs_physics",
                      ("x", "--epochs", "4", "--n", "8192"))
    out = capsys.readouterr().out
    assert "ROC-AUC" in out
    assert acc > 0.8, acc


def test_packed_moe_serving_example(capsys):
    run_example("examples.packed_moe_serving")
    out = capsys.readouterr().out
    assert "cross-document logit leak" in out and "OK" in out


def test_moe_serving_example(capsys):
    matches = run_example("examples.moe_serving")
    out = capsys.readouterr().out
    assert "token-identical to generate()" in out
    assert "expert_load" in out and "moe_route" in out
    assert "expert-parallel decode over" in out
    assert matches == 4 and "OK" in out


def test_telemetry_tour_example(capsys):
    acc = run_example("examples.telemetry_tour")
    out = capsys.readouterr().out
    assert "unified telemetry snapshot" in out
    # the one-snapshot acceptance surface: rates, goodput, MFU,
    # per-function compile counts, prefetch stalls, serving percentiles
    for key in ("imgs_per_sec", "goodput", "mfu", "recompiles",
                "stall_s_total", "ttft_s_p50"):
        assert key in out, key
    assert "JSONL round-trip OK" in out
    assert acc > 0.7, acc


def test_request_tracing_example(capsys):
    served = run_example("examples.request_tracing")
    out = capsys.readouterr().out
    # the request-level acceptance surface: per-request timelines, the
    # Perfetto trace artifact, the SLO burn-rate report, and the
    # flight-recorder ring
    assert "request timelines" in out
    assert "Chrome trace:" in out and "Perfetto" in out
    assert "SLO report:" in out and "burn_rate" in out
    assert "flight recorder ring" in out
    assert "shed by bounded admission" in out
    assert served >= 5


def test_long_context_serving_example(capsys):
    run_example("examples.long_context_serving")
    out = capsys.readouterr().out
    assert "int8 KV cache greedy match vs bf16: 1.00" in out
    assert "ring attention + packed segment_ids" in out and "OK" in out
