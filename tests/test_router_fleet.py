"""Fleet elasticity (fleet-autoscale PR): mid-flight
``add_replica``/``remove_replica`` mutations, the AutoscaleController's
hysteresis loop, remaining-deadline propagation across migrations and
failover, and replica death inside a fused decode window or a tree
speculation — all under the router's token-identity oracle (every
surviving stream byte-identical to ``generate()`` / a single engine)."""

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.models.decoding import generate
from distkeras_tpu.resilience import faults
from distkeras_tpu.serving import (AdmissionRejected,
                                   AutoscaleController, ControllerChain,
                                   EngineReplica, NgramDraft,
                                   ReplicaState, RequestState, Router,
                                   ServingEngine, ServingMetrics,
                                   SLOBurnController)

PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    return pattern_lm


def _engine(m, eid, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 32)
    return ServingEngine(m, engine_id=eid, **kw)


def _steps(router, n, out=None):
    out = {} if out is None else out
    for _ in range(n):
        for g, req in router.step().items():
            out[g] = req
    return out


def _drive(router, warm_steps=0):
    out = _steps(router, warm_steps)
    while router.pending:
        for g, req in router.step().items():
            out[g] = req
    return out


PROMPTS = [PATTERN[:4], PATTERN[:6], PATTERN[:3], PATTERN[:5],
           PATTERN[:7], PATTERN[:5]]
BUDGETS = [7, 5, 9, 6, 4, 8]


def _refs(m):
    return [generate(m, PROMPTS[i][None], max_new_tokens=BUDGETS[i],
                     temperature=0.0)[0] for i in range(len(PROMPTS))]


def _sampled_ref(m, prompt, budget, seed, **kw):
    eng = ServingEngine(m, num_slots=1, max_len=32, **kw)
    rid = eng.submit(prompt, budget, temperature=0.9, top_p=0.95,
                     seed=seed)
    return eng.run(max_steps=500)[rid]


# --- add/remove mid-flight ---------------------------------------------------


def test_add_replica_mid_flight_serves_queued_backlog(memorized_lm):
    """Work queued behind a loaded 1-slot replica moves to a replica
    added MID-FLIGHT (factory form) and every stream stays
    byte-identical; the fleet views and counters track the mutation."""
    m = memorized_lm
    r = Router([EngineReplica(_engine(m, "af0", num_slots=1))])
    grids = [r.submit(PROMPTS[i], BUDGETS[i]) for i in range(4)]
    out = _steps(r, 2)
    assert any(r._requests[g].req.state is RequestState.QUEUED
               for g in grids if g in r._requests)
    rep = r.add_replica(lambda: EngineReplica(_engine(m, "af1")))
    assert rep.name == "af1" and rep.state is ReplicaState.SERVING
    assert r.fleet_counts()["serving"] == 2
    assert r.counters()["replicas_added"] == 1
    moved = r.rebalance_queued(r.replica("af0"))
    assert moved >= 1
    out.update(_drive(r))
    refs = _refs(m)
    for i, g in enumerate(grids):
        np.testing.assert_array_equal(out[g].tokens, refs[i])
    # the new replica actually served rebalanced work
    assert rep.engine.metrics.requests_finished >= 1
    assert [e for _, e, n in r.fleet_events if n == "af1"] == ["add"]


def test_add_replica_rejects_duplicates_and_accepts_instance(memorized_lm):
    m = memorized_lm
    r = Router([EngineReplica(_engine(m, "ai0"))])
    rep = r.add_replica(EngineReplica(_engine(m, "ai1")))
    assert rep.state is ReplicaState.SERVING
    with pytest.raises(ValueError, match="duplicate"):
        r.add_replica(EngineReplica(_engine(m, "x"), name="ai1"))
    g = r.submit(PROMPTS[0], BUDGETS[0])
    out = r.run(max_steps=500)
    np.testing.assert_array_equal(out[g], _refs(m)[0])


def test_remove_affinity_hottest_replica_token_identical(memorized_lm):
    """Remove the replica whose PrefixCache is hottest (both templates'
    home) while its streams are mid-decode and more sit queued:
    drain -> rebalance -> retire-when-empty, every request finishing
    byte-identically on the survivor, and the retired replica leaves
    the fleet views."""
    m = memorized_lm
    r = Router([EngineReplica(_engine(m, "rh0", num_slots=1,
                                      page_len=4)),
                EngineReplica(_engine(m, "rh1", num_slots=1,
                                      page_len=4))],
               policy="prefix_affinity")
    grids = [r.submit(PROMPTS[i], BUDGETS[i]) for i in range(4)]
    out = _steps(r, 3)
    by_rep = {}
    for g in grids:
        if g in r._requests:
            by_rep.setdefault(r._requests[g].replica.name, []).append(g)
    hottest = max(by_rep, key=lambda n: len(by_rep[n]))
    r.remove_replica(hottest)
    victim = next(x for x in r.replicas if x.name == hottest)
    assert victim.retiring
    assert victim.state is ReplicaState.DRAINING
    out.update(_drive(r))
    refs = _refs(m)
    for i, g in enumerate(grids):
        np.testing.assert_array_equal(out[g].tokens, refs[i])
    # retired: gone from the fleet, views consistent
    assert hottest not in {x.name for x in r.replicas}
    assert r.fleet_counts() == {"total": 1, "serving": 1, "starting": 0,
                                "draining": 0, "dead": 0}
    assert r.counters()["replicas_removed"] == 1
    assert any(e == "remove" and n == hottest
               for _, e, n in r.fleet_events)
    # aggregate_serving still sums the SURVIVING fleet
    agg = obs.aggregate_serving()
    assert agg["totals"]["requests_finished"] >= 1


def test_remove_replica_guards_last_survivor(memorized_lm):
    m = memorized_lm
    r = Router([EngineReplica(_engine(m, "lg0"))])
    with pytest.raises(ValueError):
        r.remove_replica("lg0")          # no admission-capable survivor
    with pytest.raises(KeyError):
        r.remove_replica("no-such-replica")
    # disaggregated: the only decode replica is also irremovable
    rd = Router([EngineReplica(_engine(m, "lgp"), role="prefill"),
                 EngineReplica(_engine(m, "lgd"), role="decode")])
    with pytest.raises(ValueError):
        rd.remove_replica("lgd")


def test_dead_replica_gc_via_remove_path(memorized_lm):
    """A DEAD replica is garbage-collected through the same
    remove/retire funnel: its in-flight work is already failed over,
    remove_replica() marks it retiring and the next step pops it."""
    m = memorized_lm
    try:
        r = Router([EngineReplica(_engine(m, "gc0")),
                    EngineReplica(_engine(m, "gc1"))])
        grids = [r.submit(PROMPTS[i], BUDGETS[i]) for i in range(4)]
        _steps(r, 2)
        faults.inject("replica.die", nth=1)
        out = _drive(r)
        dead = next(x for x in r.replicas
                    if x.state is ReplicaState.DEAD)
        r.remove_replica(dead.name)
        r.step()
        assert dead.name not in {x.name for x in r.replicas}
        assert r.fleet_counts()["dead"] == 0
        refs = _refs(m)
        for i, g in enumerate(grids):
            np.testing.assert_array_equal(out[g].tokens, refs[i])
    finally:
        faults.reset()


# --- deadline budget across migrations/failover ------------------------------


def _virtual_fleet(m, names, t, **kw):
    """Replicas sharing one controllable virtual clock (the replay
    discipline: deadlines and elapsed time derive from metrics.clock)."""
    reps = []
    for n in names:
        e = _engine(m, n, **kw)
        e.metrics = ServingMetrics(clock=lambda: t[0])
        reps.append(EngineReplica(e))
    return reps


def test_deadline_expires_mid_handoff_not_reset(memorized_lm):
    """The regression: a queued request whose deadline budget is
    already spent when it is HANDED OFF (rebalanced off a draining
    replica) must come back TIMED_OUT — before this PR the transfer
    re-stamped submit_t on the adopting engine, silently granting the
    stream a fresh deadline."""
    m = memorized_lm
    t = [0.0]
    r = Router(_virtual_fleet(m, ["dh0", "dh1"], t, num_slots=1),
               policy="least_loaded")
    g0 = r.submit(PROMPTS[0], BUDGETS[0])
    g1 = r.submit(PROMPTS[1], BUDGETS[1])
    _steps(r, 1)                     # both streams into their slots
    gq = r.submit(PROMPTS[2], BUDGETS[2], deadline_s=0.5)
    src = r._requests[gq].replica
    assert r._requests[gq].req.state is RequestState.QUEUED
    t[0] = 1.0                       # budget spent while queued
    src.drain()
    r.rebalance_queued(src)
    out = _drive(r)
    assert out[gq].state is RequestState.TIMED_OUT
    assert r.counters()["deadline_expired"] >= 1
    refs = _refs(m)
    np.testing.assert_array_equal(out[g0].tokens, refs[0])
    np.testing.assert_array_equal(out[g1].tokens, refs[1])


def test_failover_carries_remaining_deadline_budget(memorized_lm):
    """Replica death: the re-placed stream gets its REMAINING budget
    (original minus elapsed on the dead replica), not the original."""
    m = memorized_lm
    t = [0.0]
    try:
        r = Router(_virtual_fleet(m, ["db0", "db1"], t))
        g = r.submit(PROMPTS[0], BUDGETS[0], deadline_s=10.0)
        home = r._requests[g].replica
        _steps(r, 2)
        t[0] = 3.0
        # the fleet steps in list order, so arm the nth trigger to hit
        # the HOME replica specifically
        faults.inject("replica.die", nth=r.replicas.index(home) + 1)
        while r._requests.get(g) is not None \
                and r._requests[g].replica is home:
            r.step()
        tr = r._requests.get(g)
        if tr is not None:           # still in flight on the survivor
            assert tr.req.deadline_s == pytest.approx(7.0)
        out = _drive(r)
        assert out[g].state is RequestState.FINISHED
        np.testing.assert_array_equal(out[g].tokens, _refs(m)[0])
    finally:
        faults.reset()


# --- chaos inside fused decode / tree speculation ----------------------------


def test_death_during_fused_decode_failover_token_identical(memorized_lm):
    """Kill a replica while its streams decode through the FUSED
    multi-step window (fuse_steps=4): failover replays from the host
    token mirror byte-identically — the fused window must not have
    advanced state the router's request log doesn't know about."""
    m = memorized_lm
    try:
        r = Router([EngineReplica(_engine(m, "fd0", fuse_steps=4)),
                    EngineReplica(_engine(m, "fd1", fuse_steps=4))])
        grids = [r.submit(PROMPTS[i], BUDGETS[i]) for i in range(4)]
        gs = r.submit(PATTERN[:5], 8, temperature=0.9, top_p=0.95,
                      seed=5)
        out = _steps(r, 3)           # inside the fused windows
        faults.inject("replica.die", nth=1)
        out.update(_drive(r))
        refs = _refs(m)
        for i, g in enumerate(grids):
            np.testing.assert_array_equal(out[g].tokens, refs[i])
        np.testing.assert_array_equal(
            out[gs].tokens, _sampled_ref(m, PATTERN[:5], 8, seed=5))
        assert r.counters()["failovers"] >= 1
    finally:
        faults.reset()


def test_death_during_tree_speculation_failover_token_identical(
        memorized_lm):
    """Kill a replica mid tree-speculative decode (NgramDraft token
    trees): the survivor — itself speculating — continues every stream
    byte-identically from the seed-replayed request log."""
    m = memorized_lm
    kw = dict(draft=NgramDraft(), spec_k=3, spec_tree=True,
              spec_width=2)
    try:
        r = Router([EngineReplica(_engine(m, "td0", **kw)),
                    EngineReplica(_engine(m, "td1", **kw))])
        grids = [r.submit(PROMPTS[i], BUDGETS[i]) for i in range(4)]
        out = _steps(r, 3)
        faults.inject("replica.die", nth=1)
        out.update(_drive(r))
        refs = _refs(m)
        for i, g in enumerate(grids):
            np.testing.assert_array_equal(out[g].tokens, refs[i])
        assert r.counters()["failovers"] >= 1
    finally:
        faults.reset()


# --- AutoscaleController hysteresis ------------------------------------------


def _idle_router(m, names, **kw):
    return Router([EngineReplica(_engine(m, n, **kw)) for n in names])


def test_autoscale_scales_up_on_shed_and_respects_bounds(memorized_lm):
    """Shed onset is overload: after ``up_sustain`` consecutive
    overloaded ticks the controller adds a replica through the
    factory; the cooldown then blocks (and records) the next wish;
    ``max_replicas`` caps growth."""
    m = memorized_lm
    r = _idle_router(m, ["as0"], num_slots=1, max_queue=1)
    minted = []

    def factory():
        rep = EngineReplica(_engine(m, f"as{len(minted) + 1}",
                                    num_slots=1, max_queue=1))
        minted.append(rep)
        return rep

    ctl = AutoscaleController(r, factory, min_serving=1, max_replicas=2,
                              up_sustain=2, cooldown=3)
    kept = []

    def shed_once():
        # submit until every replica refuses: the admitted requests
        # are kept (they must still finish), the rejection is the
        # controller's shed-onset signal
        with pytest.raises(AdmissionRejected):
            for i in range(6):
                kept.append(r.submit(PROMPTS[i % len(PROMPTS)], 4))

    shed_once()
    assert ctl.tick() == {}                  # streak 1 of 2: no action
    shed_once()                              # fresh shed delta
    actions = ctl.tick()                     # streak 2: scale up
    assert actions.get("as1") == "add"
    assert len(r.replicas) == 2 and minted
    assert ctl.counts()["scale_up"] == 1
    # cooldown: the next sustained overload is BLOCKED and recorded
    shed_once()
    ctl.tick()
    shed_once()
    ctl.tick()
    assert ctl.counts()["blocked"] >= 1
    assert any(d["action"] == "blocked" and "cooldown" in d["reason"]
               for d in ctl.decisions)
    assert len(r.replicas) == 2              # max_replicas caps growth
    out = r.run(max_steps=2000)
    assert set(kept) <= set(out)             # admitted work all served


def test_autoscale_scales_down_after_sustained_idle(memorized_lm):
    """Sustained idle shrinks the fleet LIFO (controller-added replica
    first) down to ``min_serving``, where further wishes are blocked —
    a standing blocker records a bounded decision log, not one entry
    per tick."""
    m = memorized_lm
    r = _idle_router(m, ["sd0"])
    ctl = AutoscaleController(
        r, lambda: EngineReplica(_engine(m, "sd-added")),
        min_serving=1, max_replicas=2, idle_sustain=2, cooldown=0)
    added = r.add_replica(lambda: EngineReplica(_engine(m, "sd1")))
    ctl._added.append(added.name)            # adopt as controller-added
    acted = {}
    for _ in range(6):
        acted.update(ctl.tick())
        r.step()                             # lets retirement land
    assert acted.get("sd1") == "remove"
    assert "sd1" not in {x.name for x in r.replicas}
    assert ctl.counts()["scale_down"] == 1
    # at the floor: the wish is blocked once per refilled sustain
    # window (every idle_sustain ticks), not once per tick
    before = len(ctl.decisions)
    for _ in range(8):
        ctl.tick()
    blocked = [d for d in ctl.decisions[before:]
               if d["action"] == "blocked"]
    assert blocked and len(blocked) <= 8 // ctl.idle_sustain
    assert all("min_serving" in d["reason"] for d in blocked)
    # the fleet still serves
    g = r.submit(PROMPTS[0], BUDGETS[0])
    out = r.run(max_steps=500)
    np.testing.assert_array_equal(out[g], _refs(m)[0])


def test_autoscale_never_removes_draining_replica(memorized_lm):
    """Composition with the burn controller: while any replica is
    draining for SLO burn, scale-down is blocked — one replica cannot
    be both drained and retired, and drain-for-burn wins."""
    m = memorized_lm
    r = _idle_router(m, ["nd0", "nd1", "nd2"])
    ctl = AutoscaleController(
        r, lambda: EngineReplica(_engine(m, "nd-x")),
        min_serving=1, max_replicas=4, idle_sustain=1, cooldown=0)
    r.replica("nd2").drain()
    acted = {}
    for _ in range(3):
        acted.update(ctl.tick())
    assert "remove" not in acted.values()
    assert any(d["action"] == "blocked" and "draining" in d["reason"]
               for d in ctl.decisions)
    # resume: with nothing draining, idle shrink proceeds
    r.replica("nd2").resume()
    acted = {}
    for _ in range(3):
        acted.update(ctl.tick())
        r.step()
    assert "remove" in acted.values()


def test_controller_chain_merges_burn_and_autoscale(memorized_lm):
    """ControllerChain ticks burn first, autoscale second, and the
    router accepts the chain as its attached controller."""
    m = memorized_lm
    r = _idle_router(m, ["cc0", "cc1"])
    burn = SLOBurnController(r, min_serving=1)
    auto = AutoscaleController(
        r, lambda: EngineReplica(_engine(m, "cc-x")),
        min_serving=1, max_replicas=2, idle_sustain=1, cooldown=0)
    chain = ControllerChain(burn, auto)
    r.attach_controller(chain)
    actions = chain.tick()
    assert isinstance(actions, dict)
    g = r.submit(PROMPTS[0], BUDGETS[0])
    out = r.run(max_steps=2000)              # controller ticks inline
    np.testing.assert_array_equal(out[g], _refs(m)[0])


def test_retiring_replica_not_resumed_by_burn_controller(memorized_lm):
    m = memorized_lm
    r = _idle_router(m, ["rr0", "rr1"], num_slots=1)
    burn = SLOBurnController(r, min_serving=1)
    # load BOTH 1-slot replicas so rr1 has in-flight work and the
    # remove below leaves it in the retiring DRAINING window instead
    # of retiring instantly
    grids = [r.submit(PROMPTS[i], BUDGETS[i]) for i in range(2)]
    _steps(r, 1)
    r.remove_replica("rr1")
    rep = next(x for x in r.replicas if x.name == "rr1")
    assert rep.retiring and rep.state is ReplicaState.DRAINING
    burn._drained = {"rr1": True}            # claim drain ownership
    actions = burn.tick()
    assert actions.get("rr1") != "resume"
    out = _drive(r)                          # finishes, then retires
    assert "rr1" not in {x.name for x in r.replicas}
    refs = _refs(m)
    for i, g in enumerate(grids):
        np.testing.assert_array_equal(out[g].tokens, refs[i])
