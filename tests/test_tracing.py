"""Request-level tracing (``obs.tracing``): the per-request timeline,
its token-exact duration accounting, the Chrome/Perfetto trace export,
and the serving-engine integration points."""

import json

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.models import Model, zoo
from distkeras_tpu.obs.tracing import (NULL_TRACER, RequestTracer,
                                       resolve_tracer)
from distkeras_tpu.serving import ServingEngine, ServingMetrics


class FakeClock:
    """Deterministic injectable clock (monotonic; advance() moves it)."""

    def __init__(self):
        self.t = 100.0

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# --- tracer unit behavior ---------------------------------------------------


def test_timeline_durations_sum_exactly_to_latency():
    clk = FakeClock()
    tr = RequestTracer(clock=clk)
    tr.on_submit(0, queue_depth=3)
    clk.advance(0.5)                       # queued
    tr.on_admit(0, slot=1, queue_depth=2)
    clk.advance(0.25)                      # prefill
    tr.on_first_token(0)
    clk.advance(1.25)                      # decode
    tr.on_terminal(0, "finished", n_tokens=10)
    s = tr.summaries()[0]
    d = s["durations"]
    assert d["queued_s"] == pytest.approx(0.5)
    assert d["prefill_s"] == pytest.approx(0.25)
    assert d["ttft_s"] == pytest.approx(0.75)
    assert d["decode_s"] == pytest.approx(1.25)
    assert d["total_s"] == pytest.approx(2.0)
    # the token-exactness identity: phases partition the latency
    assert d["queued_s"] + d["prefill_s"] + d["decode_s"] \
        == pytest.approx(d["total_s"], abs=1e-12)
    assert s["slot"] == 1
    assert s["queue_depth_at_submit"] == 3
    assert s["queue_depth_at_admit"] == 2
    assert s["state"] == "finished" and s["n_tokens"] == 10


def test_timeline_terminated_mid_prefill_still_partitions_latency():
    """A request that dies after admission but before its first token
    attributes the admit->end span to prefill, so the sum-exactly
    invariant holds on every terminal path."""
    clk = FakeClock()
    tr = RequestTracer(clock=clk)
    tr.on_submit(0, 1)
    clk.advance(0.5)
    tr.on_admit(0, slot=0, queue_depth=0)
    clk.advance(0.75)                      # dies ingesting its prompt
    tr.on_terminal(0, "cancelled", 0)
    d = tr.summaries()[0]["durations"]
    assert d == {"queued_s": pytest.approx(0.5),
                 "prefill_s": pytest.approx(0.75),
                 "total_s": pytest.approx(1.25)}
    assert "ttft_s" not in d and "decode_s" not in d


def test_timeline_terminated_while_queued_has_no_slot_phases():
    clk = FakeClock()
    tr = RequestTracer(clock=clk)
    tr.on_submit(5, queue_depth=9)
    clk.advance(2.0)
    tr.on_terminal(5, "timed_out", n_tokens=0)
    d = tr.summaries()[5]["durations"]
    assert d == {"queued_s": pytest.approx(2.0),
                 "total_s": pytest.approx(2.0)}


def test_decode_events_aggregate_per_n_iterations():
    clk = FakeClock()
    tr = RequestTracer(clock=clk, decode_agg=4)
    tr.on_submit(0, 0)
    tr.on_admit(0, 0, 0)
    tr.on_first_token(0)
    for _ in range(10):
        clk.advance(0.01)
        tr.on_decode([0])
    tr.on_terminal(0, "finished", 11)
    (tl,) = tr.timelines()
    decode_events = [e for e in tl.events if e["name"] == "decode"]
    # 10 iterations at agg=4: two full windows + one terminal flush
    assert [e["iters"] for e in decode_events] == [4, 4, 2]
    assert tl.decode_iters == 10


def test_tracer_bounds_completed_timelines_and_events():
    tr = RequestTracer(max_requests=3, max_events=8)
    for rid in range(5):
        tr.on_submit(rid, 0)
        tr.on_admit(rid, 0, 0)
        for c in range(20):                 # far past max_events
            tr.on_prefill_chunk(rid, c, 1)
        tr.on_terminal(rid, "finished", 1)
    tls = tr.timelines()
    assert [t.rid for t in tls] == [2, 3, 4]   # ring: oldest evicted
    for t in tls:
        assert len(t.events) == 8
        assert t.summary()["dropped_events"] > 0
        assert t.prefill_chunks == 20           # counters stay exact


def test_events_for_unknown_rid_are_ignored():
    tr = RequestTracer()
    tr.on_first_token(42)
    tr.on_decode([42])
    tr.on_terminal(42, "finished", 1)
    assert tr.summaries() == {}


def test_resolve_tracer_policy():
    assert resolve_tracer(False) is NULL_TRACER
    t = RequestTracer()
    assert resolve_tracer(t) is t
    assert resolve_tracer(None).enabled
    obs.disable()
    try:
        assert resolve_tracer(None) is NULL_TRACER
    finally:
        obs.enable()


# --- Chrome trace export ----------------------------------------------------


def _flows(events, ph):
    return [e for e in events if e.get("ph") == ph]


def test_chrome_trace_one_complete_flow_per_request():
    clk = FakeClock()
    tr = RequestTracer(clock=clk)
    for rid in (0, 1):
        tr.on_submit(rid, rid)
        clk.advance(0.1)
        tr.on_admit(rid, rid, 0)
        clk.advance(0.1)
        tr.on_first_token(rid)
        clk.advance(0.1)
        tr.on_terminal(rid, "finished", 3)
    # a third request sheds in the queue: still one complete flow
    tr.on_submit(2, 5)
    clk.advance(0.05)
    tr.on_terminal(2, "cancelled", 0)
    ct = tr.chrome_trace()
    ct = json.loads(json.dumps(ct))        # validates as JSON
    events = ct["traceEvents"]
    starts, finishes = _flows(events, "s"), _flows(events, "f")
    assert sorted(e["id"] for e in starts) == [0, 1, 2]
    assert sorted(e["id"] for e in finishes) == [0, 1, 2]
    for s in starts:                       # each start has its finish
        (f,) = [f for f in finishes if f["id"] == s["id"]]
        assert f["ts"] >= s["ts"]
    # request tracks carry the three phase slices; slot tracks the
    # occupancy interval; a queued-only request has just "queued"
    names = {(e["pid"], e["tid"], e["name"]) for e in events
             if e.get("ph") == "X"}
    for rid in (0, 1):
        assert (1, rid, "queued") in names
        assert (1, rid, "prefill") in names
        assert (1, rid, "decode") in names
        assert (0, rid, f"req {rid}") in names
    assert (1, 2, "queued") in names
    assert not any(t == (1, 2, "prefill") for t in names)
    # durations are microseconds on the shared clock
    (q0,) = [e for e in events if e.get("ph") == "X"
             and e["pid"] == 1 and e["tid"] == 0
             and e["name"] == "queued"]
    assert q0["dur"] == pytest.approx(0.1 * 1e6)


def test_chrome_trace_dump_is_loadable_json(tmp_path):
    tr = RequestTracer()
    tr.on_submit(0, 0)
    tr.on_admit(0, 0, 0)
    tr.on_first_token(0)
    tr.on_terminal(0, "finished", 2)
    path = tr.dump_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as f:
        ct = json.load(f)
    assert ct["displayTimeUnit"] == "ms"
    assert any(e.get("ph") == "M" for e in ct["traceEvents"])


# --- engine integration -----------------------------------------------------

V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def tiny_lm():
    """Untrained tiny LM: tracing asserts timelines, not token values."""
    return Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=0)


def test_engine_timelines_are_token_exact_under_staggered_arrivals(
        tiny_lm):
    """The acceptance shape: a staggered-arrival run's per-request
    traces show admitted -> TTFT -> finish with durations summing
    (exactly — same clock on both sides) to the measured latency, and
    every request's decode-iteration count equals its generated tokens
    minus the prefill-sampled first one."""
    eng = ServingEngine(tiny_lm, num_slots=2, max_len=32)
    rids = [eng.submit(PATTERN[:4], 6), eng.submit(PATTERN[:6], 5)]
    eng.step()
    eng.step()
    rids += [eng.submit(PATTERN[:3], 7), eng.submit(PATTERN[:5], 4)]
    out = eng.run(max_steps=500)
    assert sorted(out) == sorted(rids)
    summ = eng.tracer.summaries()
    for i, rid in enumerate(rids):
        s = summ[rid]
        d = s["durations"]
        assert s["state"] == "finished"
        assert s["slot"] in (0, 1)
        # phases partition the request's life exactly
        assert d["queued_s"] + d["prefill_s"] + d["decode_s"] \
            == pytest.approx(d["total_s"], abs=1e-9)
        assert d["ttft_s"] == pytest.approx(
            d["queued_s"] + d["prefill_s"], abs=1e-9)
        # token-exact: one decode iteration per generated token after
        # the prefill-sampled first
        budget = [6, 5, 7, 4][i]
        assert s["n_tokens"] == budget
        assert s["decode_iters"] == budget - 1
    # the engine-measured latency histogram and the timeline totals are
    # the same numbers on the same clock; the edges are adjacent (not
    # shared) clock reads, so agreement is within clock tolerance
    lats = sorted(eng.metrics.latencies())
    totals = sorted(s["durations"]["total_s"] for s in summ.values())
    assert lats == pytest.approx(totals, abs=5e-3)
    # Chrome trace: one complete flow per request
    ct = json.loads(json.dumps(eng.tracer.chrome_trace()))
    starts = _flows(ct["traceEvents"], "s")
    finishes = _flows(ct["traceEvents"], "f")
    assert sorted(e["id"] for e in starts) == sorted(rids)
    assert sorted(e["id"] for e in finishes) == sorted(rids)


def test_engine_merges_request_summaries_into_component(tiny_lm):
    eng = ServingEngine(tiny_lm, num_slots=1, max_len=24)
    rid = eng.submit(PATTERN[:4], 3)
    eng.run(max_steps=200)
    # earlier engines may still be alive and own the plain "serving"
    # name; THIS engine's component is whichever serving* entry holds
    # our rid
    comps = obs.telemetry_snapshot()["components"]
    mine = [c for n, c in comps.items() if n.startswith("serving")
            and rid in c.get("requests", {})]
    assert len(mine) == 1
    assert mine[0]["requests"][rid]["state"] == "finished"


def test_engine_tracer_records_queue_depth_and_slot(tiny_lm):
    eng = ServingEngine(tiny_lm, num_slots=1, max_len=24)
    r0 = eng.submit(PATTERN[:4], 3)
    r1 = eng.submit(PATTERN[:4], 3)      # waits behind r0
    eng.run(max_steps=300)
    s0, s1 = eng.tracer.summaries()[r0], eng.tracer.summaries()[r1]
    assert s0["queue_depth_at_submit"] == 1   # itself, pre-admission
    assert s1["queue_depth_at_submit"] == 2
    assert s0["slot"] == 0 and s1["slot"] == 0  # slot recycled
    assert s1["durations"]["queued_s"] > 0


def test_engine_with_disabled_obs_uses_null_tracer(tiny_lm):
    obs.disable()
    try:
        eng = ServingEngine(tiny_lm, num_slots=1, max_len=24)
        assert eng.tracer is NULL_TRACER
        assert eng.scheduler.tracer is None
        eng.submit(PATTERN[:4], 2)
        eng.run(max_steps=200)
        assert eng.tracer.summaries() == {}
    finally:
        obs.enable()


def test_engine_cancel_and_timeout_land_in_timeline(tiny_lm):
    clk = FakeClock()
    metrics = ServingMetrics(clock=clk)
    eng = ServingEngine(tiny_lm, num_slots=1, max_len=24,
                        metrics=metrics)
    # tracer auto-created on the SAME injectable clock
    assert eng.tracer.clock is clk
    r0 = eng.submit(PATTERN[:4], 5, deadline_s=1.0)
    clk.advance(2.0)                       # expire before any work
    eng.step()
    s = eng.tracer.summaries()[r0]
    assert s["state"] == "timed_out"
    assert s["durations"]["total_s"] == pytest.approx(2.0)
    r1 = eng.submit(PATTERN[:4], 5)
    eng.cancel(r1)
    assert eng.tracer.summaries()[r1]["state"] == "cancelled"
