"""tools/lint_kernel_oracles.py wired into tier-1: every Pallas kernel
entry point in ``ops/`` must carry an interpret-mode oracle test (the
docs/testing.md convention), and the checker itself must detect the
gaps it claims to — negative injection below builds a synthetic repo
with an uncovered kernel and asserts the finding fires."""

import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint_kernel_oracles import (  # noqa: E402
    ALLOW_MARK, check_tree, kernel_entry_points)

KERNEL_MOD = textwrap.dedent("""
    from jax.experimental import pallas as pl

    def _kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def _launch(x):
        return pl.pallas_call(_kernel, out_shape=x)(x)

    def covered_op(x):
        return _launch(x)

    def naked_op(x):
        return _launch(x)

    def helper_without_kernel(n):
        return n % 128 == 0
""")


def _fake_repo(tmp_path, test_body):
    ops = tmp_path / "distkeras_tpu" / "ops"
    ops.mkdir(parents=True)
    (ops / "newkernel.py").write_text(KERNEL_MOD)
    tests = tmp_path / "tests"
    tests.mkdir()
    (tests / "test_newkernel.py").write_text(test_body)
    return tmp_path


def test_repo_kernels_all_have_interpret_oracles():
    findings = check_tree(REPO)
    assert not findings, "\n".join(
        f"{f}:{ln}: {msg}" for f, ln, msg in findings)


def test_entry_points_are_transitive_and_public_only():
    entries = [n for n, _ in kernel_entry_points(KERNEL_MOD, "m.py")]
    # covered_op/naked_op reach pallas_call through _launch; the
    # private helpers and the kernel-free public helper do not appear
    assert entries == ["covered_op", "naked_op"]


def test_negative_injection_uncovered_kernel_is_flagged(tmp_path):
    """A kernel module whose entry point no test names in an
    interpret-exercising file must produce a finding."""
    root = _fake_repo(tmp_path, textwrap.dedent("""
        from distkeras_tpu.ops.newkernel import covered_op

        def test_oracle():
            with force_interpret():
                covered_op(x)
    """))
    findings = check_tree(root)
    assert len(findings) == 1
    assert findings[0][2].startswith("kernel entry point 'naked_op'")


def test_name_mention_without_interpret_does_not_count(tmp_path):
    """Referencing the kernel in a test that never runs interpreter
    mode is not an oracle — both entries flag."""
    root = _fake_repo(tmp_path, textwrap.dedent("""
        from distkeras_tpu.ops.newkernel import covered_op, naked_op

        def test_shapes_only():
            assert covered_op is not naked_op
    """))
    assert {f[2].split("'")[1] for f in check_tree(root)} == \
        {"covered_op", "naked_op"}


def test_allow_mark_exempts_the_def_line(tmp_path):
    root = _fake_repo(tmp_path, "")
    mod = root / "distkeras_tpu" / "ops" / "newkernel.py"
    mod.write_text(KERNEL_MOD.replace(
        "def covered_op(x):",
        f"def covered_op(x):  # {ALLOW_MARK}: oracle rides on naked_op"
    ).replace(
        "def naked_op(x):",
        f"def naked_op(x):  # {ALLOW_MARK}: synthetic"))
    assert check_tree(root) == []


def test_syntax_error_is_its_own_finding(tmp_path):
    root = _fake_repo(tmp_path, "")
    (root / "distkeras_tpu" / "ops" / "broken.py").write_text(
        "def broken(:\n")
    findings = check_tree(root)
    assert any("syntax" in msg for _, _, msg in findings)
