"""Windowed time-series telemetry (``obs.timeseries``): the bounded
``Ring``, counter rates with the reset clamp, gauge levels, per-window
histogram percentiles from reservoir deltas, the interval gate, the
JSONL/Prometheus exports — and the serving integration: engine scrapes
ride the deferred host-window flush cadence (no new host syncs), and a
Router fleet's interleaved per-engine scrapes keep engine tags separate
while ``obs.aggregate_serving()`` totals match the per-replica sums."""

import json

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.obs.registry import MetricsRegistry
from distkeras_tpu.obs.timeseries import Ring, TimeSeries


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


# --- Ring -------------------------------------------------------------------


def test_ring_bounds_window_and_span():
    r = Ring(4)
    assert r.last() is None and len(r) == 0 and r.span_s() == 0.0
    for i in range(6):
        r.append(float(i), {"i": i})
    assert len(r) == 4                       # capacity-bounded
    assert [t for t, _ in r] == [2.0, 3.0, 4.0, 5.0]
    assert r.last()[1] == {"i": 5}
    assert [t for t, _ in r.window(3.0, 4.0)] == [3.0, 4.0]  # inclusive
    assert [t for t, _ in r.window(4.5)] == [5.0]
    assert r.span_s() == 3.0
    with pytest.raises(ValueError):
        Ring(0)


# --- scrape semantics -------------------------------------------------------


def test_counter_scrape_value_delta_rate():
    clk = FakeClock()
    reg = MetricsRegistry()
    c = reg.counter("t.count")
    ts = TimeSeries(reg, clock=clk)
    c.inc(5)
    e = ts.sample()["counters"]["t.count"][""]
    assert e == {"value": 5.0, "delta": 5.0, "rate": None}  # first scrape
    clk.advance(2.0)
    c.inc(4)
    e = ts.sample()["counters"]["t.count"][""]
    assert e["delta"] == 4.0 and e["rate"] == pytest.approx(2.0)


def test_counter_reset_clamp_on_registry_swap():
    """A shrinking counter means the backing registry was swapped (the
    engine's per-phase metrics windows): the clamp records the fresh
    level as the delta instead of a negative rate."""
    clk = FakeClock()
    box = [MetricsRegistry()]
    box[0].counter("t.count").inc(10)
    ts = TimeSeries(lambda: box[0], clock=clk)
    ts.sample()
    clk.advance(1.0)
    box[0] = MetricsRegistry()               # swap: counter back to 0
    box[0].counter("t.count").inc(3)
    e = ts.sample()["counters"]["t.count"][""]
    assert e == {"value": 3.0, "delta": 3.0, "rate": pytest.approx(3.0)}


def test_reset_baseline_after_deliberate_swap():
    """The clamp alone cannot see a swap whose new value coincidentally
    equals the old one — callers that swap the registry on purpose (the
    trace replayer's per-phase windows) call reset_baseline() so the
    next scrape starts from zero."""
    clk = FakeClock()
    box = [MetricsRegistry()]
    box[0].counter("t.count").inc(3)
    ts = TimeSeries(lambda: box[0], clock=clk)
    ts.sample()
    clk.advance(1.0)
    box[0] = MetricsRegistry()               # swap: same value reached
    box[0].counter("t.count").inc(3)
    ts.reset_baseline()
    e = ts.sample()["counters"]["t.count"][""]
    assert e == {"value": 3.0, "delta": 3.0, "rate": pytest.approx(3.0)}


def test_gauge_scrape_is_level():
    reg = MetricsRegistry()
    g = reg.gauge("t.depth")
    ts = TimeSeries(reg, clock=FakeClock())
    g.set(7.0)
    assert ts.sample()["gauges"]["t.depth"][""] == {"value": 7.0}
    g.set(2.0)
    assert ts.sample()["gauges"]["t.depth"][""] == {"value": 2.0}


def test_histogram_windowed_percentiles_from_reservoir_deltas():
    """Each scrape's histogram stats cover ONLY the observations since
    the previous scrape — not the cumulative distribution — with the
    exact window count from the streaming counter."""
    clk = FakeClock()
    reg = MetricsRegistry()
    h = reg.histogram("t.lat")
    ts = TimeSeries(reg, clock=clk)
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    e = ts.sample()["histograms"]["t.lat"][""]
    assert e["count"] == 3 and e["p50"] == pytest.approx(2.0)
    clk.advance(1.0)
    for v in (10.0, 20.0):                   # a much slower window
        h.observe(v)
    e = ts.sample()["histograms"]["t.lat"][""]
    assert e["count"] == 2
    assert e["p50"] == pytest.approx(15.0)   # window values only
    assert e["min"] == 10.0 and e["max"] == 20.0
    clk.advance(1.0)
    assert "t.lat" not in ts.sample()["histograms"]  # empty window


def test_interval_gate_and_extras():
    clk = FakeClock()
    reg = MetricsRegistry()
    reg.counter("t.c").inc()
    ts = TimeSeries(reg, clock=clk, interval_s=1.0)
    assert ts.maybe_sample(iteration=1) is not None
    clk.advance(0.5)
    assert ts.maybe_sample(iteration=2) is None       # too soon
    clk.advance(0.6)
    s = ts.maybe_sample(iteration=3)
    assert s is not None and s["iteration"] == 3
    assert len(ts.ring) == 2
    assert ts.series("t.c", field="value") == [(0.0, 1.0), (1.1, 1.0)]
    with pytest.raises(ValueError):
        TimeSeries(reg, interval_s=-1.0)


def test_summary_is_compact_and_json_safe():
    clk = FakeClock()
    reg = MetricsRegistry()
    reg.counter("t.c").inc()
    ts = TimeSeries(reg, clock=clk, interval_s=0.0, tags={"engine": "e0"})
    ts.sample(iteration=4)
    s = ts.summary()
    assert s["n_samples"] == 1 and s["tags"] == {"engine": "e0"}
    assert s["last_iteration"] == 4
    json.dumps(s)


# --- exports ----------------------------------------------------------------


def test_jsonl_export_is_forward_compatible(tmp_path):
    """New ``timeseries`` record types under the existing
    SCHEMA_VERSION: typed lines old readers skip, no version bump."""
    from distkeras_tpu.obs.exporters import SCHEMA_VERSION
    clk = FakeClock()
    reg = MetricsRegistry()
    reg.counter("t.c").inc(2)
    reg.gauge("t.g").set(1.5)
    ts = TimeSeries(reg, clock=clk)
    ts.sample()
    path = tmp_path / "ts.jsonl"
    ts.export_jsonl(str(path))
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert lines[0]["type"] == "meta"
    assert lines[0]["schema_version"] == SCHEMA_VERSION
    kinds = {ln["type"] for ln in lines[1:]}
    assert kinds == {"timeseries"}
    names = {ln["name"] for ln in lines[1:]}
    assert names == {"t.c", "t.g"}


def test_prometheus_text_is_timestamped():
    clk = FakeClock(5.0)
    reg = MetricsRegistry()
    reg.counter("t.c").inc(3)
    reg.histogram("t.lat").observe(0.5)
    ts = TimeSeries(reg, clock=clk)
    ts.sample()
    text = ts.prometheus_text()
    assert "distkeras_t_c" in text
    assert "_window_count" in text
    for line in text.splitlines():
        if line.startswith("#") or not line.strip():
            continue
        # exposition format: "name{labels} value timestamp_ms"
        assert line.split()[-1].lstrip("-").isdigit(), line


# --- serving integration ----------------------------------------------------


def test_engine_scrapes_on_host_window_cadence(pattern_lm):
    """The engine's TimeSeries samples land on the deferred host-window
    flush (and the final drain) — zero scrapes are taken anywhere else,
    and the telemetry snapshot carries the summary."""
    from distkeras_tpu.serving import ServingEngine
    eng = ServingEngine(pattern_lm, num_slots=2, max_len=32,
                        engine_id="ts-cadence")
    pattern = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])
    eng.submit(pattern[:8], 8)
    eng.run(max_steps=400)
    assert len(eng.timeseries.ring) >= 1
    finished = eng.timeseries.series("serving.requests_finished",
                                     field="value")
    assert finished[-1][1] == 1.0
    snap = obs.telemetry_snapshot()
    # match by engine_id: other tests' engines may still be attached
    # to the global component registry
    comp = next(v for k, v in snap["components"].items()
                if "ts-cadence" in k)
    assert comp["timeseries"]["n_samples"] == len(eng.timeseries.ring)


def test_engine_timeseries_opt_out_and_injection(pattern_lm):
    from distkeras_tpu.serving import ServingEngine
    eng = ServingEngine(pattern_lm, num_slots=1, max_len=32,
                        timeseries=False)
    assert eng.timeseries is None
    own = TimeSeries(MetricsRegistry(), clock=FakeClock())
    eng2 = ServingEngine(pattern_lm, num_slots=1, max_len=32,
                         timeseries=own)
    assert eng2.timeseries is own


def test_fleet_scrapes_separate_by_engine_and_sum_to_aggregate(pattern_lm):
    """Satellite: interleaved per-engine scrapes under a Router fleet.
    Each engine's samples carry its own tag and counters; the
    ``obs.aggregate_serving()`` fleet totals equal the sum of the
    per-replica counter values at the same point."""
    from distkeras_tpu.serving import Router, ServingEngine
    pattern = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])
    engines = [ServingEngine(pattern_lm, engine_id=f"tse{i}",
                             num_slots=2, max_len=32)
               for i in range(2)]
    router = Router(engines)
    for i in range(6):
        router.submit(np.tile(pattern, 2)[:8 + (i % 2) * 4], 6)
    steps = 0
    while router.pending:
        router.step()
        steps += 1
        assert steps < 500
    # drain both engines' deferred windows, then scrape once more so
    # the final counters are visible in each ring
    for eng in engines:
        eng._flush_pending()
        eng._flush_host_window()
        eng.timeseries.sample()
    per_engine = {}
    for eng in engines:
        tag = eng.timeseries.tags["engine"]
        assert tag == eng.engine_id            # tags separate cleanly
        finished = eng.timeseries.series("serving.requests_finished",
                                         field="value")
        per_engine[tag] = finished[-1][1]
    assert set(per_engine) == {e.engine_id for e in engines}
    # aggregate over exactly this fleet's components (other tests may
    # have live engines attached to the global snapshot)
    snap = obs.telemetry_snapshot()
    mine = {k: v for k, v in snap["components"].items()
            if any(e.engine_id in k for e in engines)}
    assert len(mine) == 2
    agg = obs.aggregate_serving({"components": mine})
    assert agg["totals"]["requests_finished"] == \
        pytest.approx(sum(per_engine.values()))
    assert sum(per_engine.values()) == 6.0
