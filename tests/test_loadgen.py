"""Production-shaped traffic simulator (``serving.loadgen``) and the
scenario SLO report (``obs.report``): seeded synthesis is bit-identical
and JSONL round-trips; a replay through the engine is deterministic —
two replays of the same trace on identically-configured fresh engines
produce identical outcomes, token CRCs, and per-phase report numbers;
overload sheds deterministically and the report detects the onset; the
same contract holds through a Router fleet."""

import copy
import dataclasses
import gc
import json

import numpy as np
import pytest

from distkeras_tpu.obs import report as scenario_report
from distkeras_tpu.obs.slo import availability, ttft_p99
from distkeras_tpu.resilience import faults
from distkeras_tpu.serving import (AutoscaleController, ChaosSpec,
                                   EngineReplica, PhaseSpec, Router,
                                   ServingEngine, TenantSpec,
                                   Trace, WorkloadSpec,
                                   diurnal_burst_scenario, replay,
                                   synthesize)

PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


def _spec(**kw):
    kw.setdefault("vocab", 29)
    kw.setdefault("scale", 0.3)
    kw.setdefault("prompt_max", 16)
    kw.setdefault("output_max", 8)
    return diurnal_burst_scenario(**kw)


# --- synthesis --------------------------------------------------------------


def test_synthesize_is_seed_deterministic_and_shaped():
    spec = _spec()
    t1, t2 = synthesize(spec, seed=7), synthesize(spec, seed=7)
    assert t1 == t2
    assert t1 != synthesize(spec, seed=8)
    assert len(t1.requests) > 5
    names = [p.name for p in t1.phases]
    assert names == ["ramp_up", "steady", "burst", "recovery", "flash",
                     "cooldown"]
    q = spec.length_quantum
    for r in t1.requests:
        assert 1 <= len(r.prompt) <= spec.prompt_max
        assert len(r.prompt) % q == 0         # quantized prompt lengths
        assert 1 <= r.max_new_tokens <= spec.output_max
        assert all(1 <= tok < spec.vocab for tok in r.prompt)
        assert r.tenant in ("interactive", "standard", "batch")
    # arrivals are ordered and inside the phase spans
    spans = {p.name: (p.start, p.end) for p in t1.phases}
    for r in t1.requests:
        lo, hi = spans[r.phase]
        assert lo <= r.arrival < hi
    # the burst phase offers a higher rate than steady
    by_phase = {n: 0 for n in names}
    for r in t1.requests:
        by_phase[r.phase] += 1
    per_it = {p.name: by_phase[p.name] / (p.end - p.start)
              for p in t1.phases}
    assert per_it["burst"] > per_it["steady"]


def test_templates_exercise_shared_prefixes():
    spec = _spec(scale=1.0)
    tr = synthesize(spec, seed=3)
    templated = [r for r in tr.requests if r.template is not None]
    assert templated
    by_template = {}
    for r in templated:
        by_template.setdefault(r.template, set()).add(
            r.prompt[:spec.template_len])
    # every request tagged with template i shares that exact prefix
    assert all(len(prefixes) == 1 for prefixes in by_template.values())


def test_workload_spec_validation():
    ph = (PhaseSpec("p", 10, 0.1),)
    with pytest.raises(ValueError, match="vocab"):
        WorkloadSpec(vocab=2, phases=ph)
    with pytest.raises(ValueError, match="phase"):
        WorkloadSpec(vocab=29, phases=())
    with pytest.raises(ValueError, match="template_len"):
        WorkloadSpec(vocab=29, phases=ph, template_len=32,
                     prompt_max=32)
    with pytest.raises(ValueError, match="shape"):
        PhaseSpec("p", 10, 0.1, shape="square")
    with pytest.raises(ValueError, match="duration"):
        PhaseSpec("p", 0, 0.1)


def test_trace_jsonl_roundtrip(tmp_path):
    tr = synthesize(_spec(), seed=5)
    path = tmp_path / "trace.jsonl"
    tr.to_jsonl(str(path))
    back = Trace.from_jsonl(str(path))
    assert back.requests == tr.requests
    assert back.phases == tr.phases
    assert back.meta["seed"] == 5
    # forward-compat: unknown record types are skipped, not fatal
    with open(path, "a") as f:
        f.write(json.dumps({"type": "from_the_future", "x": 1}) + "\n")
    assert Trace.from_jsonl(str(path)).requests == tr.requests


# --- replay determinism (the acceptance gate) -------------------------------


def _mk_engine(pattern_lm, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 32)
    kw.setdefault("max_queue", 6)
    return ServingEngine(pattern_lm, **kw)


def test_replay_twice_identical_outcomes_and_reports(pattern_lm):
    """The tentpole contract: same seeded scenario through the same
    engine config twice => identical traces, outcomes (including token
    CRCs), and per-phase report numbers."""
    spec = _spec(prompt_max=16, output_max=8)
    tr = synthesize(spec, seed=7)
    objs = [ttft_p99(0.25), availability(0.5)]
    r1 = replay(tr, _mk_engine(pattern_lm), objectives=objs, dt=1e-3)
    r2 = replay(tr, _mk_engine(pattern_lm), objectives=objs, dt=1e-3)
    assert r1.iterations == r2.iterations
    assert r1.outcomes == r2.outcomes          # states + token CRCs
    assert any("tokens_crc" in o for o in r1.outcomes)
    rep1 = scenario_report.build_report(r1)
    rep2 = scenario_report.build_report(r2)
    assert scenario_report.to_json(rep1) == scenario_report.to_json(rep2)
    # every request reached a terminal state and the report says so
    assert r1.totals.get("finished", 0) + r1.totals.get("shed", 0) \
        == len(tr.requests)
    assert {ph["name"] for ph in rep1["phases"]} \
        >= {p.name for p in tr.phases}
    h = rep1["headline"]
    assert 0.0 <= h["min_attainment"] <= 1.0


def test_overload_sheds_and_report_detects_onset(pattern_lm):
    """A tiny admission queue under the flash crowd: sheds happen,
    deterministically, and the report's saturation join finds the
    shed onset inside an overloaded phase."""
    spec = _spec(scale=1.5, prompt_max=8, output_max=6)
    tr = synthesize(spec, seed=13)
    objs = [availability(0.9)]
    r = replay(tr, _mk_engine(pattern_lm, max_queue=2),
               objectives=objs, dt=1e-3)
    assert r.totals.get("shed", 0) > 0
    rep = scenario_report.build_report(r)
    shed_phases = [ph for ph in rep["phases"] if ph["shed"] > 0]
    assert shed_phases
    assert any(
        s.get("shed_onset_t") is not None
        for ph in shed_phases for s in ph["saturation"].values())
    # attainment dips below 1 in at least one overloaded phase
    assert rep["headline"]["min_attainment"] < 1.0
    md = scenario_report.to_markdown(rep)
    html = scenario_report.to_html(rep)
    assert "Scenario report" in md and "<svg" in html


def test_replay_through_router_fleet_is_deterministic(pattern_lm):
    spec = _spec(scale=0.5, prompt_max=16, output_max=8)
    tr = synthesize(spec, seed=11)

    def mk():
        from distkeras_tpu.serving import Router
        return Router([
            _mk_engine(pattern_lm, engine_id="lg0"),
            _mk_engine(pattern_lm, engine_id="lg1")])

    objs = [ttft_p99(0.25), availability(0.5)]
    r1 = replay(tr, mk(), objectives=objs, dt=1e-3)
    r2 = replay(tr, mk(), objectives=objs, dt=1e-3)
    assert r1.fleet and sorted(r1.engine_ids) == ["lg0", "lg1"]
    assert r1.outcomes == r2.outcomes
    rep1 = scenario_report.build_report(r1)
    assert scenario_report.to_json(rep1) \
        == scenario_report.to_json(scenario_report.build_report(r2))
    # fleet rows carry per-replica divergence
    assert any("divergence" in ph for ph in rep1["phases"])


# --- chaos schedules (phase-anchored fault scripts) --------------------------


def test_chaos_spec_validation_and_inject_kwargs():
    with pytest.raises(ValueError, match="point"):
        ChaosSpec("", at=3)
    with pytest.raises(ValueError, match="at must be"):
        ChaosSpec("replica.die", at=-1)
    with pytest.raises(ValueError, match="clear_at"):
        ChaosSpec("serving.decode", at=5, clear_at=5)
    # trigger knobs map 1:1 onto faults.inject; no trigger => nth=1
    assert ChaosSpec("replica.die", at=3).inject_kwargs()["nth"] == 1
    kw = ChaosSpec("serving.prefill", at=2, clear_at=9, every=4,
                   action="stall", stall_s=0.05).inject_kwargs()
    assert kw["every"] == 4 and kw["stall_s"] == 0.05
    assert "nth" not in kw


def test_chaos_script_rides_trace_jsonl(tmp_path):
    """Chaos entries serialize as additive ``chaos`` records in the
    same JSONL artifact as the traffic and survive the round trip;
    unknown keys in a future chaos record are skipped, not fatal."""
    script = (ChaosSpec("replica.die", at=40),
              ChaosSpec("serving.decode", at=10, clear_at=20, every=3,
                        action="stall", stall_s=0.01))
    tr = synthesize(dataclasses.replace(_spec(), chaos=script), seed=5)
    assert tr.chaos == tuple(sorted(script, key=lambda c: c.at))
    path = tmp_path / "chaos.jsonl"
    tr.to_jsonl(str(path))
    back = Trace.from_jsonl(str(path))
    assert back.chaos == tr.chaos
    assert back.requests == tr.requests
    # forward-compat: a chaos record with an unknown field parses
    with open(path, "a") as f:
        f.write(json.dumps({"type": "chaos", "point": "replica.die",
                            "at": 99, "blast_radius": "zone"}) + "\n")
    extended = Trace.from_jsonl(str(path))
    assert ChaosSpec("replica.die", at=99) in extended.chaos


def test_chaos_replay_twice_byte_identical_through_autoscaled_fleet(
        pattern_lm):
    """The chaos acceptance gate at tier-1 scale: a seeded scenario
    with a scripted mid-crowd replica kill, replayed twice through a
    fresh 2-replica fleet WITH the autoscale controller attached —
    outcomes (token CRCs), incidents, the fleet-size timeline, the
    autoscale decision stream and the rendered report must all be
    byte-identical."""
    spec = WorkloadSpec(
        vocab=29,
        phases=(PhaseSpec("steady", 25, 0.15),
                PhaseSpec("crowd", 30, 0.5),
                PhaseSpec("recovery", 25, 0.1)),
        prompt_max=16, output_max=8, length_quantum=8,
        sampled_frac=0.5,
        chaos=(ChaosSpec("replica.die", at=30),))
    tr = synthesize(spec, seed=17)

    def run_once():
        try:
            minted = []

            def factory():
                rep = EngineReplica(_mk_engine(
                    pattern_lm, engine_id=f"czs{len(minted)}"))
                minted.append(rep)
                return rep

            r = Router([
                EngineReplica(_mk_engine(pattern_lm, engine_id="cz0",
                                         max_queue=4)),
                EngineReplica(_mk_engine(pattern_lm, engine_id="cz1",
                                         max_queue=4))])
            ctl = AutoscaleController(
                r, factory, min_serving=1, max_replicas=3,
                up_sustain=1, idle_sustain=4, cooldown=2)
            r.attach_controller(ctl)
            res = replay(tr, r, objectives=[availability(0.9)],
                         dt=1e-3)
            rep = scenario_report.build_report(res)
            # snapshot the comparables and drop every live handle:
            # lingering engines would collide in the obs component
            # registry and rename run 2's series
            return copy.deepcopy({
                "outcomes": res.outcomes,
                "incidents": res.incidents,
                "fleet_timeline": res.fleet_timeline,
                "autoscale_events": res.autoscale_events,
                "report": scenario_report.to_json(rep)})
        finally:
            faults.reset()

    d1 = run_once()
    gc.collect()
    d2 = run_once()
    gc.collect()
    assert d1 == d2
    # the scripted kill actually fired and the census saw the death
    assert any(ev["point"] == "replica.die" for ev in d1["incidents"])
    assert any(row.get("dead", 0) >= 1 for row in d1["fleet_timeline"])
    # recovery section is in the report when incidents exist
    assert '"recovery"' in d1["report"]


def test_report_artifacts_save_and_parse(tmp_path, pattern_lm):
    spec = _spec(scale=0.4, prompt_max=8, output_max=6)
    tr = synthesize(spec, seed=2)
    r = replay(tr, _mk_engine(pattern_lm),
               objectives=[availability(0.5)], dt=1e-3)
    rep = scenario_report.build_report(r)
    paths = scenario_report.save_report(rep, str(tmp_path))
    assert set(paths) == {"json", "md", "html"}
    parsed = json.loads(open(paths["json"]).read())
    assert parsed["kind"] == "scenario_report"
    assert parsed["schema_version"] == rep["schema_version"]
