"""Continuous-batching serving engine (this PR): the oracle contract —
greedy outputs under iteration-level batching must be token-identical
per request to standalone ``generate()`` — plus scheduler/state-machine,
pooled-cache, per-slot-sampling, interleaved-prefill and metrics
coverage."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import (decode_step, decode_step_slots,
                                           generate, init_cache,
                                           _resolve_head_dims)
from distkeras_tpu.serving import (FIFOScheduler, KVPool, PagedKVPool,
                                   PriorityScheduler, Request,
                                   RequestState, ServingEngine,
                                   ServingMetrics)

V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    """The shared session-scoped overfit-PATTERN LM (conftest pattern_lm): huge greedy argmax margins keep token-identity assertions robust; trained once per test session."""
    return pattern_lm


# --- the oracle: continuous batching == generate(), per request ------------


def test_oracle_staggered_arrivals_match_generate(memorized_lm):
    """Requests arriving at staggered times with mixed prompt lengths
    and budgets, more requests than slots (so slots recycle and the
    queue is exercised): every request's greedy tokens must equal its
    own standalone generate() call."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=3, max_len=32)
    prompts = [PATTERN[:4], PATTERN[:6], PATTERN[:3], PATTERN[:5],
               PATTERN[:4], PATTERN[:7]]
    budgets = [7, 5, 9, 6, 8, 4]
    rids = [eng.submit(prompts[i], budgets[i]) for i in range(2)]
    eng.step()
    eng.step()                     # in-flight work before later arrivals
    rids += [eng.submit(prompts[i], budgets[i]) for i in range(2, 6)]
    out = eng.run(max_steps=500)
    assert sorted(out) == sorted(rids)
    for i, rid in enumerate(rids):
        ref = generate(m, prompts[i][None], max_new_tokens=budgets[i],
                       temperature=0.0)
        np.testing.assert_array_equal(out[rid], ref[0])


def test_oracle_chunked_prefill_matches_generate(memorized_lm):
    """The interleaved chunked prefill must hand decode the same cache
    the one-shot path builds: greedy tokens equal generate() with the
    matching prefill_chunk (prompt not a multiple of the chunk)."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=48, prefill_chunk=4)
    prompt = np.tile(PATTERN, 3)[:26]
    rid = eng.submit(prompt, 6)
    out = eng.run(max_steps=300)
    ref = generate(m, prompt[None], max_new_tokens=6, temperature=0.0,
                   prefill_chunk=4)
    np.testing.assert_array_equal(out[rid], ref[0])


def test_oracle_int8_pooled_cache_matches_generate(memorized_lm):
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, cache_dtype="int8")
    rid = eng.submit(PATTERN[:4], 7)
    out = eng.run(max_steps=300)
    ref = generate(m, PATTERN[None, :4], max_new_tokens=7,
                   temperature=0.0, cache_dtype="int8")
    np.testing.assert_array_equal(out[rid], ref[0])


def test_stop_token_frees_slot_early(memorized_lm):
    """A stop-token request releases its slot before max_new_tokens;
    the engine result ends AT the stop token (no padding — unlike
    generate()'s static-shape tail fill)."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=1, max_len=32)
    rid = eng.submit(PATTERN[:4], 7, stop_token=9)     # pattern hits 9
    out = eng.run(max_steps=300)
    ref = generate(m, PATTERN[None, :4], max_new_tokens=7,
                   temperature=0.0, stop_token=9)
    got = out[rid]
    assert got[-1] == 9 and len(got) < 4 + 7
    np.testing.assert_array_equal(got, ref[0, :len(got)])
    # the tail generate() padded must be exactly the stop token — the
    # engine simply does not emit it
    assert (ref[0, len(got):] == 9).all()


def test_heterogeneous_sampling_coexists(memorized_lm):
    """Per-slot sampling state: a greedy request sharing the batch with
    sampled neighbours must produce exactly its solo-greedy tokens, and
    a sampled request must be reproducible from its seed regardless of
    neighbours."""
    m = memorized_lm

    def run_engine(extra_first):
        eng = ServingEngine(m, num_slots=3, max_len=32)
        if extra_first:
            eng.submit(PATTERN[:3], 8, temperature=1.3, top_k=4, seed=11)
        g = eng.submit(PATTERN[:4], 7)                   # greedy
        s = eng.submit(PATTERN[:5], 6, temperature=0.9, top_p=0.95,
                       seed=5)
        out = eng.run(max_steps=500)
        return out[g], out[s]

    greedy_a, sampled_a = run_engine(extra_first=False)
    greedy_b, sampled_b = run_engine(extra_first=True)
    ref = generate(m, PATTERN[None, :4], max_new_tokens=7,
                   temperature=0.0)
    np.testing.assert_array_equal(greedy_a, ref[0])
    np.testing.assert_array_equal(greedy_b, ref[0])
    # per-slot PRNG keys: the sampled request's draws depend only on its
    # own seed, not on which neighbours shared the batch
    np.testing.assert_array_equal(sampled_a, sampled_b)
    assert (sampled_a[5:] < V).all() and (sampled_a[5:] >= 0).all()


def test_long_prefill_does_not_stall_inflight_decode(memorized_lm):
    """The scheduling property chunked prefill exists for: while a long
    prompt ingests chunk-by-chunk, an already-decoding request keeps
    emitting tokens every iteration."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=64, prefill_chunk=2)
    fast = eng.submit(PATTERN[:3], 20)
    while not eng.scheduler.running:                     # fast decoding
        eng.step()
    tokens_before = len(eng[fast].generated)
    slow = eng.submit(np.tile(PATTERN, 3)[:24], 4)       # 12 chunks
    for _ in range(6):                                   # mid-prefill
        eng.step()
    assert eng[slow].state is RequestState.PREFILLING
    assert 0 < eng[slow].prefill_pos < 24
    # the in-flight stream advanced ~1 token per iteration, not zero
    assert len(eng[fast].generated) >= tokens_before + 6
    out = eng.run(max_steps=500)
    ref = generate(m, np.tile(PATTERN, 3)[None, :24], max_new_tokens=4,
                   temperature=0.0, prefill_chunk=2)
    np.testing.assert_array_equal(out[slow], ref[0])


def test_decode_jit_compiles_once_across_requests(memorized_lm):
    """The engine's whole point: static shapes, compiled decode
    programs reused across every request mix — one argmax variant for
    all-greedy batches, one sampler variant for mixed batches, each
    traced exactly once."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32)
    eng.submit(PATTERN[:4], 5)
    eng.run(max_steps=300)
    assert set(eng._step_fns) == {True}          # all-greedy so far
    fn = eng._step_fns[True]
    assert fn._cache_size() == 1
    eng.submit(PATTERN[:6], 7, temperature=1.0, top_k=3, seed=1)
    eng.submit(PATTERN[:2], 4, stop_token=9)
    eng.run(max_steps=300)
    assert eng._step_fns[True] is fn and fn._cache_size() == 1
    assert eng._step_fns[False]._cache_size() == 1  # mixed variant


# --- slot-level decode path -------------------------------------------------


def test_decode_step_slots_staggered_positions_match_scalar():
    """decode_step_slots at HETEROGENEOUS positions must agree with
    per-sequence scalar decode_step runs: two sequences advanced to
    different depths, stepped together with a vector t."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=4)
    _resolve_head_dims(m.module, m.params)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, V, (2, 8)).astype(np.int32)

    # scalar oracle: advance sequence 0 to position 5, sequence 1 to 3
    caches = []
    refs = []
    for row, depth in ((0, 5), (1, 3)):
        c = init_cache(m.module, 1, S)
        logits = None
        for t in range(depth):
            logits, c = decode_step(m.module, m.params, m.state, c,
                                    jnp.asarray(toks[row:row + 1, t]), t)
        caches.append(c)
        refs.append(np.asarray(logits))

    # pooled: same per-row caches side by side, one vector-t step
    pool = [None if a is None else
            {k: jnp.concatenate([a[k], b[k]], axis=0) for k in a}
            for a, b in zip(*caches)]
    t_prev = np.array([4, 2])          # the last written positions were
    tok_prev = np.stack([toks[0, 4], toks[1, 2]])
    # re-run the LAST step of each row in pooled form to compare logits
    pool_before = [None if a is None else
                   {k: jnp.concatenate([a[k], b[k]], axis=0) for k in a}
                   for a, b in zip(*[
                       _advance(m, toks[r:r + 1], d - 1)
                       for r, d in ((0, 5), (1, 3))])]
    logits, _ = decode_step_slots(m.module, m.params, m.state,
                                  pool_before, jnp.asarray(tok_prev),
                                  jnp.asarray(t_prev))
    np.testing.assert_allclose(np.asarray(logits),
                               np.concatenate(refs, axis=0), atol=2e-5)


def _advance(m, row_toks, depth):
    """Scalar-decode a single row ``depth`` steps; returns its cache."""
    c = init_cache(m.module, 1, S)
    for t in range(depth):
        _, c = decode_step(m.module, m.params, m.state, c,
                           jnp.asarray(row_toks[:, t]), t)
    return c


def test_decode_step_slots_sentinel_t_writes_nothing():
    """A slot whose t is out of range (the engine's free-slot sentinel)
    must not touch the cache — the one-hot write misses everywhere."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=16, num_heads=2, num_layers=1,
                           mlp_ratio=2, use_rope=True), (S,), seed=0)
    _resolve_head_dims(m.module, m.params)
    cache = init_cache(m.module, 2, S)
    kv0 = next(c for c in cache if c is not None)
    before = np.array(kv0["k"])
    _, cache2 = decode_step_slots(
        m.module, m.params, m.state, cache,
        jnp.asarray([3, 5], jnp.int32), jnp.asarray([S, S], jnp.int32))
    kv1 = next(c for c in cache2 if c is not None)
    np.testing.assert_array_equal(np.asarray(kv1["k"]), before)


def test_prefill_program_cache_is_lru_capped(memorized_lm):
    """Varied prompt lengths each compile their own ragged-tail prefill
    program; the engine must bound how many it retains."""
    eng = ServingEngine(memorized_lm, num_slots=1, max_len=32)
    eng.MAX_PREFILL_PROGRAMS = 3
    for n in (2, 3, 4, 5, 6):                  # 5 distinct lengths
        eng.submit(PATTERN[:n], 2)
        eng.run(max_steps=200)
    assert len(eng._prefill_fns) == 3
    # most-recent lengths retained (dict order = LRU order)
    assert sorted(k[0] for k in eng._prefill_fns) == [4, 5, 6]
    # reuse refreshes recency and does not recompile
    fn6 = eng._prefill_fns[(6, 0, True)]
    eng.submit(PATTERN[:6], 2)
    eng.run(max_steps=200)
    assert eng._prefill_fns[(6, 0, True)] is fn6


# --- kv pool ----------------------------------------------------------------


def test_kv_pool_insert_places_request_rows():
    m = Model.build(
        zoo.transformer_lm(V, d_model=16, num_heads=2, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=1)
    _resolve_head_dims(m.module, m.params)
    pool = KVPool(m.module, num_slots=3, max_len=10)
    req = pool.make_request_cache()
    req = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, 7.0), req)
    pool.insert(req, 1)
    for layer in pool.cache:
        if layer is None:
            continue
        arr = np.asarray(layer["k"])
        assert (arr[1] == 7.0).all()
        assert (arr[0] == 0.0).all() and (arr[2] == 0.0).all()
    with pytest.raises(ValueError, match="slot"):
        pool.insert(req, 3)


def test_kv_pool_rejects_capacity_beyond_position_table():
    m = Model.build(
        zoo.transformer_lm(V, d_model=16, num_heads=2, num_layers=1,
                           mlp_ratio=2, use_rope=False, max_len=16),
        (S,), seed=1)
    _resolve_head_dims(m.module, m.params)
    with pytest.raises(ValueError, match="too small"):
        KVPool(m.module, num_slots=2, max_len=17)


# --- scheduler --------------------------------------------------------------


def _req(rid, p_len=4, budget=5, **kw):
    return Request(rid=rid, prompt=PATTERN[:p_len].copy(),
                   max_new_tokens=budget, **kw)


def test_scheduler_fifo_admission_and_slot_reuse():
    sched = FIFOScheduler(2)
    reqs = [_req(i) for i in range(4)]
    for r in reqs:
        sched.submit(r)
    admitted = sched.admit()
    assert [r.rid for r in admitted] == [0, 1]
    assert [r.slot for r in admitted] == [0, 1]        # deterministic
    assert sched.queue_depth == 2 and sched.occupied == 2
    assert not sched.admit()                           # no free slots
    # finish 0 from PREFILLING; its slot goes to request 2
    sched.release(reqs[0])
    assert reqs[0].state is RequestState.FINISHED
    assert sched.admit()[0] is reqs[2] and reqs[2].slot == 0
    # request 1 finishes from DECODING
    sched.to_decoding(reqs[1])
    assert sched.running == {1: reqs[1]}
    sched.release(reqs[1])
    assert sched.admit()[0] is reqs[3] and reqs[3].slot == 1
    assert sched.queue_depth == 0


def test_scheduler_single_prefill_stream_is_fcfs():
    sched = FIFOScheduler(3)
    reqs = [_req(i) for i in range(3)]
    for r in reqs:
        sched.submit(r)
    sched.admit()
    assert sched.next_prefill() is reqs[0]
    sched.to_decoding(reqs[0])
    assert sched.next_prefill() is reqs[1]
    with pytest.raises(AssertionError):
        sched.to_decoding(reqs[2])                     # FCFS enforced


def test_request_done_semantics():
    r = _req(0, budget=2, stop_token=9)
    assert not r.done
    r.generated.append(3)
    assert not r.done and not r.stopped
    r.generated.append(9)
    assert r.stopped and r.done
    r2 = _req(1, budget=1)
    r2.generated.append(9)                             # no stop_token set
    assert r2.done and not r2.stopped
    np.testing.assert_array_equal(r2.tokens,
                                  np.concatenate([PATTERN[:4], [9]]))


# --- engine validation ------------------------------------------------------


def test_submit_validation(memorized_lm):
    eng = ServingEngine(memorized_lm, num_slots=1, max_len=16)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(PATTERN[:10], 7)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(PATTERN[:4], 0)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(PATTERN[:4], 2, top_p=1.5)
    with pytest.raises(ValueError, match="at least one"):
        eng.submit(np.zeros((0,), np.int32), 2)


def test_engine_rejects_non_sequential():
    class Fake:
        module = object()
    with pytest.raises(TypeError, match="Sequential"):
        ServingEngine(Fake())


# --- metrics ----------------------------------------------------------------


def test_metrics_lifecycle_and_summary():
    clock = iter(np.arange(0.0, 100.0, 0.5))
    mtr = ServingMetrics(clock=lambda: float(next(clock)))
    mtr.record_submit(0)                   # t=0.0
    mtr.record_first_token(0)              # t=0.5 -> ttft 0.5
    mtr.record_iteration(queue_depth=2, occupied=1, num_slots=2)
    mtr.record_decode(n_decoding=2, dt=0.25)
    mtr.record_decode(n_decoding=1, dt=0.25)
    mtr.record_finish(0, n_generated=5)    # t=1.0 -> latency 1.0
    s = mtr.summary()
    assert s["requests_finished"] == 1
    assert s["tokens_generated"] == 5
    assert s["ttft_s"]["p50"] == pytest.approx(0.5)
    assert s["latency_s"]["p50"] == pytest.approx(1.0)
    assert s["queue_depth"]["max"] == 2
    assert s["slot_occupancy"]["mean"] == pytest.approx(0.5)
    # all-iterations marginal decode rate: 3 tokens / 0.5 s
    assert s["decode_tokens_per_sec"] == pytest.approx(6.0)
    # full-occupancy steady state: 2 tokens / 0.25 s
    assert mtr.decode_tokens_per_sec(min_occupancy=2) \
        == pytest.approx(8.0)


# --- paged KV cache ---------------------------------------------------------
#
# The default engine layout since the paged-cache PR: every oracle test
# above already runs through the paged data plane (page_len 16 covers
# those short prompts in one page). The tests below force multi-page
# requests, prefix sharing, copy-on-write and preemption explicitly.


def test_paged_small_pages_oracle_matches_generate(memorized_lm):
    """Pages far smaller than the prompt (crossing mid-prompt and
    mid-decode): greedy tokens equal standalone generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=3, max_len=32, page_len=4)
    prompts = [PATTERN[:5], PATTERN[:7], PATTERN[:3], PATTERN[:6]]
    budgets = [9, 5, 8, 7]
    rids = [eng.submit(p, b) for p, b in zip(prompts, budgets)]
    out = eng.run(max_steps=500)
    for i, rid in enumerate(rids):
        ref = generate(m, prompts[i][None], max_new_tokens=budgets[i],
                       temperature=0.0)
        np.testing.assert_array_equal(out[rid], ref[0])


def test_slab_layout_oracle_still_matches_generate(memorized_lm):
    """The legacy slab pool stays selectable and token-identical (the
    equal-HBM bench baseline)."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, kv_layout="slab")
    assert isinstance(eng.pool, KVPool) and eng.prefix is None
    rid = eng.submit(PATTERN[:4], 7)
    out = eng.run(max_steps=300)
    ref = generate(m, PATTERN[None, :4], max_new_tokens=7,
                   temperature=0.0)
    np.testing.assert_array_equal(out[rid], ref[0])


def test_paged_int8_cache_shares_tables_with_scales(memorized_lm):
    """int8 quantized cache x paged pool: payload AND scale planes move
    through the same page tables — token-identical to generate() with
    the int8 cache, across page boundaries."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, cache_dtype="int8",
                        page_len=4)
    prompt = np.tile(PATTERN, 2)[:13]
    rid = eng.submit(prompt, 7)
    rid2 = eng.submit(PATTERN[:5], 6)
    out = eng.run(max_steps=300)
    ref = generate(m, prompt[None], max_new_tokens=7, temperature=0.0,
                   cache_dtype="int8")
    np.testing.assert_array_equal(out[rid], ref[0])
    ref2 = generate(m, PATTERN[None, :5], max_new_tokens=6,
                    temperature=0.0, cache_dtype="int8")
    np.testing.assert_array_equal(out[rid2], ref2[0])


def test_decode_step_slots_paged_matches_slab_logits():
    """The paged decode step over scattered physical pages must produce
    the slab step's logits: same values in logical order after the
    gather, same masked attention."""
    from distkeras_tpu.models.decoding import decode_step_slots_paged
    m = Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=4)
    _resolve_head_dims(m.module, m.params)
    rs = np.random.RandomState(1)
    toks = rs.randint(0, V, (2, 8)).astype(np.int32)
    slab = [None if a is None else
            {k: jnp.concatenate([a[k], b[k]], axis=0) for k in a}
            for a, b in zip(_advance(m, toks[0:1], 4),
                            _advance(m, toks[1:2], 2))]
    page_len = 4
    n_logical = S // page_len                    # 3 logical pages/slot
    # scrambled physical placement: slot 0 -> pages [5, 2, 0],
    # slot 1 -> pages [1, 4, 3]
    tables = np.array([[5, 2, 0], [1, 4, 3]], np.int32)
    paged = []
    for layer in slab:
        if layer is None:
            paged.append(None)
            continue
        entry = {}
        for k, arr in layer.items():
            arr = np.asarray(arr)                # [2, H, S, ...]
            pool = np.zeros((6,) + arr.shape[1:2]
                            + (page_len,) + arr.shape[3:], arr.dtype)
            for slot in range(2):
                for j in range(n_logical):
                    pool[tables[slot, j]] = \
                        arr[slot, :, j * page_len:(j + 1) * page_len]
            entry[k] = jnp.asarray(pool)
        paged.append(entry)
    tok = jnp.asarray(np.stack([toks[0, 4], toks[1, 2]]))
    t = jnp.asarray(np.array([4, 2], np.int32))
    ref_logits, _ = decode_step_slots(m.module, m.params, m.state,
                                      slab, tok, t)
    got_logits, _ = decode_step_slots_paged(
        m.module, m.params, m.state, paged, tok,
        t, jnp.asarray(tables), page_len)
    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(ref_logits), atol=1e-5)


def test_prefix_sharing_skips_prefill_and_matches_generate(memorized_lm):
    """A second request with an identical prompt reuses the first's
    registered pages: its prefill runs a single ragged chunk (the
    recomputed final position), the hit counters move, and both
    outputs equal standalone generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=48, page_len=4,
                        prefill_chunk=4)
    prompt = np.tile(PATTERN, 2)[:12]            # 3 full pages
    r0 = eng.submit(prompt, 5)
    out0 = eng.run(max_steps=300)
    chunks_before = eng.metrics.prefill_chunks
    r1 = eng.submit(prompt, 5)
    out1 = eng.run(max_steps=300)
    ref = generate(m, prompt[None], max_new_tokens=5, temperature=0.0,
                   prefill_chunk=4)
    np.testing.assert_array_equal(out0[r0], ref[0])
    np.testing.assert_array_equal(out1[r1], ref[0])
    s = eng.metrics.summary()
    assert s["prefix_cache"]["hits"] == 1        # r1 hit, r0 missed
    assert s["prefix_cache"]["hit_rate"] > 0.4
    # 11 of r1's 12 prompt positions came off shared pages: one chunk
    # (position 11) vs r0's three
    assert eng.metrics.prefill_chunks - chunks_before == 1


def test_prefix_partial_page_copy_on_write(memorized_lm):
    """A prompt that diverges INSIDE a cached page: the matched head of
    the donor page is reused (copy-on-write into the new request's
    private page), the divergent tail is recomputed, and the donor's
    original content stays valid for its own chain."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=48, page_len=4)
    a = np.tile(PATTERN, 2)[:12]                 # 3 full cached pages
    b = a.copy()
    b[10] = (a[10] + 1) % V                      # diverge inside page 2
    ra = eng.submit(a, 5)
    out_a = eng.run(max_steps=300)
    rb = eng.submit(b, 5)
    out_b = eng.run(max_steps=300)
    # b shared a's two full pages + two tokens of page 2 via the donor
    assert eng.metrics.summary()["prefix_cache"]["hits"] == 1
    tl = [t for t in eng.tracer.timelines() if t.rid == rb][0]
    assert tl.prefix_hit_tokens == 10            # 8 full + 2 donor
    np.testing.assert_array_equal(
        out_a[ra], generate(m, a[None], 5, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out_b[rb], generate(m, b[None], 5, temperature=0.0)[0])
    # the donor chain is uncorrupted: a re-run of prompt a (full hit
    # on its own pages now) still matches
    ra2 = eng.submit(a, 5)
    out_a2 = eng.run(max_steps=300)
    np.testing.assert_array_equal(
        out_a2[ra2], generate(m, a[None], 5, temperature=0.0)[0])


@pytest.mark.parametrize("host_pages", [0, 16])
def test_preemption_resume_token_identity(memorized_lm, host_pages):
    """Two streams outgrow a deliberately small page pool: the younger
    is preempted mid-decode, resumes — via the recompute prefill
    (``host_pages=0``) or the host-page SWAP (offload PR: D2H at
    eviction, H2D + table restore at re-admission, no re-prefill) —
    and BOTH stay token-identical to standalone generate() — the
    acceptance bar for preemption correctness. Staggered arrivals."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        num_pages=8, prefix_cache=False,
                        host_kv_pages=host_pages)
    r0 = eng.submit(PATTERN[:5], 16)
    eng.step()
    eng.step()
    r1 = eng.submit(PATTERN[:6], 15)
    out = eng.run(max_steps=2000)
    assert eng.metrics.requests_preempted >= 1
    assert eng.metrics.summary()["requests_preempted"] >= 1
    if host_pages:
        # the victim's resume really was a page swap, not a re-prefill
        assert eng.pool.pages_offloaded >= 1
        assert eng.pool.pages_restored == eng.pool.pages_offloaded
        off = eng.metrics.summary()["offload"]
        assert off["pages_restored"] >= 1
        assert off["resume_swap_s"] is not None
        assert off["reprefill_tokens_avoided"] > 0
    np.testing.assert_array_equal(
        out[r0], generate(m, PATTERN[None, :5], 16, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[r1], generate(m, PATTERN[None, :6], 15, temperature=0.0)[0])


@pytest.mark.parametrize("host_pages", [0, 16])
def test_preempted_sampled_request_resumes_key_stream(memorized_lm,
                                                      host_pages):
    """A SAMPLED request preempted mid-decode must draw the same
    tokens as under an ample page budget: its per-slot PRNG key is
    snapshotted at eviction and restored at resume, so the draw
    stream depends only on its own seed and step count. With the
    host tier on, the swap resume must be BYTE-identical too — the
    cache pages return bit-for-bit, so this also pins swap-resume ==
    re-prefill-resume == uninterrupted run (the offload acceptance
    criterion: the ample run IS the uninterrupted stream)."""
    m = memorized_lm

    def run(num_pages, host):
        eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                            num_pages=num_pages, prefix_cache=False,
                            host_kv_pages=host)
        eng.submit(PATTERN[:5], 16)              # greedy page hog
        srid = eng.submit(PATTERN[:4], 14, temperature=0.9,
                          top_p=0.95, seed=7)
        out = eng.run(max_steps=3000)
        return (out[srid], eng.metrics.requests_preempted,
                eng.pool.pages_offloaded)

    ample, p_ample, _ = run(num_pages=16, host=0)
    tight, p_tight, offloaded = run(num_pages=8, host=host_pages)
    assert p_ample == 0 and p_tight >= 1
    assert bool(offloaded) == bool(host_pages)
    np.testing.assert_array_equal(ample, tight)


def test_offload_swap_events_and_recorder(memorized_lm):
    """The swap lifecycle is observable: swap_out/swap_in timeline
    events on the preempted request, the iteration ring carries the
    host-pool occupancy, and health() exposes the host tier."""
    from distkeras_tpu.obs.recorder import get_recorder, reset_recorder
    m = memorized_lm
    reset_recorder()
    try:
        eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                            num_pages=8, prefix_cache=False,
                            host_kv_pages=16)
        eng.submit(PATTERN[:5], 16)
        eng.step()
        eng.step()
        eng.submit(PATTERN[:6], 15)
        eng.run(max_steps=2000)
        assert eng.metrics.requests_preempted >= 1
        kinds = [e["name"] for t in eng.tracer.timelines()
                 for e in t.events]
        assert "swap_out" in kinds and "swap_in" in kinds
        recs = get_recorder().records()
        pre = [r for r in recs if r["kind"] == "serving.preempted"]
        assert pre and pre[0]["pages_swapped"] >= 1
        iters = [r for r in recs if r["kind"] == "serving.iteration"]
        assert any("host_pages_free" in r for r in iters)
        h = eng.health()
        assert h["pages"]["host"]["total"] == 16
        assert h["pages"]["host"]["restored"] >= 1
    finally:
        reset_recorder()


def test_prefix_cache_spills_to_host_and_restores(memorized_lm):
    """Cold prefix chains spill D2H instead of dropping: after a full
    reclaim, a same-template request still HITS the cache (the chain
    restores H2D page by page) and stays token-identical."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=48, page_len=4,
                        host_kv_pages=32)
    prompt = np.tile(PATTERN, 2)[:12]            # 3 full cached pages
    ra = eng.submit(prompt, 5)
    out_a = eng.run(max_steps=300)
    np.testing.assert_array_equal(
        out_a[ra], generate(m, prompt[None], 5, temperature=0.0)[0])
    n_nodes = len(eng.prefix)
    assert n_nodes >= 3
    # pressure: reclaim everything — with a host tier this SPILLS
    # (nodes stay matchable) rather than dropping
    freed = eng.prefix.reclaim(eng.pool.num_pages)
    assert freed >= n_nodes
    assert eng.pool.pages_offloaded >= n_nodes
    assert len(eng.prefix) == n_nodes            # chain survived
    restored_before = eng.pool.pages_restored
    rb = eng.submit(prompt, 5)
    out_b = eng.run(max_steps=300)
    assert eng.pool.pages_restored > restored_before
    assert eng.metrics.summary()["prefix_cache"]["hits"] >= 1
    np.testing.assert_array_equal(
        out_b[rb], generate(m, prompt[None], 5, temperature=0.0)[0])


def test_transfer_of_swapped_queued_request_drops_swap(memorized_lm):
    """Review fix: a QUEUED preempted-and-swapped request leaving via
    transfer_out must release its host pages and shed the swap record
    — the record names the SOURCE engine's host pool, which the
    adopting engine cannot read (a stale one would restore garbage
    or raise on a host-less target). The handoff then rides the
    re-prefill resume, token-identical."""
    m = memorized_lm
    src = ServingEngine(m, num_slots=1, max_len=32, page_len=4,
                        num_pages=8, prefix_cache=False,
                        host_kv_pages=16)
    rid = src.submit(PATTERN[:5], 10)
    # bring it to DECODING, then preempt via a higher-priority arrival
    while src.scheduler.running.get(0) is None \
            or src.scheduler.running[0].rid != rid:
        src.step()
    for _ in range(2):
        src.step()
    req = src[rid]
    src._preempt(req)
    assert req._swap is not None and src.pool.host_free_pages < 16
    out = src.transfer_out(rid)
    assert out is req and req._swap is None
    assert src.pool.host_free_pages == 16      # host pages released
    dst = ServingEngine(m, num_slots=1, max_len=32, page_len=4)
    new_rid = dst.transfer_in(req)
    res = dst.run(max_steps=500)
    np.testing.assert_array_equal(
        res[new_rid],
        generate(m, PATTERN[None, :5], 10, temperature=0.0)[0])


def test_pool_offload_roundtrip_and_host_accounting(memorized_lm):
    """PagedKVPool host-tier unit contract: D2H/H2D round trip is
    byte-identical, capacity exhaustion returns None (callers fall
    back to discard), and host double-free is loud."""
    from distkeras_tpu.serving import PagedKVPool
    m = memorized_lm
    pool = PagedKVPool(m.module, num_slots=2, max_len=32, page_len=4,
                       host_pages=3)
    # write recognizable content into pages 0..2 via direct scatter
    rs = np.random.RandomState(0)
    pool.cache = jax.tree_util.tree_map(
        lambda a: jnp.asarray(rs.randn(*a.shape).astype(a.dtype)),
        pool.cache)
    before = jax.tree_util.tree_map(np.asarray, pool.cache)
    hids = pool.offload_pages([0, 2])
    assert hids is not None and len(hids) == 2
    assert pool.host_free_pages == 1
    assert pool.offload_pages([0, 1]) is None    # capacity: only 1 left
    # scramble the device pages, then restore onto different ids
    pool.cache = jax.tree_util.tree_map(jnp.zeros_like, pool.cache)
    pool.restore_pages(hids, [5, 7])
    after = jax.tree_util.tree_map(np.asarray, pool.cache)
    for b, a in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(b[0], a[5])
        np.testing.assert_array_equal(b[2], a[7])
    pool.free_host(hids)
    assert pool.host_free_pages == 3
    with pytest.raises(RuntimeError, match="double-freed"):
        pool.free_host([hids[0]])
    assert pool.pages_offloaded == 2 and pool.pages_restored == 2
    assert pool.offload_bytes > 0


def test_priority_scheduler_order_and_preempt():
    sched = PriorityScheduler(2)
    reqs = [_req(0, priority=2), _req(1, priority=0),
            _req(2, priority=1)]
    for r in reqs:
        sched.submit(r)
    assert sched.peek() is reqs[1]               # class before arrival
    admitted = sched.admit()
    assert [r.rid for r in admitted] == [1, 2]
    sched.to_decoding(reqs[1])
    sched.preempt(reqs[1])
    assert reqs[1].state is RequestState.QUEUED
    assert reqs[1].slot is None and reqs[1].n_preempted == 1
    # preempted requests resume ahead of their class peers
    sched.submit(_req(3, priority=0))
    assert sched.peek() is reqs[1]
    # PREFILLING requests are preemptable too (they hold budget pages)
    sched.preempt(reqs[2])
    assert reqs[2].state is RequestState.QUEUED and reqs[2].slot is None
    with pytest.raises(RuntimeError, match="preempt"):
        sched.preempt(reqs[0])                   # QUEUED: holds nothing


def test_engine_priority_admission_preempts_lower_class(memorized_lm):
    """A priority-0 arrival that cannot fit the page budget preempts a
    decoding batch-class stream; both finish token-identically."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        num_pages=4, prefix_cache=False)
    low = eng.submit(PATTERN[:9], 6, priority=2)   # 3 admission pages
    while not eng.scheduler.running:
        eng.step()
    high = eng.submit(PATTERN[:6], 4, priority=0)  # needs 2, 1 free
    out = eng.run(max_steps=2000)
    assert eng.metrics.requests_preempted >= 1
    np.testing.assert_array_equal(
        out[low], generate(m, PATTERN[None, :9], 6, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[high], generate(m, PATTERN[None, :6], 4, temperature=0.0)[0])


def test_paged_pool_refcounts_and_partial_insert():
    """PagedKVPool unit contract: alloc/incref/decref accounting,
    release returns pages, and insert touches ONLY the pages the
    prompt fills (the slab pool's full-row admit write, fixed)."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=16, num_heads=2, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=1)
    _resolve_head_dims(m.module, m.params)
    pool = PagedKVPool(m.module, num_slots=2, max_len=12, page_len=4)
    assert pool.num_pages == 6 and pool.free_pages == 6
    pool.cache = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, 9.0), pool.cache)
    staging = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, 7.0), pool.make_request_cache())
    p0, p1 = pool.alloc_page(), pool.alloc_page()
    pool.assign(0, 0, p0)
    pool.assign(0, 1, p1)
    assert pool.free_pages == 4
    # 6 positions -> exactly 2 pages written; the other 4 untouched
    pool.insert_pages(staging, 0, skip_pages=0, n_pos=6)
    for layer in pool.cache:
        if layer is None:
            continue
        arr = np.asarray(layer["k"])
        for pid in range(pool.num_pages):
            want = 7.0 if pid in (p0, p1) else 9.0
            assert (arr[pid] == want).all(), pid
    # sharing: second holder keeps the page alive past one release
    pool.incref(p0)
    assert pool.shared_pages == 1
    assert pool.release_slot(0) == 2
    assert pool.free_pages == 5                  # p1 freed, p0 held
    pool.decref(p0)
    assert pool.free_pages == 6
    with pytest.raises(RuntimeError, match="refcount"):
        pool.decref(p1)


def test_slab_insert_writes_only_prompt_positions():
    """Satellite fix on the legacy pool: admit writes the prompt's
    rows, not all max_len positions."""
    m = Model.build(
        zoo.transformer_lm(V, d_model=16, num_heads=2, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=1)
    _resolve_head_dims(m.module, m.params)
    pool = KVPool(m.module, num_slots=3, max_len=10)
    pool.cache = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, 9.0), pool.cache)
    req = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, 7.0), pool.make_request_cache())
    pool.insert(req, 1, n_pos=3)
    for layer in pool.cache:
        if layer is None:
            continue
        arr = np.asarray(layer["k"])
        assert (arr[1][:, :3] == 7.0).all()
        assert (arr[1][:, 3:] == 9.0).all()      # tail untouched
        assert (arr[0] == 9.0).all() and (arr[2] == 9.0).all()
    with pytest.raises(ValueError, match="n_pos"):
        pool.insert(req, 1, n_pos=11)


def test_page_metrics_summary_and_health(memorized_lm):
    """Satellite: page-accounting gauges + prefix hit counters land in
    summary() and health(); the slab engine honestly reports None."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4)
    eng.submit(np.tile(PATTERN, 2)[:9], 5)
    eng.submit(np.tile(PATTERN, 2)[:9], 5)
    eng.run(max_steps=500)
    s = eng.metrics.summary()
    assert s["pages"] is not None
    assert s["pages"]["free"] == eng.pool.free_pages
    assert 0.0 <= s["pages"]["fragmentation"] <= 1.0
    assert s["prefix_cache"]["lookups"] == 2
    h = eng.health()
    assert h["pages"]["total"] == eng.pool.num_pages
    assert h["pages"]["page_len"] == 4
    assert h["prefix_cache"]["nodes"] == len(eng.prefix)
    assert h["requests"]["preempted"] == 0
    slab = ServingEngine(m, num_slots=1, max_len=16, kv_layout="slab")
    slab.submit(PATTERN[:4], 3)
    slab.run(max_steps=200)
    assert slab.metrics.summary()["pages"] is None
    assert "pages" not in slab.health()


def test_preemption_lands_in_flight_recorder(memorized_lm):
    """Satellite: iteration records carry the free-page count and
    preemptions write their own record — admission stalls are
    explainable post-mortem."""
    from distkeras_tpu.obs.recorder import get_recorder, reset_recorder
    m = memorized_lm
    reset_recorder()
    try:
        eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                            num_pages=8, prefix_cache=False)
        eng.submit(PATTERN[:5], 16)
        eng.submit(PATTERN[:6], 15)
        eng.run(max_steps=2000)
        assert eng.metrics.requests_preempted >= 1
        recs = get_recorder().records()
        iters = [r for r in recs if r["kind"] == "serving.iteration"]
        assert iters and all("pages_free" in r for r in iters)
        pre = [r for r in recs if r["kind"] == "serving.preempted"]
        assert pre and {"rid", "slot", "pages_freed",
                        "pages_free"} <= set(pre[0])
    finally:
        reset_recorder()


def test_prefilling_hog_is_preemptable_not_deadlock(memorized_lm):
    """Review fix: pages held by a MID-PREFILL request are page-budget
    holders too — a decoding stream that outgrows the pool preempts
    the prefilling hog instead of crashing the serve loop with 'page
    pool exhausted'."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=24, page_len=4,
                        num_pages=6, prefill_chunk=2,
                        prefix_cache=False)
    a = eng.submit(PATTERN[:4], 20)              # worst 6 pages == pool
    while not eng.scheduler.running:
        eng.step()
    b = eng.submit(np.tile(PATTERN, 2)[:13], 4)  # 4 admission pages
    out = eng.run(max_steps=3000)
    assert eng.metrics.requests_preempted >= 1
    np.testing.assert_array_equal(
        out[a], generate(m, PATTERN[None, :4], 20, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[b], generate(m, np.tile(PATTERN, 2)[None, :13], 4,
                         temperature=0.0)[0])


def test_growth_preemption_never_evicts_higher_priority(memorized_lm):
    """Review fix: when a LOW-priority stream outgrows the pool and
    the only other stream is higher-priority, the low stream preempts
    ITSELF — growing it at the interactive stream's expense would
    invert the promised priority."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=20, page_len=4,
                        num_pages=7, prefix_cache=False)
    hi = eng.submit(PATTERN[:5], 10, priority=0)
    lo = eng.submit(PATTERN[:5], 10, priority=2)
    done = {}
    steps = 0
    while eng.scheduler.pending:
        for r in eng.step():
            done[r.rid] = r
        steps += 1
        assert steps < 3000
    assert done[hi].n_preempted == 0
    assert done[lo].n_preempted >= 1
    ref = generate(m, PATTERN[None, :5], 10, temperature=0.0)
    np.testing.assert_array_equal(done[hi].tokens, ref[0])
    np.testing.assert_array_equal(done[lo].tokens, ref[0])


def test_unfundable_admission_preserves_prefix_cache(memorized_lm):
    """Review fix: an admission whose page deficit exceeds free +
    evictable must NOT drain the prefix cache on the way to failing —
    later same-template requests would lose all sharing for nothing."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=24, page_len=4,
                        num_pages=6)
    big_prompt = np.tile(PATTERN, 2)[:17]
    r0 = eng.submit(np.tile(PATTERN, 2)[:9], 3)  # registers 2 pages
    out0 = eng.run(max_steps=300)
    assert len(eng.prefix) == 2
    hog = eng.submit(PATTERN[:4], 19)            # decoding page hog
    while not eng.scheduler.running:
        eng.step()
    big = eng.submit(big_prompt, 5)              # needs 5 private now
    eng.step()
    # unfundable (free 2 + evictable 2 < 5): cache must survive
    assert len(eng.prefix) == 2
    assert eng[big].state is RequestState.QUEUED
    out = eng.run(max_steps=3000)
    np.testing.assert_array_equal(
        out[big], generate(m, big_prompt[None], 5, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[hog], generate(m, PATTERN[None, :4], 19, temperature=0.0)[0])


def test_paged_submit_rejects_impossible_request(memorized_lm):
    """A request whose worst case exceeds the whole pool can never
    finish — refused at submit, not deadlocked at runtime."""
    eng = ServingEngine(memorized_lm, num_slots=2, max_len=32,
                        page_len=4, num_pages=4)
    with pytest.raises(ValueError, match="pages"):
        eng.submit(PATTERN[:8], 12)              # 5 pages > 4
    with pytest.raises(ValueError, match="num_pages"):
        PagedKVPool(memorized_lm.module, num_slots=1, max_len=32,
                    page_len=4, num_pages=0)


def test_engine_records_serving_metrics(memorized_lm):
    eng = ServingEngine(memorized_lm, num_slots=2, max_len=32,
                        prefill_chunk=4)
    rids = [eng.submit(PATTERN[:6], 5), eng.submit(PATTERN[:4], 6),
            eng.submit(PATTERN[:5], 4)]
    eng.run(max_steps=500)
    s = eng.metrics.summary()
    assert s["requests_finished"] == 3
    assert s["tokens_generated"] == 5 + 6 + 4
    assert s["ttft_s"] is not None and s["ttft_s"]["p99"] >= \
        s["ttft_s"]["p50"] >= 0
    assert s["latency_s"]["p50"] > 0
    assert s["prefill_chunks"] >= 2 + 1 + 2    # ceil(6/4)+ceil(4/4)+...
    assert s["slot_occupancy"]["max"] == 1.0   # both slots ran together
    assert s["queue_depth"]["max"] >= 1        # third request queued
    assert s["phases"]["prefill"]["count"] == s["prefill_chunks"]
    assert s["decode_tokens_per_sec"] > 0
