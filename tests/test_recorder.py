"""Flight recorder (``obs.recorder``): the bounded ring, JSONL dumps,
and every auto-dump trigger — chaos-injected serving faults, admission
storms, degraded drains, supervisor restarts — plus the disabled
NULL-object path."""

import json

import numpy as np
import pytest

from distkeras_tpu import obs
from distkeras_tpu.data import Dataset
from distkeras_tpu.models import Dense, Model, Sequential, zoo
from distkeras_tpu.obs.recorder import (NULL_RECORDER, FlightRecorder,
                                        get_recorder, read_flight_dump,
                                        reset_recorder, resolve_recorder)
from distkeras_tpu.parallel import SingleTrainer
from distkeras_tpu.resilience import (InjectedFault, TrainingSupervisor,
                                      faults)
from distkeras_tpu.serving import (AdmissionRejected, DegradedRequest,
                                   ServingEngine)


@pytest.fixture(autouse=True)
def _isolation(tmp_path):
    """Fresh global recorder (dumping under tmp_path, unthrottled) and
    a disarmed fault registry around every test."""
    faults.reset()
    reset_recorder()
    rec = get_recorder()
    rec.dump_dir = str(tmp_path / "flight")
    rec.min_auto_interval_s = 0.0
    yield rec
    faults.reset()
    reset_recorder()


# --- ring + dump mechanics --------------------------------------------------


def test_ring_is_bounded_and_ordered():
    rec = FlightRecorder(capacity=5)
    for i in range(12):
        rec.record("t.kind", i=i)
    records = rec.records()
    assert len(records) == 5
    assert [r["i"] for r in records] == list(range(7, 12))
    assert all(r["kind"] == "t.kind" for r in records)


def test_dump_writes_versioned_jsonl(tmp_path):
    rec = FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    rec.record("a.b", x=1)
    rec.record("c.d", y="z")
    path = rec.dump(reason="unit test!")
    header, records = read_flight_dump(path)
    assert header["schema_version"] == obs.SCHEMA_VERSION
    assert header["reason"] == "unit test!"
    assert header["n_records"] == 2
    assert [r["kind"] for r in records] == ["a.b", "c.d"]
    assert records[0]["x"] == 1 and records[1]["y"] == "z"
    # every line is valid standalone JSON
    with open(path) as f:
        for line in f:
            json.loads(line)


def test_auto_dump_is_throttled_but_explicit_dump_is_not(tmp_path):
    rec = FlightRecorder(dump_dir=str(tmp_path),
                         min_auto_interval_s=3600.0)
    rec.record("x.y")
    assert rec.auto_dump("first") is not None
    assert rec.auto_dump("second") is None        # throttled
    assert rec.dump("explicit") is not None       # never throttled
    assert len(rec.dumps) == 2


def test_resolve_recorder_null_object_when_disabled(_isolation):
    assert resolve_recorder() is _isolation
    obs.disable()
    try:
        rec = resolve_recorder()
        assert rec is NULL_RECORDER and not rec.enabled
        # the whole surface is a no-op
        rec.record("a.b", x=1)
        rec.note_rejection()
        assert rec.auto_dump("r") is None and rec.dump() is None
        assert rec.records() == []
    finally:
        obs.enable()


# --- serving integration ----------------------------------------------------

V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def tiny_lm():
    return Model.build(
        zoo.transformer_lm(V, d_model=32, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (S,), seed=0)


def test_chaos_fault_dump_contains_failing_and_preceding_iterations(
        tiny_lm, _isolation):
    """THE acceptance shape: an armed ``serving.prefill`` fault fires
    mid-run; the auto-dump holds the failing iteration (recorded at
    step() entry, before the fault site runs) plus the preceding
    iterations still in the ring."""
    # the synchronous loop records every iteration on the ring; the
    # pipelined default batches steady-state ring writes onto the
    # host-window cadence (tested in test_serving_overlap.py), which
    # would thin the preceding-history this test pins down
    eng = ServingEngine(tiny_lm, num_slots=2, max_len=32,
                        overlap=False)
    assert eng.recorder is _isolation
    # build up preceding history: several full iterations first
    eng.submit(PATTERN[:4], 6)
    eng.submit(PATTERN[:5], 5)
    for _ in range(5):
        eng.step()
    fault_iter = eng._iters                 # the iteration that will fail
    faults.inject("serving.prefill", nth=1)
    eng.submit(PATTERN[:3], 4)              # its prefill will be poisoned
    eng.step()                              # fault fires -> auto dump
    assert faults.fired("serving.prefill") == 1
    assert len(_isolation.dumps) == 1
    header, records = read_flight_dump(_isolation.dumps[0])
    assert header["reason"] == "fault:serving.prefill"
    iters = [r["iter"] for r in records
             if r["kind"] == "serving.iteration"]
    assert fault_iter in iters              # the failing iteration
    assert len([i for i in iters if i < fault_iter]) >= 4  # preceding
    # the fault trigger itself is on the ring, after the iteration
    kinds = [r["kind"] for r in records]
    assert kinds.index("fault.triggered") \
        > kinds.index("serving.iteration")
    # batch composition rides on each iteration record
    assert all({"queue_depth", "occupied", "decoding", "prefilling",
                "admitted"} <= set(r)
               for r in records if r["kind"] == "serving.iteration")


def test_decode_fault_dump_fires_too(tiny_lm, _isolation):
    eng = ServingEngine(tiny_lm, num_slots=1, max_len=24)
    eng.submit(PATTERN[:4], 4)
    eng.step()                               # prefill + first decode
    faults.inject("serving.decode", nth=1)
    with pytest.raises(InjectedFault):
        eng.step()
    assert any("fault_serving.decode" in p or "serving.decode" in p
               for p in _isolation.dumps)


def test_admission_storm_triggers_dump(tiny_lm, _isolation):
    _isolation.reject_storm = 3
    eng = ServingEngine(tiny_lm, num_slots=1, max_len=24, max_queue=1)
    eng.submit(PATTERN[:4], 4)
    eng.step()                               # admits into the one slot
    eng.submit(PATTERN[:4], 4)               # fills the bounded queue
    sheds = 0
    for _ in range(5):
        with pytest.raises(AdmissionRejected):
            eng.submit(PATTERN[:4], 4)
        sheds += 1
    assert sheds == 5
    assert len(_isolation.dumps) >= 1
    header, records = read_flight_dump(_isolation.dumps[0])
    assert header["reason"] == "admission_storm"
    rejected = [r for r in records if r["kind"] == "serving.rejected"]
    assert len(rejected) == 3                 # the storm threshold
    assert all(r["max_queue"] == 1 for r in rejected)


def test_degraded_request_drain_dumps(tiny_lm, _isolation):
    eng = ServingEngine(tiny_lm, num_slots=1, max_len=24)
    eng.submit(PATTERN[:4], 4, deadline_s=1e-6)
    with pytest.raises(DegradedRequest):
        eng.run(max_steps=100)
    assert any("degraded_request_timed_out" in p
               for p in _isolation.dumps)


def test_disabled_engine_records_nothing(tiny_lm, _isolation):
    obs.disable()
    try:
        eng = ServingEngine(tiny_lm, num_slots=1, max_len=24)
        assert eng.recorder is NULL_RECORDER
        eng.submit(PATTERN[:4], 3)
        eng.run(max_steps=100)
    finally:
        obs.enable()
    assert _isolation.records() == []         # global ring untouched
    assert _isolation.dumps == []


# --- trainer + supervisor integration ---------------------------------------


def _ds(n=256):
    rs = np.random.RandomState(0)
    X = rs.randn(n, 8).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.int64)
    return Dataset({"features": X, "label": y})


def _trainer(ckpt, num_epoch=3):
    return SingleTrainer(
        Model.build(Sequential([Dense(16, activation="relu"), Dense(2)]),
                    (8,), seed=0),
        batch_size=32, num_epoch=num_epoch, worker_optimizer="adam",
        learning_rate=0.01,
        loss="sparse_categorical_crossentropy_from_logits",
        checkpoint_dir=ckpt)


def test_epoch_ring_hook_records_every_epoch(tmp_path, _isolation):
    _trainer(str(tmp_path / "ck")).train(_ds())
    epochs = [r for r in _isolation.records()
              if r["kind"] == "train.epoch"]
    assert [r["epoch"] for r in epochs] == [0, 1, 2]
    assert all(r["trainer"] == "SingleTrainer" for r in epochs)
    assert all(r["saved"] for r in epochs)    # checkpoint_every=1


def test_supervisor_restart_dumps_crash_context(tmp_path, _isolation):
    faults.inject("train.epoch", nth=2)       # crash in epoch 1
    sup = TrainingSupervisor(_trainer(str(tmp_path / "ck")),
                             max_restarts=2, handle_signals=())
    result = sup.run(_ds())
    assert result.restarts == 1
    restart_dumps = [p for p in _isolation.dumps
                     if "supervisor.restart" in p]
    assert len(restart_dumps) == 1
    header, records = read_flight_dump(restart_dumps[0])
    kinds = [r["kind"] for r in records]
    # crash context: the epochs before the crash, the fault trigger,
    # and the supervisor's intervention record
    assert "train.epoch" in kinds
    assert "fault.triggered" in kinds
    assert kinds[-1] == "supervisor.restart"
    (restart,) = [r for r in records
                  if r["kind"] == "supervisor.restart"]
    assert restart["attempt"] == 1
    assert "InjectedFault" in restart["error"]
