"""Driver-facing bench.py helpers: the serving footprint model, batch
sizing, spreads, and the cumulative summary line. These shape the
BENCH record the driver captures — regressions here silently corrupt
the round's evidence, so they get unit coverage even though bench.py
itself only runs on the chip."""

import json
import os
import sys

# repo root (bench.py is not in the package) — cwd-independent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench


def test_serving_footprint_monotonic_in_batch():
    f4 = bench._serving_footprint_gb(4, 16, 8192, 256, False, bench.LM_CFG)
    f8 = bench._serving_footprint_gb(8, 16, 8192, 256, False, bench.LM_CFG)
    assert f8 > f4 > 0


def test_serving_batch_reproduces_round4_edge():
    """The footprint budget was calibrated so MHA-bf16 P=8192 sizes to
    batch 4 (the measured round-4 OOM edge) while gqa4-int8 gets the
    headroom its 16x smaller cache earns."""
    mha = bench._serving_batch(16, 8192, 256, False, bench.LM_CFG)
    gqa_i8 = bench._serving_batch(4, 8192, 256, True, bench.LM_CFG)
    assert mha == 4
    assert gqa_i8 >= 8
    # max_batch caps the ladder (the CPU smoke path)
    assert bench._serving_batch(4, 8192, 256, True, bench.LM_CFG,
                                max_batch=2) == 2


def test_serving_cap_matches_generate_rounding():
    """Footprint cache sizes must mirror generate()'s block rounding, or
    the batch choice is for a different buffer than the one allocated."""
    from distkeras_tpu.ops.decode_attention import (MIN_KERNEL_LEN,
                                                    choose_block)
    total = 8192 + 257
    bl = choose_block(total)
    assert bench._serving_cap(total) == -(-total // bl) * bl
    assert bench._serving_cap(MIN_KERNEL_LEN - 1) == MIN_KERNEL_LEN - 1


def test_lm_param_count_against_known_configs():
    # 218M headline config and the 838M lm_big config (docs/PERF.md)
    assert round(bench._lm_param_count(bench.LM_CFG) / 1e6) == 218
    assert round(bench._lm_param_count(bench.LM_BIG_CFG) / 1e6) == 839
    # GQA shrinks only the kv projections
    full = bench._lm_param_count(bench.LM_CFG)
    gqa = bench._lm_param_count(bench.LM_CFG, kv_heads=4)
    assert 0 < full - gqa < full * 0.1


def test_spread_is_min_median_max():
    assert bench._spread([3.0, 1.0, 2.0]) == [1.0, 2.0, 3.0]


def test_summary_line_carries_every_headline_and_stays_compact():
    records = [
        {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2571.0,
         "vs_baseline": 2.571, "unit": "imgs/sec", "mfu": 0.313},
        {"metric": "lm_train_tokens_per_sec_per_chip", "value": 64156.0,
         "vs_baseline": 2.14, "mfu": 0.363},
        {"metric": "lm_generate_new_tokens_per_sec_per_chip",
         "value": 6809.0, "vs_baseline": 1.0},
        {"metric": "lm_generate_p8192_decode_tokens_per_sec_per_chip",
         "value": 4449.0, "vs_baseline": 6.2,
         "headline_variant": "gqa4_p8192_int8"},
        {"metric": "moe_lm_train_tokens_per_sec_per_chip",
         "value": 47218.0, "vs_baseline": 0.73},
        {"metric": "lm_big_train_tokens_per_sec_per_chip",
         "value": 20679.0, "vs_baseline": 1.54, "mfu": 0.559},
    ]
    line = bench._summary_line(records, "TPU v5 lite")
    parsed = json.loads(line)
    assert len(parsed["headlines"]) == 6
    assert parsed["headlines"][
        "lm_generate_p8192_decode_tokens_per_sec_per_chip"][
        "headline_variant"] == "gqa4_p8192_int8"
    # the whole point: the line must fit the driver's 2,000-char tail
    # capture window with room for the preceding family line
    assert len(line) < 1500, len(line)
    # first record doubles as the line's own metric fields
    assert parsed["value"] == 2571.0 and parsed["unit"] == "imgs/sec"


# -- regression tripwire (overlap PR) ----------------------------------------

def test_prev_headlines_reads_newest_round():
    import glob
    import re
    root = os.path.dirname(bench.__file__)
    rounds = [int(re.search(r"BENCH_r(\d+)\.json$", p).group(1))
              for p in glob.glob(os.path.join(root, "BENCH_r*.json"))]
    heads, src, kind = bench._prev_headlines(root)
    # whatever rounds the repo carries, the newest must win (r05 as of
    # this test's writing; hardcoding it would break on every new round)
    assert src == f"BENCH_r{max(rounds):02d}.json"
    assert isinstance(heads, dict) and heads
    assert isinstance(kind, str) and kind  # gate for cross-hw comparisons


def test_regression_check_flags_value_drop():
    prev = {"m": {"value": 1000.0, "vs_baseline": 2.0}}
    rec = {"metric": "m", "value": 850.0, "vs_baseline": 2.0}
    out = bench._regression_check(rec, prev, "BENCH_r05.json")
    assert out["value_vs_prev"] == 0.85
    assert any("value dropped" in f for f in out["flags"])


def test_regression_check_passes_within_tolerance():
    prev = {"m": {"value": 1000.0, "vs_baseline": 2.0}}
    rec = {"metric": "m", "value": 950.0, "vs_baseline": 1.95}
    out = bench._regression_check(rec, prev, "BENCH_r05.json")
    assert out is not None and "flags" not in out
    assert out["value_vs_prev"] == 0.95


def test_regression_check_flags_the_known_moe_below_anchor():
    """The standing moe_lm_train 0.735x regression (BENCH_r05's
    numbers, pinned here as a synthetic prev record so the test
    outlives the repo's BENCH files): even when the value matches the
    previous round exactly, the below-anchor flag keeps it visible
    instead of letting two matching rounds silently normalize it."""
    heads = {"moe_lm_train_tokens_per_sec_per_chip":
             {"value": 47156.5, "vs_baseline": 0.735}}
    rec = {"metric": "moe_lm_train_tokens_per_sec_per_chip",
           "value": 47156.5, "vs_baseline": 0.735}
    out = bench._regression_check(rec, heads, "BENCH_r05.json")
    assert any("below_anchor" in f for f in out["flags"])
    assert "value dropped" not in " ".join(out["flags"])  # value held


def test_regression_check_none_without_history_or_flags():
    rec = {"metric": "m", "value": 100.0, "vs_baseline": 1.2}
    assert bench._regression_check(rec, None, None) is None


def test_summary_line_surfaces_regression_flags():
    records = [
        {"metric": "a", "value": 1.0, "vs_baseline": 1.0,
         "regression": {"flags": ["value dropped to 0.850x of r05"]}},
        {"metric": "b", "value": 2.0, "vs_baseline": 1.5,
         "regression": None},
    ]
    parsed = json.loads(bench._summary_line(records, "cpu"))
    assert parsed["regressions"] == {
        "a": ["value dropped to 0.850x of r05"]}


def test_regression_check_skips_cross_hardware_comparison():
    """A CPU smoke run vs a TPU-captured record must not flag a bogus
    100x 'drop' — anchors carry device_kind, and a prior-round record
    from different hardware reports as a STALE ANCHOR instead of
    flagging every run (the in-run below-anchor check still applies)."""
    prev = {"m": {"value": 64000.0, "vs_baseline": 2.0}}
    rec = {"metric": "m", "value": 600.0, "vs_baseline": 2.0,
           "device_kind": "cpu"}
    out = bench._regression_check(rec, prev, "BENCH_r05.json",
                                  prev_kind="TPU v5 lite")
    assert "flags" not in out and "value_vs_prev" not in out
    assert "device_kind" in out["stale_anchor"]
    assert "stale" in out["stale_anchor"]
    # same hardware: the comparison runs and flags
    out = bench._regression_check(dict(rec, device_kind="TPU v5 lite"),
                                  prev, "BENCH_r05.json",
                                  prev_kind="TPU v5 lite")
    assert any("dropped" in f for f in out["flags"])


def test_summary_line_surfaces_stale_anchors():
    """The cumulative summary line names the families whose prior-round
    anchor came from different hardware (one shared note, not flags)."""
    records = [
        {"metric": "a", "value": 1.0, "vs_baseline": 1.1,
         "regression": {"stale_anchor":
                        "BENCH_r05.json was captured on device_kind "
                        "'TPU v5 lite', this run is 'cpu': cross-device "
                        "anchor is stale, vs-prev comparison skipped"}},
        {"metric": "b", "value": 2.0, "vs_baseline": 1.5,
         "regression": None},
    ]
    parsed = json.loads(bench._summary_line(records, "cpu"))
    assert parsed["stale_anchors"] == ["a"]
    assert "stale" in parsed["stale_anchor_note"]
    assert "regressions" not in parsed


def test_regression_check_inverts_for_lower_is_better_metric():
    """overlap_train_ckpt_overhead_x is lower-is-better: an improvement
    (value drop) must NOT flag, a >11% rise must."""
    metric = "overlap_train_ckpt_overhead_x"
    assert metric in bench.LOWER_IS_BETTER
    prev = {metric: {"value": 1.2, "vs_baseline": 0.833}}
    improved = {"metric": metric, "value": 1.0, "vs_baseline": 1.0}
    out = bench._regression_check(improved, prev, "BENCH_r05.json")
    assert "flags" not in out, out
    worse = {"metric": metric, "value": 1.4, "vs_baseline": 0.714}
    out = bench._regression_check(worse, prev, "BENCH_r05.json")
    assert any("rose" in f for f in out["flags"])
    assert any("below_anchor" in f for f in out["flags"])


def test_regression_check_flags_pre_serving_era_anchor():
    """A prior record whose headline roster is entirely pre-serving
    families (the real BENCH_r05 shape) is a stale anchor: the moe
    0.735x comparison against it is archaeology, not a regression.
    The in-run below-anchor tripwire still applies."""
    metric = "moe_lm_train_tokens_per_sec_per_chip"
    prev = {metric: {"value": 47156.5, "vs_baseline": 0.735},
            "lm_train_tokens_per_sec_per_chip":
                {"value": 100.0, "vs_baseline": 1.0}}
    assert set(prev) <= bench.PRE_SERVING_FAMILIES
    rec = {"metric": metric, "value": 20000.0, "vs_baseline": 0.735}
    out = bench._regression_check(rec, prev, "BENCH_r05.json")
    assert "predates the serving stack" in out["stale_anchor"]
    assert "value_vs_prev" not in out          # comparison skipped
    assert any("below_anchor" in f for f in out["flags"])  # in-run


def test_regression_check_runs_against_serving_era_anchor():
    """One serving-era family in the prior roster means the record
    postdates the stack: comparisons run (and flag) normally."""
    prev = {"lm_train_tokens_per_sec_per_chip":
                {"value": 1000.0, "vs_baseline": 2.0},
            "serving_steady_decode_tokens_per_sec_per_chip":
                {"value": 50.0, "vs_baseline": 0.95}}
    rec = {"metric": "lm_train_tokens_per_sec_per_chip",
           "value": 850.0, "vs_baseline": 2.0}
    out = bench._regression_check(rec, prev, "BENCH_r06.json")
    assert "stale_anchor" not in out
    assert out["value_vs_prev"] == 0.85
    assert any("dropped" in f for f in out["flags"])


def test_footprint_cache_dtype_ladder():
    """int4 pages are half of int8's payload; both quantized rungs pay
    the f32 scale planes; the legacy bool knob still means int8."""
    args = (8, 16, 8192, 256)
    bf16 = bench._serving_footprint_gb(*args, "auto", bench.LM_CFG)
    i8 = bench._serving_footprint_gb(*args, "int8", bench.LM_CFG)
    i4 = bench._serving_footprint_gb(*args, "int4", bench.LM_CFG)
    assert bf16 > i8 > i4
    assert i8 == bench._serving_footprint_gb(*args, True, bench.LM_CFG)
    assert bf16 == bench._serving_footprint_gb(*args, False,
                                               bench.LM_CFG)
    # int4 sizes at least the int8 batch at the same config
    assert bench._serving_batch(4, 8192, 256, "int4", bench.LM_CFG) >= \
        bench._serving_batch(4, 8192, 256, "int8", bench.LM_CFG)


def test_quant_ladder_covers_every_rung():
    names = [n for n, _ in bench.QUANT_LADDER]
    assert names[0] == "bf16" and bench.QUANT_LADDER[0][1] == {}
    assert {"w_int8", "w_int4", "kv_int8", "kv_int4",
            "w4kv4"} <= set(names)
    corner = dict(bench.QUANT_LADDER)["w4kv4"]
    assert corner == {"weights_dtype": "int4", "cache_dtype": "int4"}


def test_quant_hbm_math_rider():
    """The untimed byte rider: int4 weights ~halve int8's bytes; the
    KV bytes/token ladder ordering holds with scale planes counted."""
    from distkeras_tpu.models import Model, zoo

    cfg = dict(vocab=64, d_model=32, num_heads=4, num_layers=2,
               mlp_ratio=2, seq=16)
    model = Model.build(zoo.transformer_lm(
        cfg["vocab"], d_model=cfg["d_model"],
        num_heads=cfg["num_heads"], num_layers=cfg["num_layers"],
        mlp_ratio=cfg["mlp_ratio"], use_rope=True), (16,), seed=0)
    hm = bench._quant_hbm_math(model, cfg)
    wb, kv = hm["weight_bytes"], hm["kv_bytes_per_token"]
    assert wb["int8"] < wb["bf16"] * 0.75
    assert wb["int4"] < wb["int8"]
    assert kv["bf16"] > kv["int8"] > kv["int4"]
