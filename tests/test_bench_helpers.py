"""Driver-facing bench.py helpers: the serving footprint model, batch
sizing, spreads, and the cumulative summary line. These shape the
BENCH record the driver captures — regressions here silently corrupt
the round's evidence, so they get unit coverage even though bench.py
itself only runs on the chip."""

import json
import os
import sys

# repo root (bench.py is not in the package) — cwd-independent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench


def test_serving_footprint_monotonic_in_batch():
    f4 = bench._serving_footprint_gb(4, 16, 8192, 256, False, bench.LM_CFG)
    f8 = bench._serving_footprint_gb(8, 16, 8192, 256, False, bench.LM_CFG)
    assert f8 > f4 > 0


def test_serving_batch_reproduces_round4_edge():
    """The footprint budget was calibrated so MHA-bf16 P=8192 sizes to
    batch 4 (the measured round-4 OOM edge) while gqa4-int8 gets the
    headroom its 16x smaller cache earns."""
    mha = bench._serving_batch(16, 8192, 256, False, bench.LM_CFG)
    gqa_i8 = bench._serving_batch(4, 8192, 256, True, bench.LM_CFG)
    assert mha == 4
    assert gqa_i8 >= 8
    # max_batch caps the ladder (the CPU smoke path)
    assert bench._serving_batch(4, 8192, 256, True, bench.LM_CFG,
                                max_batch=2) == 2


def test_serving_cap_matches_generate_rounding():
    """Footprint cache sizes must mirror generate()'s block rounding, or
    the batch choice is for a different buffer than the one allocated."""
    from distkeras_tpu.ops.decode_attention import (MIN_KERNEL_LEN,
                                                    choose_block)
    total = 8192 + 257
    bl = choose_block(total)
    assert bench._serving_cap(total) == -(-total // bl) * bl
    assert bench._serving_cap(MIN_KERNEL_LEN - 1) == MIN_KERNEL_LEN - 1


def test_lm_param_count_against_known_configs():
    # 218M headline config and the 838M lm_big config (docs/PERF.md)
    assert round(bench._lm_param_count(bench.LM_CFG) / 1e6) == 218
    assert round(bench._lm_param_count(bench.LM_BIG_CFG) / 1e6) == 839
    # GQA shrinks only the kv projections
    full = bench._lm_param_count(bench.LM_CFG)
    gqa = bench._lm_param_count(bench.LM_CFG, kv_heads=4)
    assert 0 < full - gqa < full * 0.1


def test_spread_is_min_median_max():
    assert bench._spread([3.0, 1.0, 2.0]) == [1.0, 2.0, 3.0]


def test_summary_line_carries_every_headline_and_stays_compact():
    records = [
        {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2571.0,
         "vs_baseline": 2.571, "unit": "imgs/sec", "mfu": 0.313},
        {"metric": "lm_train_tokens_per_sec_per_chip", "value": 64156.0,
         "vs_baseline": 2.14, "mfu": 0.363},
        {"metric": "lm_generate_new_tokens_per_sec_per_chip",
         "value": 6809.0, "vs_baseline": 1.0},
        {"metric": "lm_generate_p8192_decode_tokens_per_sec_per_chip",
         "value": 4449.0, "vs_baseline": 6.2,
         "headline_variant": "gqa4_p8192_int8"},
        {"metric": "moe_lm_train_tokens_per_sec_per_chip",
         "value": 47218.0, "vs_baseline": 0.73},
        {"metric": "lm_big_train_tokens_per_sec_per_chip",
         "value": 20679.0, "vs_baseline": 1.54, "mfu": 0.559},
    ]
    line = bench._summary_line(records, "TPU v5 lite")
    parsed = json.loads(line)
    assert len(parsed["headlines"]) == 6
    assert parsed["headlines"][
        "lm_generate_p8192_decode_tokens_per_sec_per_chip"][
        "headline_variant"] == "gqa4_p8192_int8"
    # the whole point: the line must fit the driver's 2,000-char tail
    # capture window with room for the preceding family line
    assert len(line) < 1500, len(line)
    # first record doubles as the line's own metric fields
    assert parsed["value"] == 2571.0 and parsed["unit"] == "imgs/sec"
