"""Quantized decode-GEMM weights (quantized-decode PR).

``ops.quant_matmul``: per-channel int8/int4 weight quantization with a
fused dequant-matmul Pallas kernel, pinned against the XLA reference
under ``interpret=True`` (the tier-1 CPU oracle convention), plus the
``ServingEngine(weight_quant=)`` wiring — in-graph dequant for the
non-attention leaves, the kernel path for the attention projections —
and the ``obs.report`` accuracy-drift hook.

Documented tolerance: symmetric per-channel quantization bounds the
per-entry weight error by half a quantization step
(``scale / 2 = absmax / (2 * qmax)``); the matmul tests below assert
kernel == reference to f32 round-off (both compute the SAME factored
``(x @ q) * scale``), and the engine tests assert greedy token
identity on the overfit pattern LM (margins far exceed int4 drift).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import generate
from distkeras_tpu.ops import quant_matmul as qm
from distkeras_tpu.serving.engine import ServingEngine


# --- quantize_weight / pack format -----------------------------------------


def test_pack_rows_roundtrip():
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randint(-7, 8, size=(64, 3, 5)), jnp.int8)
    np.testing.assert_array_equal(
        np.asarray(qm.unpack_rows(qm.pack_rows(q))), np.asarray(q))


@pytest.mark.parametrize("shape,reduce_axes,bits", [
    ((128, 4, 32), (0,), 8),        # wq layout, per-(h, e) channels
    ((128, 4, 32), (0,), 4),
    ((4, 32, 128), (0, 1), 4),      # wo layout, per-d channels
    ((256, 384), None, 8),          # MLP default (all-but-last)
    ((255, 384), None, 4),          # odd axis 0: int4 stays unpacked
])
def test_quantize_weight_error_within_half_step(shape, reduce_axes, bits):
    rs = np.random.RandomState(1)
    w = rs.randn(*shape).astype(np.float32)
    wq = qm.quantize_weight(w, bits, reduce_axes=reduce_axes)
    # the packing contract: int4 nibble-packs along axis 0 iff even
    assert ("q4" in wq) == (bits == 4 and shape[0] % 2 == 0)
    deq = np.asarray(qm.dequant_weight(wq)).reshape(shape)
    red = reduce_axes if reduce_axes else tuple(range(w.ndim - 1))
    step = np.abs(w).max(axis=red, keepdims=True) / (7 if bits == 4
                                                     else 127)
    assert np.all(np.abs(deq - w) <= step * 0.5 + 1e-6)


def test_quantize_weight_validates():
    with pytest.raises(ValueError, match="bits"):
        qm.quantize_weight(np.ones((4, 4), np.float32), 3)
    with pytest.raises(ValueError, match="matrix"):
        qm.quantize_weight(np.ones(4, np.float32))
    with pytest.raises(ValueError, match="prefix"):
        qm.quantize_weight(np.ones((4, 4, 4), np.float32),
                           reduce_axes=(1,))


def test_zero_channel_dequantizes_to_zero():
    w = np.zeros((16, 8), np.float32)
    w[:, 0] = 3.0
    wq = qm.quantize_weight(w, 4)
    np.testing.assert_allclose(np.asarray(qm.dequant_weight(wq)), w,
                               atol=3 / 14 + 1e-6)
    assert np.asarray(qm.dequant_weight(wq))[:, 1:].max() == 0.0


# --- the kernel vs the reference (interpret-mode oracle) -------------------


@pytest.mark.parametrize("bits,layout", [
    (8, "proj"), (4, "proj"), (8, "out"), (4, "out")])
def test_kernel_matches_reference(bits, layout):
    """The Pallas kernel (interpreter mode — the CI oracle) computes
    the same factored ``(x @ q) * scale`` as ``reference_matmul``."""
    rs = np.random.RandomState(2)
    if layout == "proj":
        w = rs.randn(128, 4, 32).astype(np.float32)     # [d, h, e]
        wq = qm.quantize_weight(w, bits, reduce_axes=(0,))
        x = jnp.asarray(rs.randn(3, 5, 128), jnp.float32)
    else:
        w = rs.randn(4, 32, 256).astype(np.float32)     # [h, e, d]
        wq = qm.quantize_weight(w, bits, reduce_axes=(0, 1))
        x = jnp.asarray(rs.randn(7, 128), jnp.float32)  # odd M: pad path
    with qm.force_interpret():
        assert qm.fused_supported(128, 128)
        out = qm.quant_matmul(x, wq)
    ref = qm.reference_matmul(x, wq)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # and the factored product equals dequant-then-matmul exactly in
    # f32 math terms (scale is constant along the contraction)
    k = x.shape[-1]
    deq = np.asarray(qm.dequant_weight(wq)).reshape(k, -1)
    want = np.asarray(x).reshape(-1, k) @ deq
    np.testing.assert_allclose(
        np.asarray(ref).reshape(want.shape), want, rtol=1e-4, atol=1e-4)


def test_alignment_and_backend_gate():
    assert not qm.fused_supported(128, 128)   # CPU, no force: closed
    with qm.force_interpret():
        assert qm.fused_supported(128, 128)
        assert qm.fused_supported(128, 640)   # 640 = 5 * 128
        assert not qm.fused_supported(96, 128)    # K % 128
        assert not qm.fused_supported(128, 100)   # no 128-divisor of N
    assert qm.choose_block_n(512) == 512
    assert qm.choose_block_n(1024) == 512     # capped
    assert qm.choose_block_n(100) is None


def test_misaligned_shapes_fall_back_to_reference():
    rs = np.random.RandomState(3)
    wq = qm.quantize_weight(rs.randn(128, 100).astype(np.float32), 8)
    x = jnp.asarray(rs.randn(4, 128), jnp.float32)
    with qm.force_interpret():
        out = qm.quant_matmul(x, wq)          # N=100: silently reference
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(qm.reference_matmul(x, wq)),
                               rtol=1e-6)


def test_resolve_rejects_mismatched_contraction():
    wq = qm.quantize_weight(np.ones((64, 8), np.float32), 8)
    with pytest.raises(ValueError, match="contract"):
        qm.quant_matmul(jnp.ones((2, 100), jnp.float32), wq)


# --- params-tree plumbing --------------------------------------------------


def _tiny_lm(vocab=29, d=32, seed=2):
    return Model.build(
        zoo.transformer_lm(vocab, d_model=d, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (12,), seed=seed)


def test_tree_roundtrip_preserves_shapes_and_error_bound():
    m = _tiny_lm()
    qt = qm.quantize_params_tree(m.params, 4)
    deq = qm.dequant_params_tree(qt, jnp.float32)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(m.params)[0],
            jax.tree_util.tree_flatten_with_path(deq)[0]):
        assert np.asarray(a).shape == np.asarray(b).shape, pa
    errs = qm.tree_quant_errors(m.params, qt)
    assert errs and all(e["rel_rms"] < 0.2 for e in errs.values())
    # keep_attn leaves exactly the projection qdicts quantized
    keep = qm.dequant_params_tree(qt, jnp.float32, keep_attn=True)
    attn = keep[1]["attn"]
    assert all(qm.is_qdict(attn[k]) for k in ("wq", "wk", "wv", "wo"))
    flat = jax.tree_util.tree_leaves(
        {k: v for k, v in keep[1].items() if k != "attn"})
    assert all(np.issubdtype(np.asarray(l).dtype, np.floating)
               or np.asarray(l).ndim < 2 for l in flat)


# --- engine wiring ---------------------------------------------------------


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    return pattern_lm


PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


def _run(eng, prompt, budget):
    rid = eng.submit(prompt, budget)
    return eng.run(max_steps=300)[rid]


@pytest.mark.parametrize("wq", ["int8", "int4"])
def test_engine_weight_quant_matches_baseline_tokens(memorized_lm, wq):
    m = memorized_lm
    base = _run(ServingEngine(m, num_slots=2, max_len=32), PATTERN[:4], 7)
    eng = ServingEngine(m, num_slots=2, max_len=32, weight_quant=wq)
    np.testing.assert_array_equal(_run(eng, PATTERN[:4], 7), base)
    errs = eng.weight_quant_error
    assert errs and all(
        v["rel_rms"] < (0.25 if wq == "int4" else 0.05)
        for v in errs.values())


def test_engine_weight_quant_composes_with_int4_kv(memorized_lm):
    """The full quantization ladder at once: int4 weights over int4 KV
    pages still reproduce the baseline greedy stream."""
    m = memorized_lm
    base = _run(ServingEngine(m, num_slots=2, max_len=32), PATTERN[:4], 7)
    eng = ServingEngine(m, num_slots=2, max_len=128, page_len=64,
                        weight_quant="int4", cache_dtype="int4")
    np.testing.assert_array_equal(_run(eng, PATTERN[:4], 7), base)


def test_engine_kernel_path_matches_reference_path():
    """d_model=128 aligns the projections with the kernel gate: the
    decode programs route QKV/out through the fused dequant-matmul
    (interpreter mode) and must emit the same tokens as the pure
    in-graph-dequant reference engine over the SAME qdicts."""
    m = Model.build(
        zoo.transformer_lm(31, d_model=128, num_heads=4, num_layers=2,
                           mlp_ratio=2, use_rope=True), (8,), seed=0)
    prompt = np.array([1, 2, 3, 4])
    ref_eng = ServingEngine(m, num_slots=1, max_len=16,
                            weight_quant="int4")
    assert not ref_eng._wq_keep_attn          # CPU: gate closed
    ref = _run(ref_eng, prompt, 5)
    with qm.force_interpret():
        k_eng = ServingEngine(m, num_slots=1, max_len=16,
                              weight_quant="int4")
        assert k_eng._wq_keep_attn
        got = _run(k_eng, prompt, 5)
    np.testing.assert_array_equal(got, ref)


def test_engine_weight_quant_validates():
    m = _tiny_lm()
    with pytest.raises(ValueError, match="weight_quant"):
        ServingEngine(m, num_slots=1, max_len=32, weight_quant="fp8")
    with pytest.raises(ValueError, match="paged"):
        ServingEngine(m, num_slots=1, max_len=32, kv_layout="slab",
                      hbm_budget=1 << 20)


def test_generate_int4_weights_close_to_float(memorized_lm):
    """generate()'s weights_dtype ladder gained the int4 rung (unpacked
    4-bit grid via models.quantize): greedy tokens match f32 on the
    overfit LM."""
    m = memorized_lm
    ref = generate(m, PATTERN[None, :4], max_new_tokens=7,
                   temperature=0.0)
    got = generate(m, PATTERN[None, :4], max_new_tokens=7,
                   temperature=0.0, weights_dtype="int4")
    np.testing.assert_array_equal(got, ref)


# --- the obs report hook ---------------------------------------------------


def test_weight_quant_report(memorized_lm):
    from distkeras_tpu.obs.report import (weight_quant_markdown,
                                          weight_quant_report)
    eng = ServingEngine(memorized_lm, num_slots=1, max_len=32,
                        weight_quant="int4")
    rep = weight_quant_report(eng)
    assert rep["weight_quant"] == "int4"
    assert rep["num_leaves"] == len(eng.weight_quant_error)
    assert rep["worst_leaf"] in eng.weight_quant_error
    assert 0 < rep["worst_rel_rms"] < 0.25
    md = weight_quant_markdown(rep)
    assert "Weight quantization accuracy (int4)" in md
    assert rep["worst_leaf"] in md
    with pytest.raises(ValueError, match="weight_quant"):
        weight_quant_report(
            ServingEngine(memorized_lm, num_slots=1, max_len=32))
