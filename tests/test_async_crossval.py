"""Cross-validation of the SPMD async emulation against the true-async path.

The engine (``parallel/engine.py``) *emulates* the reference's async PS
dynamics inside one compiled SPMD program; ``HostAsyncTrainer``
(``parallel/async_host.py``) *reproduces* them with real racing threads
against a mutex-guarded parameter server — the reference's actual
concurrency model (``distkeras/workers.py`` vs the driver-side PS). The
thread path is therefore the only available ground truth for the
emulation (SURVEY §7 hard part (a)): the same problem, model and seeds
must converge to the same quality through both.

Trajectories cannot match step-for-step (thread scheduling is wall-clock
nondeterministic by design), so the oracle is converged-model agreement:
final evaluation loss and accuracy within tolerance, on held-out data.
"""

import jax
import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.models import Dense, Model, Sequential
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.metrics import accuracy
from distkeras_tpu.parallel import AEASGD, DOWNPOUR
from distkeras_tpu.parallel.async_host import HostAsyncTrainer

N, D, C = 4096, 16, 4
EPOCHS = 8


def make_data(seed=0):
    rs = np.random.RandomState(seed)
    X = rs.randn(N, D).astype(np.float32)
    W = rs.randn(D, C)
    y = np.argmax(X @ W + 0.1 * rs.randn(N, C), axis=1)
    n_tr = N - 1024
    return (Dataset({"features": X[:n_tr], "label": y[:n_tr]}),
            X[n_tr:], y[n_tr:])


def mlp(seed=0):
    return Model.build(Sequential([
        Dense(64, activation="relu"), Dense(C)]), (D,), seed=seed)


def final_quality(model, X_ev, y_ev):
    logits = model.predict(X_ev)
    loss = float(get_loss("sparse_categorical_crossentropy_from_logits")(
        y_ev, logits))
    return loss, float(accuracy(y_ev, logits))


COMMON = dict(num_workers=8, batch_size=32, num_epoch=EPOCHS,
              worker_optimizer="sgd",
              optimizer_kwargs={"learning_rate": 0.05},
              loss="sparse_categorical_crossentropy_from_logits", seed=7)


@pytest.mark.parametrize("window", [4, 8])
def test_downpour_engine_matches_host_async(window):
    ds, X_ev, y_ev = make_data()
    engine_tr = DOWNPOUR(mlp(), communication_window=window, **COMMON)
    host_tr = HostAsyncTrainer(mlp(), algorithm="downpour",
                               communication_window=window, **COMMON)
    el, ea = final_quality(engine_tr.train(ds), X_ev, y_ev)
    hl, ha = final_quality(host_tr.train(ds), X_ev, y_ev)
    assert ea > 0.8 and ha > 0.8, (ea, ha)
    assert abs(ea - ha) < 0.08, f"accuracy gap engine={ea:.3f} host={ha:.3f}"
    assert abs(el - hl) < 0.25, f"eval-loss gap engine={el:.3f} host={hl:.3f}"


def test_aeasgd_engine_matches_host_async():
    """Wider tolerance than DOWNPOUR: the emulation's batched elastic
    rounds mix the replicas deterministically every K steps, while the
    thread path's center evolves under genuinely stale arrivals — the
    emulation consistently converges slightly FASTER (engine ~0.91 vs
    host ~0.83 at these settings), never slower."""
    ds, X_ev, y_ev = make_data()
    common = dict(COMMON, optimizer_kwargs={"learning_rate": 0.1},
                  num_epoch=12)
    engine_tr = AEASGD(mlp(), rho=5.0, learning_rate=0.02,
                       communication_window=8, **common)
    host_tr = HostAsyncTrainer(mlp(), algorithm="easgd", rho=5.0,
                               elastic_lr=0.02, communication_window=8,
                               **common)
    el, ea = final_quality(engine_tr.train(ds), X_ev, y_ev)
    hl, ha = final_quality(host_tr.train(ds), X_ev, y_ev)
    assert ea > 0.8 and ha > 0.8, (ea, ha)
    assert ea >= ha - 0.02, (
        f"emulation must not converge WORSE than the true-async oracle: "
        f"engine={ea:.3f} host={ha:.3f}")
    assert abs(ea - ha) < 0.12, f"accuracy gap engine={ea:.3f} host={ha:.3f}"
    assert abs(el - hl) < 0.40, f"eval-loss gap engine={el:.3f} host={hl:.3f}"


def test_staleness_profiles_comparable():
    """The emulation's commit cadence should produce center-update counts
    in the same regime as the thread path: with window K and S steps per
    epoch per worker, both paths apply ~(workers * S / K) commits' worth
    of contributions per epoch."""
    ds, X_ev, y_ev = make_data()
    window = 8
    host_tr = HostAsyncTrainer(mlp(), algorithm="downpour",
                               communication_window=window, **COMMON)
    host_tr.train(ds)
    S = (N - 1024) // (8 * 32)
    expected = 8 * (S // window + 1) * EPOCHS
    n_updates = host_tr.parameter_server.num_updates
    # thread workers commit every K steps plus a final residual flush
    assert 0.5 * expected <= n_updates <= 1.5 * expected, (
        n_updates, expected)
