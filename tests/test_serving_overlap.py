"""Zero-bubble serving loop (this PR): the oracle contract — pipelined
dispatch (``overlap=True``, the engine default) and the fused
multi-step window (``fuse_steps=K``) must produce TOKEN-IDENTICAL
outputs (byte-identical for sampled streams) to the synchronous
launch-and-wait loop and to standalone ``generate()`` — across
slab/paged layouts, int8 cache, speculation, MoE dispatched decode and
preempt/resume — plus the lagged-fetch edge cases: stop tokens
mid-window and mid-fused-scan, preemption during a fused window
(fall back to single-step, rejoin identically), cancel/metrics-swap
pipeline flushes, fault injection inside a fused window, and the
deferred host-window tracer/metrics cadence staying exact-count."""

import numpy as np
import pytest

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.models.decoding import generate
from distkeras_tpu.resilience import InjectedFault, faults
from distkeras_tpu.serving import (NgramDraft, ServingEngine,
                                   ServingMetrics)

V, S = 29, 12
PATTERN = np.array([3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8])


@pytest.fixture(scope="module")
def memorized_lm(pattern_lm):
    """The shared session-scoped overfit-PATTERN LM (conftest pattern_lm): huge greedy argmax margins keep token-identity assertions robust; trained once per test session."""
    return pattern_lm


@pytest.fixture(scope="module")
def memorized_moe_lm(pattern_moe_lm):
    """The shared session-scoped all-MoE overfit-PATTERN LM
    (conftest pattern_moe_lm); trained once per session."""
    return pattern_moe_lm


def _drive(eng, subs, stagger=0):
    """Submit ``subs`` (kwargs for ``submit``), optionally stepping
    ``stagger`` iterations between arrivals, then drain. Returns
    ``{rid: tokens}`` in submit order alongside the rid list."""
    out = {}

    def tick():
        for r in eng.step():
            out[r.rid] = np.asarray(r.tokens)

    rids = []
    for kw in subs:
        rids.append(eng.submit(**kw))
        for _ in range(stagger):
            tick()
    steps = 0
    while eng.scheduler.pending:
        tick()
        steps += 1
        assert steps < 5000, "engine failed to drain"
    return out, rids


def _paged_kw(paged):
    return (dict(page_len=4, num_pages=24, prefix_cache=False)
            if paged else {})


# --- pipelined dispatch: token identity --------------------------------------


@pytest.mark.parametrize("paged", [False, True])
def test_pipelined_staggered_arrivals_match_generate(memorized_lm,
                                                     paged):
    """Staggered arrivals with mixed prompt lengths/budgets through
    the overlap engine (slots recycle mid-pipeline): every request's
    greedy tokens equal standalone generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=3, max_len=32, overlap=True,
                        **_paged_kw(paged))
    prompts = [PATTERN[:4], PATTERN[:6], PATTERN[:3], PATTERN[:5],
               PATTERN[:7]]
    budgets = [7, 5, 9, 6, 4]
    subs = [dict(prompt=p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    out, rids = _drive(eng, subs, stagger=2)
    for i, rid in enumerate(rids):
        ref = generate(m, prompts[i][None], budgets[i],
                       temperature=0.0)
        np.testing.assert_array_equal(out[rid], ref[0])


@pytest.mark.parametrize("paged", [False, True])
def test_pipelined_stop_token_mid_stream_matches_generate(memorized_lm,
                                                          paged):
    """A stop token that fires while the NEXT step is already in
    flight (the overshoot contract: the stream is stepped at most once
    past its stop, the extra token never consumed)."""
    m = memorized_lm
    prompt = PATTERN[:5]
    ref = generate(m, prompt[None], 16, temperature=0.0,
                   stop_token=9)[0]
    assert 9 in np.asarray(ref)[len(prompt):], \
        "fixture drift: 9 must appear in the greedy continuation"
    eng = ServingEngine(m, num_slots=2, max_len=32, overlap=True,
                        **_paged_kw(paged))
    out, rids = _drive(eng, [
        dict(prompt=prompt, max_new_tokens=16, stop_token=9),
        dict(prompt=PATTERN[:4], max_new_tokens=8)])
    got = out[rids[0]]
    assert got[-1] == 9 and len(got) < len(prompt) + 16
    np.testing.assert_array_equal(got, np.asarray(ref)[:len(got)])
    assert (np.asarray(ref)[len(got):] == 9).all()   # generate()'s pad
    np.testing.assert_array_equal(
        out[rids[1]],
        generate(m, PATTERN[None, :4], 8, temperature=0.0)[0])


@pytest.mark.parametrize("paged", [False, True])
def test_sampled_byte_identity_vs_synchronous_engine(memorized_lm,
                                                     paged):
    """Sampled streams: the pipelined engine's draws must be
    BYTE-identical to the synchronous engine's — key chaining through
    the device-side feedback path replays the same per-slot splits."""
    m = memorized_lm
    subs = [dict(prompt=PATTERN[:5], max_new_tokens=10,
                 temperature=0.9, top_p=0.95, seed=7),
            dict(prompt=PATTERN[:4], max_new_tokens=12,
                 temperature=0.7, top_k=8, seed=11),
            dict(prompt=PATTERN[:6], max_new_tokens=8)]   # greedy rider
    outs = {}
    for overlap in (False, True):
        eng = ServingEngine(m, num_slots=2, max_len=32,
                            overlap=overlap, **_paged_kw(paged))
        outs[overlap], rids = _drive(eng, subs, stagger=1)
    for a, b in zip(sorted(outs[False]), sorted(outs[True])):
        np.testing.assert_array_equal(outs[False][a], outs[True][b])


def test_int8_cache_overlap_matches_generate(memorized_lm):
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, cache_dtype="int8",
                        overlap=True)
    out, rids = _drive(eng, [dict(prompt=PATTERN[:6], max_new_tokens=8),
                             dict(prompt=PATTERN[:4], max_new_tokens=6)])
    for rid, p, b in zip(rids, (PATTERN[:6], PATTERN[:4]), (8, 6)):
        ref = generate(m, p[None], b, temperature=0.0,
                       cache_dtype="int8")
        np.testing.assert_array_equal(out[rid], ref[0])


def test_spec_decode_with_pipelined_plain_iterations(memorized_lm):
    """A drafted engine: speculative iterations stay synchronous (the
    in-iteration verify fetch) but plain iterations around them
    pipeline — the mix must stay token-identical to generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=48, overlap=True,
                        draft=NgramDraft(), spec_k=3)
    prompt = np.tile(PATTERN, 3)[:10]
    out, rids = _drive(eng, [
        dict(prompt=prompt, max_new_tokens=16),
        dict(prompt=PATTERN[:5], max_new_tokens=8, speculate=False)])
    np.testing.assert_array_equal(
        out[rids[0]], generate(m, prompt[None], 16, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[rids[1]],
        generate(m, PATTERN[None, :5], 8, temperature=0.0)[0])


def test_moe_dispatched_overlap_and_fused_match_generate(
        memorized_moe_lm):
    """MoE dispatched decode under the zero-bubble loop: overlap and
    fused engines both equal dense-routing generate()."""
    m = memorized_moe_lm
    prompt, budget = PATTERN[:5], 10
    ref = generate(m, prompt[None], budget, temperature=0.0)[0]
    for kw in (dict(overlap=True),
               dict(overlap=True, fuse_steps=4)):
        eng = ServingEngine(m, num_slots=2, max_len=32, **kw)
        out, rids = _drive(eng, [dict(prompt=prompt,
                                      max_new_tokens=budget)])
        np.testing.assert_array_equal(out[rids[0]], ref)


# --- fused multi-step windows ------------------------------------------------


@pytest.mark.parametrize("paged", [False, True])
def test_fused_steady_state_matches_generate(memorized_lm, paged):
    """Closed-loop quiescent batch on a fuse_steps=4 engine: fused
    windows engage after the prefill ramp and outputs equal
    generate() per request."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, overlap=True,
                        fuse_steps=4, **_paged_kw(paged))
    prompts = [PATTERN[:5], PATTERN[:4]]
    budgets = [14, 11]
    subs = [dict(prompt=p, max_new_tokens=b)
            for p, b in zip(prompts, budgets)]
    out, rids = _drive(eng, subs)
    assert eng._fused_fns, "fused window never compiled/engaged"
    for i, rid in enumerate(rids):
        ref = generate(m, prompts[i][None], budgets[i],
                       temperature=0.0)
        np.testing.assert_array_equal(out[rid], ref[0])


@pytest.mark.parametrize("paged", [False, True])
def test_fused_stop_token_mid_scan(memorized_lm, paged):
    """A stop token firing INSIDE a fused window: the in-program done
    mask pads the rest of the window with the stop token and the host
    truncates — output equals generate() with the same stop."""
    m = memorized_lm
    prompt = PATTERN[:5]
    ref = generate(m, prompt[None], 16, temperature=0.0,
                   stop_token=9)[0]
    eng = ServingEngine(m, num_slots=2, max_len=40, overlap=True,
                        fuse_steps=4, **_paged_kw(paged))
    out, rids = _drive(eng, [
        dict(prompt=prompt, max_new_tokens=16, stop_token=9),
        dict(prompt=PATTERN[:4], max_new_tokens=16)])
    got = out[rids[0]]
    assert got[-1] == 9 and len(got) < len(prompt) + 16
    np.testing.assert_array_equal(got, np.asarray(ref)[:len(got)])
    np.testing.assert_array_equal(
        out[rids[1]],
        generate(m, PATTERN[None, :4], 16, temperature=0.0)[0])


def test_fused_sampled_byte_identity_vs_synchronous(memorized_lm):
    """Sampled fused windows (keys split in-program, once per window
    step) must replay the synchronous engine's exact draw stream."""
    m = memorized_lm
    subs = [dict(prompt=PATTERN[:5], max_new_tokens=12,
                 temperature=0.9, top_p=0.95, seed=7),
            dict(prompt=PATTERN[:4], max_new_tokens=12,
                 temperature=0.7, top_k=8, seed=3)]
    sync = ServingEngine(m, num_slots=2, max_len=32, overlap=False)
    out_s, rids_s = _drive(sync, subs)
    fused = ServingEngine(m, num_slots=2, max_len=32, overlap=True,
                          fuse_steps=4)
    out_f, rids_f = _drive(fused, subs)
    assert fused._fused_fns
    for a, b in zip(rids_s, rids_f):
        np.testing.assert_array_equal(out_s[a], out_f[b])


def test_arrival_mid_fused_run_breaks_quiescence_and_matches(
        memorized_lm):
    """A request arriving while fused windows run: the next iteration
    sees the queue, falls back to single-step, admits, and rejoins
    fused later — all streams still equal generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=40, overlap=True,
                        fuse_steps=4)
    r0 = eng.submit(PATTERN[:5], 20)
    for _ in range(6):                     # into fused steady state
        eng.step()
    r1 = eng.submit(PATTERN[:4], 10)
    out = eng.run(max_steps=2000)
    np.testing.assert_array_equal(
        out[r0], generate(m, PATTERN[None, :5], 20, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[r1], generate(m, PATTERN[None, :4], 10, temperature=0.0)[0])


def test_preemption_during_fused_run_falls_back_and_rejoins(
        memorized_lm):
    """Paged fuse engine under page pressure: funding a window (or an
    admission) preempts a stream mid-run — the engine must fall back
    to single-step, resume the victim via recompute prefill, and BOTH
    streams stay token-identical to generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, page_len=4,
                        num_pages=8, prefix_cache=False, overlap=True,
                        fuse_steps=4)
    r0 = eng.submit(PATTERN[:5], 16)
    eng.step()
    eng.step()
    r1 = eng.submit(PATTERN[:6], 15)
    out = eng.run(max_steps=2000)
    assert eng.metrics.requests_preempted >= 1
    assert eng._fused_fns, "fused window never engaged"
    np.testing.assert_array_equal(
        out[r0], generate(m, PATTERN[None, :5], 16, temperature=0.0)[0])
    np.testing.assert_array_equal(
        out[r1], generate(m, PATTERN[None, :6], 15, temperature=0.0)[0])


def test_fault_inside_fused_run_is_retryable(memorized_lm):
    """``serving.decode`` fault injection while fused windows run: the
    chaos hook fires BEFORE the iteration mutates state, so step()
    raises, the next step() retries wholesale, and the final output is
    unaffected."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=1, max_len=40, overlap=True,
                        fuse_steps=4)
    rid = eng.submit(PATTERN[:5], 20)
    for _ in range(4):                     # past prefill, into fused
        eng.step()
    faults.inject("serving.decode", nth=1)
    with pytest.raises(InjectedFault):
        eng.step()
    out = eng.run(max_steps=2000)
    np.testing.assert_array_equal(
        out[rid], generate(m, PATTERN[None, :5], 20, temperature=0.0)[0])


def test_fuse_steps_validation(memorized_lm):
    with pytest.raises(ValueError, match="fuse_steps"):
        ServingEngine(memorized_lm, num_slots=1, max_len=16,
                      fuse_steps=-1)


# --- pipeline flush points ---------------------------------------------------


def test_cancel_mid_flight_lands_inflight_tokens(memorized_lm):
    """cancel() drains the pipeline first: the returned request holds
    every token generated up to the cancel, a prefix of generate()."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=1, max_len=32, overlap=True)
    rid = eng.submit(PATTERN[:5], 16)
    for _ in range(6):
        eng.step()
    req = eng.cancel(rid)
    got = np.asarray(req.tokens)
    ref = generate(m, PATTERN[None, :5], 16, temperature=0.0)[0]
    assert len(got) > len(PATTERN[:5])     # some decode landed
    np.testing.assert_array_equal(got, np.asarray(ref)[:len(got)])


def test_metrics_window_swap_drains_deferred_host_work(memorized_lm):
    """Swapping the metrics window mid-flight (the reporting-interval
    pattern) flushes the pipeline and the deferred buffers into the
    OLD window: token counts across windows sum to exactly the tokens
    generated, none lost or double-counted."""
    m = memorized_lm
    eng = ServingEngine(m, num_slots=2, max_len=32, overlap=True)
    r0 = eng.submit(PATTERN[:5], 12)
    for _ in range(5):
        eng.step()
    w0 = eng.metrics
    eng.metrics = ServingMetrics()
    out = eng.run(max_steps=2000)
    w1 = eng.metrics

    def toks(w):
        return sum(a[0] for a in w._decode_agg.values())

    # 12 budgeted: 1 from prefill + 11 decode, split across windows
    assert toks(w0) + toks(w1) == 11
    assert toks(w0) > 0 and toks(w1) > 0
    assert len(out[r0]) == len(PATTERN[:5]) + 12


def test_tracer_decode_ticks_exact_under_deferred_cadence(memorized_lm):
    """The deferred on_decode_batch cadence keeps per-request decode
    tick TOTALS exact: one tick per emitted token (the first token is
    the prefill's), same as the synchronous per-iteration path."""
    m = memorized_lm
    for kw in (dict(overlap=False), dict(overlap=True),
               dict(overlap=True, fuse_steps=4)):
        eng = ServingEngine(m, num_slots=2, max_len=32, **kw)
        out, rids = _drive(eng, [
            dict(prompt=PATTERN[:5], max_new_tokens=10),
            dict(prompt=PATTERN[:4], max_new_tokens=7)])
        summaries = eng.tracer.summaries()
        for rid, (p, b) in zip(rids, ((PATTERN[:5], 10),
                                      (PATTERN[:4], 7))):
            assert summaries[rid]["decode_iters"] == b - 1, kw
