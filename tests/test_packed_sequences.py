"""Packed/variable-length sequences: segment-id attention masking through
the XLA path and the flash kernel (fwd + BOTH backwards) vs a band+segment
masked oracle, provably-zero cross-segment attention end-to-end, and the
padding-masked LM loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import Model, zoo
from distkeras_tpu.ops.attention import NEG_INF, dot_product_attention
from distkeras_tpu.ops.flash_attention import flash_attention
from distkeras_tpu.ops.losses import get_loss


def _segmented_oracle(q, k, v, seg, causal=True):
    S = q.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    allowed = seg[:, :, None] == seg[:, None, :]
    if causal:
        qp, kp = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
        allowed = allowed & (qp >= kp)[None]
    w = jax.nn.softmax(jnp.where(allowed[:, None], s, NEG_INF), -1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _packed(rs, b=2, s=40, h=2, d=8, n_seg=3):
    q, k, v = (jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
               for _ in range(3))
    seg = jnp.asarray(np.sort(rs.randint(0, n_seg, (b, s)), axis=1))
    return q, k, v, seg


@pytest.mark.parametrize("causal", [True, False])
def test_xla_segment_masking_matches_oracle(causal):
    rs = np.random.RandomState(0)
    q, k, v, seg = _packed(rs)
    out = dot_product_attention(q, k, v, causal=causal, segment_ids=seg)
    ref = _segmented_oracle(q, k, v, seg, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("bwd", ["pallas", "xla"])
def test_flash_segment_masking_grads_match_oracle(bwd):
    """Both flash backwards exact vs the masked oracle (non-divisible
    length exercises the pad path with -1 pad segments)."""
    rs = np.random.RandomState(1)
    q, k, v, seg = _packed(rs, s=44)
    co = jnp.asarray(rs.randn(*q.shape), jnp.float32)

    out = flash_attention(q, k, v, causal=True, segment_ids=seg,
                          interpret=True, block_q=16, block_k=16)
    ref = _segmented_oracle(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    gr = jax.grad(lambda *a: jnp.sum(_segmented_oracle(*a, seg) * co),
                  argnums=(0, 1, 2))(q, k, v)
    gw = jax.grad(lambda *a: jnp.sum(flash_attention(
        *a, causal=True, segment_ids=seg, interpret=True, bwd=bwd,
        block_q=16, block_k=16) * co), argnums=(0, 1, 2))(q, k, v)
    for x, y in zip(gw, gr):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=2e-5)


def test_cross_segment_attention_provably_zero_end_to_end():
    """Invariance proof on the full LM, in the direction CAUSALITY DOES
    NOT COVER: causal attention alone would already isolate an earlier
    segment from a later one, so the load-bearing check is that
    perturbing the EARLIER segment leaves the LATER segment's logits
    unchanged — that holds only when segment masking actually works."""
    V, S, CUT = 32, 24, 10
    model = Model.build(zoo.transformer_lm(V, d_model=32, num_heads=4,
                                           num_layers=2, mlp_ratio=2),
                        (S,), seed=0)
    rs = np.random.RandomState(2)
    toks = rs.randint(0, V, (2, S))
    toks2 = toks.copy()
    toks2[:, :CUT] = rs.randint(0, V, (2, CUT))       # perturb segment 1
    seg = jnp.asarray((np.arange(S) >= CUT).astype(np.int32))[None, :] \
        .repeat(2, axis=0)

    def logits(t, s=seg):
        out, _ = model.module.apply(model.params, model.state,
                                    jnp.asarray(t), segment_ids=s)
        return out

    l1, l2 = logits(toks), logits(toks2)
    # segment-2 logits identical although segment 1 (its causal PAST)
    # changed completely — impossible unless the mask cut the link
    np.testing.assert_array_equal(np.asarray(l1[:, CUT:]),
                                  np.asarray(l2[:, CUT:]))
    # ...and segment 1's own logits DID change
    assert not np.allclose(np.asarray(l1[:, :CUT]), np.asarray(l2[:, :CUT]))
    # sanity: WITHOUT segment ids the same perturbation leaks into
    # segment 2 (proves the check has teeth)
    u1, u2 = logits(toks, None), logits(toks2, None)
    assert not np.allclose(np.asarray(u1[:, CUT:]), np.asarray(u2[:, CUT:]))

    # gradient side: loss restricted to segment 2 is invariant to what
    # segment 1 contained — identical param grads under both contents
    def seg2_loss(params, t):
        out, _ = model.module.apply(params, model.state, jnp.asarray(t),
                                    segment_ids=seg)
        return jnp.sum(jnp.square(out[:, CUT:].astype(jnp.float32)))

    g1 = jax.grad(seg2_loss)(model.params, toks)
    g2 = jax.grad(seg2_loss)(model.params, toks2)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        d = np.abs(np.asarray(a) - np.asarray(b))
        # embedding rows of the perturbed tokens legitimately differ in
        # WHICH rows receive gradient; everything flowing through
        # attention/mlp weights must match exactly
        if a.shape == (V, 32):
            continue
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_segment_ids_through_remat_and_rejection():
    """Containers forward segment_ids (Remat-wrapped block == bare
    block); a stack with no accepting layer fails loudly."""
    from distkeras_tpu.models import Sequential
    from distkeras_tpu.models.attention import TransformerBlock
    from distkeras_tpu.models.blocks import Remat
    from distkeras_tpu.models.layers import Dense, Embedding

    V, S = 16, 12
    rs = np.random.RandomState(5)
    toks = rs.randint(0, V, (2, S))
    seg = jnp.asarray(np.sort(rs.randint(0, 3, (2, S)), axis=1))

    def build(wrap):
        blk = TransformerBlock(num_heads=2, mlp_ratio=2, causal=True)
        layers = [Embedding(V, 16),
                  Remat(blk) if wrap else blk, Dense(V)]
        return Model.build(Sequential(layers), (S,), seed=3)

    m_plain, m_remat = build(False), build(True)
    # same seed -> same params; remat must not change masked numerics
    o1, _ = m_plain.module.apply(m_plain.params, m_plain.state,
                                 jnp.asarray(toks), segment_ids=seg)
    o2, _ = m_remat.module.apply(m_remat.params, m_remat.state,
                                 jnp.asarray(toks), segment_ids=seg)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=1e-6)
    # and segment ids demonstrably took effect through the Remat wrapper
    o3, _ = m_remat.module.apply(m_remat.params, m_remat.state,
                                 jnp.asarray(toks))
    assert not np.allclose(np.asarray(o2), np.asarray(o3))

    mlp_only = Model.build(Sequential([Embedding(V, 8), Dense(V)]),
                           (S,), seed=0)
    with pytest.raises(ValueError, match="segment_ids"):
        mlp_only.module.apply(mlp_only.params, mlp_only.state,
                              jnp.asarray(toks), segment_ids=seg)


def test_packed_batch_trains_with_masked_loss():
    """End-to-end packed training: two sequences per row, padding labeled
    -1, masked loss; training converges on a copy task."""
    from distkeras_tpu.ops import apply_updates, get_optimizer

    V, S = 16, 16
    model = Model.build(zoo.transformer_lm(V, d_model=32, num_heads=4,
                                           num_layers=1, mlp_ratio=2),
                        (S,), seed=0)
    rs = np.random.RandomState(3)
    # rows: [seq A (7 tok) | seq B (6 tok) | pad (3)]
    X = rs.randint(1, V, (32, S))
    seg = np.zeros((32, S), np.int32)
    seg[:, 7:13] = 1
    seg[:, 13:] = -1
    Y = X.copy()
    Y[:, 13:] = -1                                     # padding ignored
    loss_fn = get_loss("masked_sparse_categorical_crossentropy_from_logits")
    opt = get_optimizer("adam", learning_rate=5e-3)
    params = model.params
    opt_state = opt.init(params)
    segj = jnp.asarray(seg)

    @jax.jit
    def step(params, opt_state):
        def lf(p):
            out, _ = model.module.apply(p, model.state, jnp.asarray(X),
                                        training=True, segment_ids=segj)
            return loss_fn(jnp.asarray(Y), out)
        l, g = jax.value_and_grad(lf)(params)
        upd, opt_state2 = opt.update(g, opt_state, params)
        return apply_updates(params, upd), opt_state2, l

    first = None
    for _ in range(120):
        params, opt_state, l = step(params, opt_state)
        if first is None:
            first = float(l)
    assert np.isfinite(float(l))
    assert float(l) < 0.5 * first, (first, float(l))


def test_masked_loss_ignores_negative_labels():
    logits = jnp.asarray(np.random.RandomState(4).randn(2, 5, 7))
    y = jnp.asarray([[1, 2, -1, -1, 3], [0, -1, 4, 5, -1]])
    fn = get_loss("masked_sparse_categorical_crossentropy_from_logits")
    full = get_loss("sparse_categorical_crossentropy_from_logits")
    # equals the unmasked mean over ONLY the valid positions
    valid = [(0, 0), (0, 1), (0, 4), (1, 0), (1, 2), (1, 3)]
    ref = np.mean([float(full(y[i][j][None], logits[i][j][None]))
                   for i, j in valid])
    np.testing.assert_allclose(float(fn(y, logits)), ref, rtol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_with_segments_matches_oracle(causal):
    """Packed sequences COMPOSE with ring sequence parallelism (round 4,
    VERDICT r3 weak #4): fwd + custom-VJP bwd vs the dense segmented
    oracle on the 8-device mesh. The k-side ids rotate with their K/V
    shards, so cross-shard blocks mask correctly too (segments straddle
    shard boundaries by construction here)."""
    import functools

    from distkeras_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.ops.ring_attention import ring_attention

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("seq",))
    b, s, h, d = 2, 8 * n, 2, 8
    rs = np.random.RandomState(21)
    q, k, v = (jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
               for _ in range(3))
    # sorted ids -> contiguous packed docs whose boundaries do NOT align
    # with the s/n shard edges
    seg = jnp.asarray(np.sort(rs.randint(0, 5, (b, s)), axis=1))
    co = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)

    def ring_local(q, k, v, seg):
        return ring_attention(q, k, v, axis_name="seq", causal=causal,
                              segment_ids=seg)

    ring = shard_map(ring_local, mesh=mesh,
                     in_specs=(P(None, "seq"),) * 4,
                     out_specs=P(None, "seq"))

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v, seg) * co)

    def loss_ref(q, k, v):
        return jnp.sum(_segmented_oracle(q, k, v, seg, causal=causal) * co)

    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda: ring(q, k, v, seg))()),
        np.asarray(_segmented_oracle(q, k, v, seg, causal=causal)),
        atol=1e-5)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, o in zip(gr, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(o), atol=1e-4)


def test_ulysses_attention_with_segments_matches_oracle():
    """Same composition through the all-to-all path: the ids all_gather
    alongside the head scatter. fwd + bwd vs the dense segmented oracle."""
    import functools

    from distkeras_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.ops.ulysses import ulysses_attention

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("seq",))
    b, s, h, d = 2, 4 * n, n, 8
    rs = np.random.RandomState(22)
    q, k, v = (jnp.asarray(rs.randn(b, s, h, d), jnp.float32)
               for _ in range(3))
    seg = jnp.asarray(np.sort(rs.randint(0, 4, (b, s)), axis=1))
    co = jnp.asarray(rs.randn(b, s, h, d), jnp.float32)

    def uly_local(q, k, v, seg):
        return ulysses_attention(q, k, v, axis_name="seq", causal=True,
                                 segment_ids=seg)

    uly = shard_map(uly_local, mesh=mesh,
                    in_specs=(P(None, "seq"),) * 4,
                    out_specs=P(None, "seq"))

    def loss_uly(q, k, v):
        return jnp.sum(uly(q, k, v, seg) * co)

    def loss_ref(q, k, v):
        return jnp.sum(_segmented_oracle(q, k, v, seg, causal=True) * co)

    np.testing.assert_allclose(
        np.asarray(jax.jit(lambda: uly(q, k, v, seg))()),
        np.asarray(_segmented_oracle(q, k, v, seg, causal=True)),
        atol=1e-5)
    gu = jax.grad(loss_uly, argnums=(0, 1, 2))(q, k, v)
    go = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, o in zip(gu, go):
        np.testing.assert_allclose(np.asarray(a), np.asarray(o), atol=1e-4)


def test_mha_layer_segments_on_ring_path():
    """The layer-level path that round 3 REJECTED now runs: a
    MultiHeadAttention(attn_impl='ring') inside shard_map with
    segment_ids matches the same layer on the xla path unsharded."""
    import functools

    from distkeras_tpu.compat import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from distkeras_tpu.models.attention import MultiHeadAttention

    devs = jax.devices()
    n = len(devs)
    mesh = Mesh(np.array(devs), ("sp",))
    b, s, dm = 2, 8 * n, 16
    rs = np.random.RandomState(23)
    x = jnp.asarray(rs.randn(b, s, dm), jnp.float32)
    seg = jnp.asarray(np.sort(rs.randint(0, 3, (b, s)), axis=1))

    ring_mha = MultiHeadAttention(num_heads=2, attn_impl="ring",
                                  seq_axis_name="sp", use_rope=True)
    params, state, _ = ring_mha.init(jax.random.PRNGKey(0), (s, dm))
    xla_mha = MultiHeadAttention(num_heads=2, attn_impl="xla",
                                 use_rope=True)

    def local(xs, segs):
        y, _ = ring_mha.apply(params, state, xs, segment_ids=segs)
        return y

    sharded = shard_map(local, mesh=mesh,
                        in_specs=(P(None, "sp"), P(None, "sp")),
                        out_specs=P(None, "sp"))
    out = jax.jit(sharded)(x, seg)
    ref, _ = xla_mha.apply(params, state, x, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("bwd", ["pallas", "xla"])
def test_segments_compose_with_sliding_window(bwd):
    """segment_ids AND window on the same call: the masks must intersect
    (both features edit the same score tile) — fwd and both backwards vs
    the banded+segmented oracle, with remap-active blocks."""
    window = 6
    rs = np.random.RandomState(9)
    B, S, H, D = 1, 64, 2, 8
    q, k, v = (jnp.asarray(rs.randn(B, S, H, D), jnp.float32)
               for _ in range(3))
    seg = jnp.asarray(np.sort(rs.randint(0, 3, (B, S)), axis=1))
    co = jnp.asarray(rs.randn(B, S, H, D), jnp.float32)

    def oracle(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        qp, kp = jnp.arange(S)[:, None], jnp.arange(S)[None, :]
        allowed = (qp >= kp) & (kp > qp - window)
        allowed = allowed[None] & (seg[:, :, None] == seg[:, None, :])
        w = jax.nn.softmax(jnp.where(allowed[:, None], s, NEG_INF), -1)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v)

    kw = dict(causal=True, window=window, segment_ids=seg, interpret=True,
              block_q=16, block_k=8)
    from distkeras_tpu.ops.flash_attention import _window_kblocks
    assert _window_kblocks(16, 8, S // 8, window, S // 16) < S // 8
    out = flash_attention(q, k, v, **kw)
    np.testing.assert_allclose(out, oracle(q, k, v), atol=1e-5)
    gr = jax.grad(lambda *a: jnp.sum(oracle(*a) * co),
                  argnums=(0, 1, 2))(q, k, v)
    gw = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, bwd=bwd, **kw) * co),
        argnums=(0, 1, 2))(q, k, v)
    for x, y in zip(gw, gr):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=2e-5)
