"""Parameter servers, framed networking, and the true-async trainer."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.data import Dataset
from distkeras_tpu.models import Model, zoo
from distkeras_tpu.parallel import (
    ADAGParameterServer, DeltaParameterServer, DynSGDParameterServer,
    HostAsyncTrainer, PSClient)
from distkeras_tpu.parallel import networking


# ---------------------------------------------------------------------------
# networking: framing
# ---------------------------------------------------------------------------

def _echo_server():
    server = networking.MessageServer(lambda msg: msg, host="127.0.0.1")
    server.start()
    return server


def test_framed_roundtrip_pickle_and_npy():
    server = _echo_server()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        obj = {"action": "commit", "delta": [1, 2, 3], "s": "x"}
        assert networking.request(sock, obj) == obj
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = networking.request(sock, arr)
        np.testing.assert_array_equal(out, arr)
        sock.close()
    finally:
        server.stop()


def test_frame_rejects_bad_magic():
    server = _echo_server()
    try:
        sock = networking.connect("127.0.0.1", server.port)
        sock.sendall(b"JUNKJUNKJUNKJUNK")
        # server drops the connection instead of crashing; further requests
        # on a NEW connection still work
        sock2 = networking.connect("127.0.0.1", server.port)
        assert networking.request(sock2, {"ok": 1}) == {"ok": 1}
        sock.close()
        sock2.close()
    finally:
        server.stop()


def test_determine_host_address_returns_ip():
    addr = networking.determine_host_address()
    assert isinstance(addr, str) and addr.count(".") == 3


# ---------------------------------------------------------------------------
# parameter servers: update rules
# ---------------------------------------------------------------------------

def _center():
    return {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))}


def test_delta_ps_accumulates_and_counts():
    ps = DeltaParameterServer(_center())
    client = PSClient(ps=ps)
    leaves, clock = client.pull()
    assert clock == 0
    # leaf order = tree_flatten order (dict keys sorted: b, then w)
    delta = [np.ones((2,)), np.full((2, 2), 0.5)]
    client.commit(delta)
    client.commit(delta)
    got = ps.get_model()
    np.testing.assert_allclose(np.asarray(got["w"]), 2.0)
    np.testing.assert_allclose(np.asarray(got["b"]), 2.0)
    assert ps.num_updates == 2


def test_dynsgd_ps_scales_by_staleness():
    ps = DynSGDParameterServer({"w": jnp.zeros(())})
    # 3 foreign commits advance the clock
    for _ in range(3):
        ps.handle_commit({"delta": [np.asarray(0.0)], "clock": 0})
    # a stale commit (pulled at clock 0, now clock 3): staleness 4
    ps.handle_commit({"delta": [np.asarray(8.0)], "clock": 0})
    np.testing.assert_allclose(np.asarray(ps.get_model()["w"]), 2.0)


def test_adag_ps_normalizes_commits():
    ps = ADAGParameterServer({"w": jnp.zeros(())}, learning_rate=0.1)
    ps.handle_commit({"delta": [np.asarray(4.0)]})
    # acc = 16, update = 0.1 * 4/sqrt(16) = 0.1
    np.testing.assert_allclose(np.asarray(ps.get_model()["w"]), 0.1,
                               rtol=1e-5)


def test_ps_socket_transport_matches_inprocess():
    ps = DeltaParameterServer(_center())
    port = ps.start(host="127.0.0.1")
    client = PSClient(host="127.0.0.1", port=port)
    leaves, clock = client.pull()
    np.testing.assert_allclose(leaves[1], 1.0)  # leaves = [b, w]
    client.commit([np.zeros((2,)), np.full((2, 2), 1.0)])
    leaves2, clock2 = client.pull()
    np.testing.assert_allclose(leaves2[1], 2.0)
    assert clock2 == 1
    client.close()
    ps.stop()


def test_ps_concurrent_commits_all_land():
    ps = DeltaParameterServer({"w": jnp.zeros(())})
    n_threads, n_commits = 8, 25

    def worker():
        c = PSClient(ps=ps)
        for _ in range(n_commits):
            c.commit([np.asarray(1.0)])

    ts = [threading.Thread(target=worker) for _ in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert ps.num_updates == n_threads * n_commits
    np.testing.assert_allclose(np.asarray(ps.get_model()["w"]),
                               n_threads * n_commits)


# ---------------------------------------------------------------------------
# true-async trainer
# ---------------------------------------------------------------------------

def _toy_problem(n=512, d=10, c=3, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, c)
    X = rs.randn(n, d).astype(np.float32)
    Y = (X @ w).argmax(-1)
    return Dataset({"features": X, "label": Y}), X, Y, d, c


@pytest.mark.parametrize("algorithm", ["downpour", "easgd", "dynsgd", "adag"])
def test_host_async_trainer_converges(algorithm):
    ds, X, Y, d, c = _toy_problem()
    model = Model.build(zoo.mlp((32,), num_classes=c), (d,), seed=1)
    tr = HostAsyncTrainer(
        model, algorithm=algorithm, num_workers=4, batch_size=16,
        communication_window=4, num_epoch=4 if algorithm != "easgd" else 10,
        worker_optimizer="sgd", optimizer_kwargs={"learning_rate": 0.1},
        loss="sparse_categorical_crossentropy_from_logits")
    trained = tr.train(ds)
    losses = tr.get_history().losses()
    assert np.isfinite(losses).all()
    acc = (trained.predict(X).argmax(-1) == Y).mean()
    assert acc > 0.6, (algorithm, acc)
    assert tr.parameter_server.num_updates > 0


def test_host_async_socket_transport_converges():
    ds, X, Y, d, c = _toy_problem(seed=3)
    model = Model.build(zoo.mlp((16,), num_classes=c), (d,), seed=1)
    tr = HostAsyncTrainer(
        model, algorithm="downpour", num_workers=2, batch_size=32,
        communication_window=2, num_epoch=3, transport="socket",
        worker_optimizer="sgd", optimizer_kwargs={"learning_rate": 0.1},
        loss="sparse_categorical_crossentropy_from_logits")
    trained = tr.train(ds)
    acc = (trained.predict(X).argmax(-1) == Y).mean()
    assert acc > 0.6, acc


def test_host_async_heterogeneous_windows():
    ds, X, Y, d, c = _toy_problem(seed=4)
    model = Model.build(zoo.mlp((16,), num_classes=c), (d,), seed=1)
    tr = HostAsyncTrainer(
        model, algorithm="dynsgd", num_workers=4, batch_size=16,
        communication_window=[1, 2, 4, 8], num_epoch=3,
        worker_optimizer="sgd", optimizer_kwargs={"learning_rate": 0.1},
        loss="sparse_categorical_crossentropy_from_logits")
    trained = tr.train(ds)
    assert np.isfinite(tr.get_history().losses()).all()
    acc = (trained.predict(X).argmax(-1) == Y).mean()
    assert acc > 0.6, acc


def test_host_async_rejects_unknown_algorithm():
    model = Model.build(zoo.mlp((8,), num_classes=2), (4,), seed=0)
    with pytest.raises(ValueError, match="algorithm"):
        HostAsyncTrainer(model, algorithm="sparkle")


def test_ps_socket_handler_error_propagates():
    ps = DeltaParameterServer({"w": jnp.zeros(())})
    port = ps.start()
    client = PSClient(host="127.0.0.1", port=port)
    with pytest.raises(RuntimeError, match="parameter server error"):
        # malformed commit: missing 'delta'
        networking_reply = client._checked(
            networking.request(client._sock, {"action": "commit"}))
    client.close()
    ps.stop()


def test_host_async_window_longer_than_epoch_still_learns():
    # window(8) > steps-per-epoch(4): progress lands via the per-epoch
    # residual flush rather than in-window commits
    ds, X, Y, d, c = _toy_problem(seed=5)
    model = Model.build(zoo.mlp((16,), num_classes=c), (d,), seed=1)
    tr = HostAsyncTrainer(
        model, algorithm="downpour", num_workers=4, batch_size=32,
        communication_window=8, num_epoch=4,
        worker_optimizer="sgd", optimizer_kwargs={"learning_rate": 0.1},
        loss="sparse_categorical_crossentropy_from_logits")
    trained = tr.train(ds)
    acc = (trained.predict(X).argmax(-1) == Y).mean()
    assert acc > 0.6, acc


def test_host_async_checkpoint_and_resume(tmp_path):
    ds, X, Y, d, c = _toy_problem(seed=6)
    model = Model.build(zoo.mlp((16,), num_classes=c), (d,), seed=1)
    kwargs = dict(
        algorithm="downpour", num_workers=2, batch_size=32,
        communication_window=2, worker_optimizer="sgd",
        optimizer_kwargs={"learning_rate": 0.1},
        loss="sparse_categorical_crossentropy_from_logits",
        checkpoint_dir=str(tmp_path))
    tr = HostAsyncTrainer(model, num_epoch=2, **kwargs)
    tr.train(ds)

    # resume continues from epoch 2 (history only has the remaining epochs)
    model2 = Model.build(zoo.mlp((16,), num_classes=c), (d,), seed=1)
    tr2 = HostAsyncTrainer(model2, num_epoch=4, resume=True, **kwargs)
    trained = tr2.train(ds)
    assert tr2.get_history().losses().shape[0] > 0
    acc = (trained.predict(X).argmax(-1) == Y).mean()
    assert acc > 0.6, acc


def test_ps_socket_stress_interleaved_pull_commit():
    """Race harness (SURVEY §5.2 role): many socket clients interleave
    pulls and distinct commits; the mutex must serialize them so the final
    center equals the exact sum and every pull observes a consistent
    (never torn) value."""
    ps = DeltaParameterServer({"w": jnp.zeros((32,))})
    ps.initialize()
    port = ps.start(host="127.0.0.1")
    n_threads, n_commits = 12, 40
    torn = []

    def worker(widx):
        c = PSClient(host="127.0.0.1", port=port)
        try:
            for i in range(n_commits):
                delta = np.full((32,), float(widx * n_commits + i))
                c.commit([delta])
                pulled, _ = c.pull()
                # a torn read would mix elements from different commits;
                # every committed delta is CONSTANT across the vector, so
                # any consistent sum is also constant across the vector
                if not np.allclose(pulled[0], pulled[0][0]):
                    torn.append(pulled[0])
        finally:
            c.close()

    ts = [threading.Thread(target=worker, args=(i,))
          for i in range(n_threads)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    ps.stop()
    assert not torn, f"torn reads observed: {torn[:2]}"
    assert ps.num_updates == n_threads * n_commits
    total = sum(float(w * n_commits + i)
                for w in range(n_threads) for i in range(n_commits))
    np.testing.assert_allclose(np.asarray(ps.get_model()["w"]),
                               np.full((32,), total))


def test_host_async_trainer_callbacks_early_stop():
    from distkeras_tpu.utils import EarlyStopping
    ds, X, Y, d, c = _toy_problem()
    model = Model.build(zoo.mlp((16,), num_classes=c), (d,), seed=1)
    es = EarlyStopping(monitor="loss", min_delta=1e9, patience=0)
    tr = HostAsyncTrainer(
        model, algorithm="downpour", num_workers=2, batch_size=16,
        communication_window=4, num_epoch=10, worker_optimizer="sgd",
        optimizer_kwargs={"learning_rate": 0.1},
        loss="sparse_categorical_crossentropy_from_logits",
        callbacks=[es])
    tr.train(ds)
    assert len(tr.get_history().epochs) == 2  # epoch 0 best, stop at 1
