"""Communication-amortization tests for the SPMD engine.

The whole point of ``communication_window`` in the reference
(``distkeras/workers.py`` window counters, SURVEY §2.3) is *comms
amortization*: K local steps per parameter-server round-trip. These tests
pin down that the engine's compiled epoch preserves that property on the
mesh — a param-sized collective fires once per window, NOT once per
micro-step — and that the amortized program is semantically faithful to
the per-step masked path.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distkeras_tpu.models import Dense, Model, Sequential
from distkeras_tpu.ops.losses import get_loss
from distkeras_tpu.ops.optimizers import get_optimizer
from distkeras_tpu.parallel.engine import (
    DistributedEngine, DownpourAlgo, ElasticAlgo, EngineConfig)
from distkeras_tpu.parallel.mesh import make_mesh

D, C, B, W = 16, 4, 4, 8


def _make_engine(algo, window, amortized=None):
    model = Model.build(
        Sequential([Dense(8, activation="relu"), Dense(C)]), (D,), seed=0)
    engine = DistributedEngine(
        model.module, get_loss("sparse_categorical_crossentropy_from_logits"),
        get_optimizer("sgd", learning_rate=0.05), algo, make_mesh(W),
        EngineConfig(num_workers=W, window=window, amortized=amortized))
    return model, engine


def _epoch_args(engine, model, S, seed=0):
    rs = np.random.RandomState(seed)
    Xf = rs.randn(S * W * B, D).astype(np.float32)
    yf = np.argmax(Xf @ rs.randn(D, C), axis=1)  # separable teacher
    X = jnp.asarray(Xf.reshape(S, W, B, D))
    Y = jnp.asarray(yf.reshape(S, W, B))
    # copy params: run_epoch donates its state, and the center leaf aliases
    # the model's params buffer
    params = jax.tree_util.tree_map(jnp.array, model.params)
    state = engine.init_state(params, model.state, jax.random.PRNGKey(0))
    state = jax.device_put(state, engine.shardings())
    return state, X, Y


# -- dynamic psum count: the S/K-proportionality proof ----------------------

def _subjaxprs(eqn):
    mult = eqn.params.get("length", 1) if eqn.primitive.name == "scan" else 1
    for p in eqn.params.values():
        if hasattr(p, "eqns"):
            yield p, mult
        elif hasattr(p, "jaxpr"):
            yield p.jaxpr, mult
        elif isinstance(p, (list, tuple)):
            for pi in p:
                if hasattr(pi, "jaxpr"):
                    yield pi.jaxpr, mult


def count_dynamic_psums(jaxpr, trips=1):
    """Total psum *executions* per call: each psum eqn weighted by the
    product of enclosing scan lengths. Weighted by outvars because some
    jax versions batch one ``lax.psum(tree)`` call into a single
    multi-output eqn while others emit one eqn per leaf — per-leaf
    reductions crossing the mesh is the invariant under test."""
    total = 0
    for eqn in jaxpr.eqns:
        if "psum" in eqn.primitive.name:
            total += trips * len(eqn.outvars)
        for sub, mult in _subjaxprs(eqn):
            total += count_dynamic_psums(sub, trips * mult)
    return total


def _psums_per_epoch(algo, window, S, amortized):
    model, engine = _make_engine(algo, window, amortized)
    state, X, Y = _epoch_args(engine, model, S)
    engine._build()
    return count_dynamic_psums(jax.make_jaxpr(engine._epoch_fn)(
        state, X, Y).jaxpr)


@pytest.mark.parametrize("window,S", [(4, 32), (8, 32), (5, 32), (16, 32)])
def test_psum_executions_proportional_to_windows(window, S):
    # 4 param leaves + 1 n_commits scalar cross the mesh per commit round
    per_commit = 5
    amortized = _psums_per_epoch(DownpourAlgo(), window, S, amortized=True)
    n_windows = -(-S // window)  # ceil: remainder block flushes once
    assert amortized == n_windows * per_commit, (
        f"window={window}: expected {n_windows} collective rounds/epoch, "
        f"got {amortized / per_commit}")
    perstep = _psums_per_epoch(DownpourAlgo(), window, S, amortized=False)
    assert perstep == S * per_commit  # the round-1 behavior: every step


def test_window_one_is_per_step_either_way():
    assert _psums_per_epoch(DownpourAlgo(), 1, 16, True) == \
        _psums_per_epoch(DownpourAlgo(), 1, 16, False)


# -- compiled-HLO check: collective sits OUTSIDE the inner step loop --------

def _while_depths(txt, op):
    depths = set()
    for line in txt.splitlines():
        if f"%{op}" in line and "op_name=" in line:
            m = re.search(r'op_name="([^"]+)"', line)
            if m:
                depths.add(m.group(1).count("while/"))
    return depths


def test_hlo_all_reduce_outside_inner_loop():
    """In the lowered+compiled epoch, matmuls run inside the two-level
    scan nest (while-depth 2) but all-reduce only in the outer window loop
    (depth 1). The per-step build keeps both at the same depth."""
    model, engine = _make_engine(DownpourAlgo(), 8, amortized=True)
    state, X, Y = _epoch_args(engine, model, 32)
    engine._build()
    txt = engine._epoch_fn.lower(state, X, Y).compile().as_text()
    ar, dot = _while_depths(txt, "all-reduce"), _while_depths(txt, "dot")
    assert ar and dot, "HLO should contain all-reduce and dot ops"
    assert max(ar) < max(dot), (
        f"all-reduce nesting {ar} should be shallower than compute {dot}")

    model, engine = _make_engine(DownpourAlgo(), 8, amortized=False)
    state, X, Y = _epoch_args(engine, model, 32)
    engine._build()
    txt = engine._epoch_fn.lower(state, X, Y).compile().as_text()
    ar, dot = _while_depths(txt, "all-reduce"), _while_depths(txt, "dot")
    assert max(ar) == max(dot)


# -- semantic equivalence ----------------------------------------------------

def _run_epochs(engine, model, S, epochs=2):
    state, X, Y = _epoch_args(engine, model, S)
    for _ in range(epochs):
        state, outs = engine.run_epoch(state, X, Y)
    params, mstate = engine.extract_model(state)
    return params, jax.device_get(outs)


def test_sync_elastic_amortized_equals_perstep():
    """Synchronous algorithms (offsets = 0) commit at the window's final
    step, where the amortized snapshot IS the live params — the two builds
    must produce the same trajectory to float tolerance."""
    algo = lambda: ElasticAlgo(alpha=0.05, synchronous=True)
    model, e_am = _make_engine(algo(), 4, amortized=True)
    _, e_ps = _make_engine(algo(), 4, amortized=False)
    p_am, l_am = _run_epochs(e_am, model, 16)
    p_ps, l_ps = _run_epochs(e_ps, model, 16)
    np.testing.assert_allclose(l_am, l_ps, rtol=1e-5, atol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        p_am, p_ps)


def test_staggered_amortized_still_learns_vs_perstep():
    """Staggered (async-emulation) algorithms change commit batching under
    amortization by design; both paths must still descend comparably."""
    model, e_am = _make_engine(DownpourAlgo(), 4, amortized=True)
    _, e_ps = _make_engine(DownpourAlgo(), 4, amortized=False)
    _, l_am = _run_epochs(e_am, model, 32, epochs=3)
    _, l_ps = _run_epochs(e_ps, model, 32, epochs=3)
    # both trajectories end well below the ~ln(4)=1.39 random-init loss
    assert float(np.mean(l_am[-8:])) < 1.0
    assert float(np.mean(l_ps[-8:])) < 1.0


# -- heterogeneous windows ---------------------------------------------------

def test_heterogeneous_windows_use_perstep_path():
    _, engine = _make_engine(DownpourAlgo(), [2] * 4 + [4] * 4)
    assert engine.amortized is False


def test_amortized_forced_with_heterogeneous_windows_raises():
    with pytest.raises(ValueError, match="uniform window"):
        _make_engine(DownpourAlgo(), [2] * 4 + [4] * 4, amortized=True)


def test_uniform_window_defaults_to_amortized():
    _, engine = _make_engine(DownpourAlgo(), 8)
    assert engine.amortized is True
    # a list of equal windows is uniform too
    _, engine = _make_engine(DownpourAlgo(), [8] * W)
    assert engine.amortized is True


def test_non_amortizable_algorithms_stay_per_step():
    """DynSGD's staleness damping and ADAG's nonlinear accumulator require
    per-commit serialization; the engine must not amortize them even with
    a uniform window."""
    from distkeras_tpu.parallel.engine import AdagAlgo, DynSGDAlgo

    for algo_cls in (DynSGDAlgo, AdagAlgo):
        _, engine = _make_engine(algo_cls(), 8)
        assert engine.amortized is False, algo_cls.__name__
        with pytest.raises(ValueError, match="not amortizable"):
            _make_engine(algo_cls(), 8, amortized=True)
